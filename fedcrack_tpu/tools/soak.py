"""The first concurrent mini-soak — every plane at once, watched live.

``python -m fedcrack_tpu.tools.soak --duration 10 --out soak.json``

Rounds 6–14 drilled every subsystem in isolation (17 chaos scenarios, kill
drills, storm A/Bs); this harness is the ROADMAP's continuous-operation
item shrunk to a bounded wall: a **buffered federation** (FedBuff root,
real FedClients looping pull→train→push through the r12 compressed
transport), an **edge-tier shard** (buffered EdgeAggregator + raw relay
feeding the same root), a **serve plane** (compiled bucket engine,
micro-batcher, hot-swap manager polling the federation's LIVE statefile —
the models being served are the models being trained), and a **driver
leg** (a small ``run_mesh_federation`` session), all running CONCURRENTLY
under a rolling chaos schedule:

- a seeded straggler storm (``FaultPlan.storm``) delaying every client's
  pushes with heavy-tail draws,
- periodic CORRUPT_COMPRESSED_FRAME / STALE_REPLAY poisons (rejected
  loudly; the poisoned client dies and is restarted, like a pod),
- one mid-soak server **kill → restart on the same port** over the durable
  statefile, with clients riding the restart on their retry budgets.

The soak watches itself through the round-15 telemetry plane: it exports
the process registry on an ephemeral ``/metrics`` port, SCRAPES ITS OWN
ENDPOINT mid-run and at the end (valid Prometheus text format covering all
five instrumented planes — fed, serve, driver, edge, transport-client),
records correlated trace spans to JSONL, and finishes with the invariant
audit the ROADMAP names:

- **zero torn versions** — per-batch served model versions are
  monotonically non-decreasing and every served version was actually
  published (initial weights or a recorded hot-swap);
- **EF mass conserved** — a top-k error-feedback twin runs alongside the
  chaos and checks, per encode, that the codec's accumulator equals the
  conservation-implied remainder (kept + residual == delta + prior
  residual), then drains on a quiet tail;
- **statefile restores bit-identical** — the final durable statefile
  round-trips through load → save to byte-identical bytes (canonical
  snapshot idempotence, under whatever arrival order the chaos produced);
- **watermarks steady** — RSS + device-memory leak sentries marked after
  warmup must stay inside their slack.

bench.py embeds :func:`run_soak` as ``detail.observability`` (schema-
guarded); tests/test_telemetry.py runs a short version tier-1 and the
60-second version slow-marked.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from fedcrack_tpu.configs import FedConfig, ModelConfig, ServeConfig

JOIN_S = 30.0


class _SoakStop(Exception):
    """Raised inside a client train_fn when the wall expires — unwinds the
    session thread without waiting on the server."""


def _perturb_tree(tree, rng: np.random.Generator, scale: float = 1e-3):
    """A cheap deterministic 'local fit': base + seeded noise per leaf.
    Real training would need a compiled program per client; the soak is
    about the PROTOCOL planes, so the update only has to be a plausible
    finite delta."""
    import jax

    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(leaf)
        + rng.normal(0.0, scale, np.shape(leaf)).astype(np.asarray(leaf).dtype)
        if np.issubdtype(np.asarray(leaf).dtype, np.floating)
        else np.asarray(leaf),
        tree,
    )


def _ef_conservation_leg(template, stop: threading.Event, out: dict, seed: int) -> None:
    """The error-feedback mass audit: drive a TopKDeltaCodec twin with
    seeded deltas WHILE the soak's real traffic contends for the GIL, and
    verify after every encode that the codec's residual mass equals the
    conservation-implied remainder — |delta + prior_residual| split
    exactly into |transmitted| + |residual|. Then feed zero deltas and
    require the accumulator to drain monotonically ('nothing lost, only
    delayed' converges)."""
    from fedcrack_tpu.compress.codecs import TopKDeltaCodec
    from fedcrack_tpu.compress.frames import decode_update
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes

    import jax

    rng = np.random.default_rng(seed + 777)
    codec = TopKDeltaCodec(fraction=0.25)
    base_tree = tree_from_bytes(tree_to_bytes(template), template=template)
    base_blob = tree_to_bytes(base_tree)
    violations = 0
    checks = 0
    mirror = None  # our independent residual mirror

    def leaves(t):
        return [np.asarray(x, np.float64) for x in jax.tree_util.tree_leaves(t)]

    base_leaves = leaves(base_tree)
    while not stop.is_set() and checks < 200:
        trained = _perturb_tree(base_tree, rng, scale=1e-2)
        delta = [t - b for t, b in zip(leaves(trained), base_leaves)]
        if mirror is None:
            mirror = [np.zeros_like(d) for d in delta]
        eff = [d + m for d, m in zip(delta, mirror)]
        frame = codec.encode_update(
            tree_to_bytes(trained), base_blob, round=checks + 1, base_version=0
        )
        decoded, _ = decode_update(frame, template, base_tree)
        kept = [t - b for t, b in zip(leaves(decoded), base_leaves)]
        mirror = [e - k for e, k in zip(eff, kept)]
        implied = float(sum(np.abs(m).sum() for m in mirror))
        got = float(codec.residual_mass())
        checks += 1
        if not np.isclose(got, implied, rtol=1e-5, atol=1e-7):
            violations += 1
        time.sleep(0.02)
    # Quiet tail: zero deltas must drain the accumulator toward zero.
    drain = [codec.residual_mass()]
    for i in range(12):
        codec.encode_update(base_blob, base_blob, round=1000 + i, base_version=0)
        drain.append(codec.residual_mass())
    out["checks"] = checks
    out["violations"] = violations
    out["drain_start_mass"] = round(drain[0], 9)
    out["drain_end_mass"] = round(drain[-1], 9)
    out["drained"] = drain[-1] <= drain[0] * 0.05 + 1e-12


def run_soak(
    duration_s: float = 8.0,
    seed: int = 0,
    workdir: str | None = None,
    n_clients: int = 3,
    buffer_k: int = 2,
    staleness_alpha: float = 0.5,
    max_staleness: int = 8,
    update_codec: str = "topk_delta",
    topk_fraction: float = 0.25,
    kill_restart: bool = True,
    rss_slack_bytes: int = 256 * 1024 * 1024,
    slo_rules: str | None = None,
) -> dict:
    """Run the concurrent mini-soak for ``duration_s`` of traffic wall
    (warmup/compile excluded) and return the audit artifact.

    Round 16 adds the watchdog + flight-recorder + tracing layer: SLO
    rules (``slo_rules`` = a configs/slo_*.json path, default the built-in
    set) are machine-evaluated DURING the run, a breach dumps the flight
    ring and fails the audit, and the span JSONL is stitched into
    end-to-end update-lifecycle chains (client → root → serve under one
    trace id) embedded as the artifact's ``tracing`` arm."""
    import jax

    from fedcrack_tpu.chaos.plan import (
        CORRUPT_COMPRESSED_FRAME,
        STALE_REPLAY,
        Fault,
        FaultPlan,
    )
    from fedcrack_tpu.chaos.inject import ClientChaos
    from fedcrack_tpu.ckpt import load_state_file, save_state_file
    from fedcrack_tpu.fed import rounds as R
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
    from fedcrack_tpu.fed.tree import EdgeAggregator
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs import spans as tracing
    from fedcrack_tpu.obs.metrics import MetricsLogger, read_metrics
    from fedcrack_tpu.obs.promexp import MetricsExporter, scrape
    from fedcrack_tpu.obs.registry import REGISTRY
    from fedcrack_tpu.obs.sentries import LeakSentry
    from fedcrack_tpu.parallel import make_mesh, run_mesh_federation
    from fedcrack_tpu.serve.batcher import MicroBatcher
    from fedcrack_tpu.serve.engine import InferenceEngine, watch_recompiles
    from fedcrack_tpu.serve.hot_swap import ModelVersionManager
    from fedcrack_tpu.transport.client import FedClient
    from fedcrack_tpu.transport.edge import raw_caller
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    from fedcrack_tpu.health import ledger as health_ledger
    from fedcrack_tpu.health.canary import CanaryEvaluator
    from fedcrack_tpu.health.drift import (
        DriftMonitor,
        export_drift_metrics,
        write_drift_json,
    )
    from fedcrack_tpu.obs import flight
    from fedcrack_tpu.obs.watchdog import Watchdog, load_rules

    ctx = tempfile.TemporaryDirectory(prefix="soak_") if workdir is None else None
    base_dir = ctx.name if ctx is not None else workdir
    os.makedirs(base_dir, exist_ok=True)
    state_path = os.path.join(base_dir, "server_state.msgpack")
    spans_path = os.path.join(base_dir, "spans.jsonl")
    serve_metrics_path = os.path.join(base_dir, "serve_metrics.jsonl")
    metrics_dump_path = os.path.join(base_dir, "metrics.prom")
    flight_path = os.path.join(base_dir, "flight.json")
    stitched_path = os.path.join(base_dir, "trace_stitched.json")
    # Size-bounded span sink: an hours-long soak rotates instead of
    # appending one unbounded JSONL (the stitcher reads the whole set).
    tracing.install(spans_path, max_bytes=64 * 1024 * 1024, keep=3)
    flight.install(path=flight_path)
    watchdog = Watchdog(load_rules(slo_rules) if slo_rules else None)

    model_config = ModelConfig(
        img_size=32, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4),
    )
    template = init_variables(jax.random.key(seed), model_config)
    names = [f"c{i}" for i in range(n_clients)]
    edge_id = "edge-0"
    cfg = FedConfig(
        max_rounds=100_000,  # the soak is wall-bounded, never round-bounded
        cohort_size=n_clients + 1,  # + the edge shard
        mode="buffered",
        buffer_k=buffer_k,
        staleness_alpha=staleness_alpha,
        max_staleness=max_staleness,
        registration_window_s=10.0,
        round_deadline_s=2.0,  # partial-flush liveness backstop
        port=0,
        state_path=state_path,
        update_codec=update_codec,
        topk_fraction=topk_fraction,
    )

    # ---- serve plane (compiled BEFORE the traffic wall starts) ----
    serve_config = ServeConfig(
        bucket_sizes=(16,), max_batch=4, max_delay_ms=5.0, tile_overlap=4
    )
    engine = InferenceEngine(model_config, serve_config)
    serve_metrics = MetricsLogger(serve_metrics_path)
    # Round 18 health plane: canary IoU per installed version (evaluated
    # from the manager's poll thread AFTER each pointer flip — never on
    # the serving path) + serve-side drift vs a frozen install-time
    # reference profile (observed from the load loop's consumer thread).
    canary = CanaryEvaluator(engine, metrics=serve_metrics)
    manager = ModelVersionManager(
        engine,
        template,
        initial_version=0,
        state_path=state_path,
        poll_s=0.15,
        template=template,
        metrics=None,
        canary=canary,
    )
    engine.warmup(manager.snapshot()[1])
    recompile_sentry = watch_recompiles(engine)
    # The canary reference and the frozen drift profile both pin to the
    # BOOT weights, after warmup (their probe batches reuse the compiled
    # bucket programs; recompiles_since_warmup must stay 0 through them).
    canary.evaluate(0, manager.snapshot()[1])
    drift_monitor = DriftMonitor(
        reference=DriftMonitor.capture_reference(engine, manager.snapshot()[1])
    )
    batcher = MicroBatcher(engine, manager, metrics=serve_metrics)
    manager.start()  # hot-swap poller: the federation's statefile IS the feed

    # ---- leak sentries: steady state begins after warmup/compiles ----
    leak_sentry = LeakSentry(rss_slack_bytes=rss_slack_bytes)
    leak_sentry.mark()

    # ---- the /metrics endpoint the soak scrapes ITSELF through ----
    exporter = MetricsExporter(REGISTRY)
    exporter.start()
    # Pre-traffic baseline: the process registry is shared (bench runs the
    # storm drill in the same process minutes earlier), so every number the
    # artifact reports from a scrape must be a DELTA over this snapshot.
    from fedcrack_tpu.obs.promexp import sample_value as _sample_value

    pre_scrape = scrape(exporter.url)
    pre_accepted = _sample_value(
        pre_scrape, "fed_updates_total", {"result": "accepted"}
    ) or 0.0

    # ---- rolling chaos schedule (seeded) ----
    plan = FaultPlan.storm(
        seed,
        clients=names,
        n_iterations=200,
        tail_alpha=1.1,
        scale_s=0.02,
        cap_s=0.5,
    )
    storm_fired = plan.take("straggler_storm", round=1) is not None
    for r in range(3, 200, 9):
        plan.pending.append(
            Fault(kind=CORRUPT_COMPRESSED_FRAME, client=names[0], round=r)
        )
    for r in range(5, 200, 11):
        plan.pending.append(
            Fault(kind=STALE_REPLAY, client=names[-1], round=r)
        )

    stop = threading.Event()
    counters = {"client_restarts": 0, "client_errors": []}
    counters_lock = threading.Lock()

    def make_train_fn(cname: str, idx: int):
        it = {"n": 0}
        rng = np.random.default_rng((seed, idx))

        def train(weights_bytes: bytes, rnd: int):
            if stop.is_set():
                raise _SoakStop()
            it["n"] += 1
            tree = tree_from_bytes(weights_bytes, template=template)
            trained = _perturb_tree(tree, rng)
            return tree_to_bytes(trained), 8 + idx, {"loss": 1.0 / it["n"]}

        return train

    port_ref = {"port": None}

    def client_loop(cname: str, idx: int) -> None:
        """Run sessions until the wall; a poisoned/killed session is
        restarted with a fresh FedClient (operators restart pods)."""
        first = True
        while not stop.is_set():
            if not first:
                with counters_lock:
                    counters["client_restarts"] += 1
            first = False
            try:
                client = FedClient(
                    cfg,
                    make_train_fn(cname, idx),
                    cname=cname,
                    port=port_ref["port"],
                    max_retries=6,
                    call_timeout_s=10.0,
                    retry_budget_s=8.0,
                    chaos=ClientChaos(plan),
                )
                client.run_session()
            except _SoakStop:
                return
            except Exception as e:
                if stop.is_set():
                    return
                with counters_lock:
                    counters["client_errors"].append(f"{cname}: {e!r}")
                time.sleep(0.1)

    edge_stats = {"flushes": 0, "accepted": 0, "resyncs": 0, "errors": []}

    def edge_loop() -> None:
        """The edge-tier shard: two synthetic leaves fold into a buffered
        EdgeAggregator whose partials relay up to the SAME root."""
        from fedcrack_tpu.transport import transport_pb2 as pb
        from fedcrack_tpu.transport.codec import decode_scalar_map, encode_scalar_map

        edge = EdgeAggregator(
            edge_id,
            template,
            mode="buffered",
            buffer_k=2,
            staleness_alpha=staleness_alpha,
            max_staleness=max_staleness,
            state_path=os.path.join(base_dir, "edge_state.msgpack"),
        )
        rng = np.random.default_rng((seed, 99))
        channel = call = None
        enrolled = False
        leaf_it = 0
        while not stop.is_set():
            try:
                if call is None:
                    channel, call = raw_caller(port_ref["port"])
                if not enrolled:
                    msg = pb.ClientMessage(cname=edge_id)
                    msg.ready.SetInParent()
                    if call(msg).status != R.SW:
                        time.sleep(0.1)
                        continue
                    enrolled = True
                msg = pb.ClientMessage(cname=edge_id)
                msg.pull.SetInParent()
                rep = call(msg)
                pcfg = decode_scalar_map(rep.config)
                version = int(pcfg.get("model_version", 0))
                rnd = int(pcfg.get("current_round", 1))
                if version != edge.base_version:
                    if edge.base_version < 0:
                        edge.begin_round(rnd, rep.weights, version, ["l0", "l1"])
                    else:
                        edge.advance_base(rnd, rep.weights, version)
                base_tree = tree_from_bytes(edge.base_blob, template=template)
                for leaf in ("l0", "l1"):
                    leaf_it += 1
                    leaf_ctx = tracing.TraceContext(
                        tracing.version_trace(edge.base_version),
                        f"train:{leaf}:n{leaf_it}",
                    )
                    with tracing.span(
                        "client.train",
                        trace=leaf_ctx.trace,
                        cname=leaf,
                        ctx=leaf_ctx.to_wire(),
                    ):
                        blob = tree_to_bytes(_perturb_tree(base_tree, rng))
                    ok, _why = edge.offer_buffered(
                        leaf, blob, 4 + leaf_it % 3, edge.base_version,
                        trace_ctx=leaf_ctx.to_wire(),
                    )
                    edge_stats["accepted"] += bool(ok)
                if edge.buffer_ready():
                    partial, total, info = edge.flush_partial()
                    msg = pb.ClientMessage(cname=edge_id)
                    msg.done.round = rnd
                    msg.done.weights = partial
                    msg.done.sample_count = total
                    # The edge flush's wire context rides the hop up like
                    # any client push's — the root re-parents it onto the
                    # flush that folds this partial.
                    encode_scalar_map(
                        msg.done.metrics, {"__trace": info["trace_ctx"]}
                    )
                    prep = call(msg)
                    edge_stats["flushes"] += 1
                    if prep.status == R.NOT_WAIT:
                        edge_stats["resyncs"] += 1
                time.sleep(0.05)
            except Exception as e:
                # Server restart mid-soak: drop the channel, re-dial the
                # (same) port. A dead channel is the EXPECTED fault here.
                if stop.is_set():
                    return
                edge_stats["errors"].append(repr(e))
                if channel is not None:
                    channel.close()
                channel = call = None
                time.sleep(0.2)

    load_stats = {"submitted": 0, "completed": 0, "failed": 0}
    versions_seen: set[int] = set()

    def load_loop() -> None:
        """Closed-loop serve traffic: small bursts of bucket-shaped
        requests; every future is awaited (zero-drop accounting)."""
        rng = np.random.default_rng((seed, 7))
        while not stop.is_set():
            futures = []
            for _ in range(4):
                img = rng.integers(0, 256, (16, 16, 3), dtype=np.uint8)
                futures.append((img, batcher.submit(img, deadline_ms=250.0)))
                load_stats["submitted"] += 1
            for img, f in futures:
                try:
                    res = f.result(timeout=10.0)
                    load_stats["completed"] += 1
                    versions_seen.add(res.model_version)
                    # Drift profiling happens HERE — after the future
                    # resolved, on this consumer thread, never inside the
                    # batcher (the hot path pays nothing for it).
                    drift_monitor.observe(img, res.probs)
                except Exception:
                    load_stats["failed"] += 1
            time.sleep(0.01)

    driver_stats: dict = {}

    def driver_leg() -> None:
        """A small concurrent run_mesh_federation session — the mesh/driver
        plane's counters and spans land in the same registry the scrape
        reads. The round program is a host-side stub: the DRIVER machinery
        (staging, records, telemetry) is what this leg exercises, not XLA."""
        try:
            mesh = make_mesh(1, 1)

            def round_fn(variables, images, masks, active, n_samples):
                return variables, {"loss": np.zeros((1,), np.float32)}

            def data_fn(r):
                images = np.zeros((1, 1, 1, 8, 8, 3), np.uint8)
                masks = np.zeros((1, 1, 1, 8, 8, 1), np.uint8)
                return (
                    images, masks,
                    np.ones(1, np.float32), np.ones(1, np.float32),
                )

            t0 = time.perf_counter()
            _, records = run_mesh_federation(
                round_fn, template, data_fn, 3, mesh,
                recompile_sentry=recompile_sentry,
            )
            driver_stats["rounds"] = len(records)
            driver_stats["wall_s"] = round(time.perf_counter() - t0, 4)
        except Exception as e:
            driver_stats["error"] = repr(e)

    ef_out: dict = {}

    # ---- boot the root and unleash ----
    server = FedServer(cfg, template, tick_period_s=0.05)
    st = ServerThread(server)
    st.__enter__()
    port_ref["port"] = st.port
    threads = [
        threading.Thread(target=client_loop, args=(n, i), name=f"soak-{n}")
        for i, n in enumerate(names)
    ]
    threads.append(threading.Thread(target=edge_loop, name="soak-edge"))
    threads.append(threading.Thread(target=load_loop, name="soak-load"))
    threads.append(threading.Thread(target=driver_leg, name="soak-driver"))
    threads.append(
        threading.Thread(
            target=_ef_conservation_leg,
            args=(template, stop, ef_out, seed),
            name="soak-ef",
        )
    )
    t_start = time.monotonic()
    deadline = t_start + duration_s
    for t in threads:
        t.start()

    mid_scrape_families = 0
    kill_event: dict = {"killed": False}
    st_current = st
    last_watchdog_eval = 0.0
    try:
        # Mid-soak: scrape our own endpoint while everything is in flight.
        while time.monotonic() < deadline:
            remaining = deadline - time.monotonic()
            if time.monotonic() - last_watchdog_eval >= 0.5:
                # The SLO watchdog rides the run: rules evaluated over the
                # live registry every ~0.5 s; a breach dumps the flight
                # ring immediately (Watchdog.enforce) — the audit verdict
                # lands below.
                last_watchdog_eval = time.monotonic()
                watchdog.enforce()
            if kill_restart and not kill_event["killed"] and (
                time.monotonic() - t_start >= duration_s * 0.45
            ):
                held_port = st_current.port
                t_kill = time.monotonic()
                st_current.kill()
                server2 = FedServer(
                    dataclasses.replace(cfg, port=held_port),
                    template,
                    tick_period_s=0.05,
                )
                restored_version = server2.state.model_version
                st_current = ServerThread(server2).__enter__()
                kill_event.update(
                    killed=True,
                    restart_s=round(time.monotonic() - t_kill, 4),
                    restored_version=restored_version,
                    restored_buffer=len(server2.state.buffer),
                )
                continue
            if mid_scrape_families == 0 and time.monotonic() - t_start > min(
                2.0, duration_s / 3
            ):
                mid_scrape_families = len(scrape(exporter.url))
                leak_sentry.sample()
                continue
            time.sleep(min(0.1, max(0.01, remaining)))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=JOIN_S)
        hung = [t.name for t in threads if t.is_alive()]
        st_current.__exit__(None, None, None)
        manager.stop()
        batcher.close()
    traffic_wall_s = time.monotonic() - t_start

    # ---- final scrape + dump (the CI artifact) ----
    exposition = REGISTRY.exposition()
    with open(metrics_dump_path, "w", encoding="utf-8") as f:
        f.write(exposition)
    parsed = scrape(exporter.url)
    # One final watchdog pass over the REAL scrape (the same text a
    # dashboard would read), then the verdict.
    watchdog.enforce(parsed)
    watchdog_audit = watchdog.audit()
    exporter.stop()
    final_state = st_current.state
    tracing.uninstall()

    # ---- invariant audit ----
    plane_prefixes = ("fed_", "serve_", "driver_", "edge_", "client_")
    planes_covered = {
        p.rstrip("_"): any(name.startswith(p) for name in parsed)
        for p in plane_prefixes
    }
    # Torn versions: serve_batch records land in batch order (one bucket =
    # one worker); versions must be non-decreasing and every served
    # version actually published.
    batch_records = read_metrics(serve_metrics_path, kind="serve_batch")
    batch_versions = [int(rec["model_version"]) for rec in batch_records]
    torn = sum(
        1 for a, b in zip(batch_versions, batch_versions[1:]) if b < a
    )
    # A dead serve plane must not audit clean: the torn-version check is
    # vacuous over zero batches, so the audit requires traffic actually
    # served (and zero loud failures) before "zero torn" means anything.
    serve_healthy = (
        load_stats["completed"] > 0
        and load_stats["failed"] == 0
        and len(batch_versions) > 0
    )
    published = {0} | {s["to_version"] for s in manager.swaps}
    unpublished_served = sorted(set(batch_versions) - published)
    # Statefile: load -> save must reproduce the file byte-identically
    # (canonical snapshot; arrival order must not leak into the bytes).
    with open(state_path, "rb") as f:
        state_bytes = f.read()
    resaved = os.path.join(base_dir, "server_state.resaved.msgpack")
    save_state_file(resaved, load_state_file(state_path, cfg))
    with open(resaved, "rb") as f:
        resaved_bytes = f.read()
    statefile_ok = state_bytes == resaved_bytes
    leak = leak_sentry.summary()
    recompiles = sum(recompile_sentry.deltas().values())
    # ---- round 18 health plane: artifacts + audit arms ----
    ledger_path = os.path.join(base_dir, "ledger.jsonl")
    canary_path = os.path.join(base_dir, "canary.json")
    drift_path = os.path.join(base_dir, "drift.json")
    health_ledger.write_ledger_jsonl(final_state.ledger, ledger_path)
    canary_audit = canary.audit()
    with open(canary_path, "w", encoding="utf-8") as f:
        json.dump(
            {"history": canary.history, "audit": canary_audit},
            f, indent=1, sort_keys=True,
        )
        f.write("\n")
    drift_psis = drift_monitor.compare()
    export_drift_metrics(drift_psis)
    write_drift_json(
        drift_path,
        reference=drift_monitor.reference,
        current=drift_monitor.profile(),
        psis=drift_psis,
    )
    ledger_conservation = health_ledger.conservation(final_state.ledger)
    audit = {
        "torn_versions": int(torn),
        "unpublished_served_versions": unpublished_served,
        "zero_torn_versions": torn == 0 and not unpublished_served,
        "serve_healthy": serve_healthy,
        "ef": ef_out,
        "ef_mass_conserved": (
            ef_out.get("violations") == 0
            and bool(ef_out.get("drained"))
            and ef_out.get("checks", 0) > 0
        ),
        "statefile_restore_bit_identical": statefile_ok,
        "watermarks": leak,
        "watermarks_steady": bool(leak.get("steady")),
        "recompiles_since_warmup": int(recompiles),
        "hung_threads": hung,
        # Round 16: the machine-checked SLO verdict joins the audit — the
        # rule set replaces what used to be hand-coded per-harness checks.
        "watchdog_clean": bool(watchdog_audit["clean"]),
        # Round 18: every gate verdict the chaos produced must be in the
        # ledger exactly once (offers == accepted + rejected + resyncs,
        # surviving the mid-soak kill→restart via the statefile), and
        # every canary eval must be a finite unit-interval IoU.
        "ledger_conservation": ledger_conservation,
        "ledger_conserved": (
            ledger_conservation["clients"] > 0
            and not ledger_conservation["violations"]
        ),
        "canary_steady": (
            canary_audit["evals"] > 0 and bool(canary_audit["all_finite_unit"])
        ),
    }
    audit["clean"] = (
        audit["zero_torn_versions"]
        and audit["serve_healthy"]
        and audit["ef_mass_conserved"]
        and audit["statefile_restore_bit_identical"]
        and audit["watermarks_steady"]
        and recompiles == 0
        and not hung
        and audit["watchdog_clean"]
        and audit["ledger_conserved"]
        and audit["canary_steady"]
    )

    def _sample(name: str, labels: dict | None = None):
        from fedcrack_tpu.obs.promexp import sample_value

        return sample_value(parsed, name, labels)

    from fedcrack_tpu.obs.spans import read_spans, span_files
    from fedcrack_tpu.tools.trace_stitch import stitch_files, summarize

    # The census must cover the whole ROTATED set (this run arms 64 MiB
    # rotation): reading only the live file would silently undercount an
    # hours-long soak's early spans.
    span_records = [
        rec for path in span_files(spans_path) for rec in read_spans(path)
    ]
    span_names: dict[str, int] = {}
    for rec in span_records:
        span_names[rec["name"]] = span_names.get(rec["name"], 0) + 1

    # Stitch the span file into end-to-end update lifecycles: in this
    # one-process harness the planes share a JSONL, but the joins are the
    # SAME wire-context/version joins a multi-process deployment stitches
    # across per-process files. The full result lands next to the spans
    # for CI upload; the artifact embeds the summary.
    stitched = stitch_files([spans_path])
    with open(stitched_path, "w", encoding="utf-8") as f:
        json.dump(stitched, f, indent=1, sort_keys=True, default=str)
    tracing_summary = summarize(stitched)

    artifact = {
        "config": {
            "duration_s": duration_s,
            "seed": seed,
            "n_clients": n_clients,
            "buffer_k": buffer_k,
            "staleness_alpha": staleness_alpha,
            "max_staleness": max_staleness,
            "update_codec": update_codec,
            "kill_restart": kill_restart,
        },
        "traffic_wall_s": round(traffic_wall_s, 3),
        "storm_fired": storm_fired,
        "federation": {
            "global_versions": int(final_state.model_version),
            "flushes": len(final_state.history),
            "accepted_updates_scraped": (
                # delta over the pre-traffic baseline: absolutes would fold
                # in earlier same-process registry traffic (e.g. bench's
                # storm drill minutes before this section)
                (_sample("fed_updates_total", {"result": "accepted"}) or 0.0)
                - pre_accepted
            ),
            "client_restarts": counters["client_restarts"],
            "client_errors": counters["client_errors"][:8],
            "kill_restart": kill_event,
        },
        "edge": {k: v if k != "errors" else v[:4] for k, v in edge_stats.items()},
        "serve": {
            **load_stats,
            "versions_seen": sorted(versions_seen),
            "swaps": len(manager.swaps),
            "latency_ms": batcher.latency.summary(),
            "deadline_missed": batcher.stats()["deadline_missed"],
        },
        "driver": driver_stats,
        "scrape": {
            "families": len(parsed),
            "mid_soak_families": mid_scrape_families,
            "planes_covered": planes_covered,
            "all_planes_covered": all(planes_covered.values()),
            "exposition_bytes": len(exposition),
        },
        "spans": {"total": len(span_records), "by_name": dict(sorted(span_names.items()))},
        "tracing": tracing_summary,
        "watchdog": watchdog_audit,
        "health": {
            "ledger_clients": ledger_conservation["clients"],
            "flagged_clients": sorted(
                name
                for name, rec in final_state.ledger.items()
                if rec.get("flags", 0)
            ),
            "canary": canary_audit,
            "drift_psi": drift_psis,
        },
        "audit": audit,
        "paths": {
            "metrics_dump": metrics_dump_path,
            "spans": spans_path,
            "statefile": state_path,
            "flight": flight_path,
            "stitched_trace": stitched_path,
            "ledger": ledger_path,
            "canary": canary_path,
            "drift": drift_path,
        },
    }
    if not audit["clean"] and not any(
        d["reason"].startswith("watchdog") for d in (flight.current().dumps if flight.current() else [])
    ):
        # A failed audit ships its flight record even when no watchdog
        # rule breached (e.g. a torn version or a leak): the dump is the
        # red run's last-N-seconds history.
        flight.dump("soak audit failed")
    flight.uninstall()
    if ctx is not None:
        # Preserve nothing from a temp workdir (the artifact embeds the
        # numbers); named workdirs keep their dumps for CI upload.
        artifact["paths"] = {}
        ctx.cleanup()
    return artifact


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fedcrack_tpu.tools.soak", description=__doc__
    )
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--clients", type=int, default=3)
    p.add_argument("--buffer-k", type=int, default=2)
    p.add_argument("--codec", default="topk_delta")
    p.add_argument("--no-kill", action="store_true",
                   help="skip the mid-soak server kill -> restart")
    p.add_argument("--slo-rules", default="",
                   help="SLO watchdog rule file (configs/slo_*.json); "
                   "empty = the built-in default set")
    p.add_argument("--workdir", default="",
                   help="keep dumps (metrics.prom, spans.jsonl, flight.json, "
                   "trace_stitched.json) here; empty = temp dir, dumps "
                   "discarded")
    p.add_argument("--out", default="", help="write the audit artifact JSON here")
    args = p.parse_args(argv)
    artifact = run_soak(
        duration_s=args.duration,
        seed=args.seed,
        n_clients=args.clients,
        buffer_k=args.buffer_k,
        update_codec=args.codec,
        kill_restart=not args.no_kill,
        workdir=args.workdir or None,
        slo_rules=args.slo_rules or None,
    )
    payload = json.dumps(artifact, indent=1, sort_keys=True)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload)
        print(f"wrote {args.out}")
        print(json.dumps(artifact["audit"], indent=1, sort_keys=True))
    else:
        print(payload)
    if artifact["watchdog"]["breaches"]:
        # The breach → flight-dump → exit-code contract (the dump already
        # landed the moment the first breaching evaluation ran).
        from fedcrack_tpu.obs.watchdog import BREACH_EXIT

        return BREACH_EXIT
    return 0 if artifact["audit"]["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
