"""Measured BASELINE c1-c5 table generator.

Produces one artifact-backed row per BASELINE config (BASELINE.json /
``configs/c1..c5_*.json``): round wall-clock, per-step time, control/data
plane bytes, val loss, pixel accuracy, crack IoU — the table the reference
never published (SURVEY.md §6) and round-2's verdict item #2.

Workloads are scaled down from the presets' reference-scale settings
(10 epochs x thousands of steps won't fit a CPU-host measurement run) and
the artifact records the exact workload + hardware for every row — the
numbers are honest about what was measured, never extrapolated. Real-chip
per-step timing for the single-chip shapes lives in the BENCH artifacts
(bench.py's sweep + reference_scale); this tool's mesh rows run wherever
it is launched (virtual 8-device CPU mesh in CI).

Run (virtual mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python -m fedcrack_tpu.tools.measure_baseline \
      --out bench_runs/r03_configs_cpu.json

Quality comes from held-out synthetic fixtures (no real crack dataset in
this image): server-side eval with BN recalibration, exactly like
``fedcrack_tpu.server --eval-synthetic``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import jax
import numpy as np


def _now() -> float:
    return time.perf_counter()


def _hardware() -> dict:
    d = jax.devices()[0]
    return {
        "platform": d.platform,
        "device_kind": getattr(d, "device_kind", "unknown"),
        "n_devices": jax.device_count(),
    }


def _load_preset(name: str):
    from fedcrack_tpu.configs import FedConfig

    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    with open(os.path.join(here, "configs", f"{name}.json")) as f:
        return FedConfig.from_json(f.read())


def _eval_quality(variables, model_cfg, n_val: int, seed: int, pos_weight: float = 1.0):
    """Held-out quality with BN recalibration (the server eval path)."""
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.train.local import (
        create_train_state,
        evaluate,
        recalibrate_batch_stats,
    )

    images, masks = synth_crack_batch(n_val, model_cfg.img_size, seed=seed)
    ds = ArrayDataset(images, masks, batch_size=8, shuffle=False, drop_last=False)
    st = create_train_state(jax.random.key(0), model_cfg)
    st = st.replace_variables(
        jax.tree_util.tree_map(lambda t, x: np.asarray(x, t.dtype), st.variables, variables)
    )
    st = recalibrate_batch_stats(st, ds, model_cfg)
    m = evaluate(st, ds, pos_weight=pos_weight)
    return {
        "val_loss": round(float(m["loss"]), 4),
        "pixel_acc": round(float(m["pixel_acc"]), 4),
        "iou": round(float(m["iou"]), 4),
    }


def measure_c1(args) -> dict:
    """c1: single-client local fit (the centralized trainer),
    reference 128 px crops."""
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.train.centralized import train_centralized

    cfg = _load_preset("c1_single_client_cpu")
    img = cfg.model.img_size
    n_train, n_val = args.samples, max(16, args.samples // 4)
    images, masks = synth_crack_batch(n_train + n_val, img, seed=0)
    train_ds = ArrayDataset(
        images[:n_train], masks[:n_train], batch_size=cfg.data.batch_size, seed=0
    )
    val_ds = ArrayDataset(
        images[n_train:], masks[n_train:], batch_size=cfg.data.batch_size,
        shuffle=False, drop_last=False,
    )
    t0 = _now()
    _, history = train_centralized(
        train_ds, val_ds, cfg.model, epochs=args.epochs,
        learning_rate=cfg.learning_rate, pos_weight=args.pos_weight,
        log_fn=lambda s: None,
    )
    total_s = _now() - t0
    steps = args.epochs * len(train_ds)
    best = min(history, key=lambda h: h["val_loss"])
    return {
        "config": "c1_single_client_cpu",
        "hardware": _hardware(),
        "workload": {
            "img_size": img, "batch": cfg.data.batch_size,
            "train_samples": n_train, "epochs": args.epochs,
            "pos_weight": args.pos_weight,
        },
        "wall_clock_s": round(total_s, 2),
        "per_step_ms": round(total_s / steps * 1e3, 2),
        "epoch_s": round(total_s / args.epochs, 2),
        "val_loss": round(float(best["val_loss"]), 4),
        "pixel_acc": round(float(best["val_pixel_acc"]), 4),
        "iou": round(float(best["val_iou"]), 4),
        "notes": "best-val epoch; per_step includes the per-epoch BN "
                 "recalibration + validation sweeps",
    }


def measure_c2(args, preset="c2_two_client_grpc", partition="iid", mu=None) -> dict:
    """c2/c4: K-client FedAvg over real localhost gRPC, end to end."""
    import threading

    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.train.federated import make_train_fn
    from fedcrack_tpu.train.local import (
        create_train_state,
        evaluate,
        recalibrate_batch_stats,
    )
    from fedcrack_tpu.transport.client import FedClient
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    cfg = _load_preset(preset)
    n_clients = min(cfg.cohort_size, args.grpc_clients)
    img = cfg.model.img_size
    cfg = dataclasses.replace(
        cfg,
        cohort_size=n_clients,
        max_rounds=args.rounds,
        local_epochs=args.epochs,
        pos_weight=args.pos_weight,
        poll_period_s=0.2,
        registration_window_s=10.0,
        port=0,
        fedprox_mu=cfg.fedprox_mu if mu is None else mu,
        data=dataclasses.replace(cfg.data, img_size=img, batch_size=8),
    )

    # Held-out eval set (server side), distinct seed from every client shard.
    ev_images, ev_masks = synth_crack_batch(32, img, seed=999)
    eval_ds = ArrayDataset(ev_images, ev_masks, batch_size=8, shuffle=False, drop_last=False)

    state_tmpl = create_train_state(jax.random.key(cfg.seed), cfg.model)

    def eval_fn(blob: bytes) -> dict:
        st = state_tmpl.replace_variables(
            tree_from_bytes(blob, template=state_tmpl.variables)
        )
        st = recalibrate_batch_stats(st, eval_ds, cfg.model)
        return evaluate(st, eval_ds, pos_weight=cfg.pos_weight)

    server = FedServer(cfg, state_tmpl.variables, tick_period_s=0.1, eval_fn=eval_fn)
    results = {}
    t0 = _now()
    with ServerThread(server) as st_thread:
        def run_client(i):
            # Non-IID (c4): per-client crack prevalence skew via crack_prob.
            crack_prob = 0.8 if partition == "iid" else (0.35 + 0.9 * i / max(1, n_clients - 1))
            imgs, msks = synth_crack_batch(
                args.samples, img, seed=10 + i, crack_prob=min(crack_prob, 1.0)
            )
            ds = ArrayDataset(imgs, msks, batch_size=8, seed=i)
            train_fn, _ = make_train_fn(cfg, ds, batch_size=8, seed=i)
            c = FedClient(cfg, train_fn, cname=f"c{i}", port=st_thread.port)
            results[i] = c.run_session()

        threads = [threading.Thread(target=run_client, args=(i,)) for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # eval tasks run off-loop; wait for the last round's record
        deadline = _now() + 120
        while len(server.eval_history) < args.rounds and _now() < deadline:
            time.sleep(0.5)
        history = list(st_thread.state.history)
        eval_hist = list(server.eval_history)
    total_s = _now() - t0

    # A crashed client thread would leave its key out of `results` and a
    # values()-only check would pass vacuously — the artifact must never
    # describe a degraded run as the full cohort.
    assert len(results) == n_clients, (
        f"only {sorted(results)} of {n_clients} clients completed"
    )
    assert all(r.enrolled for r in results.values())
    steps_per_round = n_clients * args.epochs * (args.samples // 8)
    round_wall = [h["wall_clock_s"] for h in history]
    last_eval = eval_hist[-1] if eval_hist else {}

    def _q(key):
        # None (-> JSON null) when the off-loop eval missed the deadline;
        # float('nan') would serialize as bare NaN and break strict parsers.
        v = last_eval.get(key)
        return None if v is None else round(float(v), 4)
    return {
        "config": preset if mu is None else "c4_noniid_fedprox",
        "hardware": _hardware(),
        "workload": {
            "img_size": img, "batch": 8, "clients": n_clients,
            "rounds": args.rounds, "local_epochs": args.epochs,
            "samples_per_client": args.samples, "partition": partition,
            "fedprox_mu": cfg.fedprox_mu, "pos_weight": cfg.pos_weight,
        },
        "session_wall_clock_s": round(total_s, 2),
        "round_wall_clock_s": round(float(np.median(round_wall)), 3),
        "per_step_ms": round(float(np.median(round_wall)) / steps_per_round * 1e3, 2),
        "control_plane_bytes": {
            "received_per_round": int(np.median([h["bytes_received"] for h in history])),
            "broadcast_per_round": int(np.median([h["bytes_broadcast"] for h in history])),
        },
        "val_loss": _q("loss"),
        "pixel_acc": _q("pixel_acc"),
        "iou": _q("iou"),
        "notes": "real localhost gRPC, real trainers; round wall-clock from "
                 "the coordinator's round history; quality = server-side "
                 "eval of the final aggregated model on held-out fixtures",
    }


def measure_mesh(args, preset: str, n_clients: int, n_batch: int) -> dict:
    """c3/c5: one-program mesh rounds; quality from the final aggregate."""
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    cfg = _load_preset(preset)
    img = cfg.model.img_size if args.mesh_img is None else args.mesh_img
    model_cfg = dataclasses.replace(cfg.model, img_size=img)
    avail = jax.device_count()
    if n_clients * n_batch > avail:
        raise SystemExit(
            f"{preset}: needs {n_clients * n_batch} devices, have {avail} — "
            "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    mesh = make_mesh(n_clients, n_batch)
    round_fn = build_federated_round(
        mesh, model_cfg, learning_rate=cfg.learning_rate,
        local_epochs=args.epochs, fedprox_mu=cfg.fedprox_mu,
        pos_weight=args.pos_weight,
    )
    batch = cfg.data.batch_size
    per_client = [
        synth_crack_batch(args.mesh_steps * batch, img, seed=20 + i)
        for i in range(n_clients)
    ]
    images, masks = stack_client_data(per_client, args.mesh_steps, batch)
    active = np.ones(n_clients, np.float32)
    n_samples = np.full(n_clients, float(args.mesh_steps * batch), np.float32)
    state0 = create_train_state(jax.random.key(cfg.seed), model_cfg)

    # Multi-round loop through the package driver (parallel.driver): local
    # data is static across rounds, so data_fn returns None after round 0
    # and the shard is staged exactly once.
    variables, records = run_mesh_federation(
        round_fn,
        state0.variables,
        lambda r: (images, masks, active, n_samples) if r == 0 else None,
        args.rounds,
        mesh,
    )
    times = [rec.wall_clock_s for rec in records]
    # first round includes compilation; report the post-compile median
    round_s = float(np.median(times[1:])) if len(times) > 1 else times[0]
    steps_per_round = args.epochs * args.mesh_steps

    # Quality at a workload a 1-core CPU host can actually train: the
    # HOST-plane federation of the same config — bit-equal aggregation to
    # the mesh program (pinned by
    # tests/test_parallel.py::test_mesh_round_equals_host_round), without
    # 8 virtual device threads spin-waiting on collectives over one core.
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.fed.algorithms import fedavg
    from fedcrack_tpu.train.local import local_fit

    q_samples, q_epochs, q_rounds = args.samples, args.epochs_quality, args.rounds
    q_data = [
        synth_crack_batch(q_samples, img, seed=20 + i) for i in range(n_clients)
    ]
    vars_q = state0.variables
    for _ in range(q_rounds):
        trained = []
        for ci in range(n_clients):
            st = create_train_state(
                jax.random.key(cfg.seed), model_cfg, cfg.learning_rate
            ).replace_variables(vars_q)
            ds = ArrayDataset(q_data[ci][0], q_data[ci][1], batch_size=batch, seed=ci)
            st, _ = local_fit(
                st, ds, epochs=q_epochs, pos_weight=args.pos_weight,
                mu=cfg.fedprox_mu, anchor_params=vars_q["params"],
            )
            trained.append(jax.device_get(st.variables))
        vars_q = fedavg(trained, weights=[float(q_samples)] * n_clients)
    quality = _eval_quality(
        vars_q, model_cfg, n_val=32, seed=999, pos_weight=args.pos_weight
    )
    return {
        "config": preset,
        "hardware": _hardware(),
        "workload": {
            "img_size": img, "batch": batch, "clients": n_clients,
            "batch_dp": n_batch, "rounds": args.rounds,
            "local_epochs": args.epochs, "steps_per_epoch": args.mesh_steps,
            "compute_dtype": model_cfg.compute_dtype,
            "pos_weight": args.pos_weight,
            "quality_workload": {
                "samples_per_client": q_samples, "local_epochs": q_epochs,
                "rounds": q_rounds, "path": "host-plane equivalent",
            },
        },
        "round_wall_clock_s": round(round_s, 3),
        "compile_round_s": round(times[0], 2),
        "per_step_ms": round(round_s / steps_per_round * 1e3, 2),
        "data_plane_bytes_staged": int(images.nbytes + masks.nbytes),
        **quality,
        "notes": "one-program mesh round (psum FedAvg on the clients axis) "
                 "executed for timing/correctness; quality = held-out eval "
                 "of the HOST-plane federation of the same config (bit-equal "
                 "aggregation per the mesh-vs-host golden test) at the "
                 "quality_workload — virtual-device collectives spin-wait on "
                 "a 1-core host, so training a quality-bearing workload "
                 "through the mesh program there is infeasible; timing is "
                 "wherever this ran (hardware.platform; real-chip slopes "
                 "live in the BENCH artifact)",
    }


def measure_c3_mesh_program_quality(args) -> dict:
    """c3q: a QUALITY-BEARING micro federation through the ACTUAL mesh round
    program, with its host-plane twin on the same seed and data order.

    The c3/c5 rows' quality comes from the host-plane twin because 8 virtual
    device threads spin-wait on every psum on a 1-core host (see
    measure_mesh's note) — leaving the caveat that no quality-bearing
    workload had ever run through the mesh PROGRAM on this box (round-4
    verdict, next #8). This row retires it at micro scale: a few rounds at
    32 px through ``build_federated_round`` on the virtual 8-device mesh,
    the identical workload through sequential jitted ``train_step`` + host
    ``fedavg`` (the golden cross-check's reference implementation,
    tests/test_parallel.py::_host_round), and the held-out eval of BOTH
    final aggregates recorded side by side.
    """
    import jax.numpy as jnp

    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.algorithms import fedavg
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state, train_step

    cfg = _load_preset("c3_eight_client_mesh")
    n_clients = 8
    img = 32 if args.mesh_img is None else args.mesh_img
    batch = 4
    model_cfg = dataclasses.replace(cfg.model, img_size=img)
    avail = jax.device_count()
    if n_clients > avail:
        raise SystemExit(
            f"c3q: needs {n_clients} devices, have {avail} — run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    mesh = make_mesh(n_clients, 1)
    round_fn = build_federated_round(
        mesh, model_cfg, learning_rate=cfg.learning_rate,
        local_epochs=args.epochs, pos_weight=args.pos_weight,
    )
    per_client = [
        synth_crack_batch(args.mesh_steps * batch, img, seed=20 + i)
        for i in range(n_clients)
    ]
    images, masks = stack_client_data(per_client, args.mesh_steps, batch)
    active = np.ones(n_clients, np.float32)
    n_samples = np.full(n_clients, float(args.mesh_steps * batch), np.float32)
    state0 = create_train_state(jax.random.key(cfg.seed), model_cfg)

    t0 = _now()
    mesh_vars, records = run_mesh_federation(
        round_fn,
        state0.variables,
        lambda r: (images, masks, active, n_samples) if r == 0 else None,
        args.rounds,
        mesh,
    )
    mesh_vars = jax.device_get(mesh_vars)
    mesh_s = _now() - t0

    # Host-plane twin: same rounds, same per-round fresh optimizer, same
    # epoch-outer/step-inner data order the round program's scan uses.
    t0 = _now()
    host_vars = state0.variables
    for _ in range(args.rounds):
        trained = []
        for c in range(n_clients):
            st = create_train_state(
                jax.random.key(cfg.seed), model_cfg, cfg.learning_rate
            ).replace_variables(host_vars)
            for _e in range(args.epochs):
                for s in range(args.mesh_steps):
                    batch_cs = (jnp.asarray(images[c, s]), jnp.asarray(masks[c, s]))
                    st, _ = train_step(
                        st,
                        batch_cs,
                        host_vars["params"],
                        jnp.float32(0.0),
                        jnp.float32(args.pos_weight),
                    )
            trained.append(jax.device_get(st.variables))
        host_vars = fedavg(trained, weights=[float(n) for n in n_samples])
    host_s = _now() - t0

    # Per-leaf-class divergence, mirroring the golden test's two classes
    # (tests/test_parallel.py::_assert_trees_match): conv biases that feed
    # straight into a BatchNorm have ~0 true gradient, so Adam amplifies
    # fp-reassociation noise between the two XLA programs into lr-sized
    # steps on those leaves — across R rounds they drift by O(lr*steps*R)
    # while every OTHER leaf stays at reassociation-noise scale.
    max_diff_bn_bias = 0.0
    max_diff_rest = 0.0
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(mesh_vars["params"]),
        jax.tree_util.tree_leaves(host_vars["params"]),
    ):
        key = jax.tree_util.keystr(path)
        d = float(np.max(np.abs(np.asarray(a, np.float32) - np.asarray(b, np.float32))))
        bn_shadowed = key.endswith("'bias']") and any(
            s in key for s in ("stem_conv", "_sep", "_convT")
        )
        if bn_shadowed:
            max_diff_bn_bias = max(max_diff_bn_bias, d)
        else:
            max_diff_rest = max(max_diff_rest, d)

    q_mesh = _eval_quality(
        mesh_vars, model_cfg, n_val=32, seed=999, pos_weight=args.pos_weight
    )
    q_host = _eval_quality(
        host_vars, model_cfg, n_val=32, seed=999, pos_weight=args.pos_weight
    )
    return {
        "config": "c3q_mesh_program_quality",
        "hardware": _hardware(),
        "workload": {
            "img_size": img, "batch": batch, "clients": n_clients,
            "rounds": args.rounds, "local_epochs": args.epochs,
            "steps_per_epoch": args.mesh_steps,
            "compute_dtype": model_cfg.compute_dtype,
            "pos_weight": args.pos_weight,
        },
        "mesh_program": {
            "wall_clock_s": round(mesh_s, 2),
            "compile_round_s": round(records[0].wall_clock_s, 2),
            **{f"q_{k}": v for k, v in q_mesh.items()},
        },
        "host_plane_twin": {
            "wall_clock_s": round(host_s, 2),
            **{f"q_{k}": v for k, v in q_host.items()},
        },
        "max_abs_param_diff_bn_shadowed_bias": max_diff_bn_bias,
        "max_abs_param_diff_other_leaves": max_diff_rest,
        "quality_equal": bool(
            abs(float(q_mesh["iou"]) - float(q_host["iou"])) <= 0.005
            and abs(float(q_mesh["pixel_acc"]) - float(q_host["pixel_acc"])) <= 0.005
            and abs(float(q_mesh["val_loss"]) - float(q_host["val_loss"])) <= 0.01
        ),
        "notes": "same seed, same data, same order through both planes; a "
                 "quality-bearing trajectory through the mesh PROGRAM itself "
                 "(not just its host-plane stand-in). Equality criterion is "
                 "at the QUALITY level: the planes are equal up to fp "
                 "reassociation (the golden one-round cross-check's atol), "
                 "and across rounds Adam amplifies that noise on the "
                 "BN-shadowed zero-gradient conv biases — see the split "
                 "max_abs_param_diff fields",
    }


def _apply_platform_env() -> None:
    """This image pre-imports jax on the axon (TPU tunnel) platform at
    interpreter startup, swallowing JAX_PLATFORMS/XLA_FLAGS env overrides —
    re-apply them through the runtime config API while the backends are
    still uninitialized (same hook as bench.py / __graft_entry__)."""
    import re

    flag = re.search(
        r"xla_force_host_platform_device_count=(\d+)",
        os.environ.get("XLA_FLAGS", ""),
    )
    plats = [
        p.strip()
        for p in os.environ.get("JAX_PLATFORMS", "").lower().split(",")
        if p.strip()
    ]
    if (plats and plats[0] == "cpu") or (flag and not plats):
        # Version-tolerant CPU-platform routing (jaxcompat); no-op once
        # backends are initialized — measure where we are then.
        from fedcrack_tpu.jaxcompat import ensure_cpu_devices

        ensure_cpu_devices(int(flag.group(1)) if flag else None)


def main(argv=None) -> int:
    _apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--configs", default="c1,c2,c3,c4,c5")
    p.add_argument("--samples", type=int, default=64, help="train samples per client")
    p.add_argument("--epochs", type=int, default=2, help="local epochs")
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--grpc-clients", type=int, default=2)
    p.add_argument("--mesh-steps", type=int, default=8, help="steps per epoch (mesh rows)")
    p.add_argument("--mesh-img", type=int, default=None,
                   help="override mesh rows' crop (CPU hosts may want 128)")
    p.add_argument("--pos-weight", type=float, default=5.0)
    p.add_argument(
        "--epochs-quality", type=int, default=2, dest="epochs_quality",
        help="local epochs for the mesh rows' host-plane quality federation",
    )
    args = p.parse_args(argv)

    want = set(args.configs.split(","))
    rows = []
    if "c1" in want:
        rows.append(measure_c1(args))
        print(json.dumps(rows[-1]), flush=True)
    if "c2" in want:
        rows.append(measure_c2(args))
        print(json.dumps(rows[-1]), flush=True)
    if "c3" in want:
        rows.append(measure_mesh(args, "c3_eight_client_mesh", 8, 1))
        print(json.dumps(rows[-1]), flush=True)
    if "c4" in want:
        rows.append(measure_c2(args, preset="c4_noniid_fedprox", partition="skew", mu=0.01))
        print(json.dumps(rows[-1]), flush=True)
    if "c5" in want:
        rows.append(measure_mesh(args, "c5_bf16_batch_dp", 4, 2))
        print(json.dumps(rows[-1]), flush=True)
    if "c3q" in want:
        rows.append(measure_c3_mesh_program_quality(args))
        print(json.dumps(rows[-1]), flush=True)

    artifact = {
        "generated_by": "fedcrack_tpu.tools.measure_baseline",
        "hardware": _hardware(),
        "rows": rows,
    }
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    print(f"wrote {args.out} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
