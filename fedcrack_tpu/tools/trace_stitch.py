"""Stitch per-process span JSONL files into end-to-end update lifecycles.

``python -m fedcrack_tpu.tools.trace_stitch spans_a.jsonl spans_b.jsonl
--require client.push,fed.flush,serve.swap,serve.batch --json stitched.json``

Each process records spans to its own JSONL (``obs/spans.py``); an update's
lifecycle shatters across those files the moment it hits the wire. This
tool joins them back together on the round-16 propagation contract:

- **intra-process** edges via the recorder's local ``span``/``parent`` ids
  (e.g. ``client.train`` → ``client.push``), scoped per source file;
- **cross-process** edges via wire contexts: a span's ``ctx`` attribute is
  its ``"<trace>#<key>"`` identity, and downstream spans reference it as
  ``remote_parent`` (one upstream) or ``links`` (fan-in — a flush lists
  every contributing push, an edge flush lists its leaf offers);
- **deterministic flush/swap keys**: the flush publishing version ``V`` is
  ``flush:vV`` in trace ``fedtr-v(V-1)`` by construction, so a
  ``serve.swap`` span's ``remote_parent`` resolves even though the serve
  process never spoke to the federation — it read the version off the
  statefile.

A **chain** is anchored at each ``fed.flush`` span: its resolved upstream
(pushes → their local train parents; edge flushes → their leaf offers) plus
its downstream (the ``serve.swap`` installing the published version and the
first ``serve.batch`` answered from it). ``chain["complete"]`` means the
full ``client → root → serve`` lifecycle resolved under the flush's single
trace id; ``planes_crossed`` is the set of span-name prefixes on the chain
(``client``/``edge``/``fed``/``serve`` — one per process plane in a
multi-process deployment). A context that was dropped or corrupted on the
wire simply fails to resolve: the chain reports it missing, nothing raises.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable

from fedcrack_tpu.obs.spans import TraceContext, span_files


def load_records(paths: Iterable[str]) -> list[dict]:
    """All span records from the given JSONL files (each expanded to its
    rotation set oldest-first), tagged with their source file. Unparseable
    lines are skipped with a count — a half-written final line from a
    killed process must not sink the whole post-mortem."""
    records: list[dict] = []
    for given in paths:
        for path in span_files(given) or [given]:
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict):
                            rec["_file"] = path
                            records.append(rec)
            except FileNotFoundError:
                continue
    return records


def _by_ctx(records: list[dict]) -> dict[str, dict]:
    """wire-context string -> span record (first writer wins; duplicate
    contexts are a sender bug the summary surfaces, not a crash)."""
    out: dict[str, dict] = {}
    for rec in records:
        ctx = rec.get("ctx")
        if isinstance(ctx, str) and ctx and ctx not in out:
            out[ctx] = rec
    return out


def _by_local_id(records: list[dict]) -> dict[tuple, dict]:
    """(file, span_id) -> record: recorder-local ids are unique per file,
    ambiguous across files — same indexing discipline as ``_by_ctx`` (a
    linear scan per parent lookup would make stitching an hours-long
    soak's span set quadratic)."""
    out: dict[tuple, dict] = {}
    for rec in records:
        span_id = rec.get("span")
        if span_id is not None:
            out.setdefault((rec.get("_file"), span_id), rec)
    return out


def _local_parent(rec: dict, local_index: dict[tuple, dict]) -> dict | None:
    """Resolve a record's recorder-local parent id within its own file."""
    parent = rec.get("parent")
    if not parent:
        return None
    return local_index.get((rec.get("_file"), parent))


def _resolved_links(rec: dict, ctx_index: dict[str, dict]) -> list[dict]:
    out = []
    for wire in rec.get("links") or []:
        if TraceContext.from_wire(wire) is None:
            continue
        hit = ctx_index.get(wire)
        if hit is not None:
            out.append(hit)
    return out


def _stage(rec: dict | None) -> dict | None:
    if rec is None:
        return None
    return {
        k: rec.get(k)
        for k in ("name", "trace", "span", "ctx", "t", "dur_s", "_file")
        if rec.get(k) is not None
    }


def stitch(records: list[dict]) -> dict:
    """Assemble chains (one per ``fed.flush`` span) and summary counters."""
    ctx_index = _by_ctx(records)
    local_index = _by_local_id(records)
    swaps_by_version: dict[int, dict] = {}
    first_batch_by_version: dict[int, dict] = {}
    for rec in records:
        if rec.get("name") == "serve.swap" and rec.get("installed", True):
            v = rec.get("to_version")
            if isinstance(v, int) and v not in swaps_by_version:
                swaps_by_version[v] = rec
        if rec.get("name") == "serve.batch":
            v = rec.get("model_version")
            if isinstance(v, int):
                prev = first_batch_by_version.get(v)
                if prev is None or rec.get("t", 0) < prev.get("t", 0):
                    first_batch_by_version[v] = rec

    chains = []
    for rec in records:
        if rec.get("name") != "fed.flush":
            continue
        version = rec.get("version")
        pushes = _resolved_links(rec, ctx_index)
        upstream = []
        for push in pushes:
            entry = {"span": _stage(push)}
            if push.get("name") == "edge.flush_partial":
                entry["leaves"] = [
                    _stage(leaf) for leaf in _resolved_links(push, ctx_index)
                ]
            else:
                entry["train"] = _stage(_local_parent(push, local_index))
            upstream.append(entry)
        swap = swaps_by_version.get(version) if isinstance(version, int) else None
        batch = (
            first_batch_by_version.get(version) if isinstance(version, int) else None
        )
        stage_records = (
            [u["span"] for u in upstream]
            + [t for u in upstream for t in [u.get("train")] if t]
            + [leaf for u in upstream for leaf in u.get("leaves", []) if leaf]
            + [_stage(rec), _stage(swap), _stage(batch)]
        )
        names = sorted({s["name"] for s in stage_records if s})
        planes = sorted({n.split(".", 1)[0] for n in names})
        # The single-trace-id contract: flush, swap and first batch all
        # carry the flush's trace, and at least one upstream (client/edge)
        # span does too.
        core_traces = {r.get("trace") for r in (rec, swap, batch) if r is not None}
        upstream_same = any(
            u["span"] and u["span"].get("trace") == rec.get("trace")
            for u in upstream
        )
        chain = {
            "trace": rec.get("trace"),
            "version": version,
            "round": rec.get("round"),
            "flush": _stage(rec),
            "upstream": upstream,
            "unresolved_links": [
                w
                for w in rec.get("links") or []
                if ctx_index.get(w) is None
            ],
            "swap": _stage(swap),
            "first_batch": _stage(batch),
            "stages": names,
            "planes_crossed": planes,
            "files": sorted({s["_file"] for s in stage_records if s and "_file" in s}),
            # The acceptance contract: at least one client-side span, the
            # flush, the swap and the first served batch all resolved, and
            # the whole chain shares the flush's single trace id.
            "complete": bool(
                upstream
                and swap is not None
                and batch is not None
                and len(core_traces) == 1
                and upstream_same
            ),
        }
        chains.append(chain)

    traces = sorted({r.get("trace") for r in records if r.get("trace")})
    complete = [c for c in chains if c["complete"]]
    return {
        "records": len(records),
        "files": sorted({r["_file"] for r in records}),
        "traces": len(traces),
        "chains": chains,
        "n_chains": len(chains),
        "n_complete": len(complete),
        "complete": bool(complete),
        "best": max(
            chains,
            key=lambda c: (c["complete"], len(c["planes_crossed"]), len(c["stages"])),
            default=None,
        ),
    }


def stitch_files(paths: Iterable[str]) -> dict:
    return stitch(load_records(paths))


def summarize(stitched: dict) -> dict:
    """The compact arm ``detail.observability.tracing`` embeds."""
    best = stitched.get("best") or {}
    return {
        "records": stitched["records"],
        "traces": stitched["traces"],
        "chains": stitched["n_chains"],
        "n_complete": stitched["n_complete"],
        "complete": stitched["complete"],
        "trace": best.get("trace"),
        "planes_crossed": best.get("planes_crossed", []),
        "stages": best.get("stages", []),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fedcrack_tpu.tools.trace_stitch", description=__doc__
    )
    p.add_argument("paths", nargs="+", help="span JSONL files (one per process)")
    p.add_argument(
        "--trace", default="", help="only report chains on this trace id"
    )
    p.add_argument(
        "--require",
        default="",
        help="comma-separated span names the best chain must contain "
        "(exit 1 otherwise); default: require one complete chain",
    )
    p.add_argument("--json", default="", help="write the full stitched result here")
    args = p.parse_args(argv)
    stitched = stitch_files(args.paths)
    if args.trace:
        stitched["chains"] = [
            c for c in stitched["chains"] if c["trace"] == args.trace
        ]
        stitched["n_chains"] = len(stitched["chains"])
        stitched["n_complete"] = sum(c["complete"] for c in stitched["chains"])
        stitched["complete"] = stitched["n_complete"] > 0
        stitched["best"] = max(
            stitched["chains"],
            key=lambda c: (c["complete"], len(c["planes_crossed"])),
            default=None,
        )
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(stitched, f, indent=1, sort_keys=True, default=str)
    summary = summarize(stitched)
    print(json.dumps(summary, indent=1, sort_keys=True))
    if args.require:
        wanted = [s for s in args.require.split(",") if s]
        missing = [s for s in wanted if s not in summary["stages"]]
        if missing:
            print(f"incomplete chain: missing {missing}", file=sys.stderr)
            return 1
        return 0
    return 0 if summary["complete"] else 1


if __name__ == "__main__":
    sys.exit(main())
