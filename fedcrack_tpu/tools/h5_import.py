"""Keras h5 -> Flax ResUNet weight importer.

The reference's centralized trainer checkpoints its best U-Net to
``crack_segmentation.h5`` (reference: test/Segmentation.py:177-179) and the
federation bootstraps from Keras weights; that blob is absent from the
snapshot (SURVEY.md §0.1) but its architecture is fully specified
(SURVEY.md §2.3). This importer lets a real Keras checkpoint seed our Flax
global model tensor-for-tensor (SURVEY.md §7 step 8).

Supported files: weights-only h5 (``model.save_weights``) and full-model h5
(``model.save`` / ``ModelCheckpoint``, weights under ``model_weights``).

Kernel-layout conversions (verified empirically against Keras forward
passes, see tests/test_h5_import.py):

- ``Conv2D``: kernel ``(kh, kw, in, out)`` — identical in Flax; no transform.
- ``SeparableConv2D``: Keras depthwise kernel ``(kh, kw, in, 1)`` ->
  Flax grouped-conv kernel ``(kh, kw, 1, in)`` (transpose last two axes);
  pointwise ``(1, 1, in, out)`` unchanged; bias on the pointwise stage.
- ``Conv2DTranspose``: Keras kernel ``(kh, kw, out, in)`` is the
  gradient-of-conv orientation; Flax ``nn.ConvTranspose`` wants
  ``(kh, kw, in, out)`` un-flipped — so flip both spatial axes and swap the
  channel axes.
- ``BatchNormalization``: gamma/beta -> params ``scale``/``bias``;
  moving mean/variance -> ``batch_stats`` ``mean``/``var``.

Layer matching is by layer *type* (read from the h5 weight names), in model
order within each type, with every tensor shape validated against the target
Flax parameter — a mismatch raises instead of silently mis-seeding.

Layout transforms (``ModelConfig.stem_layout``/``res_layout``) never touch
this importer: parameter shapes are layout-invariant (the transformed
kernels are derived in-forward, models/resunet.py), so one imported
checkpoint seeds every layout and produces bit-exact logits under
``stem_layout="s2d"``/``res_layout="packed"`` (pinned in
tests/test_h5_import.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from fedcrack_tpu.configs import ModelConfig

try:  # h5py ships with the image; gate anyway so import of tools/ never fails
    import h5py

    HAVE_H5PY = True
except ImportError:  # pragma: no cover
    HAVE_H5PY = False


@dataclasses.dataclass(frozen=True)
class _Layer:
    name: str
    kind: str  # conv | separable | convT | bn
    weights: dict[str, np.ndarray]  # canonical name -> array


_CANONICAL = (
    "depthwise_kernel",
    "pointwise_kernel",
    "moving_mean",
    "moving_variance",
    "kernel",
    "bias",
    "gamma",
    "beta",
)


def _canon(weight_name: str) -> str:
    base = weight_name.split("/")[-1].split(":")[0]
    for cand in _CANONICAL:  # longest-match first (kernel vs *_kernel)
        if base == cand or base.endswith(cand):
            return cand
    raise ValueError(f"unrecognized weight name {weight_name!r}")


def _classify(layer_name: str, weights: dict[str, np.ndarray]) -> str:
    if "gamma" in weights:
        return "bn"
    if "depthwise_kernel" in weights:
        return "separable"
    if "transpose" in layer_name:
        return "convT"
    return "conv"


def read_keras_h5(path: str) -> list[_Layer]:
    """Ordered (model-order) list of weighted layers from a Keras h5 file."""
    if not HAVE_H5PY:  # pragma: no cover
        raise ImportError("h5py is required for Keras h5 import")
    layers: list[_Layer] = []
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        layer_names = [_as_str(n) for n in root.attrs["layer_names"]]
        for lname in layer_names:
            group = root[lname]
            weight_names = [_as_str(n) for n in group.attrs["weight_names"]]
            if not weight_names:
                continue  # Activation / pooling / add layers carry no weights
            weights = {
                _canon(wn): np.asarray(group[wn]) for wn in weight_names
            }
            layers.append(_Layer(lname, _classify(lname, weights), weights))
    return layers


def _as_str(name: Any) -> str:
    return name.decode() if isinstance(name, bytes) else str(name)


def _conv_targets(config: ModelConfig) -> list[str]:
    """Flax module names of plain Conv2D layers, in Keras model order."""
    names = ["stem_conv"]
    names += [f"enc{i}_res" for i in range(len(config.encoder_features))]
    names += [f"dec{i}_res" for i in range(len(config.decoder_features))]
    names.append("head")
    return names


def _bn_targets(config: ModelConfig) -> list[str]:
    names = ["stem_bn"]
    for i in range(len(config.encoder_features)):
        names += [f"enc{i}_bn1", f"enc{i}_bn2"]
    for i in range(len(config.decoder_features)):
        names += [f"dec{i}_bn1", f"dec{i}_bn2"]
    return names


def _sep_targets(config: ModelConfig) -> list[str]:
    out = []
    for i in range(len(config.encoder_features)):
        out += [f"enc{i}_sep1", f"enc{i}_sep2"]
    return out


def _convT_targets(config: ModelConfig) -> list[str]:
    out = []
    for i in range(len(config.decoder_features)):
        out += [f"dec{i}_convT1", f"dec{i}_convT2"]
    return out


def _check(src: np.ndarray, dst_shape: tuple, layer: str, tensor: str) -> np.ndarray:
    if tuple(src.shape) != tuple(dst_shape):
        raise ValueError(
            f"shape mismatch importing {layer}/{tensor}: "
            f"h5 {tuple(src.shape)} vs model {tuple(dst_shape)}"
        )
    return src


def import_resunet_h5(
    path: str, config: ModelConfig | None = None, template: dict | None = None
) -> dict:
    """Import a Keras ResUNet h5 checkpoint as Flax ``{'params','batch_stats'}``.

    ``template`` (a freshly initialized variables pytree) supplies the target
    structure/shapes; it is built from ``config`` when omitted. Every tensor
    is shape-checked; extra or missing layers raise.
    """
    import jax

    from fedcrack_tpu.models.resunet import init_variables

    config = config or ModelConfig()
    if template is None:
        template = init_variables(jax.random.key(0), config)
    params = _to_mutable(template["params"])
    stats = _to_mutable(template["batch_stats"])

    layers = read_keras_h5(path)
    by_kind: dict[str, list[_Layer]] = {}
    for layer in layers:
        by_kind.setdefault(layer.kind, []).append(layer)

    targets = {
        "conv": _conv_targets(config),
        "separable": _sep_targets(config),
        "convT": _convT_targets(config),
        "bn": _bn_targets(config),
    }
    for kind, expected in targets.items():
        got = by_kind.get(kind, [])
        if len(got) != len(expected):
            raise ValueError(
                f"layer count mismatch for {kind}: h5 has {len(got)} "
                f"({[l.name for l in got]}), model needs {len(expected)} ({expected})"
            )

    for layer, target in zip(by_kind.get("conv", []), targets["conv"]):
        w = layer.weights
        params[target]["kernel"] = _check(
            w["kernel"], params[target]["kernel"].shape, target, "kernel"
        )
        params[target]["bias"] = _check(
            w["bias"], params[target]["bias"].shape, target, "bias"
        )

    for layer, target in zip(by_kind.get("separable", []), targets["separable"]):
        w = layer.weights
        dw = np.transpose(w["depthwise_kernel"], (0, 1, 3, 2))  # (kh,kw,in,1)->(kh,kw,1,in)
        params[target]["depthwise"]["kernel"] = _check(
            dw, params[target]["depthwise"]["kernel"].shape, target, "depthwise"
        )
        params[target]["pointwise"]["kernel"] = _check(
            w["pointwise_kernel"],
            params[target]["pointwise"]["kernel"].shape,
            target,
            "pointwise",
        )
        params[target]["pointwise"]["bias"] = _check(
            w["bias"], params[target]["pointwise"]["bias"].shape, target, "bias"
        )

    for layer, target in zip(by_kind.get("convT", []), targets["convT"]):
        w = layer.weights
        # gradient-of-conv orientation -> Flax: flip spatial, swap channels
        kt = np.transpose(w["kernel"][::-1, ::-1], (0, 1, 3, 2))
        params[target]["kernel"] = _check(
            kt, params[target]["kernel"].shape, target, "kernel"
        )
        params[target]["bias"] = _check(
            w["bias"], params[target]["bias"].shape, target, "bias"
        )

    for layer, target in zip(by_kind.get("bn", []), targets["bn"]):
        w = layer.weights
        params[target]["scale"] = _check(
            w["gamma"], params[target]["scale"].shape, target, "scale"
        )
        params[target]["bias"] = _check(
            w["beta"], params[target]["bias"].shape, target, "bias"
        )
        stats[target]["mean"] = _check(
            w["moving_mean"], stats[target]["mean"].shape, target, "mean"
        )
        stats[target]["var"] = _check(
            w["moving_variance"], stats[target]["var"].shape, target, "var"
        )

    return {"params": _to_f32(params), "batch_stats": _to_f32(stats)}


def _to_mutable(tree: Any) -> dict:
    if hasattr(tree, "unfreeze"):
        tree = tree.unfreeze()
    return {
        k: _to_mutable(v) if isinstance(v, dict) or hasattr(v, "unfreeze") else v
        for k, v in dict(tree).items()
    }


def _to_f32(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32), tree)


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m fedcrack_tpu.tools.h5_import ckpt.h5 out.msgpack``."""
    import argparse

    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.ioutils import atomic_write_bytes

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("h5_path")
    p.add_argument("out_path", help="msgpack pytree output (fed/serialization format)")
    p.add_argument("--img-size", type=int, default=128)
    p.add_argument("--config", help="JSON FedConfig file; its model section wins")
    args = p.parse_args(argv)
    if args.config:
        from fedcrack_tpu.configs import FedConfig

        with open(args.config) as f:
            config = FedConfig.from_json(f.read()).model
    else:
        config = ModelConfig(img_size=args.img_size)
    variables = import_resunet_h5(args.h5_path, config)
    atomic_write_bytes(args.out_path, tree_to_bytes(variables))
    print(f"imported {args.h5_path} -> {args.out_path}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
