"""The reference's COMPLETE federation at reference scale, on the mesh plane.

The reference's actual run is 5 rounds (reference: fl_server.py:18) of
10 local epochs x ~388 steps of batch 16 at 128 px over a 6,213-sample
shard (client_fit_model.py:166,76,55-56). Round 3 benched ONE such round
for timing only; this tool executes the WHOLE workload end to end through
the production components and records the quality trajectory:

- one mesh client, the full round as one compiled XLA program
  (``parallel.build_federated_round``);
- a FIXED pool of 6,213 unique synthetic samples (not a cycled 512), freshly
  reshuffled every round (the reference's keras Sequence reshuffles per fit);
- uint8 transport staging, with the next round's reshuffled epoch
  double-buffered under the in-flight round (``parallel.driver``);
- BN-recalibrated held-out eval after every round (the server's eval path —
  ``train.local.recalibrate_batch_stats`` + ``evaluate``), so the artifact
  shows loss/IoU LEARNING across rounds, not just wall-clock.

Run on the TPU:
    python -m fedcrack_tpu.tools.refscale_federation \
        --out bench_runs/r04_refscale_federation.json

Scaled-down smoke (any host):
    python -m fedcrack_tpu.tools.refscale_federation --rounds 2 --epochs 1 \
        --samples 64 --img 32 --eval-samples 16 --out /tmp/smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import numpy as np


def _now() -> float:
    return time.perf_counter()


def run_refscale_federation(args) -> dict:
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        shuffled_epoch_data,
    )
    from fedcrack_tpu.train.local import (
        create_train_state,
        evaluate,
        recalibrate_batch_stats,
    )

    config = ModelConfig(img_size=args.img, compute_dtype=args.dtype)
    steps = args.samples // args.batch
    if steps < 1:
        raise SystemExit(f"--samples {args.samples} < --batch {args.batch}")

    # The client's fixed local shard: args.samples UNIQUE images, uint8
    # transport encoding (1/4 the staging bytes; on-device normalization is
    # bit-exact vs float32 staging — data.pipeline.as_model_batch).
    from fedcrack_tpu.data.pipeline import to_uint8_transport

    t0 = _now()
    pool_f, pool_masks_f = synth_crack_batch(args.samples, args.img, seed=args.seed)
    pool_u8, pool_masks_u8 = to_uint8_transport(pool_f, pool_masks_f)
    del pool_f
    # Held-out eval set: distinct seed from the training shard.
    ev_images, ev_masks = synth_crack_batch(
        args.eval_samples, args.img, seed=args.seed + 7919
    )
    synth_s = _now() - t0
    eval_ds = ArrayDataset(
        ev_images, ev_masks, batch_size=args.batch, shuffle=False, drop_last=False
    )

    mesh = make_mesh(1, 1)
    round_fn = build_federated_round(
        mesh,
        config,
        learning_rate=args.lr,
        local_epochs=args.epochs,
        pos_weight=args.pos_weight,
    )
    state_tmpl = create_train_state(jax.random.key(args.seed), config)
    rng = np.random.default_rng(args.seed)
    active = np.ones(1, np.float32)
    n_samples = np.full(1, float(steps * args.batch), np.float32)

    def data_fn(r: int):
        images, masks = shuffled_epoch_data(
            pool_u8, pool_masks_u8, steps, args.batch, rng
        )
        return images, masks, active, n_samples

    rounds_out = []

    def on_round(record, variables):
        # Server-side eval of the round's aggregated global model: BN
        # recalibration then held-out metrics, at the training pos_weight.
        t0 = _now()
        host_vars = jax.device_get(variables)
        st = state_tmpl.replace_variables(host_vars)
        st = recalibrate_batch_stats(st, eval_ds, config)
        m = evaluate(st, eval_ds, pos_weight=args.pos_weight)
        eval_s = _now() - t0
        train = {
            k: round(float(np.asarray(v)[0]), 4)
            for k, v in record.metrics.items()
        }
        rounds_out.append(
            {
                "round": record.round_idx + 1,
                "wall_clock_s": round(record.wall_clock_s, 3),
                "shuffle_s": round(record.data_fn_s, 3),
                "staged_bytes": record.staged_bytes,
                "overlapped_next_round_staging": record.overlapped,
                "train_last_epoch": train,
                "eval": {k: round(float(v), 4) for k, v in m.items()},
                "eval_s": round(eval_s, 2),
            }
        )
        print(json.dumps(rounds_out[-1]), flush=True)

    t0 = _now()
    _, records = run_mesh_federation(
        round_fn, state_tmpl.variables, data_fn, args.rounds, mesh, on_round=on_round
    )
    session_s = _now() - t0

    walls = [r.wall_clock_s for r in records]
    post_compile = walls[1:] if len(walls) > 1 else walls
    d = jax.devices()[0]
    ious = [r["eval"]["iou"] for r in rounds_out]
    losses = [r["eval"]["loss"] for r in rounds_out]
    return {
        "generated_by": "fedcrack_tpu.tools.refscale_federation",
        "hardware": {
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", "unknown"),
        },
        "workload": {
            "rounds": args.rounds,
            "local_epochs": args.epochs,
            "steps_per_epoch": steps,
            "batch": args.batch,
            "img_size": args.img,
            "unique_samples": args.samples,
            "compute_dtype": args.dtype,
            "pos_weight": args.pos_weight,
            "learning_rate": args.lr,
            "eval_samples": args.eval_samples,
            "reference_parity": (
                "5 rounds (fl_server.py:18) x 10 epochs x 388 steps of "
                "batch 16 at 128 px over 6213 samples "
                "(client_fit_model.py:166,76,55-56)"
            ),
        },
        "rounds": rounds_out,
        "summary": {
            "session_wall_clock_s": round(session_s, 2),
            "synthesis_s": round(synth_s, 2),
            "round_wall_clock_s_median_post_compile": round(
                float(np.median(post_compile)), 3
            ),
            "compile_round_s": round(walls[0], 2),
            "rounds_wall_clock_total_s": round(float(np.sum(walls)), 2),
            # All rounds at the post-compile rate (round 0's one-time XLA
            # compilation replaced by a typical round): the "entire
            # federation in N seconds of device time" headline number.
            "device_time_total_s_est": round(
                float(np.sum(post_compile)) + float(np.median(post_compile)), 2
            )
            if len(walls) > 1
            else round(float(np.sum(walls)), 2),
            "eval_iou_trajectory": ious,
            "eval_loss_trajectory": losses,
            "learned": bool(
                losses[-1] < losses[0] and ious[-1] > ious[0]
            )
            if len(rounds_out) >= 2
            else None,
        },
    }


def main(argv=None) -> int:
    # Same platform-override + compile-cache hooks as bench.py: the image
    # pre-imports jax on the axon platform at interpreter startup.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--samples", type=int, default=6213)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--img", type=int, default=128)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--eval-samples", type=int, default=256)
    p.add_argument("--pos-weight", type=float, default=5.0)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    artifact = run_refscale_federation(args)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
