"""The reference's COMPLETE federation at reference scale, on the mesh plane.

The reference's actual run is N registered clients (cohort size is set by
registrations, fl_server.py:59) federating for 5 rounds (fl_server.py:18):
each round every client fits 10 local epochs x ~388 steps of batch 16 at
128 px over its own 6,213-sample shard (client_fit_model.py:166,76,55-56),
the server barriers over all N uploads (fl_server.py:116-117) and averages
them (fl_server.py:92-102). Round 4 ran this with ONE mesh client — FedAvg
over a single update is the identity, so that artifact was chunked
centralized training (round-4 verdict, Missing #1). This tool runs the
actual N-client federation on the one available chip:

- ``--clients`` mesh clients (default 2), each with its OWN fixed pool of
  ``--samples`` unique synthetic images (distinct seeds = distinct shards),
  freshly reshuffled every round (the reference's keras Sequence reshuffles
  per fit);
- per round, each client's full local fit runs as one compiled XLA program
  (``parallel.build_federated_round``) SERIALLY on the chip, every fit
  starting from the same round-start global weights — time-multiplexing the
  reference's concurrent clients onto one device;
- non-degenerate sample-weighted FedAvg over the N divergent fits
  (``fed.algorithms.fedavg``), with the per-client update norms and the
  inter-client update distance recorded so the divergence being averaged is
  visible in the artifact;
- uint8 transport staging, double-buffered: the NEXT fit's reshuffled epoch
  stages while the current fit's program is in flight (same overlap the
  round driver uses, ``parallel.driver.stage_round_data``);
- BN-recalibrated held-out eval of the aggregated global model after every
  round (the server's eval path — ``train.local.recalibrate_batch_stats`` +
  ``evaluate``), so the artifact shows loss/IoU LEARNING across rounds.

Run on the TPU:
    python -m fedcrack_tpu.tools.refscale_federation \
        --out bench_runs/r05_refscale_federation.json

Scaled-down smoke (any host):
    python -m fedcrack_tpu.tools.refscale_federation --clients 2 --rounds 2 \
        --epochs 1 --samples 64 --img 32 --eval-samples 16 --out /tmp/smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import numpy as np


def _now() -> float:
    return time.perf_counter()


def _params_l2_diff(a, b) -> float:
    """||params_a - params_b||_2 computed on device, one scalar readback."""
    import jax.numpy as jnp

    sq = jax.tree_util.tree_map(
        lambda x, y: jnp.sum(
            (jnp.asarray(x, jnp.float32) - jnp.asarray(y, jnp.float32)) ** 2
        ),
        a["params"],
        b["params"],
    )
    total = sum(jax.tree_util.tree_leaves(sq))
    return float(np.sqrt(np.asarray(total)))


def run_refscale_federation(args) -> dict:
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.pipeline import ArrayDataset, SamplePool, to_uint8_transport
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.algorithms import (
        apply_server_opt,
        fedavg,
        make_server_optimizer,
    )
    from fedcrack_tpu.parallel import (
        build_federated_round,
        build_federated_round_segments,
        make_mesh,
        resident_pool_fits,
        shuffled_epoch_data,
        stage_round_data,
        stage_round_indices,
    )
    from fedcrack_tpu.train.local import (
        create_train_state,
        evaluate,
        recalibrate_batch_stats,
    )

    config = ModelConfig(img_size=args.img, compute_dtype=args.dtype)
    steps = args.samples // args.batch
    if steps < 1:
        raise SystemExit(f"--samples {args.samples} < --batch {args.batch}")
    if args.clients < 1:
        raise SystemExit(f"--clients {args.clients} < 1")
    segments = int(getattr(args, "segments", 0) or 0)
    placement = getattr(args, "data_placement", "streamed") or "streamed"
    if placement not in ("streamed", "resident"):
        raise SystemExit(f"--data-placement must be streamed|resident, got {placement!r}")
    ckpt_dir = getattr(args, "ckpt_dir", "") or ""
    resume = bool(getattr(args, "resume", False))
    if resume and not ckpt_dir:
        raise SystemExit("--resume needs --ckpt-dir")

    # Each client's fixed local shard: args.samples UNIQUE images under a
    # client-distinct seed, uint8 transport encoding (1/4 the staging bytes;
    # on-device normalization is bit-exact vs float32 staging —
    # data.pipeline.as_model_batch).
    t0 = _now()
    pools = []
    for c in range(args.clients):
        pf, pm = synth_crack_batch(
            args.samples, args.img, seed=args.seed + c * 104729
        )
        pu, pmu = to_uint8_transport(pf, pm)
        del pf, pm
        pools.append((pu, pmu))
    # Held-out eval set: distinct seed from every training shard.
    ev_images, ev_masks = synth_crack_batch(
        args.eval_samples, args.img, seed=args.seed + 7919
    )
    synth_s = _now() - t0
    eval_ds = ArrayDataset(
        ev_images, ev_masks, batch_size=args.batch, shuffle=False, drop_last=False
    )

    mesh = make_mesh(1, 1)

    # Held-out eval slab: device-resident ONCE, reused across rounds. Eval
    # was ~100 s of the round-4 206 s session — dominated by re-shipping the
    # same eval batches (recalibration + metrics passes) every round; the
    # batches never change, so stage them once and iterate device arrays.
    # The one-time transfer is charged to the first round's eval_stage_s;
    # every later round's is 0.0 (recorded per round in the artifact).
    t0 = _now()
    eval_batches = []
    for bi, bm in eval_ds:
        di, dm = jax.device_put(bi), jax.device_put(bm)
        jax.block_until_ready(di)
        jax.block_until_ready(dm)
        eval_batches.append((di, dm))
    pending_eval_stage_s = _now() - t0
    eval_staged_bytes = int(ev_images.nbytes + ev_masks.nbytes)

    # Resident data plane (round 9): every client's deduplicated pool stays
    # in HBM for the whole session (they time-share the one chip, so ALL
    # pools are resident simultaneously — the guard prices the sum); per
    # fit only the [1, epochs, steps, batch] gather plan ships. Guard
    # failure falls back to the streamed path, recorded in the artifact.
    resident = placement == "resident"
    placement_guard = None
    sample_pools = staged_pools = None
    pool_stage_s = 0.0
    if resident:
        sample_pools = [SamplePool(pu[None], pmu[None]) for pu, pmu in pools]
        total_pool_bytes = sum(p.nbytes for p in sample_pools)
        fits, placement_guard = resident_pool_fits(total_pool_bytes, mesh)
        if fits:
            t0 = _now()
            staged_pools = [p.stage(mesh) for p in sample_pools]
            pool_stage_s = _now() - t0
        else:
            resident = False
            placement = "streamed"

    if segments:
        # Epoch-segmented round: K compiled programs of epochs/K epochs each
        # with a donated device-resident carry — bit-identical to the
        # monolithic round (parallel.fedavg_mesh.SegmentedRound), but each
        # program is 1/K the size (the 256 px reference-scale fit only
        # compiles through remote-compile helpers in this chunked form).
        round_fn = build_federated_round_segments(
            mesh,
            config,
            learning_rate=args.lr,
            local_epochs=args.epochs,
            pos_weight=args.pos_weight,
            segments=segments,
            data_placement="resident" if resident else "streamed",
        )
    else:
        round_fn = build_federated_round(
            mesh,
            config,
            learning_rate=args.lr,
            local_epochs=args.epochs,
            pos_weight=args.pos_weight,
            data_placement="resident" if resident else "streamed",
        )
    state_tmpl = create_train_state(jax.random.key(args.seed), config)
    rngs = [
        np.random.default_rng(args.seed + 31 * c) for c in range(args.clients)
    ]
    active = np.ones(1, np.float32)
    n_samples = np.full(1, float(steps * args.batch), np.float32)
    fit_weight = float(steps * args.batch)

    # FedOpt server optimizer on the round pseudo-gradient (VERDICT r5 #5):
    # "fedavg"/"avg" keeps the reference's plain average (tx is None).
    server_kind = getattr(args, "server_optimizer", "fedavg")
    server_tx = make_server_optimizer(
        server_kind,
        float(getattr(args, "server_lr", 1.0)),
        float(getattr(args, "server_momentum", 0.9)),
    )

    def epoch_for(c: int):
        """One fit's data draw. Both placements consume EXACTLY one
        ``rng.permutation(samples)`` per call, so the shuffle schedule —
        and therefore the trajectory — is placement-independent (and the
        --resume rng fast-forward stays valid for both)."""
        if resident:
            return sample_pools[c].round_indices(
                [rngs[c]], args.epochs, steps, args.batch
            )
        return shuffled_epoch_data(
            pools[c][0], pools[c][1], steps, args.batch, rngs[c]
        )

    def stage_for(c: int, epoch_data):
        """Stage one fit's data; returns (staged_args, staged_bytes) where
        staged_args are the round_fn data arguments. Resident: the pool is
        already placed — only the gather plan (kilobytes) ships."""
        if resident:
            idx_dev = stage_round_indices(epoch_data, mesh)
            return (staged_pools[c], idx_dev), int(epoch_data.nbytes)
        imgs, msks = epoch_data
        return stage_round_data(imgs, msks, mesh), int(imgs.nbytes + msks.nbytes)

    global_vars = state_tmpl.variables
    server_opt_state = (
        server_tx.init(global_vars["params"]) if server_tx is not None else None
    )
    rounds_out = []
    start_round = 0
    ckptr = None
    if ckpt_dir:
        from fedcrack_tpu.ckpt.manager import FedCheckpoint, FedCheckpointer

        ckptr = FedCheckpointer(ckpt_dir)
        if resume:
            ckpt = ckptr.restore()
            if ckpt is None:
                raise SystemExit(f"--resume: no checkpoint under {ckpt_dir!r}")
            start_round = int(ckpt.current_round)
            if start_round >= args.rounds:
                raise SystemExit(
                    f"--resume: checkpoint already at round {start_round} "
                    f">= --rounds {args.rounds}"
                )
            global_vars = ckpt.variables
            rounds_out = [dict(h) for h in ckpt.history]
            if server_tx is not None:
                restored_opt = ckptr.restore_opt_state(
                    server_tx.init(global_vars["params"])
                )
                if restored_opt is not None:
                    server_opt_state = restored_opt
            # Deterministic-trajectory resume: each client's rng advanced one
            # permutation per completed round (shuffled_epoch_data draws once
            # per fit, in schedule order) — fast-forward to that exact state.
            for rng in rngs:
                for _ in range(start_round):
                    rng.permutation(args.samples)

    # (round, client) fit schedule; one staged epoch always in flight ahead.
    schedule = [
        (r, c) for r in range(start_round, args.rounds) for c in range(args.clients)
    ]
    t0 = _now()
    epoch0 = epoch_for(schedule[0][1])
    shuffle_s = _now() - t0
    staged, staged_bytes = stage_for(schedule[0][1], epoch0)

    client_vars: list = []
    fit_walls: list[float] = []
    round_t0 = _now()
    round_fits: list[dict] = []

    session_t0 = _now()
    for k, (r, c) in enumerate(schedule):
        fit_t0 = _now()
        new_vars, metrics = round_fn(global_vars, *staged, active, n_samples)

        # Double buffer: the fit's program is in flight; the next fit's
        # shuffle + staging transfers ride under it.
        staged_next = None
        next_shuffle_s = 0.0
        next_bytes = 0
        if k + 1 < len(schedule):
            td = _now()
            nxt_epoch = epoch_for(schedule[k + 1][1])
            next_shuffle_s = _now() - td
            staged_next, next_bytes = stage_for(schedule[k + 1][1], nxt_epoch)

        # Fit barrier: the metrics depend on every step of the local fit.
        train = {
            key: round(float(np.asarray(v)[0]), 4) for key, v in metrics.items()
        }
        fit_wall = _now() - fit_t0
        fit_walls.append(fit_wall)
        client_vars.append(new_vars)
        round_fits.append(
            {
                "client": c,
                "wall_clock_s": round(fit_wall, 3),
                "shuffle_s": round(shuffle_s, 3),
                "staged_bytes": staged_bytes,
                "overlapped_next_fit_staging": staged_next is not None,
                "train_last_epoch": train,
            }
        )
        staged = staged_next
        shuffle_s = next_shuffle_s
        staged_bytes = next_bytes

        if c == args.clients - 1:
            # Round boundary: sample-weighted FedAvg over the N divergent
            # fits (fl_server.py:92-102 made non-degenerate), plus the
            # divergence diagnostics that prove there was something to
            # average.
            agg_t0 = _now()
            update_l2 = [
                round(_params_l2_diff(cv, global_vars), 4) for cv in client_vars
            ]
            divergence_l2 = (
                [
                    round(_params_l2_diff(client_vars[i], client_vars[i + 1]), 4)
                    for i in range(len(client_vars) - 1)
                ]
                if len(client_vars) > 1
                else []
            )
            if len(client_vars) > 1:
                averaged = fedavg(
                    client_vars, weights=[fit_weight] * len(client_vars)
                )
            else:
                averaged = client_vars[0]
            if server_tx is not None:
                # FedOpt (Reddi et al.): pseudo-gradient = global - average,
                # stepped by the server optimizer; BN moving statistics are
                # plain-averaged (momentum on running moments is meaningless).
                new_params, server_opt_state = apply_server_opt(
                    global_vars["params"],
                    averaged["params"],
                    server_tx,
                    server_opt_state,
                )
                new_global = {
                    "params": new_params,
                    "batch_stats": averaged["batch_stats"],
                }
            else:
                new_global = averaged
            jax.block_until_ready(jax.tree_util.tree_leaves(new_global)[0])
            agg_s = _now() - agg_t0
            global_vars = new_global
            client_vars = []

            # Server-side eval of the aggregated global model: BN
            # recalibration then held-out metrics, at the training
            # pos_weight — over the DEVICE-RESIDENT eval batches staged
            # once before round 1 (eval used to re-ship the same slab every
            # round, ~100 s of the 206 s round-4 session). eval_stage_s is
            # the eval-staging paid for THIS round: the one-time transfer
            # on this process's first round, 0.0 after.
            ev_t0 = _now()
            host_vars = jax.device_get(global_vars)
            st = state_tmpl.replace_variables(host_vars)
            st = recalibrate_batch_stats(st, eval_batches, config)
            m = evaluate(st, eval_batches, pos_weight=args.pos_weight)
            eval_s = _now() - ev_t0
            eval_stage_s, pending_eval_stage_s = pending_eval_stage_s, 0.0

            rounds_out.append(
                {
                    "round": r + 1,
                    "wall_clock_s": round(_now() - round_t0 - eval_s, 3),
                    "fits": round_fits,
                    "aggregation_s": round(agg_s, 3),
                    "update_l2": update_l2,
                    "client_divergence_l2": divergence_l2,
                    "eval": {key: round(float(v), 4) for key, v in m.items()},
                    "eval_s": round(eval_s, 2),
                    # 6 decimals: the one-time toy-scale staging is sub-ms
                    # and must stay distinguishable from the 0.0 of later
                    # rounds (the smoke test pins first>0, rest==0).
                    "eval_stage_s": round(eval_stage_s, 6),
                }
            )
            print(json.dumps(rounds_out[-1]), flush=True)
            if ckptr is not None:
                # Round-boundary checkpoint: weights + full round history +
                # FedOpt moments — a killed session resumes at round r+2
                # with an identical trajectory (--resume; test-pinned).
                ckptr.save(
                    FedCheckpoint(
                        current_round=r + 1,
                        model_version=r + 1,
                        variables=jax.device_get(global_vars),
                        history=tuple(rounds_out),
                        server_opt_state=server_opt_state,
                    )
                )
            round_fits = []
            round_t0 = _now()
    session_s = _now() - session_t0

    walls = [r["wall_clock_s"] for r in rounds_out]
    post_compile = walls[1:] if len(walls) > 1 else walls
    fit_post_compile = fit_walls[1:] if len(fit_walls) > 1 else fit_walls
    d = jax.devices()[0]
    ious = [r["eval"]["iou"] for r in rounds_out]
    losses = [r["eval"]["loss"] for r in rounds_out]
    return {
        "generated_by": "fedcrack_tpu.tools.refscale_federation",
        "hardware": {
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", "unknown"),
        },
        "workload": {
            "clients": args.clients,
            "rounds": args.rounds,
            "local_epochs": args.epochs,
            "steps_per_epoch": steps,
            "batch": args.batch,
            "img_size": args.img,
            "unique_samples_per_client": args.samples,
            "compute_dtype": args.dtype,
            "pos_weight": args.pos_weight,
            "learning_rate": args.lr,
            "eval_samples": args.eval_samples,
            "segments": segments,
            "server_optimizer": server_kind,
            # The placement that actually RAN ("resident" may have been
            # bounced to "streamed" by the HBM guard — see placement_guard).
            "data_placement": placement,
            "placement_guard": placement_guard,
            "reference_parity": (
                "N-client cohort + round barrier + average "
                "(fl_server.py:59,116-117,92-102); 5 rounds (fl_server.py:18) "
                "x 10 epochs x 388 steps of batch 16 at 128 px over 6213 "
                "samples per client (client_fit_model.py:166,76,55-56); "
                "clients time-multiplexed serially on one chip"
            ),
        },
        "rounds": rounds_out,
        # Non-zero when this artifact continued a checkpointed session: the
        # first `resumed_from` round entries (and the summary terms derived
        # from them) were measured by the ORIGINAL process; session/synthesis
        # walls cover only the resumed rounds.
        "resumed_from": start_round,
        "summary": {
            "session_wall_clock_s": round(session_s, 2),
            "synthesis_s": round(synth_s, 2),
            # Eval slab staged device-resident once (per-round eval_stage_s
            # carries the one-time transfer on the first round, 0.0 after).
            "eval_staged_bytes": eval_staged_bytes,
            # Resident plane one-time costs (0/None when streamed): all
            # client pools stay in HBM for the session; per-fit staging is
            # the gather plan only (see fits[].staged_bytes).
            "pool_bytes_total": (
                sum(p.nbytes for p in sample_pools) if resident else None
            ),
            "pool_stage_s": round(pool_stage_s, 3) if resident else None,
            "round_wall_clock_s_median_post_compile": round(
                float(np.median(post_compile)), 3
            ),
            "fit_wall_clock_s_median_post_compile": round(
                float(np.median(fit_post_compile)), 3
            ),
            "compile_round_s": round(walls[0], 2),
            "rounds_wall_clock_total_s": round(float(np.sum(walls)), 2),
            # All rounds at the post-compile rate (round 0's one-time XLA
            # compilation replaced by a typical round): the "entire
            # federation in N seconds of device time" headline number.
            "device_time_total_s_est": round(
                float(np.sum(post_compile)) + float(np.median(post_compile)), 2
            )
            if len(walls) > 1
            else round(float(np.sum(walls)), 2),
            "eval_iou_trajectory": ious,
            "eval_loss_trajectory": losses,
            "learned": bool(losses[-1] < losses[0] and ious[-1] > ious[0])
            if len(rounds_out) >= 2
            else None,
        },
    }


def main(argv=None) -> int:
    # Same platform-override + compile-cache hooks as bench.py: the image
    # pre-imports jax on the axon platform at interpreter startup.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--clients", type=int, default=2)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--samples", type=int, default=6213)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--img", type=int, default=128)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--eval-samples", type=int, default=256)
    p.add_argument("--pos-weight", type=float, default=5.0)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--data-placement",
        default="streamed",
        choices=["streamed", "resident"],
        help="data plane for the mesh fits: 'streamed' restages each fit's "
        "shuffled epoch slab; 'resident' stages every client's "
        "deduplicated sample pool once (device-resident for the session) "
        "and ships only a per-fit int32 gather plan — kilobytes instead "
        "of the epoch slab, identical trajectory. Falls back to streamed "
        "(recorded in the artifact) when the HBM guard says the pools "
        "don't fit",
    )
    p.add_argument(
        "--segments",
        type=int,
        default=0,
        help="epoch-segmented fit: K device-resident-carry programs instead "
        "of one monolithic scan (0 = monolithic; K must divide --epochs; "
        "bit-identical either way, but each program compiles at 1/K size — "
        "required for the 256 px reference-scale fit on remote-compile "
        "tunnels)",
    )
    p.add_argument(
        "--server-optimizer",
        default="fedavg",
        choices=["fedavg", "fedavgm", "fedadam", "fedyogi"],
        help="FedOpt server optimizer on the round pseudo-gradient "
        "(fed/algorithms.py); fedavg = the reference's plain average",
    )
    p.add_argument("--server-lr", type=float, default=1.0)
    p.add_argument("--server-momentum", type=float, default=0.9)
    p.add_argument(
        "--ckpt-dir",
        default="",
        help="orbax checkpoint directory: saves weights + history + FedOpt "
        "moments at every round boundary; empty disables",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the latest checkpoint under --ckpt-dir at round "
        "r+1 with an identical trajectory (deterministic data path)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="Prometheus /metrics endpoint over the live registry for the "
        "session (driver round wall, staged bytes, leak-sentry "
        "watermarks); 0 disables, -1 binds an ephemeral port",
    )
    p.add_argument(
        "--spans-path",
        default="",
        help="JSONL trace-span sink (driver.round correlation spans); "
        "empty disables",
    )
    args = p.parse_args(argv)

    exporter = None
    if args.metrics_port:
        from fedcrack_tpu.obs.promexp import start_exporter
        from fedcrack_tpu.obs.sentries import LeakSentry

        exporter = start_exporter(args.metrics_port)
        if exporter is not None:
            print(f"metrics: {exporter.url}", flush=True)
            # sample_on_collect: this session has no sampling loop, so each
            # scrape refreshes the reading — a frozen startup RSS would
            # hide any leak the session develops.
            LeakSentry(sample_on_collect=True).mark()
    if args.spans_path:
        from fedcrack_tpu.obs import spans as tracing

        tracing.install(args.spans_path)

    artifact = run_refscale_federation(args)
    if exporter is not None:
        exporter.stop()
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
