"""A/B: the fused Pallas BCE+stats kernel vs plain XLA on the training hot path.

``ops/pallas_bce.py`` claims a fused one-HBM-pass win for the four
loss/metric reductions and auto-selects on TPU backends, but (round-4
verdict, weak #5) no artifact had ever measured it on the chip. This tool
applies the same discipline as the round-3 pool-backward A/B
(BASELINE.md "Pool-backward A/B"): both variants are built in ONE process
— ``FEDCRACK_BCE_IMPL`` pins the impl at trace time — and timed with
chained, host-readback-synced rounds at two scan lengths, with the
variants' timed reps INTERLEAVED (A,B,A,B,...) so tunnel drift hits both
equally. The slope of the two-scan fit is the per-step time; the verdict
(win / wash / loss) goes to BASELINE.md either way.

Run on the TPU:
    python -m fedcrack_tpu.tools.ab_pallas_bce \
        --out bench_runs/r05_pallas_bce_ab.json

CPU smoke (single impl — the Pallas interpreter cannot run inside the
shard_map round program on CPU, and the compiled kernel needs a real TPU;
numerics parity is tests/test_pallas_bce.py's job):
    python -m fedcrack_tpu.tools.ab_pallas_bce --sizes 32 --steps 2 \
        --batch 2 --reps 1 --impls jnp --dtype float32 --out /tmp/ab.json

Artifact schema: ``points[<dtype>_<size>] = {"impls": {<impl>: point...},
"speedup_first_over_second": float?}`` — per-impl dicts under "impls",
derived scalars as sibling keys (never mixed into the impl map). bench.py's
layout A/B reuses this shape.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import numpy as np


def _median_time(fn, reps: int) -> float:
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _make_runner(round_fn, variables, si, sm, active, n_samples):
    """Chained, readback-synced round (same rationale as bench.py: through
    the remote-device tunnel, block_until_ready can return early and
    repeating one identical call lets result caching fake the timing)."""
    state = {"v": variables}

    def run():
        new_vars, metrics = round_fn(state["v"], si, sm, active, n_samples)
        state["v"] = new_vars
        float(np.asarray(metrics["loss"])[0])

    return run


def run_ab(args) -> dict:
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        stack_client_data,
        stage_round_data,
    )
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.train.local import create_train_state

    impls = [s.strip() for s in args.impls.split(",") if s.strip()]
    sizes = [int(s) for s in args.sizes.split(",")]
    mesh = make_mesh(1, 1)
    device = jax.devices()[0]
    active = np.ones(1, np.float32)
    fit = max(2, args.fit_factor)
    prior_impl = os.environ.get("FEDCRACK_BCE_IMPL")

    out: dict = {
        "generated_by": "fedcrack_tpu.tools.ab_pallas_bce",
        "hardware": {
            "platform": device.platform,
            "device_kind": getattr(device, "device_kind", "unknown"),
        },
        "workload": {
            "impls": impls,
            "sizes": sizes,
            "steps": args.steps,
            "batch": args.batch,
            "reps": args.reps,
            "fit_factor": fit,
            "dtype": args.dtype,
        },
        "points": {},
    }

    try:
        for img in sizes:
            config = ModelConfig(img_size=img, compute_dtype=args.dtype)
            state0 = create_train_state(jax.random.key(args.seed), config)
            imgs, msks = synth_crack_batch(
                args.steps * args.batch, img, seed=args.seed
            )
            images, masks = stack_client_data([(imgs, msks)], args.steps, args.batch)
            si, sm = stage_round_data(images, masks, mesh)
            sharding = NamedSharding(mesh, P("clients", None, "batch"))
            tile = jax.jit(
                lambda a: jax.numpy.concatenate([a] * fit, axis=1),
                out_shardings=sharding,
            )
            si_long, sm_long = tile(si), tile(sm)
            jax.block_until_ready((si_long, sm_long))
            n_samp = np.full(1, float(args.steps * args.batch), np.float32)
            n_samp_long = np.full(1, float(fit * args.steps * args.batch), np.float32)

            # Build + warm each impl's round program (env var is read at
            # TRACE time, i.e. during the first call of each signature).
            runners = {}
            for impl in impls:
                os.environ["FEDCRACK_BCE_IMPL"] = impl
                round_fn = build_federated_round(
                    mesh, config, learning_rate=1e-3, local_epochs=1
                )
                short = _make_runner(
                    round_fn, state0.variables, si, sm, active, n_samp
                )
                long = _make_runner(
                    round_fn, state0.variables, si_long, sm_long, active, n_samp_long
                )
                for r in (short, long):
                    r()  # compile (host-pytree signature)
                    r()  # committed-device-input signature the timed reps use
                runners[impl] = (short, long)

            # Interleaved timed reps: one (short, long) pair per impl per
            # pass, so slow tunnel drift is shared across variants.
            shorts = {impl: [] for impl in impls}
            longs = {impl: [] for impl in impls}
            for _ in range(args.reps):
                for impl in impls:
                    shorts[impl].append(_median_time(runners[impl][0], 1))
                for impl in impls:
                    longs[impl].append(_median_time(runners[impl][1], 1))

            flops = train_step_flops(config, args.batch)
            pts = {}
            for impl in impls:
                short_s = float(np.median(shorts[impl]))
                long_s = float(np.median(longs[impl]))
                slope = (long_s - short_s) / ((fit - 1) * args.steps)
                fit_ok = slope > 0.0
                util = mfu(slope, flops, device) if fit_ok else None
                pts[impl] = {
                    "round_s_short": short_s,
                    "round_s_long": long_s,
                    "per_step_ms": round(slope * 1e3, 4) if fit_ok else None,
                    "mfu": None if util is None else round(util, 4),
                }
            # Schema note (ADVICE r5 #3): per-impl point dicts live under
            # "impls"; derived scalars (the speedup) are SIBLING keys, so
            # consumers can iterate points[key]["impls"] with no non-dict
            # special case. bench.py's layout A/B emits the same shape.
            point = {"impls": pts}
            if all(pts[i]["per_step_ms"] is not None for i in impls) and len(impls) == 2:
                a, b = impls
                point["speedup_first_over_second"] = round(
                    pts[b]["per_step_ms"] / pts[a]["per_step_ms"], 4
                )
            out["points"][f"{args.dtype}_{img}"] = point
            del si, sm, si_long, sm_long
    finally:
        if prior_impl is None:
            os.environ.pop("FEDCRACK_BCE_IMPL", None)
        else:
            os.environ["FEDCRACK_BCE_IMPL"] = prior_impl
    return out


def main(argv=None) -> int:
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--impls", default="pallas,jnp")
    p.add_argument("--sizes", default="128,256")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--fit-factor", type=int, default=4)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    artifact = run_ab(args)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    print(json.dumps(artifact["points"]))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
