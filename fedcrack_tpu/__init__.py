"""tpu-fedcrack: a TPU-native federated learning framework for crack segmentation.

Built from scratch in JAX/Flax/XLA with the capabilities of the reference
``MunHyeon-Kim/Crack-Detection-FederatedLearning-gRPC`` (see SURVEY.md):

- ``models``    — residual U-Net (Flax) mirroring the reference architecture
                  (reference: client_fit_model.py:92-150).
- ``ops``       — losses/metrics (sigmoid-BCE, pixel accuracy, IoU) incl. the
                  fused Pallas BCE+stats kernel.
- ``data``      — crack-image input pipeline with host-side prefetch and uint8
                  device staging; synthetic fixtures; IID/non-IID client
                  sharding (reference: client_fit_model.py:19-90).
- ``train``     — jitted local trainer, centralized baseline, BN recalibration.
- ``fed``       — pure federation logic: round state machine, FedAvg/FedProx/
                  FedOpt (FedAvgM, FedAdam, FedYogi), msgpack serialization.
- ``transport`` — asyncio gRPC control plane (enroll/rounds/version/log upload).
- ``parallel``  — the TPU data plane: one-program mesh rounds (shard_map +
                  masked psum FedAvg), intra-client batch DP, spatial context
                  parallelism with halo exchange, multi-host bring-up.
- ``obs``       — structured JSONL metrics, TensorBoard export, FLOPs/MFU.
- ``ckpt``      — orbax checkpoint/resume for the coordinator, plus the
                  mid-round durable statefile (crash-recoverable rounds).
- ``chaos``     — deterministic fault injection for both planes: seeded
                  FaultPlans hooked into the transport client and the mesh
                  driver (tests/test_chaos.py is the scenario suite).
- ``tools``     — Keras h5 weight import, crack quantification, the
                  kill→restart recovery drill (chaos_drill).
- ``native``    — first-party C++ host runtime (resize/binarize, CRC32C).

See SURVEY.md §7 for the full build plan this package follows and PARITY.md
for the reference-component map.
"""

__version__ = "0.1.0"

from fedcrack_tpu.configs import FedConfig, ModelConfig, DataConfig  # noqa: F401
