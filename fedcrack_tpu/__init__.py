"""tpu-fedcrack: a TPU-native federated learning framework for crack segmentation.

Built from scratch in JAX/Flax/XLA with the capabilities of the reference
``MunHyeon-Kim/Crack-Detection-FederatedLearning-gRPC`` (see SURVEY.md):

- ``models``    — residual U-Net (Flax) mirroring the reference architecture
                  (reference: client_fit_model.py:92-150).
- ``ops``       — losses/metrics (sigmoid-BCE, pixel accuracy, IoU).
- ``data``      — crack-image input pipeline with host-side prefetch; synthetic
                  fixtures; IID/non-IID client sharding
                  (reference: client_fit_model.py:19-90).

See SURVEY.md §7 for the full build plan this package follows.
"""

__version__ = "0.1.0"

from fedcrack_tpu.configs import FedConfig, ModelConfig, DataConfig  # noqa: F401
