"""Local training engine: jitted train/eval steps and the per-round fit loop.

This is the TPU-native replacement for the reference's client ML engine
(reference: client_fit_model.py:152-174 ``train_model_tosave``): where the
reference rebuilds and re-compiles a Keras model every round and runs
``model.fit`` with a synchronous cv2 input loop, here the model is built once,
the train step is one jitted XLA program reused across all rounds (weights are
just pytree inputs), and batches stream through the prefetching pipeline.

FedProx (BASELINE.md config 4) is built into the step as a proximal term
``mu/2 * ||params - anchor||^2`` toward the round's global weights; ``mu=0``
recovers plain FedAvg local SGD and costs nothing at runtime. ``mu`` and the
anchor are traced inputs, so switching algorithms never recompiles.
"""

from __future__ import annotations

import functools
from typing import Any, Iterable, Mapping

import jax
import jax.numpy as jnp
import optax
from flax import core, struct

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.pipeline import as_model_batch, normalize_images
from fedcrack_tpu.fed.algorithms import fedprox_penalty
from fedcrack_tpu.models import ResUNet
from fedcrack_tpu.ops.losses import iou_from_counts
from fedcrack_tpu.ops.pallas_bce import fused_segmentation_metrics


class TrainState(struct.PyTreeNode):
    """Carries params + optimizer state + BN batch_stats through jit."""

    step: jax.Array
    params: core.FrozenDict[str, Any]
    batch_stats: core.FrozenDict[str, Any]
    opt_state: optax.OptState
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Any = struct.field(pytree_node=False)

    @property
    def variables(self) -> dict:
        return {"params": self.params, "batch_stats": self.batch_stats}

    def replace_variables(self, variables: Mapping[str, Any]) -> "TrainState":
        """Inject global weights (params + BN stats) received from the server."""
        return self.replace(
            params=variables["params"], batch_stats=variables["batch_stats"]
        )


def make_optimizer(learning_rate: float = 1e-3) -> optax.GradientTransformation:
    """Adam with Keras-default hyperparameters (the reference compiles with
    optimizer="Adam", client_fit_model.py:157). Single source of truth for
    BOTH execution planes — the host/gRPC path here and the one-program mesh
    round in ``fedcrack_tpu.parallel`` must train identically."""
    return optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-7)


def create_train_state(
    rng: jax.Array,
    model_config: ModelConfig | None = None,
    learning_rate: float = 1e-3,
) -> TrainState:
    """Build the model once with the shared optimizer."""
    model_config = model_config or ModelConfig()
    model = ResUNet(config=model_config)
    dummy = jnp.zeros((1, *model_config.input_shape), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    tx = make_optimizer(learning_rate)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=tx.init(variables["params"]),
        tx=tx,
        apply_fn=model.apply,
    )


# NB: no buffer donation — `anchor_params` aliases `state.params` in the
# plain-FedAvg call, and donating aliased inputs is undefined.
@jax.jit
def train_step(
    state: TrainState,
    batch: tuple[jax.Array, jax.Array],
    anchor_params: core.FrozenDict[str, Any],
    mu: jax.Array,
    pos_weight: jax.Array = 1.0,
) -> tuple[TrainState, dict[str, jax.Array]]:
    """One SGD step: BCE + (mu/2)||params - anchor||^2, BN stats updated.

    For plain FedAvg pass ``anchor_params=state.params`` and ``mu=0.0`` —
    same compiled program either way. ``pos_weight`` (traced, default 1 =
    reference parity) up-weights crack pixels against the ~7% foreground
    imbalance. Batches may arrive as uint8 transport bytes (1/4 the
    host->device traffic, ``data.pipeline.as_model_batch``) — the on-device
    normalization reproduces the float32 staging values bit for bit (step
    outputs then differ only by XLA's usual program-to-program
    reduction-order noise). When the model config selects a space-to-depth
    ``stem_layout``, images may additionally arrive pre-packed
    (``data.pipeline.space_to_depth_images``) — the model accepts either
    layout; masks are always full-resolution.
    """
    images, masks = as_model_batch(*batch)

    def loss_fn(params):
        logits, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        # One fused pass for BCE + all statistics (Pallas kernel on TPU,
        # XLA reference elsewhere — ops/pallas_bce.py).
        metrics = fused_segmentation_metrics(logits, masks, pos_weight=pos_weight)
        prox = fedprox_penalty(params, anchor_params, mu)
        return metrics["loss"] + prox, (metrics, mutated["batch_stats"])

    (loss, (metrics, new_stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params
    )
    updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    metrics = dict(metrics)
    metrics["loss"] = loss
    new_state = state.replace(
        step=state.step + 1,
        params=new_params,
        batch_stats=new_stats,
        opt_state=new_opt_state,
    )
    return new_state, metrics


@jax.jit
def eval_step(
    state: TrainState,
    batch: tuple[jax.Array, jax.Array],
    pos_weight: jax.Array = 1.0,
) -> dict[str, jax.Array]:
    """Inference-mode metrics (running BN stats). ``pos_weight`` must match
    the training objective: selecting checkpoints by unweighted val loss
    while training a weighted objective would prefer exactly the
    low-recall models the weighting exists to avoid."""
    images, masks = as_model_batch(*batch)
    logits = state.apply_fn(state.variables, images, train=False)
    return fused_segmentation_metrics(logits, masks, pos_weight=pos_weight)


def evaluate(
    state: TrainState, batches: Iterable, pos_weight: float = 1.0
) -> dict[str, float]:
    """Aggregate metrics over a validation set: loss/acc averaged per batch,
    IoU from summed global counts (exact, shard-composable)."""
    pw_arr = jnp.asarray(pos_weight, jnp.float32)
    n = 0
    loss = acc = inter = union = 0.0
    for batch in batches:
        m = eval_step(state, batch, pw_arr)
        loss += float(m["loss"])
        acc += float(m["pixel_acc"])
        inter += float(m["iou_inter"])
        union += float(m["iou_union"])
        n += 1
    if n == 0:
        raise ValueError("empty evaluation set")
    return {
        "loss": loss / n,
        "pixel_acc": acc / n,
        "iou": float(iou_from_counts(jnp.float32(inter), jnp.float32(union))),
        "num_batches": n,
    }


@functools.lru_cache(maxsize=8)
def _calibration_forward(model_config: ModelConfig):
    """Jitted momentum-0 train-mode forward, cached per model config so
    per-epoch recalibration never re-traces the U-Net."""
    model = ResUNet(config=model_config, bn_momentum=0.0)

    @jax.jit
    def moments_of(params, batch_stats, images):
        _, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            normalize_images(images),
            train=True,
            mutable=["batch_stats"],
        )
        return mutated["batch_stats"]

    return moments_of


def recalibrate_batch_stats(
    state: TrainState,
    batches: Iterable,
    model_config: ModelConfig | None = None,
) -> TrainState:
    """Re-estimate BatchNorm running statistics from data (SWA-style BN
    re-estimation): train-mode forwards with momentum 0 yield each batch's
    exact moments; their average replaces the carried running stats. Uses
    images only — labels never enter the calibration.

    Why this exists: Keras-parity BN momentum is 0.99 (the reference relies
    on the default, client_fit_model.py:92-150), so running stats need
    ~500 steps to converge. The reference trains ~3880 steps per round and
    never notices; a short local fit — or a freshly FedAvg-averaged global
    model, whose running stats are a mixture of clients' — evaluates with
    near-initialization statistics and predicts garbage in inference mode.
    One pass over a calibration set fixes the stats without touching params.
    """
    moments_of = _calibration_forward(model_config or ModelConfig())
    # Datasets advance their shuffle epoch on every iteration; calibration is
    # order-independent and must not perturb the training shuffle sequence
    # (a seeded run has to reproduce bit-for-bit with calibration on or off).
    epoch_snapshot = getattr(batches, "_epoch", None)
    try:
        acc = None
        n = 0
        for images, _ in batches:
            stats = moments_of(state.params, state.batch_stats, jnp.asarray(images))
            acc = (
                stats
                if acc is None
                else jax.tree_util.tree_map(jnp.add, acc, stats)
            )
            n += 1
    finally:
        if epoch_snapshot is not None:
            batches._epoch = epoch_snapshot
    if n == 0:
        raise ValueError("empty calibration set")
    mean_stats = jax.tree_util.tree_map(lambda a: a / n, acc)
    return state.replace(batch_stats=mean_stats)


def local_fit(
    state: TrainState,
    train_batches: Iterable,
    epochs: int,
    mu: float = 0.0,
    anchor_params: core.FrozenDict[str, Any] | None = None,
    prefetch: int = 2,
    pos_weight: float = 1.0,
) -> tuple[TrainState, dict[str, float]]:
    """One federated client's local fit for a round.

    The reference runs ``fit(train_gen, epochs=10, ...)`` per round
    (client_fit_model.py:166). ``train_batches`` is re-iterated per epoch
    (fresh shuffle each time); batches prefetch to device ahead of compute.
    Returns the trained state and mean train metrics of the final epoch.
    """
    from fedcrack_tpu.data.pipeline import device_prefetch

    anchor = anchor_params if anchor_params is not None else state.params
    mu_arr = jnp.asarray(mu, jnp.float32)
    pw_arr = jnp.asarray(pos_weight, jnp.float32)
    last: dict[str, float] = {}
    for _ in range(max(1, epochs)):
        n = 0
        acc: dict[str, float] = {}
        for batch in device_prefetch(train_batches, prefetch):
            state, metrics = train_step(state, batch, anchor, mu_arr, pw_arr)
            n += 1
            for k, v in metrics.items():
                acc[k] = acc.get(k, 0.0) + float(v)
        if n == 0:
            raise ValueError("empty training set")
        last = {k: v / n for k, v in acc.items()}
        last["num_steps"] = n
    return state, last


def count_samples(num_batches: int, batch_size: int) -> int:
    """Sample count used to weight this client in FedAvg."""
    return num_batches * batch_size
