from fedcrack_tpu.train.local import (  # noqa: F401
    TrainState,
    create_train_state,
    eval_step,
    evaluate,
    local_fit,
    recalibrate_batch_stats,
    train_step,
)
