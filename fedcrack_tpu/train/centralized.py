"""Centralized (non-federated) baseline trainer.

Capability parity with the reference's standalone training script
(reference: test/Segmentation.py): train the same U-Net on the full dataset
for N epochs with a held-out validation split, keep the best-val-loss
weights (the reference's ``ModelCheckpoint(save_best_only=True)`` to
``crack_segmentation.h5``, test/Segmentation.py:177-179), and save the final
weights. Checkpoints are msgpack pytrees, not h5/pickle; the h5 importer in
``fedcrack_tpu.tools`` bridges real Keras checkpoints in.
"""

from __future__ import annotations

import os
from typing import Iterable

import jax

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.fed.serialization import tree_to_bytes
from fedcrack_tpu.ioutils import atomic_write_bytes
from fedcrack_tpu.train.local import (
    TrainState,
    create_train_state,
    evaluate,
    local_fit,
    recalibrate_batch_stats,
)


def train_centralized(
    train_batches: Iterable,
    val_batches: Iterable,
    model_config: ModelConfig | None = None,
    epochs: int = 60,
    learning_rate: float = 1e-3,
    out_dir: str | None = None,
    seed: int = 0,
    log_fn=print,
    recalibrate_bn: bool = True,
    pos_weight: float = 1.0,
    metrics=None,
) -> tuple[TrainState, list[dict]]:
    """Returns the final state and per-epoch history; writes
    ``best.msgpack`` (lowest val loss) and ``final.msgpack`` to ``out_dir``.

    ``recalibrate_bn`` re-estimates BatchNorm running statistics from the
    train set before every validation pass and checkpoint (one extra forward
    sweep per epoch): with Keras-parity BN momentum 0.99 the running stats
    need ~500 steps to converge, so short runs would otherwise select the
    "best" checkpoint with near-initialization statistics. The saved
    checkpoints carry the calibrated stats; the in-training running-stat
    dynamics are untouched.
    """
    state = create_train_state(jax.random.key(seed), model_config, learning_rate)
    history: list[dict] = []
    best_loss = float("inf")
    eval_state = state
    for epoch in range(epochs):
        state, train_metrics = local_fit(
            state, train_batches, epochs=1, pos_weight=pos_weight
        )
        eval_state = (
            recalibrate_batch_stats(state, train_batches, model_config)
            if recalibrate_bn
            else state
        )
        # Same objective as training: weighted val loss drives best-checkpoint
        # selection, otherwise pos_weight>1 runs would checkpoint the
        # low-recall model the weighting exists to avoid.
        val_metrics = evaluate(eval_state, val_batches, pos_weight=pos_weight)
        entry = {
            "epoch": epoch,
            **{f"train_{k}": v for k, v in train_metrics.items()},
            **{f"val_{k}": v for k, v in val_metrics.items()},
        }
        history.append(entry)
        if metrics is not None:
            # Structured per-epoch record (JSONL + TB scalars) — the
            # reference's TensorBoard-per-fit workflow
            # (client_fit_model.py:153-154) for the centralized entry point.
            metrics.log("epoch", **entry)
        log_fn(
            f"epoch {epoch}: train_loss={train_metrics['loss']:.4f} "
            f"val_loss={val_metrics['loss']:.4f} val_iou={val_metrics['iou']:.4f}"
        )
        if out_dir and val_metrics["loss"] < best_loss:
            best_loss = val_metrics["loss"]
            _save(eval_state, os.path.join(out_dir, "best.msgpack"))
    if out_dir:
        _save(eval_state, os.path.join(out_dir, "final.msgpack"))
    return eval_state, history


def _build_datasets(args, model_config: ModelConfig):
    """Train/val datasets from real paired dirs or synthetic fixtures,
    preserving the reference's split semantics (held-out validation tail,
    test/Segmentation.py:84-90)."""
    from fedcrack_tpu.data.pipeline import (
        ArrayDataset,
        dataset_from_source,
        reference_split,
    )
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    if args.synthetic:
        if args.synthetic < 2:
            raise SystemExit("--synthetic needs at least 2 samples (train + val)")
        n_val = max(1, args.synthetic // 5)
        images, masks = synth_crack_batch(
            args.synthetic, model_config.img_size, seed=args.seed
        )
        train = ArrayDataset(
            images[n_val:],
            masks[n_val:],
            batch_size=min(args.batch, args.synthetic - n_val),
            seed=args.seed,
        )
        val = ArrayDataset(
            images[:n_val], masks[:n_val], batch_size=min(args.batch, n_val), seed=args.seed
        )
        return train, val
    # Real dirs: the reference's seeded split, val = held-out tail
    # (test/Segmentation.py:84-90). The shared builder clamps batch sizes so
    # a small validation tail still yields batches, and a split side that
    # comes back empty (e.g. a single-pair directory) is a clear startup
    # error rather than a crash.
    def split_side(i):
        def pick(pairs):
            return reference_split(pairs, args.train_samples, args.split_seed)[i]

        return pick

    try:
        train = dataset_from_source(
            0, args.image_dir, args.mask_dir,
            img_size=model_config.img_size, batch_size=args.batch,
            seed=args.seed, pair_filter=split_side(0),
            transport_dtype=args.transport_dtype,
        )
        val = dataset_from_source(
            0, args.image_dir, args.mask_dir,
            img_size=model_config.img_size, batch_size=args.batch,
            seed=args.seed, pair_filter=split_side(1),
            transport_dtype=args.transport_dtype,
        )
    except ValueError as e:
        raise SystemExit(str(e))
    return train, val


def main(argv=None) -> None:
    """``python -m fedcrack_tpu.train.centralized`` — the reference's
    standalone trainer (test/Segmentation.py) as a real CLI."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image-dir")
    p.add_argument("--mask-dir")
    p.add_argument("--synthetic", type=int, default=0, help="use N generated samples")
    p.add_argument("--epochs", type=int, default=60)  # test/Segmentation.py:185
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--img-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument(
        "--pos-weight",
        type=float,
        default=1.0,
        help="crack-pixel BCE weight (>1 counters foreground imbalance; "
        "1 = the reference's plain BCE)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--transport-dtype",
        choices=("uint8", "float32"),
        default="uint8",
        help="host->device staging dtype for file datasets; uint8 ships 1/4 "
        "the bytes and is bit-identical (normalization happens on device)",
    )
    p.add_argument("--train-samples", type=int, default=6213)
    p.add_argument("--split-seed", type=int, default=1337)
    p.add_argument("--out-dir", default="centralized_out")
    p.add_argument(
        "--metrics", dest="metrics_path", help="JSONL file for per-epoch metrics"
    )
    p.add_argument(
        "--tb-dir",
        dest="tb_dir",
        help="TensorBoard event-file directory for per-epoch scalars (the "
        "reference's TB-per-fit workflow, client_fit_model.py:153-154)",
    )
    args = p.parse_args(argv)

    metrics = None
    if args.metrics_path or args.tb_dir:
        from fedcrack_tpu.obs import MetricsLogger

        metrics = MetricsLogger(
            args.metrics_path or os.devnull, tb_dir=args.tb_dir or None
        )
    model_config = ModelConfig(img_size=args.img_size)
    train, val = _build_datasets(args, model_config)
    _, history = train_centralized(
        train,
        val,
        model_config=model_config,
        epochs=args.epochs,
        learning_rate=args.lr,
        out_dir=args.out_dir,
        seed=args.seed,
        pos_weight=args.pos_weight,
        metrics=metrics,
    )
    best = min(h["val_loss"] for h in history)
    print(f"done: {len(history)} epochs, best val_loss={best:.4f} -> {args.out_dir}")


def _save(state: TrainState, path: str) -> None:
    atomic_write_bytes(path, tree_to_bytes(state.variables))


if __name__ == "__main__":
    main()
