"""Centralized (non-federated) baseline trainer.

Capability parity with the reference's standalone training script
(reference: test/Segmentation.py): train the same U-Net on the full dataset
for N epochs with a held-out validation split, keep the best-val-loss
weights (the reference's ``ModelCheckpoint(save_best_only=True)`` to
``crack_segmentation.h5``, test/Segmentation.py:177-179), and save the final
weights. Checkpoints are msgpack pytrees, not h5/pickle; the h5 importer in
``fedcrack_tpu.tools`` bridges real Keras checkpoints in.
"""

from __future__ import annotations

import os
from typing import Iterable

import jax

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.fed.serialization import tree_to_bytes
from fedcrack_tpu.train.local import TrainState, create_train_state, evaluate, local_fit


def train_centralized(
    train_batches: Iterable,
    val_batches: Iterable,
    model_config: ModelConfig | None = None,
    epochs: int = 60,
    learning_rate: float = 1e-3,
    out_dir: str | None = None,
    seed: int = 0,
    log_fn=print,
) -> tuple[TrainState, list[dict]]:
    """Returns the final state and per-epoch history; writes
    ``best.msgpack`` (lowest val loss) and ``final.msgpack`` to ``out_dir``.
    """
    state = create_train_state(jax.random.key(seed), model_config, learning_rate)
    history: list[dict] = []
    best_loss = float("inf")
    for epoch in range(epochs):
        state, train_metrics = local_fit(state, train_batches, epochs=1)
        val_metrics = evaluate(state, val_batches)
        entry = {
            "epoch": epoch,
            **{f"train_{k}": v for k, v in train_metrics.items()},
            **{f"val_{k}": v for k, v in val_metrics.items()},
        }
        history.append(entry)
        log_fn(
            f"epoch {epoch}: train_loss={train_metrics['loss']:.4f} "
            f"val_loss={val_metrics['loss']:.4f} val_iou={val_metrics['iou']:.4f}"
        )
        if out_dir and val_metrics["loss"] < best_loss:
            best_loss = val_metrics["loss"]
            _save(state, os.path.join(out_dir, "best.msgpack"))
    if out_dir:
        _save(state, os.path.join(out_dir, "final.msgpack"))
    return state, history


def _save(state: TrainState, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(tree_to_bytes(state.variables))
