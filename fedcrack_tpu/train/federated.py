"""Bridges the local TPU trainer into the federated client driver.

``make_train_fn`` adapts serialized weight blobs (the control plane's
currency) to :class:`TrainState` (the jitted trainer's currency): inject the
round's global weights, reset the optimizer (the reference rebuilds and
recompiles the whole Keras model every round, client_fit_model.py:155-157 —
here only the Adam moments reset and the compiled step is reused), run
``local_epochs`` of SGD, and hand back the trained variables + sample count
for FedAvg weighting.
"""

from __future__ import annotations

from typing import Iterable

import jax

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.train.local import (
    TrainState,
    create_train_state,
    local_fit,
    make_optimizer,
)


def reset_optimizer(state: TrainState) -> TrainState:
    """Fresh Adam moments for a new round's local fit."""
    return state.replace(opt_state=state.tx.init(state.params))


def make_train_fn(
    config: FedConfig,
    dataset: Iterable,
    batch_size: int,
    seed: int = 0,
    metrics_logger=None,
):
    """Returns ``train_fn(blob, round) -> (blob, sample_count, metrics)`` plus
    a handle to read the latest :class:`TrainState` (for final-round
    prediction).

    When ``config.profile_dir`` is set each round's local fit is wrapped in a
    ``jax.profiler`` trace; ``metrics_logger`` (an ``obs.MetricsLogger``)
    receives one structured ``local_fit`` record per round.
    """
    from fedcrack_tpu.obs import profiler_trace, stopwatch

    state = create_train_state(
        jax.random.key(seed), config.model, config.learning_rate
    )
    template = state.variables
    holder = {"state": state, "learning_rate": config.learning_rate}

    def train_fn(
        blob: bytes, rnd: int, hparams: dict | None = None
    ) -> tuple[bytes, int, dict[str, float]]:
        # The server's in-band hyperparameters (enroll handshake) override
        # the client-side defaults — one coordinator configures the cohort.
        hparams = hparams or {}
        epochs = int(hparams.get("local_epochs", config.local_epochs))
        mu = float(hparams.get("fedprox_mu", config.fedprox_mu))
        pos_weight = float(hparams.get("pos_weight", config.pos_weight))
        lr = float(hparams.get("learning_rate", config.learning_rate))
        wire_dtype = str(hparams.get("wire_dtype", config.wire_dtype))
        variables = tree_from_bytes(blob, template=template)
        st = holder["state"].replace_variables(variables)
        if lr != holder["learning_rate"]:
            st = st.replace(tx=make_optimizer(lr))
            holder["learning_rate"] = lr
        st = reset_optimizer(st)
        with profiler_trace(config.profile_dir or None), stopwatch() as timer:
            st, metrics = local_fit(
                st,
                dataset,
                epochs=epochs,
                mu=mu,
                anchor_params=st.params,
                pos_weight=pos_weight,
            )
        holder["state"] = st
        n_samples = int(metrics.pop("num_steps", 0) * batch_size)
        out_blob = tree_to_bytes(
            st.variables,
            cast_dtype="bfloat16" if wire_dtype == "bfloat16" else None,
        )
        if metrics_logger is not None:
            metrics_logger.log(
                "local_fit",
                round=rnd,
                wall_clock_s=timer["seconds"],
                num_samples=n_samples,
                bytes_in=len(blob),
                bytes_out=len(out_blob),
                **metrics,
            )
            if metrics_logger.tb_enabled:
                # Per-round weight + round-update distributions as TB
                # histograms (the reference's histogram_freq=1 callback,
                # client_fit_model.py:153-154); the update tree — trained
                # minus received params — is the round's pseudo-gradient.
                metrics_logger.log_histograms(rnd, st.params, prefix="weights")
                update = jax.tree.map(
                    lambda a, b: a - b, st.params, variables["params"]
                )
                metrics_logger.log_histograms(rnd, update, prefix="round_update")
        return out_blob, n_samples, metrics

    return train_fn, holder
