"""The privacy plane (round 23): the third trust layer.

The r18 health plane answered "who is lying to the federation"
(detection), r21's aggregation algebra answered "keep the liar's update
out" (response). This package answers the opposite question — what can
the FEDERATION learn about an honest client:

- :mod:`fedcrack_tpu.privacy.dpsgd` — differentially-private training:
  per-client gradient clipping plus seeded Gaussian noise, wired into the
  mesh plane's ``sgd_step`` closure (Abadi et al. 2016) and, update-level,
  into the gRPC client CLI (McMahan et al. 2018).
- :mod:`fedcrack_tpu.privacy.accountant` — the RDP/moments accountant
  that converts (noise multiplier, sampling rate, steps) into a
  cumulative per-client ε(δ), recorded in round history and persisted in
  the r8 statefile.
- :mod:`fedcrack_tpu.privacy.secagg` — pairwise-mask secure aggregation
  on the gRPC plane (Bonawitz et al. 2017): fixed-point int64 modular
  encoding with pairwise PRG masks that cancel EXACTLY in the r21
  ordered fold, and a seed-recovery step so a round still closes when a
  masker drops out.

Composition is deliberately restricted where the layers conflict: masked
updates are opaque to the r18 ledger's norm/cosine windows, so secagg
mode refuses robust combines and quarantine at config-validation time —
the privacy/robustness trade-off is a loud error, not a silent downgrade.
"""

from fedcrack_tpu.privacy.accountant import (  # noqa: F401
    DEFAULT_ORDERS,
    PrivacyAccountant,
    compute_epsilon,
    rdp_subsampled_gaussian,
    rdp_to_epsilon,
)
from fedcrack_tpu.privacy.secagg import (  # noqa: F401
    SECAGG_MAGIC,
    client_seed,
    decode_masked,
    fixed_point_encode,
    is_masked_blob,
    mask_update,
    pair_mask,
    round_roster,
    unmask_sum,
    unmasked_mean,
    validate_masked,
)
