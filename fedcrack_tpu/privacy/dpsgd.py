"""DP-SGD primitives: per-client clipping + seeded Gaussian noise.

Two consumers, one math:

- The MESH plane (``parallel/fedavg_mesh.py``) calls the traced
  :func:`dp_grad_transform` inside its ``sgd_step`` closure — per-step
  gradient clipping to L2 norm ``C`` and ``N(0, (sigma*C)^2)`` noise, the
  Abadi et al. 2016 recipe at client granularity (the mesh's "example" is
  one client's mini-batch gradient; the accountant's q is the batch
  sampling rate). Noise is keyed per ``(client, round, step, leaf)``
  through the fold-in chain below, so a chaos-replayed round (the r12
  codec-seed precedent: the driver restores the round counter via
  ``codec_state``) reproduces bit-identical noise.
- The gRPC client CLI applies the UPDATE-level variant
  (:func:`dp_update_host`, McMahan et al. 2018 "Learning Differentially
  Private Recurrent Language Models"): clip the whole round's delta
  ``trained - base`` to ``C`` and add one noise draw, on the host in
  numpy, seeded from ``(dp_seed, cname, round)`` so retries replay
  byte-identically.

Every random draw in this module derives from an explicit seed — fedlint
PRIV001 makes any other RNG inside ``fedcrack_tpu/privacy/`` an ERROR.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# Guards the clip-factor division when a gradient is exactly zero; far
# below any float32 gradient norm the clip could meaningfully scale.
NORM_EPS = 1e-12


def global_l2_norm(tree: Any) -> jax.Array:
    """The L2 norm over every leaf of ``tree``, accumulated in float32."""
    leaves = jax.tree_util.tree_leaves(tree)
    total = sum(
        jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves
    )
    return jnp.sqrt(total)


def clip_by_global_norm(tree: Any, clip_norm: float) -> tuple[Any, jax.Array]:
    """Scale ``tree`` by ``min(1, C / (||tree||_2 + eps))`` — the DP-SGD
    clip. Returns ``(clipped_tree, factor)``; a tree already inside the
    ball passes through scaled by a factor numerically ~1."""
    norm = global_l2_norm(tree)
    factor = jnp.minimum(1.0, clip_norm / (norm + NORM_EPS)).astype(
        jnp.float32
    )
    clipped = jax.tree_util.tree_map(
        lambda leaf: (leaf.astype(jnp.float32) * factor).astype(leaf.dtype),
        tree,
    )
    return clipped, factor


def add_gaussian_noise(tree: Any, key: jax.Array, stddev: float) -> Any:
    """Add ``N(0, stddev^2)`` noise per leaf, one subkey per leaf in
    flatten order — the deterministic leaf axis of the seed tree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))
    noised = [
        (
            leaf.astype(jnp.float32)
            + stddev * jax.random.normal(k, jnp.shape(leaf), jnp.float32)
        ).astype(leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def dp_grad_transform(
    grads: Any,
    key: jax.Array,
    clip_norm: float,
    noise_multiplier: float,
) -> Any:
    """The per-step DP-SGD transform: clip to ``clip_norm``, then (when
    ``noise_multiplier > 0``) add ``N(0, (noise_multiplier*clip_norm)^2)``.
    ``key`` must already encode (client, round, step) — the caller owns
    the fold-in chain; this function owns only the per-leaf split."""
    clipped, _ = clip_by_global_norm(grads, clip_norm)
    if noise_multiplier <= 0.0:
        return clipped
    return add_gaussian_noise(
        clipped, key, float(noise_multiplier) * float(clip_norm)
    )


def dp_step_key(
    dp_seed: int, round_seed: jax.Array, client_index: jax.Array, step: jax.Array
) -> jax.Array:
    """The (client, round, step) key chain. ``dp_seed`` is the static
    config knob (trace-time constant), ``round_seed`` the per-dispatch
    replicated scalar the r12 int8 codec already threads (restored on
    replay via ``codec_state``), ``client_index`` the in-mesh
    ``lax.axis_index``, ``step`` the scan's step counter."""
    key = jax.random.PRNGKey(jnp.uint32(dp_seed))
    key = jax.random.fold_in(key, jnp.uint32(round_seed))
    key = jax.random.fold_in(key, jnp.uint32(client_index))
    return jax.random.fold_in(key, jnp.uint32(step))


# -- host-side (gRPC client CLI) update-level DP ---------------------------


def _host_seed(dp_seed: int, cname: str, round_idx: int) -> int:
    """A 64-bit seed from sha256 of (dp_seed, cname, round) — stable
    across processes and platforms, unlike Python's hash()."""
    digest = hashlib.sha256(
        f"fedcrack-dp:{int(dp_seed)}:{cname}:{int(round_idx)}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big")


def dp_update_host(
    trained: Any,
    base: Any,
    *,
    clip_norm: float,
    noise_multiplier: float,
    dp_seed: int,
    cname: str,
    round_idx: int,
) -> Any:
    """Update-level DP on the host: clip ``trained - base`` to
    ``clip_norm`` and add one seeded Gaussian draw, returning the new
    trained tree ``base + clipped_delta + noise``. numpy throughout —
    the CLI client has no reason to trace this."""
    t_leaves, treedef = jax.tree_util.tree_flatten(trained)
    b_leaves = jax.tree_util.tree_leaves(base)
    if len(t_leaves) != len(b_leaves):
        raise ValueError(
            f"trained/base leaf mismatch: {len(t_leaves)} vs {len(b_leaves)}"
        )
    deltas = [
        np.asarray(t, np.float32) - np.asarray(b, np.float32)
        for t, b in zip(t_leaves, b_leaves)
    ]
    norm = float(np.sqrt(sum(float(np.sum(d * d)) for d in deltas)))
    factor = min(1.0, float(clip_norm) / (norm + NORM_EPS))
    rng = np.random.Generator(
        np.random.Philox(key=_host_seed(dp_seed, cname, round_idx))
    )
    stddev = float(noise_multiplier) * float(clip_norm)
    out = []
    for b, t, d in zip(b_leaves, t_leaves, deltas):
        new = np.asarray(b, np.float32) + d * np.float32(factor)
        if stddev > 0.0:
            new = new + rng.normal(0.0, stddev, size=new.shape).astype(
                np.float32
            )
        out.append(new.astype(np.asarray(t).dtype))
    return jax.tree_util.tree_unflatten(treedef, out)
