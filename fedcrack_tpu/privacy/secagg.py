"""Pairwise-mask secure aggregation (Bonawitz et al. 2017, CCS).

The mechanics of the practical secure-aggregation protocol, on the gRPC
rounds plane:

- Updates are encoded in FIXED-POINT int64 — ``round(x * 2^bits)`` — and
  all arithmetic is modular over 2^64 (numpy uint64 wraparound is exactly
  the two's-complement residue ring), so pairwise masks cancel EXACTLY:
  integer cancellation, not float cancellation, which is what lets the
  drill pin the unmasked cohort sum bit-for-bit against the plaintext
  fixed-point sum.
- Each client ``i`` uploads ``n_i * fp(x_i) + sum_{j != i} s_ij * PRG(
  pair_seed(i, j))`` where ``s_ij = +1`` if ``i`` sorts before ``j`` else
  ``-1``. Summed over the full cohort the masks telescope to zero and the
  server is left with the weighted fixed-point sum, which it divides by
  ``sum n_i`` to get the FedAvg mean.
- Dropout (the Bonawitz recovery round): a masker that uploaded nothing
  leaves every survivor's pairwise mask against it uncancelled. The
  server reconstructs those masks from the per-client seeds exchanged at
  enroll and subtracts them — the "seed-recovery step" — so the round
  closes with K of N maskers under the r8 quorum machinery.

SCOPE, stated loudly: per-client seeds are exchanged with the SERVER at
enroll in-band (no Diffie-Hellman key agreement, no Shamir shares), so
this protects updates from OTHER CLIENTS and from the wire, not from an
honest-but-curious server — the full Bonawitz protocol's threat model
needs the key-agreement and secret-sharing rounds this repo does not
carry. What IS faithfully reproduced is the aggregation math: exact
modular cancellation, weighted fixed-point averaging, and dropout
recovery, all of it drill-pinned.

Every mask derives from an explicit sha256-rooted seed — fedlint PRIV001
makes any other RNG in this package an ERROR.
"""

from __future__ import annotations

import hashlib
from typing import Any, Iterable, Mapping

import jax
import numpy as np

# Wire magic for a masked upload; the decode gate branches on it.
SECAGG_MAGIC = b"FSA1"

# Fixed-point fractional bits default; 2^24 keeps |x| < 2^39 exact per
# client in int64 headroom for cohort sums.
DEFAULT_BITS = 24

_U64 = np.uint64
_FULL64 = np.iinfo(np.uint64).max


def client_seed(cname: str, nonce: int = 0) -> int:
    """The per-client masking seed exchanged at enroll: sha256 of the
    client name (+ an optional nonce), truncated to 63 bits — it rides the
    proto's SIGNED int64 Scalar, so the top bit stays clear. Deterministic
    so chaos replays and kill-restart drills reproduce identical masks."""
    digest = hashlib.sha256(
        f"fedcrack-secagg-client:{cname}:{int(nonce)}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big") >> 1


def pair_seed(name_a: str, seed_a: int, name_b: str, seed_b: int) -> int:
    """The per-pair PRG seed, symmetric in its arguments: both ends of a
    pair derive the same value regardless of call order."""
    (n1, s1), (n2, s2) = sorted(((name_a, seed_a), (name_b, seed_b)))
    digest = hashlib.sha256(
        f"fedcrack-secagg-pair:{n1}:{int(s1)}:{n2}:{int(s2)}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big")


def round_roster(roster: Mapping[str, int], round_idx: int) -> dict[str, int]:
    """Mix the round index into every seed of an enroll-time roster, so the
    pairwise masks of round R and round R+1 are independent streams (mask
    reuse across rounds would turn the one-time pads into a difference
    leak). Both ends derive it from the same enroll roster + the round
    number already in the protocol — nothing extra crosses the wire."""
    return {
        name: int.from_bytes(
            hashlib.sha256(
                f"fedcrack-secagg-round:{int(seed)}:{int(round_idx)}".encode()
            ).digest()[:8],
            "big",
        )
        for name, seed in roster.items()
    }


def pair_mask(seed: int, shapes: Iterable[tuple]) -> list[np.ndarray]:
    """The pairwise mask: one uint64 array per leaf shape, drawn from a
    Philox stream keyed on the pair seed (counter-based, platform-stable)."""
    rng = np.random.Generator(np.random.Philox(key=int(seed)))
    return [
        rng.integers(0, _FULL64, size=shape, dtype=_U64, endpoint=True)
        for shape in shapes
    ]


def fixed_point_encode(tree: Any, bits: int = DEFAULT_BITS) -> list[np.ndarray]:
    """Per-leaf ``round(x * 2^bits)`` as uint64 residues (two's-complement
    view of the signed fixed-point value), in flatten order."""
    scale = float(1 << int(bits))
    return [
        np.round(np.asarray(leaf, np.float64) * scale)
        .astype(np.int64)
        .view(_U64)
        for leaf in jax.tree_util.tree_leaves(tree)
    ]


def fixed_point_decode(
    leaves: Iterable[np.ndarray], divisor: int, bits: int, template: Any
) -> Any:
    """Back to float: interpret each uint64 residue as signed int64, scale
    down by ``2^bits * divisor``, restore template structure/dtypes."""
    scale = float(1 << int(bits)) * float(divisor)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    out = [
        (np.asarray(leaf, _U64).view(np.int64).astype(np.float64) / scale)
        .astype(np.asarray(t).dtype)
        .reshape(np.shape(t))
        for leaf, t in zip(leaves, t_leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def weighted_fixed_sum(
    trees: Iterable[Any], samples: Iterable[int], bits: int = DEFAULT_BITS
) -> list[np.ndarray]:
    """The PLAINTEXT ``sum n_i * fp(x_i)`` in the residue ring — what the
    unmasked cohort sum must equal bit-for-bit (the drill's pin)."""
    total: list[np.ndarray] | None = None
    for tree, ns in zip(trees, samples):
        scaled = [leaf * _U64(int(ns)) for leaf in fixed_point_encode(tree, bits)]
        total = (
            scaled
            if total is None
            else [a + b for a, b in zip(total, scaled)]
        )
    if total is None:
        raise ValueError("weighted_fixed_sum over zero trees")
    return total


def mask_update(
    tree: Any,
    *,
    cname: str,
    n_samples: int,
    roster: Mapping[str, int],
    bits: int = DEFAULT_BITS,
) -> bytes:
    """Encode + mask one client's update for the wire.

    ``roster`` is the closed cohort's ``{name: seed}`` map (self
    included). The blob records the cohort it was masked against so the
    server can refuse a stale-roster upload instead of corrupting sums.
    """
    from flax import serialization

    if cname not in roster:
        raise ValueError(f"{cname!r} not in the masking roster")
    if int(n_samples) <= 0:
        raise ValueError(f"n_samples must be positive, got {n_samples}")
    leaves = [
        leaf * _U64(int(n_samples)) for leaf in fixed_point_encode(tree, bits)
    ]
    shapes = [leaf.shape for leaf in leaves]
    for other in sorted(roster):
        if other == cname:
            continue
        mask = pair_mask(
            pair_seed(cname, roster[cname], other, roster[other]), shapes
        )
        if cname < other:
            leaves = [a + m for a, m in zip(leaves, mask)]
        else:
            leaves = [a - m for a, m in zip(leaves, mask)]
    payload = serialization.msgpack_serialize(
        {
            "bits": int(bits),
            "n": int(n_samples),
            "cohort": list(sorted(roster)),
            "leaves": list(leaves),
        }
    )
    return SECAGG_MAGIC + payload


def is_masked_blob(blob: bytes) -> bool:
    return isinstance(blob, (bytes, bytearray)) and bytes(
        blob[: len(SECAGG_MAGIC)]
    ) == SECAGG_MAGIC


def decode_masked(blob: bytes) -> dict:
    """Parse a masked upload; raises ValueError on anything malformed."""
    from flax import serialization

    if not is_masked_blob(blob):
        raise ValueError("not a secagg masked blob (bad magic)")
    try:
        doc = serialization.msgpack_restore(bytes(blob[len(SECAGG_MAGIC):]))
    except Exception as e:  # msgpack raises several exception families
        raise ValueError(f"undecodable masked payload ({type(e).__name__})")
    if not isinstance(doc, dict) or not {"bits", "n", "cohort", "leaves"} <= set(doc):
        raise ValueError("masked payload missing required fields")
    return doc


def validate_masked(
    blob: bytes, template: Any, *, bits: int, cohort: Iterable[str]
) -> str | None:
    """The secagg arm of THE acceptance gate: the reason this masked blob
    must not enter the fold, or None. Masked residues are uniformly random
    by construction, so there is no norm/finiteness to check — the
    contract is structural: magic, fixed-point bits, the EXACT cohort the
    server closed, and leaf count/shape/dtype against the template."""
    try:
        doc = decode_masked(blob)
    except ValueError as e:
        return str(e)
    if int(doc["bits"]) != int(bits):
        return f"fixed-point bits mismatch: blob {doc['bits']}, server {bits}"
    want = sorted(cohort)
    got = [str(c) for c in doc["cohort"]]
    if got != want:
        return f"mask roster mismatch: blob {got}, cohort {want}"
    t_leaves = jax.tree_util.tree_leaves(template)
    leaves = doc["leaves"]
    if len(leaves) != len(t_leaves):
        return (
            f"leaf count mismatch: payload has {len(leaves)}, "
            f"template expects {len(t_leaves)}"
        )
    for i, (leaf, t) in enumerate(zip(leaves, t_leaves)):
        arr = np.asarray(leaf)
        if arr.dtype != np.uint64:
            return f"leaf {i} is {arr.dtype}, wants uint64 residues"
        if arr.shape != np.shape(np.asarray(t)):
            return (
                f"leaf {i} shape mismatch: payload {arr.shape}, "
                f"template {np.shape(np.asarray(t))}"
            )
    return None


def unmask_sum(
    uploads: Mapping[str, dict],
    roster: Mapping[str, int],
    bits: int = DEFAULT_BITS,
) -> tuple[list[np.ndarray], int, list[str]]:
    """The server's fold + seed-recovery step.

    ``uploads`` maps each SURVIVING masker to its decoded blob
    (:func:`decode_masked`); ``roster`` is the full cohort's seed map.
    Survivors' residues are summed in sorted-name order (the r21 ordered-
    fold discipline — uint64 addition is associative-exact, the order
    pins the expression anyway), then every (survivor, dropped) pairwise
    mask is reconstructed from seeds and subtracted. Returns ``(sum
    leaves, total samples, recovered drop-out names)``."""
    survivors = sorted(uploads)
    if not survivors:
        raise ValueError("secagg fold over zero uploads")
    dropped = sorted(set(roster) - set(survivors))
    unknown = sorted(set(survivors) - set(roster))
    if unknown:
        raise ValueError(f"uploads from outside the roster: {unknown}")
    total: list[np.ndarray] | None = None
    total_samples = 0
    for name in survivors:
        doc = uploads[name]
        leaves = [np.asarray(leaf, _U64) for leaf in doc["leaves"]]
        total = (
            leaves if total is None else [a + b for a, b in zip(total, leaves)]
        )
        total_samples += int(doc["n"])
    shapes = [leaf.shape for leaf in total]
    for d in dropped:
        for s in survivors:
            mask = pair_mask(
                pair_seed(s, roster[s], d, roster[d]), shapes
            )
            if s < d:  # s added +mask for the pair; take it back out
                total = [a - m for a, m in zip(total, mask)]
            else:
                total = [a + m for a, m in zip(total, mask)]
    return total, total_samples, dropped


def unmasked_mean(
    total_leaves: Iterable[np.ndarray],
    total_samples: int,
    template: Any,
    bits: int = DEFAULT_BITS,
) -> Any:
    """The FedAvg mean from the unmasked weighted sum."""
    if int(total_samples) <= 0:
        raise ValueError(f"total_samples must be positive, got {total_samples}")
    return fixed_point_decode(total_leaves, int(total_samples), bits, template)
