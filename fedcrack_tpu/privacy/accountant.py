"""RDP/moments accountant for DP-SGD (Abadi et al. 2016; Mironov 2017).

The mechanism being accounted is the subsampled Gaussian: each step, a
q-fraction sample of the data contributes a gradient clipped to L2 norm
``C`` with ``N(0, (sigma * C)^2)`` noise added. Composition is tracked in
Renyi differential privacy — additive across steps at each order — and
converted to an ``(epsilon, delta)`` guarantee at report time:

    eps(delta) = min over orders a of  T * RDP(a) + log(1/delta) / (a - 1)

For integer orders the subsampled-Gaussian RDP has the closed form
(Mironov/Talwar/Zhang 2019, eq. 6; the same bound the moments accountant
of Abadi et al. 2016 computes numerically):

    RDP(a) = 1/(a-1) * log( sum_{k=0}^{a} C(a,k) (1-q)^(a-k) q^k
                            * exp(k(k-1) / (2 sigma^2)) )

evaluated in log space (lgamma binomials + logsumexp) so large orders do
not overflow. ``q = 1`` collapses to the plain Gaussian mechanism's
``RDP(a) = a / (2 sigma^2)`` and ``q = 0`` to zero cost.

:class:`PrivacyAccountant` is the stateful per-client ledger the fed
plane drives: epsilon is a pure function of the per-client STEP COUNT
(the only state), so persistence — the r8 statefile rides ``to_wire()``
/ ``from_wire()`` — is a sorted ``[name, steps]`` list and a restart
recomputes identical epsilons bit-for-bit.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence

# Integer Renyi orders the closed form is evaluated at. Dense where the
# optimum usually lands for federation-scale (q, sigma), sparse above.
DEFAULT_ORDERS: tuple[int, ...] = tuple(range(2, 65)) + (80, 96, 128, 256, 512)


def _log_binom(a: int, k: int) -> float:
    return math.lgamma(a + 1) - math.lgamma(k + 1) - math.lgamma(a - k + 1)


def _logsumexp(xs: Sequence[float]) -> float:
    m = max(xs)
    if m == -math.inf:
        return -math.inf
    return m + math.log(sum(math.exp(x - m) for x in xs))


def rdp_subsampled_gaussian(
    q: float,
    noise_multiplier: float,
    steps: int = 1,
    orders: Sequence[int] = DEFAULT_ORDERS,
) -> tuple[float, ...]:
    """RDP of ``steps`` compositions of the subsampled Gaussian at each
    integer order. ``q`` is the per-step sampling rate, ``noise_multiplier``
    the noise-to-clip ratio sigma."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate q must be in [0, 1], got {q}")
    if noise_multiplier <= 0.0:
        raise ValueError(
            f"noise_multiplier must be > 0 to account, got {noise_multiplier}"
        )
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    sigma2 = float(noise_multiplier) ** 2
    out = []
    for a in orders:
        if a < 2 or a != int(a):
            raise ValueError(f"orders must be integers >= 2, got {a}")
        if q == 0.0:
            out.append(0.0)
        elif q == 1.0:
            out.append(steps * a / (2.0 * sigma2))
        else:
            terms = [
                _log_binom(a, k)
                + (a - k) * math.log1p(-q)
                + (k * math.log(q) if k else 0.0)
                + k * (k - 1) / (2.0 * sigma2)
                for k in range(a + 1)
            ]
            out.append(steps * _logsumexp(terms) / (a - 1))
    return tuple(out)


def rdp_to_epsilon(
    rdp: Sequence[float], orders: Sequence[int], delta: float
) -> tuple[float, int]:
    """The standard RDP -> (eps, delta) conversion (Mironov 2017, prop. 3):
    ``eps = min_a [rdp(a) + log(1/delta)/(a-1)]``. Returns ``(eps, order)``
    — the order is recorded so artifacts show where the minimum landed."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    if len(rdp) != len(orders):
        raise ValueError("rdp/orders length mismatch")
    log_inv_delta = math.log(1.0 / delta)
    best = min(
        ((r + log_inv_delta / (a - 1), a) for r, a in zip(rdp, orders)),
        key=lambda t: t[0],
    )
    return best


def compute_epsilon(
    q: float,
    noise_multiplier: float,
    steps: int,
    delta: float,
    orders: Sequence[int] = DEFAULT_ORDERS,
) -> float:
    """One-shot eps(delta) after ``steps`` subsampled-Gaussian steps."""
    if steps == 0:
        return 0.0
    rdp = rdp_subsampled_gaussian(q, noise_multiplier, steps, orders)
    return rdp_to_epsilon(rdp, orders, delta)[0]


class PrivacyAccountant:
    """Per-client cumulative privacy loss for one federation.

    The only mutable state is ``steps[name]`` — how many noise additions
    that client's data has been through — because epsilon is a pure
    function of (q, sigma, delta, steps). The per-step RDP vector is
    precomputed once; ``epsilon_of`` is a cheap min over orders, so the
    fed plane can record epsilons into EVERY round-history entry."""

    def __init__(
        self,
        noise_multiplier: float,
        sample_rate: float,
        delta: float = 1e-5,
        orders: Sequence[int] = DEFAULT_ORDERS,
    ):
        if not 0.0 < delta < 1.0:
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        self.noise_multiplier = float(noise_multiplier)
        self.sample_rate = float(sample_rate)
        self.delta = float(delta)
        self.orders = tuple(int(a) for a in orders)
        self._rdp_step = rdp_subsampled_gaussian(
            self.sample_rate, self.noise_multiplier, 1, self.orders
        )
        self.steps: dict[str, int] = {}

    def record(self, clients: Iterable[str], steps: int = 1) -> None:
        """Charge ``steps`` compositions to each named client."""
        if steps < 0:
            raise ValueError(f"steps must be >= 0, got {steps}")
        for name in clients:
            self.steps[name] = self.steps.get(name, 0) + int(steps)

    def epsilon_of(self, name: str) -> float:
        t = self.steps.get(name, 0)
        if t == 0:
            return 0.0
        rdp = [r * t for r in self._rdp_step]
        return rdp_to_epsilon(rdp, self.orders, self.delta)[0]

    def epsilons(self) -> dict[str, float]:
        """``{name: eps}`` over every charged client, sorted by name."""
        return {n: self.epsilon_of(n) for n in sorted(self.steps)}

    def max_epsilon(self) -> float:
        return max((self.epsilon_of(n) for n in self.steps), default=0.0)

    def summary(self) -> dict:
        """The artifact block health_report joins: parameters + per-client
        steps/epsilon, deterministic (sorted, rounded)."""
        return {
            "noise_multiplier": self.noise_multiplier,
            "sample_rate": self.sample_rate,
            "delta": self.delta,
            "clients": {
                n: {
                    "steps": self.steps[n],
                    "epsilon": round(self.epsilon_of(n), 6),
                }
                for n in sorted(self.steps)
            },
            "max_epsilon": round(self.max_epsilon(), 6),
        }

    # -- statefile carriage (the r8 additive-key discipline) --

    def to_wire(self) -> list:
        """Sorted ``[name, steps]`` rows — epsilon is recomputed, never
        persisted, so the snapshot cannot disagree with the math."""
        return [[n, int(self.steps[n])] for n in sorted(self.steps)]

    def load_wire(self, rows: Iterable) -> None:
        self.steps = {str(n): int(t) for n, t in rows}
