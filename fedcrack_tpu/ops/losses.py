"""Losses and quality metrics for binary crack segmentation.

The reference trains with Keras ``binary_crossentropy`` on sigmoid outputs and
tracks pixel ``accuracy`` only (reference: client_fit_model.py:157,
test/Segmentation.py:183). Here the loss is computed from **logits**
(numerically stable log-sigmoid form) and crack IoU is added as the
north-star quality metric the reference lacked (BASELINE.md).

All functions are pure jnp — safe under jit/vmap/shard_map — and reduce in
float32 regardless of the compute dtype (bf16-safe accumulation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def sigmoid_bce(
    logits: jax.Array,
    labels: jax.Array,
    pos_weight: jax.Array | float | None = None,
) -> jax.Array:
    """Mean binary cross-entropy over all pixels, from logits.

    Matches Keras ``binary_crossentropy`` applied to ``sigmoid(logits)`` up to
    clipping; computed as ``max(l,0) - l*y + log1p(exp(-|l|))`` for stability.
    ``pos_weight`` scales crack-pixel terms by ``1 + (pos_weight-1)*y``
    (class-imbalance counterweight); ``None``/1.0 is the reference's plain
    BCE (client_fit_model.py:157).
    """
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    per_pixel = optax.sigmoid_binary_cross_entropy(logits, labels)
    if pos_weight is not None:
        w = 1.0 + (jnp.asarray(pos_weight, jnp.float32) - 1.0) * labels
        per_pixel = w * per_pixel
    return jnp.mean(per_pixel)


def pixel_accuracy(logits: jax.Array, labels: jax.Array, threshold: float = 0.5) -> jax.Array:
    """Fraction of pixels whose thresholded prediction matches the mask."""
    preds = (jax.nn.sigmoid(logits.astype(jnp.float32)) > threshold)
    labels = labels > 0.5
    return jnp.mean((preds == labels).astype(jnp.float32))


def binary_iou(
    logits: jax.Array,
    labels: jax.Array,
    threshold: float = 0.5,
) -> jax.Array:
    """Crack (foreground) intersection-over-union over the whole batch.

    Computed from global pixel counts (not per-image means) so it composes
    additively across shards: ``psum`` the intersection/union counts and the
    global IoU is exact. An empty union (no crack predicted, none present)
    is a perfect prediction and scores 1.0, not 0.
    """
    inter, union = iou_counts(logits, labels, threshold)
    return iou_from_counts(inter, union)


def iou_from_counts(inter: jax.Array, union: jax.Array) -> jax.Array:
    """IoU with the 0/0 -> 1.0 (perfect empty prediction) convention."""
    return jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 1.0)


def iou_counts(
    logits: jax.Array, labels: jax.Array, threshold: float = 0.5
) -> tuple[jax.Array, jax.Array]:
    """(intersection, union) pixel counts — the psum-able form of IoU."""
    preds = jax.nn.sigmoid(logits.astype(jnp.float32)) > threshold
    labels = labels > 0.5
    inter = jnp.sum(jnp.logical_and(preds, labels).astype(jnp.float32))
    union = jnp.sum(jnp.logical_or(preds, labels).astype(jnp.float32))
    return inter, union


def segmentation_metrics(
    logits: jax.Array,
    labels: jax.Array,
    pos_weight: jax.Array | float | None = None,
) -> dict[str, jax.Array]:
    """The per-batch metric dict logged every round (SURVEY.md §5.5 fix)."""
    inter, union = iou_counts(logits, labels)
    return {
        "loss": sigmoid_bce(logits, labels, pos_weight),
        "pixel_acc": pixel_accuracy(logits, labels),
        "iou": iou_from_counts(inter, union),
        "iou_inter": inter,
        "iou_union": union,
    }
