from fedcrack_tpu.ops.losses import (  # noqa: F401
    sigmoid_bce,
    pixel_accuracy,
    binary_iou,
    segmentation_metrics,
)
from fedcrack_tpu.ops.pooling import max_pool_3x3_s2  # noqa: F401
