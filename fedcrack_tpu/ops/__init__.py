from fedcrack_tpu.ops.losses import (  # noqa: F401
    sigmoid_bce,
    pixel_accuracy,
    binary_iou,
    segmentation_metrics,
)
