"""Fused Pallas TPU kernel: BCE loss + segmentation statistics in one pass.

The training hot path computes four reductions over the same logits/mask
tensors every step: BCE sum, correct-pixel count, IoU intersection and IoU
union (ops/losses.py; the reference computed loss and accuracy in separate
Keras graph ops, client_fit_model.py:157). Naively that is four reads of the
batch from HBM; this kernel streams each (block, 128)-tile through VMEM once
and accumulates all four statistics on the VPU — one HBM pass, no
intermediate materialization.

Layout: inputs are flattened and padded to ``(rows, 128)`` lane tiles; the
grid walks row-blocks sequentially (TPU grid order), each step masking the
tail padding by global element index and accumulating partial sums into a
single shared ``(8, 128)`` VMEM output block (lanes 0..3 of row 0 hold the
four statistics).

The backward pass stays in plain XLA: d(BCE)/dlogits = sigmoid(x) - y is a
single fused elementwise op that the compiler already emits optimally — a
hand kernel would add nothing. The win is the fused multi-statistic forward
reduction; ``jax.custom_vjp`` stitches the two together.

Dispatch: ``impl=None`` selects the pure-XLA implementation everywhere —
the on-chip A/B (bench_runs/r05_pallas_bce_ab.json; see ``default_impl``)
measured the kernel at parity on the flagship shape and ~5% behind at
256 px, so XLA's fusion is the default and ``FEDCRACK_BCE_IMPL=pallas``
opts into the kernel; tests force ``impl="pallas"`` under the Pallas
interpreter for numerics parity on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

LANE = 128
BLOCK_ROWS = 256  # 256x128 f32 tiles: 128 KiB per input block in VMEM


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


# ---- forward kernel ----


def _fwd_kernel(x_ref, y_ref, out_ref, *, n_valid: int, block_rows: int):
    i = pl.program_id(0)
    x = x_ref[:]
    y = y_ref[:]
    row = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    idx = i * block_rows * LANE + row * LANE + col
    valid = idx < n_valid

    # Python-literal constants throughout: concrete jnp scalars created at
    # trace time carry an empty vma and break check_vma under shard_map.
    # Stable log-sigmoid BCE: max(x,0) - x*y + log1p(exp(-|x|)).
    bce = jnp.where(
        valid, jnp.maximum(x, 0.0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x))), 0.0
    )
    pred = x > 0.0  # sigmoid(x) > 0.5
    tgt = y > 0.5
    correct = jnp.where(valid & (pred == tgt), 1.0, 0.0)
    inter = jnp.where(valid & pred & tgt, 1.0, 0.0)
    union = jnp.where(valid & (pred | tgt), 1.0, 0.0)

    # Positive-pixel BCE sum: lets the host compose a class-weighted loss
    # (w = 1 + (pos_weight-1)*y) for ANY pos_weight from the same kernel —
    # the weight never becomes a kernel constant, so it never recompiles.
    s = (
        jnp.sum(bce),
        jnp.sum(correct),
        jnp.sum(inter),
        jnp.sum(union),
        jnp.sum(y * bce),
    )
    orow = jax.lax.broadcasted_iota(jnp.int32, out_ref.shape, 0)
    ocol = jax.lax.broadcasted_iota(jnp.int32, out_ref.shape, 1)
    vec = sum(
        jnp.where((orow == 0) & (ocol == k), s[k], 0.0) for k in range(5)
    )

    @pl.when(i == 0)
    def _init():
        out_ref[:] = vec

    @pl.when(i > 0)
    def _accumulate():
        out_ref[:] = out_ref[:] + vec


def _sums_pallas(x: jax.Array, y: jax.Array, interpret: bool) -> jax.Array:
    n = x.size
    flat_x = x.reshape(-1).astype(jnp.float32)
    flat_y = y.reshape(-1).astype(jnp.float32)
    rows = _cdiv(n, LANE)
    rows_pad = max(_cdiv(rows, BLOCK_ROWS), 1) * BLOCK_ROWS
    pad = rows_pad * LANE - n
    xp = jnp.pad(flat_x, (0, pad)).reshape(rows_pad, LANE)
    yp = jnp.pad(flat_y, (0, pad)).reshape(rows_pad, LANE)

    spec_kw = {} if _VMEM is None else {"memory_space": _VMEM}
    # Under shard_map the output varies over the same mesh axes as the inputs
    # (per-device statistics); propagate the vma so check_vma stays on
    # (no-op on pre-vma JAX — jaxcompat).
    from fedcrack_tpu.jaxcompat import shape_dtype_struct, typeof_vma

    vma = typeof_vma(xp) | typeof_vma(yp)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, n_valid=n, block_rows=BLOCK_ROWS),
        grid=(rows_pad // BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANE), lambda i: (i, 0), **spec_kw),
            pl.BlockSpec((BLOCK_ROWS, LANE), lambda i: (i, 0), **spec_kw),
        ],
        out_specs=pl.BlockSpec((8, LANE), lambda i: (0, 0), **spec_kw),
        out_shape=shape_dtype_struct((8, LANE), jnp.float32, vma=vma),
        interpret=interpret,
    )(xp, yp)
    return out[0, :5]


def _sums_jnp(x: jax.Array, y: jax.Array) -> jax.Array:
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    per_pixel = optax.sigmoid_binary_cross_entropy(x, y)
    bce = jnp.sum(per_pixel)
    ybce = jnp.sum(y * per_pixel)
    pred = x > 0
    tgt = y > 0.5
    correct = jnp.sum((pred == tgt).astype(jnp.float32))
    inter = jnp.sum((pred & tgt).astype(jnp.float32))
    union = jnp.sum((pred | tgt).astype(jnp.float32))
    return jnp.stack([bce, correct, inter, union, ybce])


# ---- differentiable public op ----


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def bce_sums(logits: jax.Array, labels: jax.Array, impl: str = "jnp") -> jax.Array:
    """``[bce_sum, n_correct, iou_inter, iou_union, pos_bce_sum]`` as one
    float32 vector (``pos_bce_sum`` = BCE summed over crack pixels only, the
    building block of a class-weighted loss).

    ``impl``: ``"pallas"`` (compiled TPU kernel), ``"interpret"`` (Pallas
    interpreter, any backend — for tests), ``"jnp"`` (pure XLA reference).
    Differentiable in ``logits``/``labels`` through the two BCE-sum
    components; the count statistics are piecewise constant with zero
    gradient.
    """
    return _dispatch(logits, labels, impl)


def _dispatch(logits, labels, impl):
    if impl == "pallas":
        return _sums_pallas(logits, labels, interpret=False)
    if impl == "interpret":
        return _sums_pallas(logits, labels, interpret=True)
    if impl == "jnp":
        return _sums_jnp(logits, labels)
    raise ValueError(f"unknown impl {impl!r}")


def _bce_sums_fwd(logits, labels, impl):
    return _dispatch(logits, labels, impl), (logits, labels)


def _bce_sums_bwd(impl, residuals, g):
    x, y = residuals
    x32 = x.astype(jnp.float32)
    y32 = y.astype(jnp.float32)
    # d(bce_sum)/dx = sigmoid(x) - y ; d(bce_sum)/dy = -x.
    # d(pos_bce_sum)/dx = y * (sigmoid(x) - y) ;
    # d(pos_bce_sum)/dy = bce + y * d(bce)/dy = bce - y*x.
    # Count statistics (g[1:4]) are piecewise constant: zero gradient.
    sig_minus_y = jax.nn.sigmoid(x32) - y32
    dx = ((g[0] + g[4] * y32) * sig_minus_y).astype(x.dtype)
    bce = jnp.maximum(x32, 0.0) - x32 * y32 + jnp.log1p(jnp.exp(-jnp.abs(x32)))
    dy = (g[0] * (-x32) + g[4] * (bce - y32 * x32)).astype(y.dtype)
    return dx, dy


bce_sums.defvjp(_bce_sums_fwd, _bce_sums_bwd)


def default_impl() -> str:
    """XLA everywhere: the interleaved on-chip A/B
    (bench_runs/r05_pallas_bce_ab.json, v5e, slope-fit, variants alternated
    within one process) measured the kernel as a WASH at the 128 px flagship
    (0.99x) and ~5% SLOWER at 256 px — the pad/reshape to (rows, 128) lane
    tiles is a materialization boundary that blocks XLA from fusing the
    reductions into the ops producing the logits. Same honest-negative
    outcome as the custom pool backward (BASELINE.md). The kernel stays as
    the measured alternative: ``FEDCRACK_BCE_IMPL=pallas`` opts in, and
    tests pin its numerics so the option cannot rot."""
    import os

    forced = os.environ.get("FEDCRACK_BCE_IMPL")
    if forced:
        return forced
    return "jnp"


def fused_segmentation_metrics(
    logits: jax.Array,
    labels: jax.Array,
    impl: str | None = None,
    pos_weight: jax.Array | float | None = None,
) -> dict[str, jax.Array]:
    """Drop-in fused equivalent of ``ops.losses.segmentation_metrics``.

    ``pos_weight`` > 1 up-weights crack pixels in the loss (mean of
    ``(1 + (pos_weight-1)*y) * bce``) — the standard counter to the ~7%
    foreground imbalance of crack masks, where plain BCE converges to
    low-confidence predictions that threshold poorly. ``None``/1.0 is the
    reference's plain BCE (client_fit_model.py:157). Traced, never a
    compile-time constant: sweeping it does not recompile.
    """
    from fedcrack_tpu.ops.losses import iou_from_counts

    sums = bce_sums(logits, labels, impl or default_impl())
    n = jnp.float32(logits.size)
    loss = sums[0] / n
    if pos_weight is not None:
        loss = loss + (jnp.asarray(pos_weight, jnp.float32) - 1.0) * sums[4] / n
    return {
        "loss": loss,
        "pixel_acc": sums[1] / n,
        "iou": iou_from_counts(sums[2], sums[3]),
        "iou_inter": sums[2],
        "iou_union": sums[3],
    }
