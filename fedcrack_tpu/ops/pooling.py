"""3x3 / stride-2 SAME max pooling with a scatter-free backward pass.

The encoder's pool (reference: Keras ``MaxPooling2D(3, strides=2, "same")``,
client_fit_model.py:113) takes its gradient through XLA's SelectAndScatter
by default, which on TPU lowers to a poorly-vectorized windowed scan —
BASELINE.md's round-2 profile put it (with the upsample-gradient reduces)
behind roughly a third of non-conv device time at the flagship shape.

This op keeps the forward EXACTLY as ``flax.linen.max_pool`` computes it
(same ``lax.reduce_window``, so forward parity tests — h5 import, mesh
golden values — pin it bit-for-bit) and swaps the backward for nine
strided-slice comparisons plus interior-dilated dense pads:

- for each window offset (dy, dx) in row-major order, the candidate slice
  ``c = xp[:, dy::2, dx::2, :]`` is compared against the pooled output;
- the FIRST offset (row-major, XLA SelectAndScatter's own visit order) that
  matches claims the output's cotangent (``claimed`` mask), so every output
  routes its gradient to exactly one input — tie-break identical to the
  default lowering;
- each claimed contribution returns to input coordinates via ``lax.pad``
  with interior dilation (a dense op the TPU vectorizes), not a scatter.

Cost: 9 elementwise compares over the output grid + 9 dense adds over the
(padded) input grid — all fusable, no serialized window walk.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

_WINDOW = 3
_STRIDE = 2


def _same_pads(size: int) -> tuple[int, int, int]:
    """(out_size, pad_lo, pad_hi) for window 3 / stride 2 SAME."""
    out = -(-size // _STRIDE)  # ceil
    total = max((out - 1) * _STRIDE + _WINDOW - size, 0)
    lo = total // 2
    return out, lo, total - lo


def _reduce_window_max(x: jax.Array) -> jax.Array:
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        (1, _WINDOW, _WINDOW, 1),
        (1, _STRIDE, _STRIDE, 1),
        "SAME",
    )


@jax.custom_vjp
def max_pool_3x3_s2(x: jax.Array) -> jax.Array:
    """NHWC max pool, window 3x3, stride 2, SAME — forward identical to
    ``nn.max_pool(x, (3, 3), (2, 2), "SAME")``, backward scatter-free."""
    return _reduce_window_max(x)


def _fwd(x: jax.Array):
    out = _reduce_window_max(x)
    return out, (x, out)


def _bwd(res, g):
    """Accumulate per-offset contributions in OUTPUT-grid space, then
    interleave the four (row, col) parity classes into input coordinates
    with one reshape — input position ``p = 2i + dy - pad`` has row parity
    ``dy % 2``, so offsets partition cleanly by parity. A first draft
    instead dilated each contribution to the padded INPUT grid and summed
    nine full-size arrays; measured on a v5e that was 1.4-1.7x SLOWER than
    XLA's SelectAndScatter — the output-grid accumulation carries ~4x less
    HBM traffic."""
    x, out = res
    n, h, w, c = x.shape
    ho, lo_h, hi_h = _same_pads(h)
    wo, lo_w, hi_w = _same_pads(w)
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((0, 0), (lo_h, hi_h), (lo_w, hi_w), (0, 0)), constant_values=neg)

    zero = jnp.zeros((), g.dtype)
    u, v = ho + 1, wo + 1  # parity-class grids ([2*u, 2*v] covers the padded input)
    classes = {
        (a, b): jnp.zeros((n, u, v, c), g.dtype) for a in (0, 1) for b in (0, 1)
    }
    claimed = jnp.zeros(out.shape, jnp.bool_)
    for dy in range(_WINDOW):
        lim_y = dy + _STRIDE * (ho - 1) + 1
        for dx in range(_WINDOW):
            lim_x = dx + _STRIDE * (wo - 1) + 1
            cand = lax.slice(
                xp, (0, dy, dx, 0), (n, lim_y, lim_x, c), (1, _STRIDE, _STRIDE, 1)
            )
            # ~(cand < out) instead of (cand == out): identical for finite
            # values (cand <= out always, out being the window max), but a
            # NaN max still claims an offset — an equality mask would match
            # nothing (NaN != NaN) and silently ZERO the gradient where the
            # default lowering propagates it, hiding mid-training divergence.
            # The validity mask bars SAME-pad candidates from claiming: for
            # finite values they are -inf and lose anyway, but under a NaN
            # max every comparison is False and a pad cell at the window's
            # first offset would swallow the cotangent (the slice at the end
            # discards pad positions).
            ys = jnp.arange(ho) * _STRIDE + dy
            xs = jnp.arange(wo) * _STRIDE + dx
            valid = (
                ((ys >= lo_h) & (ys < lo_h + h))[:, None]
                & ((xs >= lo_w) & (xs < lo_w + w))[None, :]
            )
            m = ~(cand < out) & ~claimed & valid[None, :, :, None]
            claimed = claimed | m
            contrib = jnp.where(m, g, zero)
            # Padded-input row hit by window row i at this offset: 2i + dy.
            # Row parity a = dy % 2; class-row index u' = i + (1 if dy == 2).
            a, b = dy % 2, dx % 2
            ro, co = (1 if dy == 2 else 0), (1 if dx == 2 else 0)
            classes[(a, b)] = (
                classes[(a, b)].at[:, ro : ro + ho, co : co + wo, :].add(contrib)
            )
    # Interleave: stack the parity axis right after its grid axis, then
    # flatten — index order (u', a) reads back as padded row 2u' + a.
    cols = {
        a: jnp.stack([classes[(a, 0)], classes[(a, 1)]], axis=3).reshape(n, u, 2 * v, c)
        for a in (0, 1)
    }
    dxp = jnp.stack([cols[0], cols[1]], axis=2).reshape(n, 2 * u, 2 * v, c)
    dx_full = lax.slice(dxp, (0, lo_h, lo_w, 0), (n, lo_h + h, lo_w + w, c))
    return (dx_full.astype(x.dtype),)


max_pool_3x3_s2.defvjp(_fwd, _bwd)

# Grid-size crossover for the automatic choice, measured on a TPU v5e
# (round-level A/B, bf16, batch 16): the scatter-free backward is ~1.6x
# faster per train step when every pool grid is <= 64x64 (the reference's
# 128 px crop), but ~25% SLOWER than SelectAndScatter on a 128x128 grid
# (the first pool of a 256 px crop) — at that size its output-grid
# accumulation and interleave cost more HBM round-trips than XLA's
# windowed scan. Override with FEDCRACK_POOL_CUSTOM_MAX_GRID.
_CUSTOM_MAX_GRID = int(os.environ.get("FEDCRACK_POOL_CUSTOM_MAX_GRID", "64"))


def max_pool_auto(x: jax.Array) -> jax.Array:
    """3x3/s2 SAME max pool choosing the faster backward for this grid
    size (values identical either way; the choice is trace-time static)."""
    if max(x.shape[1], x.shape[2]) <= _CUSTOM_MAX_GRID:
        return max_pool_3x3_s2(x)
    return _reduce_window_max(x)
