"""First-party native host runtime (C++ via ctypes).

Compiles ``fedcrack_native.cpp`` on first import (g++ is in the image;
pybind11 is not, so the binding is ctypes over an ``extern "C"`` ABI) and
exposes:

- :func:`resize_normalize` / :func:`resize_binarize` — fused per-sample
  decode-side transforms (bilinear + /255 or >0 in one pass). These free the
  framework from a hard OpenCV dependency (the reference requires cv2,
  client_fit_model.py:12); when cv2 IS present the pipeline prefers its
  AVX2 fixed-point resize, which benchmarks ~1.4x faster than this scalar
  float kernel.
- :func:`resize_u8` / :func:`resize_binarize_u8` — uint8-domain variants
  (round-to-nearest) backing ``transport_dtype="uint8"`` (1/4 staging
  bytes) when cv2 is absent.
- :func:`weighted_accumulate` / :func:`scale_inplace` — host-plane FedAvg
  primitives over flat float32 buffers (OpenMP, GIL released);
- :func:`crc32c` — hardware (SSE4.2) Castagnoli checksum for chunked-upload
  integrity framing; the reference shipped 100 MB chunks with no checksums
  (fl_client.py:35-50).

Everything degrades gracefully: when no compiler is available the pure
numpy/OpenCV paths keep working and :data:`AVAILABLE` is False. The build is
cached next to the source and rebuilt when the source hash changes.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import platform
import subprocess
import tempfile
import threading

import numpy as np

log = logging.getLogger("fedcrack.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fedcrack_native.cpp")

_lib = None
_lib_lock = threading.Lock()
# Tri-state: None = not yet attempted, True = loaded, False = build/load
# failed (never retried — a broken toolchain must not spawn a g++ subprocess
# per decoded image).
AVAILABLE: bool | None = None


def _build_dir() -> str:
    # Per-user, 0700: the .so gets dlopen'd, so a world-writable shared
    # directory would let another local user plant a library with a matching
    # source-hash name.
    d = os.environ.get("FEDCRACK_NATIVE_CACHE")
    if not d:
        base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
        if not os.path.isdir(os.path.dirname(base)) or base.startswith("~"):
            base = os.path.join(tempfile.gettempdir(), f"fedcrack_{os.getuid()}")
        d = os.path.join(base, "fedcrack_native")
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.stat(d)
    if st.st_uid != os.getuid():
        raise PermissionError(f"native cache dir {d!r} is not owned by this user")
    # makedirs(mode=...) does not chmod a pre-existing directory; a
    # group/world-writable cache would let another user pre-plant a .so
    # under the predictable hash name.
    if st.st_mode & 0o077:
        os.chmod(d, 0o700)
    return d


def _cpu_tag() -> str:
    # The .so is built -march=native; a cache dir shared across machines
    # (NFS home, XDG_CACHE_HOME) must not serve e.g. AVX-512 code to a CPU
    # without it (SIGILL at first kernel call, not at load time).
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = line
                    break
    except OSError:
        pass
    return hashlib.sha256((platform.machine() + feats).encode()).hexdigest()[:8]


def _compile() -> str | None:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16] + "_" + _cpu_tag()
    try:
        out = os.path.join(_build_dir(), f"libfedcrack_{tag}.so")
    except OSError as e:
        log.warning("native cache unavailable (%s); using fallbacks", e)
        return None
    if os.path.exists(out):
        return out
    # Unique temp name: concurrent cold-start processes must not interleave
    # writes into one file; os.replace makes the publish atomic.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out))
    os.close(fd)
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        log.warning("native build failed (%s); using pure-python fallbacks: %s",
                    e, detail.decode(errors="replace")[:500])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return out


def _load():
    global _lib, AVAILABLE
    # Lock-free fast path: after one-time init this runs on the per-sample
    # decode and per-tensor FedAvg hot paths, where a contended global lock
    # would serialize the decode worker threads.
    if _lib is not None or AVAILABLE is False:
        return _lib
    with _lib_lock:
        if _lib is not None or AVAILABLE is False:
            return _lib
        try:
            path = _compile()
        except Exception as e:  # never let the fallback path die on build
            log.warning("native compile raised (%s); using fallbacks", e)
            path = None
        if path is None:
            AVAILABLE = False
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:  # corrupted/foreign .so: degrade, don't crash
            log.warning("native library load failed (%s); using fallbacks", e)
            AVAILABLE = False
            return None
        # ABI gate FIRST: a stale/foreign library that loads but predates the
        # current ABI must degrade before any symbol lookup can raise.
        try:
            lib.fedcrack_abi_version.restype = ctypes.c_int
            abi = lib.fedcrack_abi_version()
        except AttributeError:
            abi = None
        if abi != 2:
            log.warning("native ABI mismatch (%r); using fallbacks", abi)
            AVAILABLE = False
            return None
        lib.fedcrack_resize_u8_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_int, ctypes.c_float,
        ]
        lib.fedcrack_resize_u8_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_float,
        ]
        lib.fedcrack_weighted_accumulate_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_float, ctypes.c_size_t,
        ]
        lib.fedcrack_scale_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_float, ctypes.c_size_t,
        ]
        lib.fedcrack_crc32c.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32,
        ]
        lib.fedcrack_crc32c.restype = ctypes.c_uint32
        _lib = lib
        AVAILABLE = True
        return _lib


def _as_u8_3d(image: np.ndarray) -> np.ndarray:
    if image.ndim == 2:
        image = image[..., None]
    if image.ndim != 3:
        raise ValueError(f"expected HxW[xC] image, got shape {image.shape}")
    return np.ascontiguousarray(image, dtype=np.uint8)


def _resize(image: np.ndarray, size: int, scale: float, binarize: bool,
            thresh: float) -> np.ndarray:
    lib = _load()
    src = _as_u8_3d(image)
    h, w, ch = src.shape
    if lib is None:
        return _resize_numpy(src, size, scale, binarize, thresh)
    dst = np.empty((size, size, ch), np.float32)
    lib.fedcrack_resize_u8_f32(
        src.ctypes.data, 1, h, w, ch, dst.ctypes.data, size, size,
        ctypes.c_float(scale), int(binarize), ctypes.c_float(thresh),
    )
    return dst


def resize_normalize(image: np.ndarray, size: int) -> np.ndarray:
    """uint8 HxWxC -> float32 size x size x C in [0,1]; bilinear, fused /255
    (the reference's image contract, client_fit_model.py:30-38)."""
    return _resize(image, size, 1.0 / 255.0, False, 0.0)


def resize_binarize(image: np.ndarray, size: int, thresh: float = 0.5) -> np.ndarray:
    """uint8 HxW[x1] -> float32 {0,1} size x size x 1; bilinear then ``> thresh``
    (the reference's mask contract, client_fit_model.py:39-43).

    The default ``thresh=0.5`` reproduces the reference's uint8-domain
    ``resize(mask) > 0``: cv2 rounds the interpolated value to nearest int,
    so a pixel survives iff the float interpolation is >= 0.5 — keeping the
    cv2 and native decode paths label-identical at mask boundaries."""
    out = _resize(image, size, 1.0, True, thresh)
    return out if out.shape[-1] == 1 else out[..., :1]


def _resize_u8(image: np.ndarray, size: int, binarize: bool,
               thresh: float) -> np.ndarray:
    lib = _load()
    src = _as_u8_3d(image)
    h, w, ch = src.shape
    if lib is None:
        v = _resize_numpy(src, size, 1.0, binarize, thresh)
        # kRound semantics of the native kernel: floor(v + 0.5)
        return np.floor(v + np.float32(0.5)).astype(np.uint8)
    dst = np.empty((size, size, ch), np.uint8)
    lib.fedcrack_resize_u8_u8(
        src.ctypes.data, 1, h, w, ch, dst.ctypes.data, size, size,
        int(binarize), ctypes.c_float(thresh),
    )
    return dst


def resize_u8(image: np.ndarray, size: int) -> np.ndarray:
    """uint8 HxWxC -> uint8 size x size x C; bilinear, rounded to nearest —
    the uint8-transport decode path (the device applies the /255, see
    data.pipeline.as_model_batch). The cv2-free analog of the reference's
    uint8-domain resize (client_fit_model.py:30-38)."""
    return _resize_u8(image, size, False, 0.0)


def resize_binarize_u8(image: np.ndarray, size: int, thresh: float = 0.5) -> np.ndarray:
    """uint8 HxW[x1] -> uint8 {0,1} size x size x 1 mask for uint8 transport;
    same interpolation + threshold as :func:`resize_binarize`, so the mask
    labels are bit-identical across the two transport dtypes."""
    out = _resize_u8(image, size, True, thresh)
    return out if out.shape[-1] == 1 else out[..., :1]


def _resize_numpy(src: np.ndarray, size: int, scale: float, binarize: bool,
                  thresh: float) -> np.ndarray:
    """Pure-numpy bilinear with identical half-pixel geometry (fallback and
    test oracle)."""
    h, w, ch = src.shape
    fy = np.clip((np.arange(size) + 0.5) * (h / size) - 0.5, 0, h - 1)
    fx = np.clip((np.arange(size) + 0.5) * (w / size) - 0.5, 0, w - 1)
    y0 = fy.astype(np.int64)
    x0 = fx.astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0).astype(np.float32)[:, None, None]
    wx = (fx - x0).astype(np.float32)[None, :, None]
    s = src.astype(np.float32)
    v = ((1 - wy) * (1 - wx) * s[y0][:, x0]
         + (1 - wy) * wx * s[y0][:, x1]
         + wy * (1 - wx) * s[y1][:, x0]
         + wy * wx * s[y1][:, x1])
    if binarize:
        return (v > thresh).astype(np.float32)
    return v * np.float32(scale)


def weighted_accumulate(acc: np.ndarray, x: np.ndarray, w: float) -> None:
    """In-place ``acc += w * x`` over float32 buffers (host FedAvg inner op,
    the reference's numpy loop equivalent — fl_server.py:92-102)."""
    if acc.dtype != np.float32 or x.dtype != np.float32:
        raise ValueError("weighted_accumulate requires float32 buffers")
    if acc.shape != x.shape:
        raise ValueError(f"shape mismatch {acc.shape} vs {x.shape}")
    lib = _load()
    if lib is None or not acc.flags.c_contiguous or not x.flags.c_contiguous:
        acc += np.float32(w) * x
        return
    lib.fedcrack_weighted_accumulate_f32(
        acc.ctypes.data, x.ctypes.data, ctypes.c_float(w), acc.size
    )


def scale_inplace(acc: np.ndarray, s: float) -> None:
    """In-place ``acc *= s`` (the weighted mean's final divide)."""
    if acc.dtype != np.float32:
        raise ValueError("scale_inplace requires a float32 buffer")
    lib = _load()
    if lib is None or not acc.flags.c_contiguous:
        acc *= np.float32(s)
        return
    lib.fedcrack_scale_f32(acc.ctypes.data, ctypes.c_float(s), acc.size)


def crc32c(data: bytes | bytearray | memoryview | np.ndarray, init: int = 0) -> int:
    """CRC32C (Castagnoli) checksum — chunked-upload integrity framing.

    ndarray input is checksummed over its full C-order byte image (any dtype,
    any layout), identically in the native and pure-Python paths.
    """
    if isinstance(data, np.ndarray):
        buf = np.ascontiguousarray(data).view(np.uint8).ravel()
    else:
        buf = np.frombuffer(bytes(data), np.uint8)
    lib = _load()
    if lib is None or buf.size == 0:
        return _crc32c_python(buf.tobytes(), init)
    return int(lib.fedcrack_crc32c(buf.ctypes.data, buf.size, init))


_CRC_TABLE: list[int] | None = None


def _crc32c_python(data: bytes, init: int = 0) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _CRC_TABLE = table
    crc = ~init & 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return (~crc) & 0xFFFFFFFF
