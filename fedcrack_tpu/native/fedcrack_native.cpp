// Native host-runtime kernels for the TPU-side federation framework.
//
// The reference's host runtime is native only through its third-party wheels
// (OpenCV's C++ resize, numpy's C loops — SURVEY.md §2.7); its own input
// pipeline drives them one Python call per image, synchronously, per batch
// (reference: client_fit_model.py:30-43, SURVEY.md §3.3 "first-order
// bottleneck"). This library is the first-party native replacement for the
// per-sample hot path:
//
//   - fused bilinear resize + /255 normalize (images) and resize + >0
//     binarize (masks), uint8 -> float32 in one pass, OpenMP across rows;
//   - weighted elementwise accumulate for host-plane FedAvg
//     (acc += w * x over flattened weight buffers);
//   - CRC32C (Castagnoli, SSE4.2 hardware when available) for integrity
//     framing of chunked uploads (reference's 100 MB chunker, fl_client.py:35-50,
//     shipped chunks with no checksums).
//
// Geometry matches OpenCV INTER_LINEAR: half-pixel source centers,
// src = (dst + 0.5) * (src_size / dst_size) - 0.5, edges clamped.
//
// Build: g++ -O3 -fopenmp -shared -fPIC (see native/__init__.py); bound via
// ctypes — no pybind11 in this image.

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <cmath>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

// ---- fused bilinear resize, uint8 -> float32 / uint8 ----
//
// src: [sh, sw, ch] uint8 (C-contiguous), dst: [dh, dw, ch] OutT.
// Each output value is bilinear(src) * scale + (binarize ? threshold step).
// With binarize != 0, output is 1 when the interpolated value > thresh
// (the reference's mask contract: resize then `> 0`, client_fit_model.py:41).
// kRound (the uint8 output path) rounds to nearest like cv2's fixed-point
// u8 resize, so uint8 transport exists without OpenCV.
template <typename OutT, bool kRound>
static void resize_one(const uint8_t* src, int sh, int sw, int ch,
                       OutT* dst, int dh, int dw, float scale,
                       int binarize, float thresh) {
  const float ry = static_cast<float>(sh) / static_cast<float>(dh);
  const float rx = static_cast<float>(sw) / static_cast<float>(dw);

  // Column coefficients depend only on x: compute once, reuse every row.
  // Serial on purpose: callers parallelize at the sample level (the Python
  // pipeline's decode ThreadPool, or the batched entry's omp loop below);
  // an inner omp team here would oversubscribe and thrash caches.
  int* x0s = new int[dw];
  int* x1s = new int[dw];
  float* wxs = new float[dw];
  for (int x = 0; x < dw; ++x) {
    float fx = (static_cast<float>(x) + 0.5f) * rx - 0.5f;
    fx = std::max(0.0f, std::min(fx, static_cast<float>(sw - 1)));
    x0s[x] = static_cast<int>(fx);
    x1s[x] = std::min(x0s[x] + 1, sw - 1);
    wxs[x] = fx - static_cast<float>(x0s[x]);
  }

  for (int y = 0; y < dh; ++y) {
    float fy = (static_cast<float>(y) + 0.5f) * ry - 0.5f;
    fy = std::max(0.0f, std::min(fy, static_cast<float>(sh - 1)));
    const int y0 = static_cast<int>(fy);
    const int y1 = std::min(y0 + 1, sh - 1);
    const float wy = fy - static_cast<float>(y0);
    const float omwy = 1.0f - wy;
    OutT* out_row = dst + static_cast<size_t>(y) * dw * ch;
    const uint8_t* row0 = src + static_cast<size_t>(y0) * sw * ch;
    const uint8_t* row1 = src + static_cast<size_t>(y1) * sw * ch;
    for (int x = 0; x < dw; ++x) {
      const int x0 = x0s[x] * ch;
      const int x1 = x1s[x] * ch;
      const float wx = wxs[x];
      const float w00 = omwy * (1.0f - wx);
      const float w01 = omwy * wx;
      const float w10 = wy * (1.0f - wx);
      const float w11 = wy * wx;
      for (int c = 0; c < ch; ++c) {
        const float v = w00 * row0[x0 + c] + w01 * row0[x1 + c] +
                        w10 * row1[x0 + c] + w11 * row1[x1 + c];
        const float o = binarize ? (v > thresh ? 1.0f : 0.0f) : v * scale;
        out_row[x * ch + c] =
            kRound ? static_cast<OutT>(o + 0.5f) : static_cast<OutT>(o);
      }
    }
  }

  delete[] x0s;
  delete[] x1s;
  delete[] wxs;
}

// Batched entry: src [n, sh, sw, ch] uint8 -> dst [n, dh, dw, ch] float32.
extern "C" void fedcrack_resize_u8_f32(const uint8_t* src, int n, int sh, int sw, int ch,
                            float* dst, int dh, int dw, float scale,
                            int binarize, float thresh) {
  const size_t src_stride = static_cast<size_t>(sh) * sw * ch;
  const size_t dst_stride = static_cast<size_t>(dh) * dw * ch;
#pragma omp parallel for schedule(dynamic) if (n > 1)
  for (int i = 0; i < n; ++i) {
    resize_one<float, false>(src + i * src_stride, sh, sw, ch,
                             dst + i * dst_stride, dh, dw, scale, binarize,
                             thresh);
  }
}

// Batched uint8-domain entry: src [n, sh, sw, ch] uint8 -> dst uint8.
// Images (binarize=0, scale=1): bilinear rounded to nearest — the resized
// transport bytes the device normalizes with /255. Masks (binarize=1):
// {0,1} uint8. Keeps transport_dtype="uint8" (1/4 staging bytes) available
// without OpenCV.
extern "C" void fedcrack_resize_u8_u8(const uint8_t* src, int n, int sh, int sw, int ch,
                           uint8_t* dst, int dh, int dw,
                           int binarize, float thresh) {
  const size_t src_stride = static_cast<size_t>(sh) * sw * ch;
  const size_t dst_stride = static_cast<size_t>(dh) * dw * ch;
#pragma omp parallel for schedule(dynamic) if (n > 1)
  for (int i = 0; i < n; ++i) {
    resize_one<uint8_t, true>(src + i * src_stride, sh, sw, ch,
                              dst + i * dst_stride, dh, dw, 1.0f, binarize,
                              thresh);
  }
}

// ---- host-plane FedAvg accumulate: acc += w * x ----
extern "C" void fedcrack_weighted_accumulate_f32(float* acc, const float* x, float w,
                                      size_t n) {
#pragma omp parallel for simd schedule(static)
  for (size_t i = 0; i < n; ++i) {
    acc[i] += w * x[i];
  }
}

// in-place scale: acc *= s (the final divide of the weighted mean)
extern "C" void fedcrack_scale_f32(float* acc, float s, size_t n) {
#pragma omp parallel for simd schedule(static)
  for (size_t i = 0; i < n; ++i) {
    acc[i] *= s;
  }
}

// ---- CRC32C (Castagnoli) ----
struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    // bit-reflected polynomial 0x1EDC6F41 -> 0x82F63B78
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int j = 0; j < 8; ++j) {
        crc = (crc >> 1) ^ (0x82F63B78u & (~(crc & 1u) + 1u));
      }
      t[i] = crc;
    }
  }
};

// C++11 magic static: thread-safe one-time init (ctypes calls arrive with
// the GIL released, so concurrent first use is real).
static const uint32_t* crc32c_table() {
  static const Crc32cTable tbl;
  return tbl.t;
}

extern "C" uint32_t fedcrack_crc32c(const uint8_t* data, size_t len, uint32_t init) {
  uint32_t crc = ~init;
#if defined(__SSE4_2__)
  while (len >= 8) {
    uint64_t v;  // memcpy: well-defined unaligned load, compiles to one mov
    std::memcpy(&v, data, 8);
    crc = static_cast<uint32_t>(_mm_crc32_u64(crc, v));
    data += 8;
    len -= 8;
  }
  while (len > 0) {
    crc = _mm_crc32_u8(crc, *data++);
    --len;
  }
#else
  const uint32_t* table = crc32c_table();
  for (size_t i = 0; i < len; ++i) {
    crc = (crc >> 8) ^ table[(crc ^ data[i]) & 0xFF];
  }
#endif
  return ~crc;
}

extern "C" int fedcrack_abi_version() { return 2; }

