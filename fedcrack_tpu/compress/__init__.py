"""Compressed update transport: quantized + top-k sparsified deltas with
error feedback, on a versioned CRC-checked wire frame.

- :mod:`codecs` — the client-side encoders (NullCodec bit-exact escape
  hatch, Int8Codec delta quantization, TopKDeltaCodec + error feedback).
- :mod:`frames` — the wire frame and the server-side decode that feeds
  ``fed.serialization.validate_update`` (fedlint COMP001 keeps it there).
- :mod:`mesh` — the on-device encode∘decode twins for the mesh plane's
  zero-host-cost trajectory A/B.
"""

from fedcrack_tpu.compress.codecs import (
    CODEC_INT8,
    CODEC_NAMES,
    CODEC_NULL,
    CODEC_TOPK,
    Codec,
    DEFAULT_TOPK_FRACTION,
    Int8Codec,
    NullCodec,
    TopKDeltaCodec,
    encoded_bytes_model,
    get_codec,
)
from fedcrack_tpu.compress.frames import (
    FRAME_OVERHEAD_BYTES,
    Frame,
    decode_frame,
    decode_update,
    encode_frame,
    is_frame,
)

__all__ = [
    "CODEC_INT8",
    "CODEC_NAMES",
    "CODEC_NULL",
    "CODEC_TOPK",
    "Codec",
    "DEFAULT_TOPK_FRACTION",
    "FRAME_OVERHEAD_BYTES",
    "Frame",
    "Int8Codec",
    "NullCodec",
    "TopKDeltaCodec",
    "decode_frame",
    "decode_update",
    "encode_frame",
    "encoded_bytes_model",
    "get_codec",
    "is_frame",
]
