"""Client-side update codecs: how a round's weight update becomes wire bytes.

The reference ships every upload as the full pickled float32 weight list —
the reason it needed a 512 MB gRPC cap (fl_server.py:215) and the reason
ROADMAP's 1,000-client cohort is unaffordable on the wire. Gradient-
compression literature says ≥10x fewer bytes at accuracy parity is routine:
QSGD-style stochastic/deterministic quantization (Alistarh et al., 2017)
and top-k sparsification with error-feedback accumulators (Lin et al.,
Deep Gradient Compression, 2018). This module is the host-side half of that
subsystem; :mod:`fedcrack_tpu.compress.frames` defines the wire framing and
the server-side decode, :mod:`fedcrack_tpu.compress.mesh` the on-device
twin for the mesh plane.

Three codecs, negotiated in-band per round (the server advertises
``update_codec`` in the enroll handshake like every other hyperparameter):

- :class:`NullCodec` — the bit-exactness escape hatch. ``encode_update``
  returns the msgpack blob UNCHANGED: the wire carries exactly today's
  bytes (test-pinned), so ``update_codec="null"`` is byte-for-byte the
  pre-compression federation.
- :class:`Int8Codec` — QSGD-style symmetric int8 quantization of the round
  DELTA (trained weights minus the round-base global the client pulled):
  each leaf is split into fixed-size buckets, each bucket's scale is
  ``||bucket||_2 / 127`` (float32 scales sidecar in the frame manifest),
  and codes round STOCHASTICALLY (``floor(x/scale + u)``, seeded from
  (round, base_version, leaf, bucket) so encode is deterministic per
  round). Norm scaling is what buys the headline ratio: ``|x| <<
  ||bucket||_2`` for almost every entry, so most codes land in {-1, 0, 1}
  and the frame's zlib pass entropy-codes them far below 8 bits — max-
  scaled int8 of an Adam delta measures ~4.4x (near-uniform code
  magnitudes), norm-scaled ~10-13x at the default bucket. Stochastic
  rounding keeps the quantizer unbiased (Alistarh et al.'s convergence
  argument); per-entry error is bounded by its bucket's scale
  (property-tested).
- :class:`TopKDeltaCodec` — top-k sparsification of the round delta with a
  client-side error-feedback accumulator: each round transmits the k
  largest-magnitude entries of (delta + accumulated residual) per leaf and
  carries the dropped mass forward, so nothing is lost — only delayed
  (the accumulator drains to zero on a fixed sequence; property-tested).

All three operate on the client's msgpack blobs (the format
``transport.client`` already holds): decode, compute, re-frame. Codec
instances are PER CLIENT — the TopKDelta accumulator is client-local state,
exactly as in DGC.
"""

from __future__ import annotations

import math
import zlib
from typing import Sequence

import numpy as np

from fedcrack_tpu.compress import frames
from fedcrack_tpu.fed.serialization import tree_from_bytes

CODEC_NULL = "null"
CODEC_INT8 = "int8"
CODEC_TOPK = "topk_delta"
CODEC_NAMES = (CODEC_NULL, CODEC_INT8, CODEC_TOPK)

# Default top-k keep fraction: 1% of each leaf's entries. At 8 bytes per
# kept entry (int32 index + float32 value) vs 4 bytes per dense float32,
# the dense:sparse ratio is 4n / (8 * 0.01n) = 50x before framing overhead.
DEFAULT_TOPK_FRACTION = 0.01

# Int8Codec (QSGD) bucket size: the variance/ratio dial. sqrt(B)/127 sets
# the relative quantization noise (0.047 of the delta's energy at 8192,
# 0.17 at 32768 — measured on a real short-fit delta); larger buckets give
# sparser codes and better zlib ratios. 16384 sits at ~11x bytes reduction
# with ~9% relative noise on the reference model.
QSGD_BUCKET = 16384


def _f32_leaves(blob: bytes) -> list[np.ndarray]:
    """A blob's leaves as float32 numpy arrays (wire bf16 casts included —
    delta math is always full precision, like the server's decode template)."""
    import jax

    return [
        np.asarray(leaf, np.float32)
        for leaf in jax.tree_util.tree_leaves(tree_from_bytes(blob))
    ]


def _delta_leaves(blob: bytes, base_blob: bytes) -> list[np.ndarray]:
    update = _f32_leaves(blob)
    base = _f32_leaves(base_blob)
    if len(update) != len(base):
        raise ValueError(
            f"update has {len(update)} leaves, round base has {len(base)} — "
            "cannot form a delta (did the model change mid-federation?)"
        )
    out = []
    for i, (u, b) in enumerate(zip(update, base)):
        if u.shape != b.shape:
            raise ValueError(
                f"leaf {i} shape mismatch vs round base: {u.shape} vs {b.shape}"
            )
        out.append(u - b)
    return out


def qsgd_scales(flat: np.ndarray, bucket: int = QSGD_BUCKET) -> np.ndarray:
    """Per-bucket QSGD scales for a flat leaf: ``||bucket||_2 / 127``
    (1.0 for an all-zero bucket, where every code is 0 anyway). Shared
    verbatim by encode, decode and the property tests."""
    n = flat.size
    n_buckets = max(1, -(-n // bucket))
    scales = np.empty(n_buckets, np.float32)
    for bi in range(n_buckets):
        norm = float(np.linalg.norm(flat[bi * bucket : (bi + 1) * bucket]))
        scales[bi] = norm / 127.0 if norm > 0.0 else 1.0
    return scales


def int8_quantize(
    flat: np.ndarray,
    *,
    bucket: int = QSGD_BUCKET,
    seed: Sequence[int] = (0,),
) -> tuple[np.ndarray, np.ndarray]:
    """QSGD symmetric int8 quantization of a flat leaf: per-bucket norm
    scale, STOCHASTIC rounding ``floor(x/scale + u)`` with ``u ~ U[0,1)``
    drawn from a generator seeded by ``seed`` — unbiased
    (``E[q * scale] = x``) and deterministic for a given seed. Codes
    cannot exceed |127| because ``|x| <= ||bucket||_2`` always. Returns
    ``(codes int8, scales float32)``."""
    scales = qsgd_scales(flat, bucket)
    rng = np.random.default_rng(list(seed))
    q = np.empty(flat.size, np.int8)
    for bi in range(scales.size):
        seg = flat[bi * bucket : (bi + 1) * bucket]
        codes = np.floor(seg / scales[bi] + rng.random(seg.size))
        q[bi * bucket : bi * bucket + seg.size] = np.clip(codes, -127, 127)
    return q, scales


def int8_dequantize(
    q: np.ndarray, scales: np.ndarray, bucket: int = QSGD_BUCKET
) -> np.ndarray:
    """Inverse of :func:`int8_quantize` (flat float32); the scale
    expansion is the one shared rule in :func:`frames.expand_scales`."""
    per_entry = frames.expand_scales(scales, bucket, q.size)
    return q.astype(np.float32) * per_entry


def topk_select(leaf: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k largest-|value| entries of a flat leaf, ascending.
    Stable tie-break (lowest index wins) so encode is deterministic."""
    flat = np.abs(leaf.ravel())
    k = min(k, flat.size)
    # argsort(kind="stable") on the negated magnitudes: deterministic under
    # ties, unlike argpartition.
    order = np.argsort(-flat, kind="stable")[:k]
    return np.sort(order).astype(np.int32)


def leaf_k(n: int, fraction: float) -> int:
    """Per-leaf keep count: ceil(fraction * n), floored at one entry so
    small leaves (BN biases, scalars) still transmit their top coordinate."""
    return max(1, min(n, math.ceil(fraction * n)))


class Codec:
    """One client's update encoder. ``encode_update`` maps the locally
    trained weights blob (+ the round-base blob the client pulled) to the
    bytes that go on the wire; the server-side decode lives in
    :mod:`fedcrack_tpu.compress.frames` and is stateless."""

    name: str = "base"

    def encode_update(
        self,
        blob: bytes,
        base_blob: bytes | None,
        *,
        round: int = 0,
        base_version: int = 0,
    ) -> bytes:
        raise NotImplementedError

    def reset(self) -> None:
        """Drop any cross-round client state (error-feedback accumulators)."""

    def rollback_last(self) -> None:
        """Undo the last ``encode_update``'s cross-round state commit.

        The transport calls this when the server did NOT aggregate that
        upload — a straggler resynced past quorum with ``NOT_WAIT`` — so
        transmitted-but-discarded mass re-enters the accumulator instead
        of being lost forever ('nothing lost, only delayed' must hold
        across the protocol, not just across accepted uploads). No-op for
        stateless codecs."""


class NullCodec(Codec):
    """Identity: the wire carries exactly today's msgpack bytes."""

    name = CODEC_NULL

    def encode_update(
        self,
        blob: bytes,
        base_blob: bytes | None = None,
        *,
        round: int = 0,
        base_version: int = 0,
    ) -> bytes:
        return blob


class Int8Codec(Codec):
    """QSGD-style bucketed symmetric int8 quantization of the round delta,
    float32 scales sidecar per leaf, framed + zlib'd by :mod:`frames`.

    ``client_tag`` (the transport sets it to the cname) decorrelates the
    stochastic-rounding streams ACROSS the cohort: with a shared stream
    every client would draw identical rounding noise, the errors would
    correlate, and the averaged model's quantization noise would stay at
    per-client magnitude instead of shrinking ~1/sqrt(C) — exactly the
    cohort-scale regime this codec exists for (the mesh twin folds in the
    client axis index for the same reason). Per client the encode stays a
    pure function of (tag, round, base, leaf), so chaos replays still
    reproduce identical frames."""

    name = CODEC_INT8

    def __init__(self, bucket: int = QSGD_BUCKET, client_tag: str = ""):
        if bucket < 1:
            raise ValueError(f"bucket must be >= 1, got {bucket}")
        self.bucket = int(bucket)
        self.client_seed = zlib.crc32(client_tag.encode("utf-8"))

    def encode_update(
        self,
        blob: bytes,
        base_blob: bytes | None,
        *,
        round: int = 0,
        base_version: int = 0,
    ) -> bytes:
        if base_blob is None:
            raise ValueError("int8 codec needs the round-base blob (delta codec)")
        deltas = _delta_leaves(blob, base_blob)
        manifest = []
        payload = bytearray()
        for i, d in enumerate(deltas):
            if not np.isfinite(d).all():
                # Quantizing a NaN/Inf delta would SILENTLY corrupt the
                # codes — a poisoned trainer must fail loudly here instead
                # of laundering its poison into a plausible-looking frame
                # (the raw path ships the NaNs and the server's sanitation
                # gate rejects them; this codec must not hide them).
                raise ValueError(
                    f"leaf {i} delta is non-finite; refusing to encode"
                )
            # Stochastic rounding seeded per (client, round, base, leaf):
            # encode is a pure function of its inputs — a chaos replay of
            # the same round re-produces the identical frame bytes — while
            # different clients draw INDEPENDENT rounding noise.
            q, scales = int8_quantize(
                d.ravel(),
                bucket=self.bucket,
                seed=(
                    self.client_seed,
                    round & 0xFFFFFFFF,
                    base_version & 0xFFFFFFFF,
                    i,
                ),
            )
            manifest.append(
                {
                    "shape": list(d.shape),
                    "enc": "int8",
                    "scales": scales.tobytes(),
                    "bucket": self.bucket,
                }
            )
            payload += q.tobytes()
        return frames.encode_frame(
            self.name, round, base_version, manifest, bytes(payload)
        )


class TopKDeltaCodec(Codec):
    """Top-k sparsified round delta with an error-feedback accumulator.

    Each round the client transmits, per leaf, the ``k = ceil(fraction *
    n)`` largest-magnitude entries of ``delta + accumulator`` and keeps the
    untransmitted remainder in the accumulator — Lin et al.'s DGC scheme:
    dropped mass re-enters the next round's selection instead of being
    lost, which is what preserves the trajectory at high sparsity.
    """

    name = CODEC_TOPK

    def __init__(self, fraction: float = DEFAULT_TOPK_FRACTION):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"topk fraction must be in (0, 1], got {fraction}")
        self.fraction = float(fraction)
        # Per-leaf residuals, lazily zero-initialized on first encode and
        # invalidated if the leaf structure changes.
        self._residual: list[np.ndarray] | None = None
        # The last encode's pre-drop effective deltas (delta + residual):
        # the rollback target when that upload was never aggregated. Valid
        # until the next encode overwrites it.
        self._rollback: list[np.ndarray] | None = None

    def reset(self) -> None:
        self._residual = None
        self._rollback = None

    def rollback_last(self) -> None:
        if self._rollback is not None:
            self._residual = self._rollback
            self._rollback = None

    def residual_mass(self) -> float:
        """Total |accumulator| mass — the property tests' convergence probe."""
        if self._residual is None:
            return 0.0
        return float(sum(np.sum(np.abs(r)) for r in self._residual))

    def encode_update(
        self,
        blob: bytes,
        base_blob: bytes | None,
        *,
        round: int = 0,
        base_version: int = 0,
        ef_decay: float = 1.0,
    ) -> bytes:
        """``ef_decay`` (round 14, staleness-aware error feedback): the
        committed residual is scaled by it — ``1.0`` (the default) is the
        classic DGC accumulator, byte-identical to pre-round-14 encodes.
        A buffered-async tier whose upload will be STALENESS-WEIGHTED by
        ``w < 1`` passes ``ef_decay=w``: only ``w`` of the transmitted
        delta reaches the global, so only ``w`` of the dropped remainder
        is owed back — banking it undecayed would re-inject mass the
        aggregator never discounted, and the accumulator would stop
        draining under sustained staleness ('nothing lost, only delayed'
        must converge; property-pinned in tests/test_buffered.py)."""
        if not 0.0 <= ef_decay <= 1.0:
            raise ValueError(f"ef_decay must be in [0, 1], got {ef_decay}")
        if base_blob is None:
            raise ValueError("topk_delta codec needs the round-base blob")
        deltas = _delta_leaves(blob, base_blob)
        if self._residual is not None and (
            len(self._residual) != len(deltas)
            or any(r.shape != d.shape for r, d in zip(self._residual, deltas))
        ):
            self._residual = None  # model structure changed; residuals stale
        if self._residual is None:
            self._residual = [np.zeros_like(d) for d in deltas]
        manifest = []
        payload = bytearray()
        new_residual = []
        for i, (d, r) in enumerate(zip(deltas, self._residual)):
            if not np.isfinite(d).all():
                # Same contract as Int8Codec: NaNs sort to the END of the
                # magnitude order, so a poisoned delta would transmit an
                # all-finite top-k (CRC-valid, sanitation-passing) while
                # the residual keeps the NaNs forever — laundered poison
                # plus a permanently corrupted accumulator. Fail loudly.
                raise ValueError(
                    f"leaf {i} delta is non-finite; refusing to encode"
                )
            eff = (d + r).ravel()
            k = leaf_k(eff.size, self.fraction)
            idx = topk_select(eff, k)
            vals = eff[idx].astype(np.float32)
            manifest.append({"shape": list(d.shape), "enc": "topk", "k": int(k)})
            payload += idx.tobytes() + vals.tobytes()
            rem = eff.copy()
            rem[idx] = 0.0
            if ef_decay != 1.0:
                rem = rem * np.float32(ef_decay)
            new_residual.append(rem.reshape(d.shape))
        # Commit the drop, but keep the pre-drop state as the rollback
        # target: residual + kept == eff, so restoring eff un-loses the
        # transmitted mass if the server never averages this upload.
        self._rollback = [
            (d + r) for d, r in zip(deltas, self._residual)
        ]
        self._residual = new_residual
        return frames.encode_frame(
            self.name, round, base_version, manifest, bytes(payload)
        )


def get_codec(
    name: str,
    *,
    topk_fraction: float = DEFAULT_TOPK_FRACTION,
    client_tag: str = "",
) -> Codec:
    """Codec registry: one fresh instance per call (TopKDelta carries
    per-client state, so instances must not be shared across clients;
    Int8Codec's ``client_tag`` decorrelates rounding noise across the
    cohort — the transport passes the cname)."""
    if name in ("", CODEC_NULL, None):
        return NullCodec()
    if name == CODEC_INT8:
        return Int8Codec(client_tag=client_tag)
    if name == CODEC_TOPK:
        return TopKDeltaCodec(fraction=topk_fraction)
    raise ValueError(f"unknown update codec {name!r}; known: {CODEC_NAMES}")


def encoded_bytes_model(
    leaf_sizes: Sequence[int],
    codec: str,
    *,
    topk_fraction: float = DEFAULT_TOPK_FRACTION,
) -> int:
    """Analytic pre-zlib wire bytes for one update under ``codec`` — the
    ``bytes_per_round`` counter's model for planes (the on-device mesh twin)
    that never materialize host bytes. Null is the dense float32 payload;
    int8 is one byte per entry plus the scale sidecar; topk is 8 bytes per
    kept entry. Frame/manifest overhead is charged per leaf."""
    per_leaf_overhead = 16
    if codec in ("", CODEC_NULL):
        return int(sum(4 * n for n in leaf_sizes))
    if codec == CODEC_INT8:
        # Codes (1 B/entry) + per-bucket f32 scales. Pre-zlib: the entropy
        # win of near-zero codes is data-dependent, so the model stays
        # conservative (measured frames run 2-3x below this).
        return int(
            sum(
                n + 4 * max(1, -(-n // QSGD_BUCKET)) + per_leaf_overhead
                for n in leaf_sizes
            )
        )
    if codec == CODEC_TOPK:
        return int(
            sum(8 * leaf_k(n, topk_fraction) + per_leaf_overhead for n in leaf_sizes)
        )
    raise ValueError(f"unknown update codec {codec!r}; known: {CODEC_NAMES}")
