"""The compressed-update wire frame: versioned, CRC-checked, self-describing.

Layout (all little-endian)::

    MAGIC "FCWF" (4) | crc32c of body (4, LE uint32) | body

where ``body`` is one msgpack map::

    {"v": 1, "codec": str, "round": int, "base_version": int,
     "leaves": [{"shape": [...], "enc": "int8"|"topk", ...}, ...],
     "zlib": bool, "payload": bytes}

``payload`` is the per-leaf codes concatenated in leaf order (int8: ``n``
quantized bytes; topk: ``k`` int32 indices then ``k`` float32 values),
zlib-compressed when ``zlib`` is true. The CRC covers the whole body, so a
single flipped bit anywhere in a frame — header, manifest, or payload — is
detected BEFORE any reconstruction happens (the chaos suite's
CORRUPT_COMPRESSED_FRAME fault pins this).

``base_version`` is the server model_version of the round-base weights the
delta was computed against; the server refuses a frame whose base does not
match its current version, so a delta can never be applied to the wrong
base (the "unambiguous delta decode" contract from the round template).

The magic bytes cannot collide with a raw update: a legitimate msgpack
weight pytree starts with a map marker (0x8x / 0xde / 0xdf), never ASCII
"F" — so :func:`is_frame` is an exact discriminator on this wire.

Every decode that feeds FedAvg must route its reconstruction through
``fed.serialization.validate_update`` (fedlint rule COMP001 enforces this
statically over ``compress/`` and ``fed/``): the frame CRC proves the bytes
are the bytes the client sent, while validate_update proves the
reconstructed tree is safe to average — a poisoned client can produce a
perfectly CRC-valid NaN frame.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass
from typing import Any, Sequence

import msgpack
import numpy as np

MAGIC = b"FCWF"
FRAME_VERSION = 1

# Manifest + header bytes a frame adds over its raw payload; conservative
# (measured frames sit well under this for any real leaf count).
FRAME_OVERHEAD_BYTES = 4096


def is_frame(blob: bytes) -> bool:
    return len(blob) >= 8 and blob[:4] == MAGIC


def expand_scales(scales: np.ndarray, bucket: int, n: int) -> np.ndarray:
    """Per-entry float32 scale vector from per-bucket scales — THE int8
    scale-expansion rule. Shared by the codec-side dequantizer
    (``codecs.int8_dequantize``) and the frame reconstruction below, so
    the two sides of the wire can never silently diverge. Index-gather,
    not ``np.repeat(scales, bucket)``: the allocation is O(n) regardless
    of ``bucket``, so a manifest declaring an absurd bucket cannot force
    a bucket-sized allocation (the scales-count check still pins
    ``scales.size == ceil(n/bucket)``)."""
    return scales.astype(np.float32, copy=False)[np.arange(n) // int(bucket)]


@dataclass(frozen=True)
class Frame:
    codec: str
    round: int
    base_version: int
    leaves: tuple[dict, ...]
    payload: bytes


def encode_frame(
    codec: str,
    round: int,
    base_version: int,
    leaves: Sequence[dict],
    payload: bytes,
    *,
    compress: bool = True,
) -> bytes:
    """Wrap per-leaf codes into one CRC-checked wire frame. ``compress``
    zlib-deflates the payload (level 1 — the entropy win on near-zero int8
    codes saturates early; higher levels only cost encode time)."""
    from fedcrack_tpu.native import crc32c

    body_payload = zlib.compress(payload, 1) if compress else payload
    body = msgpack.packb(
        {
            "v": FRAME_VERSION,
            "codec": codec,
            "round": int(round),
            "base_version": int(base_version),
            "leaves": list(leaves),
            "zlib": bool(compress),
            "payload": body_payload,
        },
        use_bin_type=True,
    )
    return MAGIC + struct.pack("<I", crc32c(body)) + body


def _manifest_payload_bytes(leaves: Sequence[dict]) -> int:
    """Payload bytes the manifest CLAIMS to carry (int8: n codes/leaf;
    topk: 8k/leaf) — the inflate bound below."""
    total = 0
    for i, spec in enumerate(leaves):
        try:
            n = 1
            for s in spec["shape"]:
                n *= int(s)
            enc = spec["enc"]
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed manifest entry {i} ({e})") from e
        if enc == "int8":
            total += n
        elif enc == "topk":
            total += 8 * int(spec.get("k", 0))
        else:
            raise ValueError(f"leaf {i} has unknown encoding {enc!r}")
    return total


def decode_frame(blob: bytes, *, max_decoded_bytes: int | None = None) -> Frame:
    """Parse + integrity-check a frame. Raises ``ValueError`` with the
    rejection reason (bad magic / CRC mismatch / unknown version /
    malformed manifest) — the server logs the reason to the round's
    ``rejected`` history map.

    ``max_decoded_bytes`` (the server path passes a template-derived
    bound via :func:`decode_update`) arms decompression-bomb protection:
    the manifest's implied payload size must fit the bound, and the zlib
    inflate is hard-capped at that implied size — a frame whose payload
    inflates past its own manifest is a ValueError, never a giant
    allocation escaping the caller's rejection handling as MemoryError."""
    from fedcrack_tpu.native import crc32c

    if not is_frame(blob):
        raise ValueError("not a compressed-update frame (bad magic)")
    declared = struct.unpack("<I", blob[4:8])[0]
    body = blob[8:]
    got = crc32c(body)
    if got != declared:
        raise ValueError(
            f"frame checksum mismatch: computed {got:#010x}, "
            f"declared {declared:#010x}"
        )
    try:
        head = msgpack.unpackb(body, raw=False)
    except Exception as e:
        raise ValueError(f"undecodable frame body ({type(e).__name__})") from e
    if not isinstance(head, dict) or head.get("v") != FRAME_VERSION:
        raise ValueError(
            f"unknown frame version {head.get('v') if isinstance(head, dict) else None!r}"
        )
    leaves = head.get("leaves")
    payload = head.get("payload")
    if not isinstance(leaves, list) or not isinstance(payload, (bytes, bytearray)):
        raise ValueError("malformed frame: missing leaves manifest or payload")
    payload = bytes(payload)
    if head.get("zlib"):
        if max_decoded_bytes is not None:
            implied = _manifest_payload_bytes(leaves)
            if implied > max_decoded_bytes:
                raise ValueError(
                    f"frame manifest implies {implied} payload bytes, "
                    f"caller bound is {max_decoded_bytes}"
                )
            try:
                payload = zlib.decompressobj().decompress(payload, implied + 1)
            except zlib.error as e:
                raise ValueError(f"frame payload inflate failed ({e})") from e
            if len(payload) > implied:
                raise ValueError(
                    "frame payload inflates past its own manifest "
                    f"({implied} bytes declared)"
                )
        else:
            try:
                payload = zlib.decompress(payload)
            except zlib.error as e:
                raise ValueError(f"frame payload inflate failed ({e})") from e
    try:
        # A CRC-valid body can still carry junk-typed fields (round=None,
        # non-dict manifest entries): every coercion failure must surface
        # as ValueError — the only family the server's rejection path
        # catches — never TypeError aborting the RPC stream.
        return Frame(
            codec=str(head.get("codec", "")),
            round=int(head.get("round", 0)),
            base_version=int(head.get("base_version", 0)),
            leaves=tuple(dict(l) for l in leaves),
            payload=payload,
        )
    except (TypeError, ValueError) as e:
        raise ValueError(f"malformed frame fields ({e})") from e


def _reconstruct_deltas(frame: Frame) -> list[np.ndarray]:
    """Per-leaf float32 delta arrays from the frame's manifest + payload,
    with explicit size accounting (a manifest lying about shapes/k fails
    here as a ValueError, never as a silent mis-slice)."""
    out: list[np.ndarray] = []
    off = 0
    buf = frame.payload
    for i, spec in enumerate(frame.leaves):
        try:
            shape = tuple(int(s) for s in spec["shape"])
            enc = spec["enc"]
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed manifest entry {i} ({e})") from e
        n = int(np.prod(shape)) if shape else 1
        if enc == "int8":
            bucket = int(spec.get("bucket", 0))
            scales_raw = spec.get("scales", b"")
            if bucket < 1 or not isinstance(scales_raw, (bytes, bytearray)):
                raise ValueError(f"leaf {i} int8 manifest missing bucket/scales")
            scales = np.frombuffer(bytes(scales_raw), np.float32)
            if scales.size != max(1, -(-n // bucket)):
                raise ValueError(
                    f"leaf {i} carries {scales.size} scales for "
                    f"{n} entries at bucket {bucket}"
                )
            end = off + n
            if end > len(buf):
                raise ValueError(f"frame payload truncated at leaf {i}")
            q = np.frombuffer(buf, np.int8, count=n, offset=off)
            off = end
            per_entry = expand_scales(scales, bucket, n)
            out.append((q.astype(np.float32) * per_entry).reshape(shape))
        elif enc == "topk":
            k = int(spec.get("k", 0))
            if k < 0 or k > n:
                raise ValueError(f"leaf {i} declares k={k} outside [0, {n}]")
            end = off + 8 * k
            if end > len(buf):
                raise ValueError(f"frame payload truncated at leaf {i}")
            idx = np.frombuffer(buf, np.int32, count=k, offset=off)
            vals = np.frombuffer(buf, np.float32, count=k, offset=off + 4 * k)
            off = end
            if k and (idx.min() < 0 or idx.max() >= n):
                raise ValueError(
                    f"leaf {i} sparse index out of range for {n} entries"
                )
            dense = np.zeros(n, np.float32)
            dense[idx] = vals
            out.append(dense.reshape(shape))
        else:
            raise ValueError(f"leaf {i} has unknown encoding {enc!r}")
    if off != len(buf):
        raise ValueError(
            f"frame payload has {len(buf) - off} trailing bytes past the manifest"
        )
    return out


def decode_update(
    blob: bytes,
    template: Any,
    base: Any,
    *,
    expected_base_version: int | None = None,
    expected_round: int | None = None,
) -> tuple[Any, Frame]:
    """Server-side decode of a framed update into a full weight pytree.

    ``template`` fixes structure/dtypes (the server's float32 decode
    template), ``base`` is the round-base global pytree the delta applies
    to. ``expected_base_version`` pins the delta to the server's current
    model_version — a frame built against any other base is REJECTED
    (stale-base), because applying it would reconstruct garbage weights
    that still pass every shape check.

    Raises ``ValueError`` on any integrity/consistency failure; the caller
    (``fed.rounds``) turns that into a REJECTED + history-logged update and
    must pass the reconstruction through
    ``fed.serialization.validate_update`` before FedAvg (COMP001).
    """
    import jax

    flat_template, treedef = jax.tree_util.tree_flatten(template)
    # Decompression bound from the TEMPLATE, not the manifest: the largest
    # honest payload is 8 bytes/entry (topk), so any frame claiming more
    # is rejected before a single byte inflates.
    total_entries = sum(
        int(np.prod(np.shape(t))) if np.shape(t) else 1 for t in flat_template
    )
    frame = decode_frame(blob, max_decoded_bytes=8 * total_entries + 1024)
    if expected_base_version is not None and frame.base_version != expected_base_version:
        raise ValueError(
            f"stale round base: frame delta is against model_version "
            f"{frame.base_version}, server is at {expected_base_version}"
        )
    if expected_round is not None and frame.round != expected_round:
        raise ValueError(
            f"frame round {frame.round} does not match message round "
            f"{expected_round}"
        )
    flat_base = jax.tree_util.tree_leaves(base)
    if len(flat_base) != len(flat_template):
        raise ValueError(
            f"base has {len(flat_base)} leaves, template expects "
            f"{len(flat_template)}"
        )
    if len(frame.leaves) != len(flat_template):
        raise ValueError(
            f"frame carries {len(frame.leaves)} leaves, template expects "
            f"{len(flat_template)}"
        )
    # Manifest shapes are pinned to the template BEFORE reconstruction: the
    # declared shape sizes every allocation below, so a lying manifest
    # (e.g. shape [10**12] with k=0, which no payload-size check would
    # bound) must fail here as a ValueError — never as a giant allocation
    # escaping the caller's rejection handling as a MemoryError.
    for i, (spec, t) in enumerate(zip(frame.leaves, flat_template)):
        try:
            declared = tuple(int(s) for s in spec["shape"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed manifest entry {i} ({e})") from e
        t_shape = tuple(np.shape(t))
        if declared != t_shape:
            raise ValueError(
                f"leaf {i} shape mismatch: frame {declared}, template "
                f"{t_shape}"
            )
    deltas = _reconstruct_deltas(frame)
    leaves = []
    for d, b, t in zip(deltas, flat_base, flat_template):
        t_arr = np.asarray(t)
        leaves.append(
            (np.asarray(b, np.float32) + d).astype(t_arr.dtype).reshape(t_arr.shape)
        )
    return jax.tree_util.tree_unflatten(treedef, leaves), frame
