"""On-device encode∘decode twins for the mesh plane.

The gRPC plane compresses real wire bytes (``codecs``/``frames``); the mesh
plane has no wire — every "client" lives on a chip and FedAvg is an ICI
psum. What compression changes there is the TRAJECTORY: quantization error
and sparsification delay perturb each client's contribution before the
average. These twins apply the identical encode-then-decode value map to
the per-client round delta ON DEVICE, inside the round program, so
``run_mesh_federation`` can A/B trajectory quality (crack-IoU vs the
NullCodec oracle) at zero host cost — no bytes ever leave HBM.

Value-map parity with the host codecs (same scale rule, same keep rule):

- int8: QSGD bucketed symmetric quantization — per-bucket scale
  ``||bucket||_2 / 127`` (identical to :func:`codecs.qsgd_scales`) with
  stochastic rounding ``floor(x/scale + u)``. The uniform draws come from
  the JAX PRNG (per call / per client / per leaf fold-ins) rather than the
  host codec's numpy generator, so int8 parity is distributional
  (unbiased, same scales, same error bound), not bitwise.
- topk_delta: per-leaf top-k by magnitude of (delta + error-feedback
  residual), untransmitted mass carried to the next round. ``lax.top_k``
  breaks magnitude ties by lowest index, same as the host codec's stable
  argsort — bitwise the same keep set.

The twins run inside ``shard_map`` blocks where each leaf is ONE client's
(per-shard) value — :func:`fedcrack_tpu.parallel.fedavg_mesh._build_round`
threads the error-feedback state through the program as a
``P('clients')``-sharded pytree, so the accumulator never leaves device.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from fedcrack_tpu.compress.codecs import CODEC_NAMES, QSGD_BUCKET, leaf_k

MESH_CODECS = CODEC_NAMES  # same registry, same names


def int8_roundtrip(tree: Any, key, bucket: int = QSGD_BUCKET) -> Any:
    """QSGD bucketed int8 quantize-dequantize (the Int8Codec value map):
    per-bucket norm scale, stochastic rounding from ``key`` (folded per
    leaf). Float32 math; codes never exceed |127| because
    ``|x| <= ||bucket||_2``."""

    def leaf(i, x):
        x32 = x.astype(jnp.float32)
        flat = x32.ravel()
        n = flat.size
        n_buckets = max(1, -(-n // bucket))
        padded = jnp.pad(flat, (0, n_buckets * bucket - n))
        segs = padded.reshape(n_buckets, bucket)
        norms = jnp.sqrt(jnp.sum(segs * segs, axis=1))
        scales = jnp.where(norms > 0.0, norms / 127.0, 1.0)
        u = jax.random.uniform(jax.random.fold_in(key, i), segs.shape)
        q = jnp.clip(jnp.floor(segs / scales[:, None] + u), -127.0, 127.0)
        deq = (q * scales[:, None]).reshape(-1)[:n]
        return deq.reshape(x.shape).astype(x.dtype)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [leaf(i, x) for i, x in enumerate(leaves)]
    )


def topk_roundtrip(tree: Any, residual: Any, fraction: float) -> tuple[Any, Any]:
    """Per-leaf top-k keep of (delta + residual); returns (kept, new
    residual). ``k`` is static per leaf (``ceil(fraction * n)``, floored at
    1), so the program shape is round-independent."""

    def leaf(x, r):
        x32 = x.astype(jnp.float32)
        eff = (x32 + r.astype(jnp.float32)).ravel()
        k = leaf_k(eff.size, fraction)
        _, idx = lax.top_k(jnp.abs(eff), k)
        kept = jnp.zeros_like(eff).at[idx].set(eff[idx])
        new_r = eff - kept
        return kept.reshape(x.shape).astype(x.dtype), new_r.reshape(x.shape)

    flat_x, treedef = jax.tree_util.tree_flatten(tree)
    flat_r = jax.tree_util.tree_leaves(residual)
    pairs = [leaf(x, r) for x, r in zip(flat_x, flat_r)]
    kept = jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs])
    new_res = jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs])
    return kept, new_res


def zero_residual_like(tree: Any) -> Any:
    """A float32 zero accumulator matching ``tree`` — the error-feedback
    state's round-0 value."""
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.float32), tree
    )


def validate_mesh_codec(codec: str | None) -> str:
    name = codec or "null"
    if name not in MESH_CODECS:
        raise ValueError(f"unknown update codec {name!r}; known: {MESH_CODECS}")
    return name
