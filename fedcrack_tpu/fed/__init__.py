from fedcrack_tpu.fed.algorithms import fedavg, fedprox_penalty  # noqa: F401
from fedcrack_tpu.fed.serialization import (  # noqa: F401
    tree_from_bytes,
    tree_to_bytes,
    validate_update,
)
from fedcrack_tpu.fed.rounds import (  # noqa: F401
    ServerState,
    initial_state,
    transition,
)
