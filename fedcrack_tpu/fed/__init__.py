from fedcrack_tpu.fed.algorithms import (  # noqa: F401
    fedavg,
    fedprox_penalty,
    sample_cohort,
)
from fedcrack_tpu.fed.serialization import (  # noqa: F401
    tree_from_bytes,
    tree_to_bytes,
    validate_update,
)
from fedcrack_tpu.fed.rounds import (  # noqa: F401
    ServerState,
    decode_and_validate_update,
    initial_state,
    quorum_target,
    transition,
)
