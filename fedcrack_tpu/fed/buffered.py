"""FedBuff-style buffered-asynchronous aggregation (round 14).

Everything before this round is barrier-synchronous: the gRPC round machine
(:mod:`fedcrack_tpu.fed.rounds`), the mesh drivers, and the r13 cohort/tree
tiers all close a round only when K-of-N updates are in — so one straggler
stalls the whole federation, exactly the failure mode the reference's
single-stream FedAvg server inherits. FedBuff (Nguyen et al., 2022) removes
the barrier server-side: updates are accepted AS THEY ARRIVE, weighted by a
polynomial staleness decay (FedAsync, Xie et al., 2019), folded into a
K-sized buffer, and flushed to a new global version at K. Clients loop
pull→train→push continuously; a slow client's update lands late, stale and
down-weighted — never blocking.

This module is that server: the :class:`BufferedAggregator` state machine,
a pure alternative to the round barrier in ``fed/rounds.py`` operating on
the SAME immutable :class:`~fedcrack_tpu.fed.rounds.ServerState`
(``rounds.transition`` dispatches ``PullWeights``/``TrainDone`` here when
``FedConfig.mode == "buffered"``). Everything composes with the machinery
already in the tree:

- every accepted update passes the one shared acceptance gate
  (``rounds.decode_and_validate_update``), decoded against the base the
  client ACTUALLY pulled — the server tracks per-client pulled versions and
  retains a ``max_staleness``-bounded window of past broadcast blobs, so a
  stale framed delta reconstructs against the right base or is rejected;
- the flush is a SORTED fold (entries ordered by ``(cname, seq)``, the r13
  ordered-fold discipline): the flushed global is a pure function of the
  buffer CONTENTS, never of cross-client arrival order (fedlint ASYNC001
  pins this statically, tests pin it dynamically);
- buffer, per-client pulled versions and the retained base window persist
  in the r8 atomic statefile, so a server killed MID-BUFFER restarts with
  the already-accepted updates intact and flushes to the bit-identical
  next global version (drilled by ``tools/chaos_drill``);
- ``buffer_k = cohort_size`` with ``staleness_alpha = 0`` degenerates to
  sync FedAvg BIT-exactly: weight ``ns * (1+s)^0 == ns`` as the same float,
  the sorted fold is the same ``fedavg`` call over the same decoded trees,
  and the FedOpt server step is the shared ``rounds.apply_fedopt``.

Observability: each flush appends a history entry carrying
``updates_per_sec``, ``buffer_fill``, the per-update ``staleness`` list and
``global_version``; :func:`async_summary` reduces a history to staleness
percentiles through :class:`fedcrack_tpu.obs.metrics.StreamingPercentiles`
for the bench payload and the chaos drills.
"""

from __future__ import annotations

import jax

from fedcrack_tpu.fed import aggregation as _aggregation
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.health import ledger as _health_ledger

MODE_SYNC = "sync"
MODE_BUFFERED = "buffered"


def staleness_weight(staleness: int, alpha: float) -> float:
    """The FedAsync polynomial decay ``(1 + staleness)^-alpha``.

    Closed form, exact at the edges (test-pinned): ``alpha == 0`` yields
    exactly ``1.0`` for EVERY staleness (Python float ``x ** -0.0 == 1.0``),
    which is what makes the sync-FedAvg degeneration bit-exact — the
    effective FedAvg weight ``ns * 1.0`` is the same float as ``ns``.
    """
    if staleness < 0:
        raise ValueError(f"staleness must be >= 0, got {staleness}")
    if alpha < 0.0:
        raise ValueError(f"staleness alpha must be >= 0, got {alpha}")
    return float((1.0 + float(staleness)) ** (-float(alpha)))


def _entry_sort_key(entry: dict) -> tuple:
    """The sorted-flush order: ``(cname, seq)``. ``seq`` is the entry's
    per-CLIENT arrival index within the current buffer — a client's own
    uploads are ordered by its own session (deterministic), so the key is
    independent of how uploads from DIFFERENT clients interleaved."""
    return (entry["cname"], entry["seq"])


# The 9-field wire row for one buffer entry — ONE codec for every place a
# buffer crosses a serialization boundary (the server statefile, the edge
# statefile), so a field added to the entry is added in exactly one
# encode/decode pair instead of drifting across positional copies.
def buffer_entry_to_wire(e: dict) -> list:
    return [
        e["cname"], int(e["seq"]), e["blob"], int(e["ns"]),
        int(e["staleness"]), float(e["weight"]), int(e["base_version"]),
        int(e["wire_len"]), e["codec"],
    ]


def buffer_entry_from_wire(row) -> dict:
    return {
        "cname": str(row[0]),
        "seq": int(row[1]),
        "blob": bytes(row[2]),
        "ns": int(row[3]),
        "staleness": int(row[4]),
        "weight": float(row[5]),
        "base_version": int(row[6]),
        "wire_len": int(row[7]),
        "codec": str(row[8]),
    }


def decode_buffer(buffer, template) -> tuple:
    """The decode half of the buffered fold: entries sorted by ``(cname,
    seq)``, decoded against ``template``. Returns ``(entries_sorted,
    counts, eff, trees)`` aligned lists — split from the combine (round
    21) so the root flush can ledger-score the decoded trees BEFORE
    folding and quarantine flagged entries out of the triples."""
    if not buffer:
        raise RuntimeError("fold of an empty buffer")
    entries = sorted(buffer, key=_entry_sort_key)
    trees = [tree_from_bytes(e["blob"], template=template) for e in entries]
    counts = [e["ns"] for e in entries]
    eff = [e["ns"] * e["weight"] for e in entries]
    return entries, counts, eff, trees


def fold_buffer(buffer, template) -> tuple:
    """THE staleness-weighted sorted fold, shared by the root flush and
    the edge tier's ``flush_partial`` (one fold, all tiers — the same
    discipline as ``decode_and_validate_update``): entries sorted by
    ``(cname, seq)``, decoded against ``template``, combined through the
    aggregation algebra's null instance (fed/aggregation.py) with
    effective weight ``ns * staleness_weight``. Returns ``(avg_tree,
    entries_sorted, counts, eff, trees)`` — ``eff`` and ``trees`` aligned
    with ``entries_sorted`` (the decoded trees, so the flush-time health
    scoring reuses this decode instead of paying a second one); the
    average is unweighted when every effective weight is zero (mirroring
    the sync barrier — ``eff[i] > 0`` iff ``ns[i] > 0``, the staleness
    decay being strictly positive)."""
    entries, counts, eff, trees = decode_buffer(buffer, template)
    triples = [
        (e["cname"], w, t) for e, w, t in zip(entries, eff, trees)
    ]
    avg = _aggregation.fold(_aggregation.FedAvg(), triples)
    return avg, entries, counts, eff, trees


# Decoded-base memo for the accept path: version -> (blob, tree). Every
# framed upload decodes its delta against a retained base; without the
# memo the single-writer transition pays a full-model decode PER PUSH on
# the continuous-loop hot path (the exact cost rounds._decoded_round_base
# exists to kill on the sync plane). Keyed by version AND the blob bytes
# (identity fast-path, equality fallback) so two servers sharing the
# process-wide memo at worst thrash and re-decode — correctness is
# carried by the key, never by which server wrote the entry. Pruned to
# the caller's retained window on every miss.
_BASE_TREE_MEMO: dict = {}


def _decoded_base(state: "R.ServerState", version: int, blob: bytes):
    hit = _BASE_TREE_MEMO.get(version)
    if hit is not None and (hit[0] is blob or hit[0] == blob):
        return hit[1]
    tree = tree_from_bytes(blob, template=state.template)
    _BASE_TREE_MEMO[version] = (blob, tree)
    for v in sorted(_BASE_TREE_MEMO):
        if v not in state.base_blobs:
            del _BASE_TREE_MEMO[v]
    return tree


class BufferedAggregator:
    """The buffered-mode event handlers, as pure transitions over
    :class:`~fedcrack_tpu.fed.rounds.ServerState` — same single-writer
    contract as ``rounds.transition`` (which is the only caller).

    State layout (all on ``ServerState``, all statefile-persisted):

    - ``pulled``: cname -> the model_version that client last pulled (the
      base its next update is trained on — framed deltas are pinned to it).
    - ``buffer``: the accepted-but-unflushed updates, each a dict of
      ``{cname, seq, blob (decoded full tree), ns, staleness, weight,
      base_version, wire_len, codec}``.
    - ``base_blobs``: version -> broadcast blob, retained for the last
      ``max_staleness`` versions so stale framed deltas can reconstruct.
    """

    # -- pull tracking --

    @staticmethod
    def record_pull(state: R.ServerState, cname: str) -> R.ServerState:
        """A client pulled the current global: remember which version it now
        holds — the base its next upload decodes against and the anchor of
        its staleness."""
        pulled = dict(state.pulled)
        pulled[cname] = state.model_version
        return state._replace(pulled=pulled)

    # -- the accept path --

    @staticmethod
    def offer(
        state: R.ServerState, event: R.TrainDone
    ) -> tuple[R.ServerState, R.Reply]:
        """One client upload, buffered-mode. Decodes against the base the
        client actually pulled, staleness-gates, staleness-weights, folds
        into the buffer, and flushes at ``buffer_k``. Sanitation failures
        are REJECTED (fail loudly, like sync); too-stale or base-less
        updates are recorded to the history's ``rejected`` map and the
        sender is RE-SYNCED with the current global (``NOT_WAIT`` — the
        sync straggler treatment: tolerated by the aggregator, averaged
        never)."""
        cname, ns, now = event.cname, event.num_samples, event.now
        if cname not in state.cohort:
            if cname in state.ledger:
                state = state._replace(
                    ledger=_health_ledger.record_offer(
                        state.ledger, cname, outcome="rejected",
                        reason_class="not_in_cohort",
                        round=state.current_round,
                    )
                )
            return state, R.Reply(
                status=R.REJECTED, config={"reason": "not in cohort"}
            )
        cfg = state.config
        base_version = state.pulled.get(cname)
        if base_version is None:
            # No recorded pull (client pushed before pulling, or the record
            # predates a server restart that lost no statefile but a client
            # raced it): there is no base to decode/staleness this update
            # against. Resync — the client pulls fresh and retrains.
            return BufferedAggregator._resync(
                state, cname, "no recorded base version (pull before push)"
            )
        staleness = state.model_version - int(base_version)
        if staleness > cfg.max_staleness:
            return BufferedAggregator._resync(
                state,
                cname,
                f"too stale: base version {base_version} is {staleness} "
                f"behind (max_staleness={cfg.max_staleness})",
                staleness=staleness,
            )
        base_blob = state.base_blobs.get(int(base_version))
        if base_blob is None:
            # Inside the staleness window but the base was not retained —
            # only possible across a config change or a pre-round-14
            # statefile. Same treatment as too-stale.
            return BufferedAggregator._resync(
                state, cname, f"base version {base_version} no longer retained"
            )
        blob, wire_len, codec_name, problem, norm = R.decode_and_validate_update(
            event.blob,
            ns,
            template=state.template,
            base_fn=lambda: _decoded_base(state, int(base_version), base_blob),
            base_version=int(base_version),
            sanitize=cfg.sanitize_updates,
        )
        if problem is not None:
            rejected = dict(state.rejected)
            rejected[cname] = problem
            state = state._replace(
                rejected=rejected,
                ledger=_health_ledger.record_offer(
                    state.ledger, cname, outcome="rejected",
                    reason_class="sanitation", num_samples=ns,
                    wire_len=wire_len, round=state.current_round,
                    staleness=staleness,
                ),
            )
            return state, R.Reply(
                status=R.REJECTED,
                config={"reason": f"update rejected: {problem}"},
            )
        seq = sum(1 for e in state.buffer if e["cname"] == cname)
        entry = {
            "cname": cname,
            "seq": seq,
            "blob": blob,
            "ns": int(ns),
            "staleness": int(staleness),
            "weight": staleness_weight(staleness, cfg.staleness_alpha),
            "base_version": int(base_version),
            "wire_len": int(wire_len),
            "codec": codec_name,
        }
        state = state._replace(
            buffer=state.buffer + (entry,),
            ledger=_health_ledger.record_offer(
                state.ledger, cname, outcome="accepted", num_samples=ns,
                wire_len=wire_len, round=state.current_round,
                staleness=staleness, norm=norm,
            ),
        )
        if (
            state.phase == R.PHASE_RUNNING
            and len(state.buffer) >= cfg.buffer_k
        ):
            state = BufferedAggregator.flush(state, now)
            # The reply carries the freshly flushed global: the sender now
            # holds the new version (recorded, so its next framed delta is
            # pinned to what it actually adopted).
            state = BufferedAggregator.record_pull(state, cname)
            if cname in state.history[-1]["quarantined"]:
                # The flush-triggering client was quarantined out of its
                # own flush: NOT_WAIT (the sanitation-reject treatment) so
                # the direct reply fires the client-side codec rollback —
                # a topk sender's error-feedback residual re-enters
                # instead of being dropped as "sent". Mirrors the sync
                # barrier's quarantined-trigger path.
                return state, R.Reply(
                    status=R.NOT_WAIT,
                    blob=state.broadcast_blob,
                    config=R._ready_config(state, R.NOT_WAIT),
                )
            status = R.FIN if state.phase == R.PHASE_FINISHED else R.RESP_ARY
            return state, R.Reply(
                status=status,
                blob=state.broadcast_blob,
                config=R._ready_config(state, status),
            )
        return state, R.Reply(
            status=R.RESP_ACY, config=R._ready_config(state, R.RESP_ACY)
        )

    @staticmethod
    def _resync(
        state: R.ServerState, cname: str, reason: str, staleness: int = 0
    ) -> tuple[R.ServerState, R.Reply]:
        """Record the refusal (observable forever, averaged never) and hand
        the sender the current global so it rejoins instead of dying."""
        rejected = dict(state.rejected)
        rejected[cname] = reason
        state = state._replace(
            rejected=rejected,
            ledger=_health_ledger.record_offer(
                state.ledger, cname, outcome="resync",
                round=state.current_round, staleness=staleness,
            ),
        )
        state = BufferedAggregator.record_pull(state, cname)
        return state, R.Reply(
            status=R.NOT_WAIT,
            blob=state.broadcast_blob,
            config=R._ready_config(state, R.NOT_WAIT),
        )

    # -- the flush --

    @staticmethod
    def flush(state: R.ServerState, now: float) -> R.ServerState:
        """Fold the buffer into a new global version.

        The fold is SORTED by ``(cname, seq)`` — arrival-order independent
        by construction (test-pinned: permuted arrival orders flush to
        byte-identical globals) — and each entry weighs
        ``num_samples * staleness_weight``. The buffer mean is then
        ANCHORED on the current global FedAsync-style: ``new = (1 - mix) *
        current + mix * buffer_mean`` with ``mix`` the sample-weighted
        MEAN staleness weight of the flush. Within-buffer weights set
        relative contributions; ``mix`` is what keeps a stale-dominated
        flush (e.g. the deadline backstop firing on one straggler) from
        REPLACING the global with a model trained on an old base — the
        weights would otherwise normalize away (the FedAsync mixing rule,
        generalized to a buffer). An all-fresh buffer has ``mix == 1.0``
        EXACTLY (every weight is exactly 1.0), so the anchor is skipped
        and ``staleness_alpha = 0`` + ``buffer_k == cohort_size`` still
        reproduces the sync barrier's aggregation bit-exactly. The FedOpt
        server step and the history/accounting shape mirror
        ``rounds._aggregate``.
        """
        import numpy as np

        entries, counts, eff, trees = decode_buffer(
            state.buffer, state.template
        )
        # Health ledger (round 18): score this flush's geometry on the
        # already-decoded trees, in the fold's own sorted order. The base
        # is the CURRENT global for every entry — a uniform reference
        # despite per-entry pull bases; norms at the gate kept the
        # per-base geometry, this window scores cohort coherence. Round
        # 21 moved the scoring BEFORE the fold so the scores can GATE it
        # (quarantine_z), mirroring rounds._aggregate.
        new_ledger, scores = _health_ledger.observe_flush(
            state.ledger,
            [(e["cname"], t) for e, t in zip(entries, trees)],
            tree_from_bytes(state.global_blob, template=state.template),
        )
        quarantined = _aggregation.quarantine_set(
            scores, [e["cname"] for e in entries], state.config.quarantine_z
        )
        for qname in sorted(quarantined):
            new_ledger = _health_ledger.record_quarantine(new_ledger, qname)
        keep = [
            i for i, e in enumerate(entries)
            if e["cname"] not in quarantined
        ]
        avg = _aggregation.fold(
            _aggregation.from_config(state.config),
            [(entries[i]["cname"], eff[i], trees[i]) for i in keep],
        )
        # The FedAsync mix anchor is computed over the KEPT entries only —
        # a quarantined update must pull the global toward nothing, not
        # even through the mix ratio.
        kept_counts = [counts[i] for i in keep]
        kept_eff = [eff[i] for i in keep]
        mix = 1.0
        total_ns = float(sum(kept_counts))
        if any(c > 0 for c in kept_counts):
            mix = float(sum(kept_eff)) / total_ns
        if mix < 1.0:
            current = tree_from_bytes(state.global_blob, template=state.template)
            keep, take = np.float32(1.0 - mix), np.float32(mix)
            avg = jax.tree_util.tree_map(
                lambda c, u: keep * np.asarray(c, np.float32)
                + take * np.asarray(u, np.float32),
                current,
                avg,
            )
        avg, opt_state = R.apply_fedopt(state, avg)
        new_blob = tree_to_bytes(avg)
        cast = R._wire_cast(state.config)
        new_wire_blob = tree_to_bytes(avg, cast_dtype=cast) if cast else b""
        new_version = state.model_version + 1
        new_round = state.current_round + 1
        finished = new_round > state.config.max_rounds
        wall = (
            now - state.round_started_at
            if state.round_started_at is not None
            else None
        )
        entry = {
            "round": state.current_round,
            "mode": MODE_BUFFERED,
            "clients": [e["cname"] for e in entries],
            "samples": counts,
            "staleness": [e["staleness"] for e in entries],
            "weights": [e["weight"] for e in entries],
            "mix": mix,
            "buffer_fill": len(entries),
            "global_version": new_version,
            "completed_at": now,
            "wall_clock_s": wall,
            "updates_per_sec": (
                len(entries) / wall if wall is not None and wall > 0 else None
            ),
            "bytes_received": sum(e["wire_len"] for e in entries),
            "decoded_bytes_received": sum(len(e["blob"]) for e in entries),
            "codecs": [e["codec"] for e in entries],
            "bytes_broadcast": len(new_wire_blob or new_blob),
            "cohort_size": len(state.cohort),
            "rejected": dict(state.rejected),
            # Round 21: cname -> the robust-z score that excluded it from
            # the fold (empty = everyone folded). The per-entry lists
            # above keep their historical meaning (what the BUFFER held).
            "quarantined": quarantined,
        }
        # DP accountant on the async plane (round 23): every buffered entry
        # is ONE local training run whose noise is already in the blob, so
        # each entry charges its sender ``dp_steps_per_round`` — including
        # quarantined entries (the budget was SPENT client-side; exclusion
        # from the fold refunds nothing). Mirrors rounds._aggregate: the
        # epsilon map lands in the flush history entry and a breached
        # budget finishes the federation loudly.
        privacy_steps = state.privacy_steps
        if state.config.dp_noise_multiplier > 0.0:
            steps_per = (
                state.config.dp_steps_per_round or state.config.local_epochs
            )
            privacy_steps = dict(privacy_steps)
            for e in entries:
                privacy_steps[e["cname"]] = (
                    privacy_steps.get(e["cname"], 0) + int(steps_per)
                )
            epsilons = R._epsilons_for(state.config, privacy_steps)
            entry["epsilon"] = epsilons
            budget = state.config.dp_epsilon_budget
            if budget > 0.0 and epsilons and max(epsilons.values()) >= budget:
                entry["epsilon_budget_exhausted"] = True
                finished = True
        # Retained-base window: the new broadcast joins, versions older
        # than max_staleness leave — the delta-decode memory bound.
        bases = {
            v: b
            for v, b in sorted(state.base_blobs.items())
            if new_version - v <= state.config.max_staleness
        }
        bases[new_version] = new_wire_blob or new_blob
        return state._replace(
            ledger=new_ledger,
            privacy_steps=privacy_steps,
            global_blob=new_blob,
            wire_blob=new_wire_blob,
            current_round=new_round,
            model_version=new_version,
            buffer=(),
            rejected={},
            base_blobs=bases,
            round_started_at=now,
            phase=R.PHASE_FINISHED if finished else R.PHASE_RUNNING,
            history=state.history + (entry,),
            server_opt_state=opt_state,
        )

    @staticmethod
    def advance_time(state: R.ServerState, now: float) -> R.ServerState:
        """Buffered-mode pure time effects, called from
        ``rounds._advance_time`` AFTER the shared enrollment machinery: a
        buffer that reached K while enrollment was still open flushes on
        the transition to RUNNING, and ``round_deadline_s`` becomes the
        flush-liveness backstop — a PARTIAL buffer older than the deadline
        flushes rather than stalling the version counter behind absent
        clients (there is no cohort to shrink; the buffer is the quorum)."""
        cfg = state.config
        if state.phase != R.PHASE_RUNNING:
            return state
        if state.buffer and len(state.buffer) >= cfg.buffer_k:
            return BufferedAggregator.flush(state, now)
        if (
            cfg.round_deadline_s > 0
            and state.round_started_at is not None
            and now - state.round_started_at >= cfg.round_deadline_s
        ):
            if state.buffer:
                return BufferedAggregator.flush(state, now)
            # Nothing buffered: re-arm the window instead of hot-firing on
            # every tick.
            return state._replace(round_started_at=now)
        return state


def async_summary(history: tuple) -> dict:
    """Reduce a buffered-mode history to the async-plane headline numbers:
    total accepted updates, global versions, the per-update staleness
    distribution (p50/p95/p99 via the obs reservoir — exact until
    capacity), and mean buffer fill. Sync entries (no ``buffer_fill``) are
    ignored, so mixed histories summarize their buffered portion."""
    from fedcrack_tpu.obs.metrics import StreamingPercentiles

    stale = StreamingPercentiles(seed=0)
    updates = 0
    fills = []
    versions = 0
    for h in history:
        if "buffer_fill" not in h:
            continue
        versions += 1
        fills.append(h["buffer_fill"])
        for s in h.get("staleness", ()):
            stale.add(float(s))
            updates += 1
    return {
        "accepted_updates": updates,
        "global_versions": versions,
        "mean_buffer_fill": (sum(fills) / len(fills)) if fills else None,
        "staleness": stale.summary(),
    }
