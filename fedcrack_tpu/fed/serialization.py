"""Pytree <-> bytes for the wire and for checkpoints. No pickle.

The reference ships ``pickle.dumps(model.get_weights())`` over gRPC and
unpickles untrusted client bytes on the server (reference: fl_client.py:63,
fl_server.py:179) — a remote-code-execution hazard (SURVEY.md §5.8). Here
payloads are Flax's msgpack encoding of the weight pytree: data-only (no
code execution on load), cross-version stable, and ~40% smaller than pickled
float32 lists when combined with bf16 casting.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from flax import serialization


def tree_to_bytes(tree: Any, cast_dtype: str | None = None) -> bytes:
    """Serialize a pytree of arrays to msgpack bytes.

    ``cast_dtype="bfloat16"`` halves wire size for weight broadcast/upload;
    values are restored to their original dtype structure by the receiver's
    template in :func:`tree_from_bytes`.
    """
    host = jax.device_get(tree)
    if cast_dtype is not None:
        dt = np.dtype(cast_dtype)
        host = jax.tree_util.tree_map(lambda a: np.asarray(a).astype(dt), host)
    return serialization.msgpack_serialize(host)


def validate_update(blob: Any, template: Any) -> str | None:
    """Sanitation gate for an untrusted client update: the reason the blob
    must NOT enter FedAvg, or None when it is clean.

    Checks, in order of what corrupts an aggregation worst-first: the bytes
    decode at all (truncated/mangled wire), the leaf count matches the
    global template, every leaf's shape matches exactly (a same-size
    transpose would silently reshape into garbage weights), and every
    numeric leaf is fully finite (one NaN client otherwise propagates into
    the global average and from there to every client). Wire-dtype casts
    (bfloat16 uploads) pass untouched — shape, not dtype, is the contract.

    ``blob`` may also be an already-materialized pytree (the compressed-
    frame path validates its reconstruction directly, skipping a redundant
    encode∘decode round-trip per upload); bytes take the wire decode first.
    """
    if isinstance(blob, (bytes, bytearray)):
        try:
            raw = serialization.msgpack_restore(bytes(blob))
        except Exception as e:  # msgpack raises several exception families
            return f"undecodable payload ({type(e).__name__})"
    else:
        raw = blob
    flat_raw = jax.tree_util.tree_leaves(raw)
    flat_template = jax.tree_util.tree_leaves(template)
    if len(flat_raw) != len(flat_template):
        return (
            f"leaf count mismatch: payload has {len(flat_raw)}, "
            f"template expects {len(flat_template)}"
        )
    for i, (r, t) in enumerate(zip(flat_raw, flat_template)):
        if np.shape(r) != np.shape(np.asarray(t)):
            return (
                f"leaf {i} shape mismatch: payload {np.shape(r)}, "
                f"template {np.shape(np.asarray(t))}"
            )
        try:
            arr = np.asarray(r).astype(np.float32)
        except (TypeError, ValueError):
            return f"leaf {i} is non-numeric"
        if not np.isfinite(arr).all():
            return f"leaf {i} has non-finite values"
    return None


def tree_from_bytes(blob: bytes, template: Any | None = None) -> Any:
    """Deserialize msgpack bytes back to a pytree.

    With a ``template`` pytree the result is restored into the template's
    exact structure and leaf dtypes (so a bf16-cast wire payload lands back
    in f32 params). Without one, returns the raw nested-dict decoding.
    """
    raw = serialization.msgpack_restore(blob)
    if template is None:
        return raw
    flat_template, treedef = jax.tree_util.tree_flatten(template)
    flat_raw = jax.tree_util.tree_leaves(raw)
    if len(flat_raw) != len(flat_template):
        raise ValueError(
            f"payload has {len(flat_raw)} leaves, template expects {len(flat_template)}"
        )
    cast = [
        np.asarray(r).astype(np.asarray(t).dtype).reshape(np.shape(t))
        for r, t in zip(flat_raw, flat_template)
    ]
    return jax.tree_util.tree_unflatten(treedef, cast)
