"""Federated aggregation as pure pytree math.

The reference's FedAvg is a Python loop over pickled Keras weight lists:
element-wise sum then division by the client count (reference:
fl_server.py:92-105 ``updateWeight``), with two accidents fixed here
(SURVEY.md §2.2(1,2)): the average is actually broadcast, and the buffer is
per-round. BatchNorm moving statistics are averaged along with the kernels —
the reference implicitly does the same since ``get_weights()`` includes BN
moments (SURVEY.md §7 "hard parts").

These functions are pure jnp and run identically on the gRPC control plane
(host, numpy arrays) and inside the one-program mesh round
(``fedcrack_tpu.parallel``, via masked psum — see fedavg_mesh.py).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _fedavg_native(updates: Sequence[Any], weights: Sequence[float]) -> Any | None:
    """Host fast path: the server-side aggregation runs on msgpack-decoded
    numpy trees (fed/serialization.py), where the native OpenMP
    ``weighted_accumulate``/``scale_inplace`` kernels beat per-leaf jnp
    dispatch. Returns None (caller falls back to jnp) unless every leaf of
    every update is a float32 ndarray with a common structure."""
    from fedcrack_tpu import native

    flat0, treedef = jax.tree_util.tree_flatten(updates[0])
    columns: list[list[np.ndarray]] = [[leaf] for leaf in flat0]
    for update in updates[1:]:
        flat, td = jax.tree_util.tree_flatten(update)
        if td != treedef:
            return None
        for col, leaf in zip(columns, flat):
            col.append(leaf)
    for col in columns:
        if not all(
            isinstance(x, np.ndarray) and x.dtype == np.float32 for x in col
        ):
            return None
    total = float(np.sum(np.asarray(weights, np.float64)))
    out = []
    for col in columns:
        acc = np.zeros_like(col[0])
        for wi, x in zip(weights, col):
            native.weighted_accumulate(acc, x, float(wi))
        native.scale_inplace(acc, 1.0 / total)
        out.append(acc)
    return jax.tree_util.tree_unflatten(treedef, out)


def fedavg(updates: Sequence[Any], weights: Sequence[float] | None = None) -> Any:
    """Weighted element-wise mean of K client pytrees.

    ``weights`` are per-client sample counts (proper FedAvg); ``None`` gives
    the reference's unweighted mean (fl_server.py:101-102 divides the sum by
    the client count). All-float32-numpy trees (the gRPC server's decoded
    payloads) take the native accumulate/scale kernels; anything else (device
    arrays, mixed dtypes) takes the jnp path — both are cross-checked in
    tests.
    """
    if not updates:
        raise ValueError("fedavg over zero clients")
    k = len(updates)
    if weights is None:
        raw_w = [1.0] * k
    else:
        if len(weights) != k:
            raise ValueError(f"{len(weights)} weights for {k} updates")
        raw_w = [float(x) for x in weights]
        if sum(raw_w) <= 0:
            raise ValueError("non-positive total weight")

    native_result = _fedavg_native(updates, raw_w)
    if native_result is not None:
        return native_result

    w = jnp.asarray(raw_w, jnp.float32)
    w = w / jnp.sum(w)

    def avg_leaf(*leaves):
        acc = jnp.zeros_like(leaves[0], dtype=jnp.float32)
        for wi, leaf in zip(w, leaves):
            acc = acc + wi * leaf.astype(jnp.float32)
        return acc.astype(leaves[0].dtype)

    return jax.tree_util.tree_map(avg_leaf, *updates)


def sample_cohort(
    n_clients: int,
    cohort_size: int,
    round_idx: int,
    seed: int = 0,
) -> np.ndarray:
    """The round's cohort: a seeded, sorted, without-replacement sample of
    ``cohort_size`` client indices from the ``n_clients`` population
    (round 13 — cross-device FL samples a fresh cohort per round instead
    of training every client every round; Bonawitz et al., MLSys 2019).

    Determinism contract (property-pinned in tests/test_fed.py): the draw
    is a pure function of ``(seed, round_idx)`` — the whole multi-round
    cohort SEQUENCE reproduces from one seed, independent of call order or
    prior draws (each round seeds a fresh ``SeedSequence([seed,
    round_idx])``; no shared RNG state to advance). Sorted output keeps
    downstream group packing / edge partitioning deterministic too.
    """
    if n_clients <= 0:
        raise ValueError(f"n_clients must be positive, got {n_clients}")
    if not 0 < cohort_size <= n_clients:
        raise ValueError(
            f"cohort_size must be in [1, n_clients={n_clients}], got {cohort_size}"
        )
    rng = np.random.default_rng(np.random.SeedSequence([int(seed), int(round_idx)]))
    picks = rng.choice(n_clients, size=cohort_size, replace=False)
    return np.sort(picks.astype(np.int64))


def fedprox_penalty(params: Any, anchor: Any, mu: float) -> jax.Array:
    """(mu/2)||params - anchor||^2 — the FedProx proximal term added to the
    client loss on non-IID shards (BASELINE.md config 4)."""
    sq = jax.tree_util.tree_map(
        lambda a, b: jnp.sum((a.astype(jnp.float32) - b.astype(jnp.float32)) ** 2),
        params,
        anchor,
    )
    return 0.5 * mu * jax.tree_util.tree_reduce(jnp.add, sq, jnp.zeros((), jnp.float32))


# ---- FedOpt: server-side optimizers on the round pseudo-gradient ----
#
# (Reddi et al., "Adaptive Federated Optimization".) FedAvg treats the round
# as "replace the global model with the client average"; FedOpt treats
# ``global - average`` as a pseudo-gradient and feeds it to a server
# optimizer, giving FedAvgM (momentum) and FedAdam. The reference has plain
# FedAvg only (fl_server.py:92-105); ``server_optimizer="avg"`` reproduces
# it exactly. Only ``params`` go through the optimizer — BatchNorm moving
# statistics are plain-averaged (momentum on running moments is meaningless).


def _fedopt_adaptive(lr: float, b1: float, b2: float, eps: float, variant: str):
    """Reddi et al.'s adaptive server updates, exactly as in the paper —
    ``m = b1*m + (1-b1)*g`` and a per-variant second moment, step
    ``-lr * m / (sqrt(v) + eps)`` with NO bias correction (``optax.adam``
    bias-corrects, which changes the effective step size of early rounds
    relative to the paper's algorithm, so the moments are hand-rolled):

    - ``adam`` (FedAdam):  ``v = b2*v + (1-b2)*g^2``
    - ``yogi`` (FedYogi):  ``v = v - (1-b2)*sign(v - g^2)*g^2`` — the
      additive update reacts slower when ``v`` overshoots, which the paper
      found more stable under heterogeneous client drift. From ``v = 0``
      the first step coincides with FedAdam.
    """
    import optax

    def init(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), t
        )
        return (zeros(params), zeros(params))

    def _v_update(vi, g):
        g2 = jnp.square(g.astype(jnp.float32))
        if variant == "yogi":
            return vi - (1.0 - b2) * jnp.sign(vi - g2) * g2
        return b2 * vi + (1.0 - b2) * g2

    def update(grads, state, params=None):
        del params
        m, v = state
        m = jax.tree_util.tree_map(
            lambda mi, g: b1 * mi + (1.0 - b1) * g.astype(jnp.float32), m, grads
        )
        v = jax.tree_util.tree_map(_v_update, v, grads)
        updates = jax.tree_util.tree_map(
            lambda mi, vi: -lr * mi / (jnp.sqrt(vi) + eps), m, v
        )
        return updates, (m, v)

    return optax.GradientTransformation(init, update)


def make_server_optimizer(kind: str, lr: float = 1.0, momentum: float = 0.9):
    """An optax transform for the server update, or None for plain FedAvg."""
    import optax

    if kind in ("", "avg", "fedavg", "none"):
        return None
    if kind in ("momentum", "fedavgm"):
        return optax.sgd(lr, momentum=momentum)
    if kind in ("adam", "fedadam"):
        # Paper hyperparameters AND paper update rule (no bias correction).
        return _fedopt_adaptive(lr, b1=0.9, b2=0.99, eps=1e-3, variant="adam")
    if kind in ("yogi", "fedyogi"):
        return _fedopt_adaptive(lr, b1=0.9, b2=0.99, eps=1e-3, variant="yogi")
    raise ValueError(f"unknown server optimizer {kind!r}")


def apply_server_opt(global_params, avg_params, tx, opt_state):
    """One FedOpt step: pseudo-gradient = global - average (so SGD with
    lr=1, no momentum, recovers plain FedAvg). Returns (new_params,
    new_opt_state)."""
    import optax

    grad = jax.tree_util.tree_map(
        lambda g, a: g.astype(jnp.float32) - a.astype(jnp.float32),
        global_params,
        avg_params,
    )
    updates, new_opt_state = tx.update(grad, opt_state, global_params)
    new_params = optax.apply_updates(global_params, updates)
    new_params = jax.tree_util.tree_map(
        lambda n, g: n.astype(g.dtype), new_params, global_params
    )
    return new_params, new_opt_state
