"""The federation round state machine, as a pure transition function.

Re-implements the reference server's protocol semantics (SURVEY.md §2.4
dispatch table; reference: fl_server.py:45-207) as
``transition(state, event) -> (new_state, reply)`` over an immutable
``ServerState``. Time is an explicit event field — no hidden clock, no
threads — so every protocol path is unit-testable and the transport layer
(asyncio gRPC) stays a thin adapter. Single-writer by construction: this
fixes the reference's unsynchronized cross-thread mutation of round state
(SURVEY.md §2.2(6)).

Status codes keep the reference's vocabulary so its client flow is
recognizable: ``SW`` (enrolled), ``CTW`` (enrollment closed, late client),
``RESP_ACY`` (update accepted, round still open), ``RESP_ARY`` (round
complete, new weights attached), ``WAIT``/``NOT_WAIT`` (version poll), and
``FIN`` (fl_server.py:69-81, 118-132, 138-149).

Deliberate fixes over observed reference behavior (SURVEY.md §2.2):
1. The round average is actually broadcast (the reference wrote it to disk
   and re-sent the initial weights every round).
2. The update buffer resets every round (the reference accumulated forever).
3. Stale-round updates get an explicit ``REJECTED`` reply (the reference
   crashed encoding a ``None`` reply).
4. A round deadline shrinks the cohort to the clients that reported, so one
   dead client cannot hang the barrier forever (SURVEY.md §5.3).
5. A deadline with ZERO reports (every cohort member died) re-opens
   enrollment instead of stalling forever — the round counter and global
   weights survive, a fresh cohort picks the federation back up.
6. A cohort member that crashes and restarts mid-federation re-enrolls and
   is re-synced to the current round (the reference turned every mid-run
   ``Ready`` away with CTW, fl_server.py:78-81, locking the client out for
   the rest of the run).
7. The in-memory log sink is capped per upload and in total; over-cap
   chunks get an explicit ``REJECTED`` (the reference streamed unbounded
   bytes into server memory before its disk write, fl_server.py:84-89).
8. Quorum aggregation (``FedConfig.quorum_fraction``, Bonawitz et al.
   MLSys 2019): the round closes at K-of-N received updates instead of the
   full barrier; the deadline stays as the backstop. A straggler whose
   report lands after the round closed is RE-SYNCED to the current round
   (``NOT_WAIT`` + weights) instead of being rejected to death — its late
   update is logged to history, never averaged (FedProx's lesson: partial
   client work is tolerated by the aggregator, not papered over).
9. Update sanitation before FedAvg (``FedConfig.sanitize_updates``): every
   ``TrainDone`` payload must decode, match the global template leaf-for-
   leaf in shape, and be fully finite — otherwise it is ``REJECTED`` and
   recorded in the round's ``rejected`` history map. The reference averaged
   whatever unpickled.
10. Mid-round durable state (``FedConfig.state_path`` + ckpt/statefile.py):
    cohort/phase/received survive a server kill, so a restart resumes the
    SAME round; restored monotonic timestamps are discarded and the
    deadline re-arms from the first post-restart event.
11. Compressed update transport (round 12, ``fedcrack_tpu.compress``): the
    server advertises ``update_codec`` in-band; a framed upload is
    CRC-checked, base-version-pinned, reconstructed against the current
    global, and passed through the SAME ``validate_update`` gate as raw
    bytes — corrupt/stale/NaN frames are REJECTED and history-logged, and
    ``history[*]["bytes_received"]`` counts wire bytes (the frame), with
    ``decoded_bytes_received``/``codecs`` alongside. Mixed cohorts (raw +
    framed) aggregate correctly because everything decodes to a full tree
    before FedAvg.
12. Async federation (round 14, ``FedConfig.mode == "buffered"``,
    :mod:`fedcrack_tpu.fed.buffered`): ``PullWeights`` and ``TrainDone``
    dispatch to the FedBuff buffered aggregator instead of the round
    barrier — updates fold into a K-sized staleness-weighted buffer as
    they arrive and flush to a new global version at K. Enrollment, log
    uploads, polls and the FIN protocol are shared verbatim; the deadline
    becomes a partial-flush liveness backstop. ``mode == "sync"`` (the
    default) is byte-for-byte the pre-round-14 machine.
"""

from __future__ import annotations

import dataclasses
import json
import math
from dataclasses import dataclass
from typing import Any, Mapping

import jax

from fedcrack_tpu.compress import frames as wire_frames
from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import aggregation as _aggregation
from fedcrack_tpu.fed.algorithms import (
    apply_server_opt,
    make_server_optimizer,
)
from fedcrack_tpu.fed.serialization import (
    tree_from_bytes,
    tree_to_bytes,
    validate_update,
)
from fedcrack_tpu.health import ledger as _health_ledger

# ---- status codes (reference vocabulary, §2.4) ----
SW = "SW"                # enrolled in this session's cohort
CTW = "CTW"              # enrollment closed; late client turned away
RESP_ACY = "RESP_ACY"    # update accepted; round still collecting
RESP_ARY = "RESP_ARY"    # round aggregated; new weights attached
WAIT = "WAIT"            # poll: round not finished
NOT_WAIT = "NOT_WAIT"    # poll: new round ready; weights attached
FIN = "FIN"              # federation finished
REJECTED = "REJECTED"    # explicit refusal (stale round / unknown client)

PHASE_ENROLL = "enroll"
PHASE_RUNNING = "running"
PHASE_FINISHED = "finished"


# ---- events (client requests + time) ----
@dataclass(frozen=True)
class Ready:
    """Registration request (reference 'R', fl_server.py:152-157).

    ``secagg_seed`` (round 23): the client's per-session masking seed,
    exchanged in-band at enroll like the codec handshake. None when the
    client sent no seed — under secagg the server falls back to the
    deterministic ``privacy.secagg.client_seed(cname)`` both ends derive."""
    cname: str
    now: float
    secagg_seed: int | None = None


@dataclass(frozen=True)
class PullWeights:
    """Global-weights fetch (reference UpdateReq type 'P', fl_server.py:159-161)."""
    cname: str
    now: float


@dataclass(frozen=True)
class TrainingNotice:
    """Client began local fit (reference 'T', fl_server.py:162-169)."""
    cname: str
    now: float


@dataclass(frozen=True)
class LogChunk:
    """Client ships a log/event-file chunk (reference 'L', fl_server.py:170-175).

    ``offset`` is the byte position of this chunk in the file: appends are
    idempotent under RPC retries (a resent chunk overwrites itself instead of
    duplicating), and ``offset=0`` restarts the upload."""
    cname: str
    title: str
    data: bytes
    now: float
    offset: int = 0


@dataclass(frozen=True)
class TrainDone:
    """Local weights for `round` (reference 'D', fl_server.py:176-196).

    ``trace_ctx`` is the sender's wire-safe span context (round 16,
    ``obs.spans.TraceContext`` — carried in-band like the codec handshake).
    Pure observability: the transition function never reads it; the
    transport layer re-parents it onto the flush span."""
    cname: str
    round: int
    blob: bytes
    num_samples: int
    now: float
    trace_ctx: str = ""


@dataclass(frozen=True)
class VersionPoll:
    """Is the next round ready? (reference VersionReq, fl_server.py:197-207)."""
    cname: str
    model_version: int
    round: int
    now: float


@dataclass(frozen=True)
class Tick:
    """Pure passage of time (enrollment window close, round deadline)."""
    now: float


Event = Ready | PullWeights | TrainingNotice | LogChunk | TrainDone | VersionPoll | Tick


# ---- replies ----
@dataclass(frozen=True)
class Reply:
    status: str
    # config-map payload mirrored from the reference's ReadyRep/UpdateRep
    config: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    blob: bytes | None = None
    title: str | None = None


# ---- server state ----
@dataclass(frozen=True)
class ServerState:
    config: FedConfig
    global_blob: bytes                       # serialized model variables
    phase: str = PHASE_ENROLL
    enroll_opened_at: float | None = None
    cohort: frozenset[str] = frozenset()
    current_round: int = 1
    model_version: int = 0
    round_started_at: float | None = None
    # client -> (weights blob, sample count), for the current round only
    received: Mapping[str, tuple[bytes, int]] = dataclasses.field(default_factory=dict)
    # client log sink: title -> accumulated bytes (reference C1.5)
    logs: Mapping[str, bytes] = dataclasses.field(default_factory=dict)
    history: tuple[dict, ...] = ()
    # FedOpt server-optimizer state (momentum/Adam moments); None for plain
    # FedAvg. Lazily initialized on the first aggregation.
    server_opt_state: Any = None
    # Float32 pytree template for decoding client uploads: keeps server math
    # full precision regardless of the wire dtype. Set by initial_state.
    template: Any = None
    # The blob actually broadcast to clients: equals global_blob for a
    # float32 wire, or its bfloat16-cast re-encoding (half the bytes) when
    # config.wire_dtype == "bfloat16". Server-side consumers (eval,
    # checkpoints) always read global_blob.
    wire_blob: bytes = b""
    # Rounds that expired with zero reports (the whole cohort died) and were
    # recovered by re-opening enrollment — observability for fix #5.
    failed_rounds: int = 0
    # Cohort members dropped by a deadline shrink (fix #4). A departed
    # member that restarts may re-admit itself via Ready (fix #6 must hold
    # even when the crash outlives the deadline).
    departed: frozenset[str] = frozenset()
    # Updates refused for THIS round (cname -> reason): sanitation failures
    # (undecodable / wrong shape / non-finite) and post-quorum stragglers.
    # Folded into the round's history entry at aggregation — rejected
    # updates are observable forever but averaged never.
    rejected: Mapping[str, str] = dataclasses.field(default_factory=dict)
    # Compressed-transport accounting for THIS round (round 12): per client,
    # the bytes that actually crossed the wire (the encoded frame — the
    # stored `received` blob is the DECODED reconstruction) and which codec
    # produced them. Folded into the history entry at aggregation.
    wire_bytes: Mapping[str, int] = dataclasses.field(default_factory=dict)
    codecs: Mapping[str, str] = dataclasses.field(default_factory=dict)
    # Buffered-async mode only (round 14, fed/buffered.py); empty in sync
    # mode. `pulled` maps each client to the model_version it last pulled
    # (the base its next upload decodes against); `buffer` holds the
    # accepted-but-unflushed staleness-weighted updates; `base_blobs`
    # retains the last max_staleness broadcast blobs so stale framed
    # deltas can reconstruct. All three persist in the statefile so a
    # mid-buffer kill resumes bit-exactly.
    pulled: Mapping[str, int] = dataclasses.field(default_factory=dict)
    buffer: tuple = ()
    base_blobs: Mapping[int, bytes] = dataclasses.field(default_factory=dict)
    # Per-client health ledger (round 18, health/ledger.py): every gate
    # verdict plus flush-time update geometry (norms, cosines, anomaly
    # scores), rolling and bounded per client. Persists in the statefile;
    # mutated only through the ledger module's pure helpers.
    ledger: Mapping[str, dict] = dataclasses.field(default_factory=dict)
    # Privacy plane (round 23, fedcrack_tpu/privacy/). `secagg_seeds` holds
    # every masking seed received at enroll; `secagg_roster` is the
    # {name: seed} map FROZEN when the cohort closed (uploads are masked
    # against it — a deadline shrink changes `cohort` but never the
    # roster, the seed-recovery step covers the dropped maskers);
    # `privacy_steps` is the RDP accountant's only state, per-client noise
    # step counts (epsilon is recomputed from them, never stored). All
    # three persist in the statefile so a mid-round kill-restart keeps
    # masks recoverable and the privacy ledger monotone.
    secagg_seeds: Mapping[str, int] = dataclasses.field(default_factory=dict)
    secagg_roster: Mapping[str, int] = dataclasses.field(default_factory=dict)
    privacy_steps: Mapping[str, int] = dataclasses.field(default_factory=dict)

    @property
    def broadcast_blob(self) -> bytes:
        return self.wire_blob or self.global_blob

    def _replace(self, **kw) -> "ServerState":
        return dataclasses.replace(self, **kw)


# One-entry memo for the decoded round base: every framed upload applies
# its delta to the broadcast tree, and at cohort scale decoding the full
# model once PER UPLOAD inside the single-writer transition would become
# the round's dominant serialized host cost. Keyed on the broadcast BYTES
# themselves (identity fast-path, equality fallback — both cheaper than a
# decode), never on hash(): a 64-bit hash collision between two servers'
# blobs sharing this process-wide memo would decode a delta against the
# WRONG base — finite, shape-correct, silently wrong, exactly the failure
# class the base_version pin exists to kill. `transition` is single-writer
# per server; concurrent servers in one process at worst thrash the entry
# and re-decode (correctness is carried by the key).
_ROUND_BASE_MEMO: dict = {}


def _decoded_round_base(state: "ServerState"):
    blob = state.broadcast_blob
    hit = _ROUND_BASE_MEMO.get("base")
    if (
        hit is not None
        and hit[0] == state.model_version
        and (hit[1] is blob or hit[1] == blob)
    ):
        return hit[2]
    tree = tree_from_bytes(blob, template=state.template)
    _ROUND_BASE_MEMO["base"] = (state.model_version, blob, tree)
    return tree


def decode_and_validate_update(
    blob: bytes,
    num_samples: int,
    *,
    template: Any,
    base_fn,
    base_version: int,
    sanitize: bool,
) -> tuple[bytes, int, str, str | None, float | None]:
    """THE upload acceptance gate, shared by every aggregation tier
    (round 13): the root's ``transition`` and the edge aggregators in
    :mod:`fedcrack_tpu.fed.tree` route every ``TrainDone`` payload through
    this one function, so "every tier sanitizes identically" is a property
    of the code shape, not of parallel maintenance.

    A framed (compressed) upload is CRC-checked, base-version-pinned,
    reconstructed against ``base_fn()`` (the decoded broadcast tree — a
    callable so callers keep their decode memo), and its reconstruction
    validated; frames are ALWAYS sanitized regardless of ``sanitize``
    (corrupt compressed bytes are the codec subsystem's own failure
    surface, and a CRC-valid frame can still carry a poisoned trainer's
    NaNs). A raw blob is validated when ``sanitize`` is on.

    Returns ``(decoded_blob, wire_len, codec_name, problem, norm)`` —
    ``problem`` is the rejection reason (never aggregate) or None; on
    acceptance ``decoded_blob`` is the full-tree msgpack bytes
    (re-serialized for a frame, the original bytes for a raw upload) and
    ``norm`` is the update's L2 distance to the base, computed here in the
    same pass over the already-decoded tree (the health ledger's gate-time
    geometry sample; None when nothing was decoded — raw uploads with
    sanitation off — or on rejection).
    """
    wire_len = len(blob)
    codec_name = "null"
    problem = None
    norm = None
    if wire_frames.is_frame(blob):
        if template is None:
            problem = "compressed frame rejected: server has no decode template"
        else:
            try:
                tree, frame = wire_frames.decode_update(
                    blob,
                    template=template,
                    base=base_fn(),
                    expected_base_version=base_version,
                )
            except ValueError as e:
                problem = f"compressed frame rejected: {e}"
            else:
                codec_name = frame.codec
                # Validate the materialized tree directly (no redundant
                # encode∘decode round-trip per upload); serialize once,
                # for storage, only on accept.
                problem = validate_update(tree, template)
                if problem is None:
                    blob = tree_to_bytes(tree)
                    norm = _health_ledger.update_norm(tree, base_fn())
        if problem is None and num_samples < 0:
            problem = f"negative sample count {num_samples}"
    elif sanitize:
        if num_samples < 0:
            problem = f"negative sample count {num_samples}"
        elif template is not None:
            problem = validate_update(blob, template)
            if problem is None:
                norm = _health_ledger.update_norm(
                    tree_from_bytes(blob, template=template), base_fn()
                )
    if problem is not None:
        norm = None
    return blob, wire_len, codec_name, problem, norm


def drop_log(state: ServerState, cname: str, title: str) -> ServerState:
    """Forget an accumulated upload (called after the transport flushes it
    to disk, so server memory does not grow with every upload)."""
    key = f"{cname}/{title}"
    if key not in state.logs:
        return state
    logs = dict(state.logs)
    del logs[key]
    return state._replace(logs=logs)


def _wire_cast(config: FedConfig) -> str | None:
    return "bfloat16" if config.wire_dtype == "bfloat16" else None


def initial_state(config: FedConfig, global_variables: Any) -> ServerState:
    """Server boot: build + serialize the initial global model
    (reference: fl_server.py:229-231 builds it via the missing
    model_evaluate module; SURVEY.md §2.5)."""
    cast = _wire_cast(config)
    blob = tree_to_bytes(global_variables)
    wire_blob = tree_to_bytes(global_variables, cast_dtype=cast) if cast else b""
    return ServerState(
        config=config,
        global_blob=blob,
        template=jax.device_get(global_variables),
        wire_blob=wire_blob,
        # Buffered mode decodes stale deltas against retained past
        # broadcasts; version 0's is the boot blob.
        base_blobs={0: wire_blob or blob} if config.mode == "buffered" else {},
    )


def _ready_config(state: ServerState, status: str) -> dict[str, Any]:
    """The handshake config map (reference keys, fl_server.py:69-75), plus
    the round's training hyperparameters — the server's algorithm choice
    configures the cohort in-band instead of relying on every client being
    launched with matching flags (the reference hardcoded epochs/batch
    client-side and ignored the ctor args, SURVEY.md §2.2(4))."""
    return {
        "state": status,
        "model_version": state.model_version,
        "current_round": state.current_round,
        "max_train_round": state.config.max_rounds,
        "model_type": state.config.model_type,
        "local_epochs": state.config.local_epochs,
        "learning_rate": state.config.learning_rate,
        "fedprox_mu": state.config.fedprox_mu,
        "pos_weight": state.config.pos_weight,
        "wire_dtype": state.config.wire_dtype,
        # Compressed update transport (round 12): the codec the server asks
        # the cohort to upload with; the round base for delta codecs is the
        # broadcast this handshake's model_version names. Legacy clients
        # that ignore the key keep sending raw blobs — always accepted.
        "update_codec": state.config.update_codec,
        "topk_fraction": state.config.topk_fraction,
        # Async federation (round 14): "sync" clients block on the round
        # close; "buffered" clients loop pull→train→push continuously
        # (transport.client dispatches on this key).
        "mode": state.config.mode,
        # Privacy plane (round 23): when on, the cohort must upload
        # pairwise-masked fixed-point updates (transport.client fetches the
        # frozen roster via TrainingNotice and masks with privacy.secagg).
        # A legacy client that ignores the key uploads plaintext — which
        # the secagg acceptance gate REJECTS (bad magic), never averages.
        "secagg": state.config.secagg,
        "secagg_bits": state.config.secagg_bits,
    }


def quorum_target(quorum_fraction: float, cohort_size: int) -> int:
    """K of the K-of-N barrier: ceil(quorum_fraction * N), floored at one
    real update. 1.0 (the default) is the full barrier. The epsilon guards
    float products like 0.6 * 5 = 3.0000000000000004 from ceiling into an
    extra required client. Shared by the root round machine and every edge
    tier of the aggregation tree (fed.tree) — one formula, all tiers."""
    return max(1, math.ceil(quorum_fraction * cohort_size - 1e-9))


def _quorum_target(state: ServerState) -> int:
    return quorum_target(state.config.quorum_fraction, len(state.cohort))


def _barrier_met(state: ServerState) -> bool:
    return (
        state.phase == PHASE_RUNNING
        and bool(state.cohort)
        and len(state.received) >= _quorum_target(state)
    )


def _start_running(state: ServerState, now: float) -> ServerState:
    """Close enrollment: phase -> RUNNING, and under secagg freeze the
    masking roster to the closed cohort (enroll-received seed, or the
    deterministic ``client_seed`` fallback both ends derive). Uploads are
    masked and validated against THIS roster for the rest of the
    federation — a deadline shrink drops members from ``cohort`` but their
    masks are recovered from the roster, never renegotiated mid-round."""
    state = state._replace(phase=PHASE_RUNNING, round_started_at=now)
    if state.config.secagg:
        from fedcrack_tpu.privacy.secagg import client_seed

        roster = {
            n: int(state.secagg_seeds.get(n, client_seed(n)))
            for n in sorted(state.cohort)
        }
        state = state._replace(secagg_roster=roster)
    return state


def _advance_time(state: ServerState, now: float) -> ServerState:
    """Apply pure time effects: enrollment close, round deadline."""
    # A statefile-restored state carries no timestamps (the dead process's
    # monotonic clocks are meaningless here): both time windows re-arm from
    # the first event the restarted server sees. Without the enrollment
    # re-arm, a server killed mid-enrollment restores a partial cohort whose
    # window can never expire — already-enrolled clients don't re-send Ready,
    # so the federation would sit in PHASE_ENROLL forever.
    if state.phase == PHASE_RUNNING and state.round_started_at is None:
        state = state._replace(round_started_at=now)
    if (
        state.phase == PHASE_ENROLL
        and state.cohort
        and state.enroll_opened_at is None
    ):
        state = state._replace(enroll_opened_at=now)
    if (
        state.phase == PHASE_ENROLL
        and state.enroll_opened_at is not None
        and now - state.enroll_opened_at >= state.config.registration_window_s
        and state.cohort
    ):
        state = _start_running(state, now)
        # fast clients may have reported while enrollment was still open
        if _barrier_met(state):
            state = _aggregate(state, now)
    if state.config.mode == "buffered":
        # Buffered mode shares the enrollment machinery above; the round
        # deadline below is replaced by the buffered flush/backstop (no
        # cohort to shrink — the buffer is the quorum).
        from fedcrack_tpu.fed.buffered import BufferedAggregator

        return BufferedAggregator.advance_time(state, now)
    if (
        state.phase == PHASE_RUNNING
        and state.config.round_deadline_s > 0
        and state.round_started_at is not None
        # ">=" like the enrollment window above: both time windows close AT
        # the boundary instant (previously the deadline fired only strictly
        # past it — an asymmetry this module's boundary-time test now pins).
        and now - state.round_started_at >= state.config.round_deadline_s
        and len(state.received) < _quorum_target(state)
    ):
        if state.received:
            # Deadline: aggregate over who reported; the missing clients are
            # dropped from the cohort (fix #4 — the reference hung forever)
            # but remembered, so a later restart can re-admit them.
            reported = frozenset(state.received.keys())
            state = state._replace(
                cohort=reported,
                departed=state.departed | (state.cohort - reported),
            )
            state = _aggregate(state, now)
        else:
            # Silent cohort: every enrolled client died before reporting.
            # Re-open enrollment so a fresh cohort can resume the federation
            # at the same round — round counter and global weights survive
            # (fix #5; previously this stalled in PHASE_RUNNING forever,
            # the same liveness class as the reference's barrier hang).
            # The dead members go to `departed` so one that restarts AFTER
            # a fresh cohort closed enrollment can still re-admit itself
            # (fix #6 — otherwise it would be CTW-locked out).
            state = state._replace(
                phase=PHASE_ENROLL,
                cohort=frozenset(),
                departed=state.departed | state.cohort,
                enroll_opened_at=None,
                round_started_at=None,
                failed_rounds=state.failed_rounds + 1,
            )
    return state


def apply_fedopt(state: ServerState, avg: Any) -> tuple[Any, Any]:
    """The FedOpt server step on an aggregated tree: shared by the sync
    barrier (:func:`_aggregate`) and the buffered flush
    (:mod:`fedcrack_tpu.fed.buffered`) so both modes step the SAME
    optimizer expression — a requirement of the buffered mode's bit-exact
    sync degeneration. Returns ``(avg, opt_state)``; plain FedAvg passes
    ``avg`` through untouched."""
    opt_state = state.server_opt_state
    tx = make_server_optimizer(
        state.config.server_optimizer,
        state.config.server_lr,
        state.config.server_momentum,
    )
    if tx is not None and "params" in avg:
        current = tree_from_bytes(state.global_blob, template=state.template)
        if opt_state is None:
            opt_state = tx.init(current["params"])
        new_params, opt_state = apply_server_opt(
            current["params"], avg["params"], tx, opt_state
        )
        avg = dict(avg)
        avg["params"] = new_params  # BN stats keep the plain average
    return avg, opt_state


# Per-step RDP vectors are a pure function of (sigma, q, delta) and cost
# ~10^4 log-space terms to evaluate — memoized process-wide so every round
# of every server reuses them. Only immutable precomputes are read from the
# cached accountant (never its steps dict), so concurrent servers sharing
# the entry stay race-free.
_ACCOUNTANT_MEMO: dict = {}


def _epsilons_for(config: FedConfig, steps: Mapping[str, int]) -> dict[str, float]:
    """Cumulative per-client eps(delta) for the given noise-step counts."""
    from fedcrack_tpu.privacy.accountant import PrivacyAccountant, rdp_to_epsilon

    key = (config.dp_noise_multiplier, config.dp_sample_rate, config.dp_delta)
    acct = _ACCOUNTANT_MEMO.get(key)
    if acct is None:
        acct = PrivacyAccountant(
            noise_multiplier=config.dp_noise_multiplier,
            sample_rate=config.dp_sample_rate,
            delta=config.dp_delta,
        )
        _ACCOUNTANT_MEMO[key] = acct
    out = {}
    for n in sorted(steps):
        t = int(steps[n])
        eps = (
            rdp_to_epsilon(
                [r * t for r in acct._rdp_step], acct.orders, acct.delta
            )[0]
            if t > 0
            else 0.0
        )
        out[str(n)] = round(eps, 6)
    return out


def privacy_summary(state: ServerState) -> dict:
    """The privacy-plane artifact block (server.py writes it beside the
    metrics; tools/health_report.py joins it): DP accountant parameters +
    cumulative per-client steps/epsilon, and the secagg mode/roster facts.
    Deterministic — sorted clients, rounded epsilons."""
    cfg = state.config
    dp_on = cfg.dp_noise_multiplier > 0.0
    eps = _epsilons_for(cfg, state.privacy_steps) if dp_on else {}
    return {
        "dp": {
            "enabled": dp_on,
            "clip_norm": float(cfg.dp_clip_norm),
            "noise_multiplier": float(cfg.dp_noise_multiplier),
            "sample_rate": float(cfg.dp_sample_rate),
            "delta": float(cfg.dp_delta),
            "epsilon_budget": float(cfg.dp_epsilon_budget),
            "clients": {
                n: {"steps": int(state.privacy_steps[n]), "epsilon": eps[n]}
                for n in sorted(state.privacy_steps)
            }
            if dp_on
            else {},
            "max_epsilon": max(eps.values(), default=0.0) if dp_on else 0.0,
        },
        "secagg": {
            "enabled": bool(cfg.secagg),
            "bits": int(cfg.secagg_bits),
            "roster_size": len(state.secagg_roster),
        },
    }


def _aggregate(state: ServerState, now: float) -> ServerState:
    """Fold the round's received updates through the configured aggregation
    algebra (round 21, fed/aggregation.py; the FedAvg null instance is
    bitwise-pinned to the historical sorted fold), optionally + the FedOpt
    server step; advance round/version. Under secagg (round 23) the fold is
    the modular unmask instead: sum the masked fixed-point residues in
    sorted order, reconstruct+subtract every (survivor, dropped) pairwise
    mask from the frozen roster's seeds, divide by the total sample count —
    EXACT integer cancellation, pinned bit-for-bit against the plaintext
    weighted fixed-point sum. Masked residues are opaque to the r18
    ledger's geometry windows, so secagg skips observe_flush/quarantine
    entirely (config validation already forced quarantine_z=0)."""
    names = sorted(state.received.keys())
    counts = [state.received[n][1] for n in names]
    secagg_info = None
    if state.config.secagg:
        from fedcrack_tpu.privacy.secagg import (
            decode_masked,
            round_roster,
            unmask_sum,
            unmasked_mean,
        )

        roster = round_roster(state.secagg_roster, state.current_round)
        uploads = {n: decode_masked(state.received[n][0]) for n in names}
        total, total_samples, dropped = unmask_sum(
            uploads, roster, state.config.secagg_bits
        )
        avg = unmasked_mean(
            total, total_samples, state.template, state.config.secagg_bits
        )
        new_ledger = state.ledger
        quarantined: list[str] = []
        secagg_info = {
            "maskers": names,
            "recovered": dropped,
            "bits": int(state.config.secagg_bits),
        }
    else:
        # Decode against the float32 template so server math keeps full
        # precision even when the wire carries bfloat16 payloads.
        trees = [
            tree_from_bytes(state.received[n][0], template=state.template)
            for n in names
        ]
        # Health ledger (round 18): score this flush's update geometry —
        # norm and cosine-to-cohort-mean per client, robust z vs the
        # window — on the SAME decoded trees the fold is about to combine
        # (no second decode). Round 21 moved the scoring BEFORE the fold
        # so the scores can GATE it: with quarantine_z > 0 a flagged
        # client is excluded from the triples entirely
        # (detection → response).
        new_ledger, scores = _health_ledger.observe_flush(
            state.ledger,
            list(zip(names, trees)),
            _decoded_round_base(state),
        )
        quarantined = _aggregation.quarantine_set(
            scores, names, state.config.quarantine_z
        )
        for qname in quarantined:
            new_ledger = _health_ledger.record_quarantine(new_ledger, qname)
        triples = [
            (n, c, t)
            for n, c, t in zip(names, counts, trees)
            if n not in quarantined
        ]
        avg = _aggregation.fold(_aggregation.from_config(state.config), triples)
    avg, opt_state = apply_fedopt(state, avg)
    new_blob = tree_to_bytes(avg)
    cast = _wire_cast(state.config)
    new_wire_blob = tree_to_bytes(avg, cast_dtype=cast) if cast else b""
    new_round = state.current_round + 1
    finished = new_round > state.config.max_rounds
    entry = {
        "round": state.current_round,
        "clients": names,
        "samples": counts,
        "completed_at": now,
        # Observability (SURVEY.md §5.5): round wall-clock + control-plane
        # bytes (client uploads in, one broadcast-sized blob out per client).
        # "bytes_received" is the bytes that crossed the WIRE — for a framed
        # (compressed) upload that is the encoded frame, not the decoded
        # reconstruction stored in `received`; "decoded_bytes_received" is
        # the post-decode size, so received/decoded is the round's measured
        # upload compression ratio.
        "wall_clock_s": (
            now - state.round_started_at if state.round_started_at is not None else None
        ),
        "bytes_received": sum(
            state.wire_bytes.get(n, len(state.received[n][0])) for n in names
        ),
        "decoded_bytes_received": sum(len(state.received[n][0]) for n in names),
        "codecs": {n: state.codecs.get(n, "null") for n in names},
        "bytes_broadcast": len(new_wire_blob or new_blob),
        # Quorum observability: how many updates closed the round out of how
        # large a cohort, plus every update refused this round and why.
        "quorum": _quorum_target(state),
        "cohort_size": len(state.cohort),
        "rejected": dict(state.rejected),
        # Quarantine observability (round 21): name -> the robust-z score
        # that excluded it from the fold. Empty means everyone folded —
        # `clients`/`samples` keep their historical meaning (who REPORTED
        # this round), so exclusion is read from this map, not from them.
        "quarantined": quarantined,
    }
    if secagg_info is not None:
        # Secagg observability: who masked, which dropped maskers were
        # closed by seed recovery, and the fixed-point precision.
        entry["secagg"] = secagg_info
    # DP accountant (round 23, privacy/accountant.py): charge this round's
    # noise steps to every contributor and record the cumulative eps(delta)
    # map in the history entry. When a budget is set and any client's
    # epsilon reaches it, the federation REFUSES further rounds — privacy
    # exhaustion finishes loudly, it never silently keeps spending.
    privacy_steps = state.privacy_steps
    if state.config.dp_noise_multiplier > 0.0:
        steps_per = state.config.dp_steps_per_round or state.config.local_epochs
        privacy_steps = dict(privacy_steps)
        for n in names:
            privacy_steps[n] = privacy_steps.get(n, 0) + int(steps_per)
        epsilons = _epsilons_for(state.config, privacy_steps)
        entry["epsilon"] = epsilons
        budget = state.config.dp_epsilon_budget
        if budget > 0.0 and epsilons and max(epsilons.values()) >= budget:
            entry["epsilon_budget_exhausted"] = True
            finished = True
    return state._replace(
        ledger=new_ledger,
        privacy_steps=privacy_steps,
        global_blob=new_blob,
        wire_blob=new_wire_blob,
        current_round=new_round,
        model_version=state.model_version + 1,
        received={},
        rejected={},
        wire_bytes={},
        codecs={},
        round_started_at=now,
        phase=PHASE_FINISHED if finished else PHASE_RUNNING,
        history=state.history + (entry,),
        server_opt_state=opt_state,
    )


def transition(state: ServerState, event: Event) -> tuple[ServerState, Reply]:
    """THE protocol. Dispatch mirrors the reference's manage_request table
    (fl_server.py:152-207), §2.4."""
    state = _advance_time(state, event.now)

    match event:
        case Tick():
            return state, Reply(status=state.phase)

        case Ready(cname=cname, now=now):
            if state.config.secagg and event.secagg_seed is not None:
                # Enroll-time seed exchange (round 23): remember the
                # client's masking seed. Idempotent across re-enrolls; the
                # roster snapshots at cohort close (_start_running).
                state = state._replace(
                    secagg_seeds={
                        **state.secagg_seeds, cname: int(event.secagg_seed)
                    }
                )
            if state.phase == PHASE_FINISHED:
                return state, Reply(status=FIN, config=_ready_config(state, FIN))
            if state.phase == PHASE_RUNNING:
                if cname in state.cohort:
                    # A cohort member that crashed and restarted: re-sync it
                    # with the current round instead of locking it out
                    # (fix #6). Its pre-crash report for this round, if any,
                    # is dropped — the client is redoing the round, and a
                    # barrier completed by the stale blob would advance the
                    # round underneath it, turning its fresh report into a
                    # REJECTED stale-round (the very lockout being fixed).
                    if cname in state.received:
                        received = dict(state.received)
                        del received[cname]
                        wire = {
                            k: v for k, v in state.wire_bytes.items() if k != cname
                        }
                        codecs = {
                            k: v for k, v in state.codecs.items() if k != cname
                        }
                        state = state._replace(
                            received=received, wire_bytes=wire, codecs=codecs
                        )
                    return state, Reply(status=SW, config=_ready_config(state, SW))
                if cname in state.departed:
                    # Dropped by a deadline shrink, now back: re-admit. Fix
                    # #6 must hold even when the restart loses the race with
                    # the deadline — otherwise the client is CTW'd forever.
                    state = state._replace(
                        cohort=state.cohort | {cname},
                        departed=state.departed - {cname},
                    )
                    return state, Reply(status=SW, config=_ready_config(state, SW))
                # enrollment closed — late client turned away (fl_server.py:78-81)
                return state, Reply(status=CTW, config=_ready_config(state, CTW))
            opened = state.enroll_opened_at if state.enroll_opened_at is not None else now
            # Leaving `departed` too: cohort and departed stay disjoint (the
            # property test drives re-enrollment after a silent-cohort
            # reopen, where the member is coming back from the departed set).
            state = state._replace(
                enroll_opened_at=opened,
                cohort=state.cohort | {cname},
                departed=state.departed - {cname},
            )
            # target cohort reached: close enrollment early (the reference
            # only had the fixed 10 s window, fl_server.py:40-52)
            if len(state.cohort) >= state.config.cohort_size:
                state = _start_running(state, now)
            return state, Reply(status=SW, config=_ready_config(state, SW))

        case PullWeights(cname=cname):
            # Broadcasts the CURRENT global weights — after round R these are
            # the round-R average (fix #1; the reference resent init weights).
            # The config map rides along so pollers learn the version/round
            # the blob corresponds to (the buffered client loop pins its
            # upload's base to it; sync clients ignore it).
            if state.config.mode == "buffered":
                from fedcrack_tpu.fed.buffered import BufferedAggregator

                state = BufferedAggregator.record_pull(state, cname)
            return state, Reply(
                status="OK",
                blob=state.broadcast_blob,
                title="parameters",
                config=_ready_config(state, "OK"),
            )

        case TrainingNotice(cname=cname):
            if (
                state.config.secagg
                and state.phase == PHASE_RUNNING
                and cname in state.cohort
                and state.secagg_roster
            ):
                # Roster distribution (round 23): once the cohort closed,
                # the TrainingNotice reply carries the frozen {name: seed}
                # masking roster in-band (the __-prefixed side-channel
                # precedent). A client whose notice lands while enrollment
                # is still open gets no roster and retries before masking.
                return state, Reply(
                    status="OK",
                    title="T",
                    config={
                        "__secagg_roster": json.dumps(
                            {n: int(s)
                             for n, s in sorted(state.secagg_roster.items())},
                            sort_keys=True,
                        ),
                        "current_round": state.current_round,
                    },
                )
            return state, Reply(status="OK", title="T")

        case LogChunk(cname=cname, title=title, data=data, offset=offset):
            # Only cohort members may write into the sink — otherwise any
            # process that can reach the port (including pre-enrollment,
            # when the cohort is still empty) could fill the total cap and
            # deny uploads to legitimate clients (the reference accepted
            # 'L' chunks from anyone, fl_server.py:170-175).
            if cname not in state.cohort:
                return state, Reply(
                    status=REJECTED, title="log upload: not in cohort"
                )
            key = f"{cname}/{title}"
            logs = dict(state.logs)
            buf = logs.get(key, b"")
            if offset > len(buf):
                return state, Reply(
                    status=REJECTED,
                    title=f"log chunk gap: offset {offset}, have {len(buf)}",
                )
            # Writing at the declared offset makes retried chunks overwrite
            # themselves rather than duplicate, and offset=0 restarts cleanly
            # after a failed or already-flushed upload.
            new_buf = buf[:offset] + data
            # Sink caps (fix #7): an upload that never sends `last` must not
            # grow server memory without bound. Per-upload and total caps
            # (0 = uncapped), rejected explicitly so the uploader fails
            # loudly.
            per_cap = state.config.log_max_mb_per_upload * 1024 * 1024
            if per_cap > 0 and len(new_buf) > per_cap:
                return state, Reply(
                    status=REJECTED,
                    title=(
                        f"log upload {title!r} over per-upload cap: "
                        f"{len(new_buf)} > {per_cap} bytes"
                    ),
                )
            total_cap = state.config.log_max_mb_total * 1024 * 1024
            total = len(new_buf) + sum(
                len(v) for k, v in logs.items() if k != key
            )
            if total_cap > 0 and total > total_cap:
                return state, Reply(
                    status=REJECTED,
                    title=(
                        f"log sink over total cap: {total} > {total_cap} bytes"
                    ),
                )
            logs[key] = new_buf
            return state._replace(logs=logs), Reply(status="OK", title=title)

        case TrainDone(cname=cname, round=rnd, blob=blob, num_samples=ns, now=now):
            if state.phase == PHASE_FINISHED:
                return state, Reply(
                    status=FIN,
                    blob=state.broadcast_blob,
                    config=_ready_config(state, FIN),
                )
            if state.config.mode == "buffered":
                # FedBuff buffered aggregation (round 14): no round
                # matching — the event's round tag is informational; the
                # update's base VERSION (tracked at pull) is what gates
                # and weights it.
                from fedcrack_tpu.fed.buffered import BufferedAggregator

                return BufferedAggregator.offer(state, event)
            if cname not in state.cohort:
                # Ledger-feed only for names we have already seen (an
                # unknown-name flood must not grow the ledger unboundedly).
                if cname in state.ledger:
                    state = state._replace(
                        ledger=_health_ledger.record_offer(
                            state.ledger, cname, outcome="rejected",
                            reason_class="not_in_cohort", round=rnd,
                        )
                    )
                return state, Reply(
                    status=REJECTED, config={"reason": "not in cohort"}
                )
            if rnd < state.current_round:
                # A report for an already-closed round: a straggler that
                # missed the quorum/deadline, or a replayed capture. Either
                # way the update must never be averaged (it was computed
                # against superseded weights) — log it, then RE-SYNC the
                # sender with the current round + weights (NOT_WAIT, the
                # same reply a version poll would get) so a live straggler
                # rejoins instead of dying on a rejection.
                reason = f"stale round {rnd} (server at {state.current_round})"
                rejected = dict(state.rejected)
                rejected[cname] = reason
                state = state._replace(
                    rejected=rejected,
                    ledger=_health_ledger.record_offer(
                        state.ledger, cname, outcome="resync",
                        num_samples=ns, round=rnd,
                        staleness=state.current_round - rnd,
                    ),
                )
                return state, Reply(
                    status=NOT_WAIT,
                    blob=state.broadcast_blob,
                    config=_ready_config(state, NOT_WAIT),
                )
            if rnd != state.current_round:
                # FUTURE round: a protocol violation no resync can explain —
                # explicit rejection (fix #3; the reference returned None
                # and crashed on encode).
                state = state._replace(
                    ledger=_health_ledger.record_offer(
                        state.ledger, cname, outcome="rejected",
                        reason_class="stale", round=rnd,
                    )
                )
                return state, Reply(
                    status=REJECTED,
                    config={
                        "reason": "stale round",
                        "client_round": rnd,
                        "server_round": state.current_round,
                    },
                )
            # Compressed-frame decode + sanitation (rounds 12/13): the
            # shared decode_and_validate_update gate. The delta base is the
            # BROADCAST blob — the bytes the client actually pulled and
            # subtracted (with wire_dtype=bfloat16 that is the bf16-cast
            # wire blob, NOT global_blob: decoding against the f32 global
            # would reconstruct finite, shape-correct, silently-wrong
            # weights). Cost note for the raw path: the payload decodes
            # once here and again at the barrier — both inside the
            # single-writer transition, like every other state-machine
            # step; an operator who needs multi-GB uploads sanitized
            # off-thread should gate at the transport instead. fedlint
            # COMP001 pins the frame decode to validate_update statically.
            if state.config.secagg:
                # Secagg gate (round 23): a masked upload is uniformly-
                # random residues — no norm/finiteness exists to check, so
                # the gate is structural (magic, bits, the EXACT frozen
                # roster, leaf shapes/dtypes) plus the sample-count pin
                # between the event and the masked payload. The blob stays
                # MASKED in `received`; only the cohort fold unmasks.
                from fedcrack_tpu.privacy.secagg import (
                    decode_masked,
                    validate_masked,
                )

                wire_len = len(blob)
                codec_name = "secagg"
                norm = None
                problem = validate_masked(
                    blob,
                    state.template,
                    bits=state.config.secagg_bits,
                    cohort=state.secagg_roster,
                )
                if problem is None:
                    declared = int(decode_masked(blob)["n"])
                    if declared != int(ns):
                        problem = (
                            f"masked sample count {declared} disagrees "
                            f"with the declared {ns}"
                        )
            else:
                blob, wire_len, codec_name, problem, norm = decode_and_validate_update(
                    blob,
                    ns,
                    template=state.template,
                    base_fn=lambda: _decoded_round_base(state),
                    base_version=state.model_version,
                    sanitize=state.config.sanitize_updates,
                )
            if problem is not None:
                # Refused BEFORE it can touch FedAvg; observable in the
                # round's history entry. The client fails loudly — a
                # poisoned trainer must not silently keep federating.
                rejected = dict(state.rejected)
                rejected[cname] = problem
                state = state._replace(
                    rejected=rejected,
                    ledger=_health_ledger.record_offer(
                        state.ledger, cname, outcome="rejected",
                        reason_class="sanitation", num_samples=ns,
                        wire_len=wire_len, round=rnd,
                    ),
                )
                return state, Reply(
                    status=REJECTED,
                    config={
                        "reason": f"update rejected: {problem}",
                        "client_round": rnd,
                    },
                )
            # NB: updates arriving while enrollment is still open are buffered
            # but never trigger aggregation — the cohort isn't final yet.
            # `received` holds the DECODED blob (a framed upload was
            # reconstructed above); `wire_bytes`/`codecs` remember what
            # actually crossed the wire for the round's history accounting.
            received = dict(state.received)
            received[cname] = (blob, ns)
            wire = dict(state.wire_bytes)
            wire[cname] = wire_len
            codecs = dict(state.codecs)
            codecs[cname] = codec_name
            state = state._replace(
                received=received, wire_bytes=wire, codecs=codecs,
                ledger=_health_ledger.record_offer(
                    state.ledger, cname, outcome="accepted", num_samples=ns,
                    wire_len=wire_len, round=rnd, norm=norm,
                ),
            )
            if _barrier_met(state):
                state = _aggregate(state, now)
                if cname in state.history[-1]["quarantined"]:
                    # The barrier-closing client was itself quarantined out
                    # of the fold it triggered: re-sync it NOT_WAIT (the
                    # sanitation-reject treatment) instead of handing it a
                    # RESP_ARY that claims its update was averaged. The
                    # direct NOT_WAIT reply is what fires the client-side
                    # codec rollback (transport/client.py rollback_last),
                    # so a topk sender's error-feedback residual re-enters
                    # instead of being dropped as "sent".
                    return state, Reply(
                        status=NOT_WAIT,
                        blob=state.broadcast_blob,
                        config=_ready_config(state, NOT_WAIT),
                    )
                status = FIN if state.phase == PHASE_FINISHED else RESP_ARY
                return state, Reply(
                    status=status,
                    blob=state.broadcast_blob,
                    config=_ready_config(state, status),
                )
            return state, Reply(status=RESP_ACY, config=_ready_config(state, RESP_ACY))

        case VersionPoll(model_version=mv):
            if state.phase == PHASE_FINISHED:
                # FIN carries the final average so pollers don't end the
                # session holding only their own local weights
                return state, Reply(
                    status=FIN,
                    blob=state.broadcast_blob,
                    config=_ready_config(state, FIN),
                )
            if state.model_version > mv:
                return state, Reply(
                    status=NOT_WAIT,
                    blob=state.broadcast_blob,
                    config=_ready_config(state, NOT_WAIT),
                )
            return state, Reply(status=WAIT, config=_ready_config(state, WAIT))

    raise TypeError(f"unknown event {event!r}")
