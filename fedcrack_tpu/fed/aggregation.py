"""THE aggregation algebra (round 21): one ordered fold, four planes.

Before this round the repo carried FOUR structurally-identical aggregation
folds — the rounds-plane sorted FedAvg (``fed/rounds.py``), the buffered
``fold_buffer`` (``fed/buffered.py``, shared by the root flush and the edge
tier's ``flush_partial``), the edge sync ``partial`` (``fed/tree.py``), and
the mesh-plane ordered cohort fold (``parallel/fedavg_mesh.py``). Four
copies of one shape was the failure surface the r18 health plane exposed:
the SCALED_UPDATE drill proved a sanitation-passing x1000 poisoned update
is *flagged* by the ledger yet still averaged in at full weight on every
one of them, because "how updates combine" lived in four places and none
had a seam to swap the combine.

This module is that seam. An aggregation algebra is an ordered fold over
``(name, weight, update_tree)`` triples:

    acc = algebra.init()
    for triple in triples:          # triples in CANONICAL order
        acc = algebra.combine(acc, triple)
    result = algebra.finalize(acc)

Canonical order is the caller's contract (sorted client names on the
rounds/edge planes, ``(cname, seq)`` on the buffered plane, client index
on the mesh) — the fold itself never re-orders, so the algebra composes
with the r13 ordered-fold bitwise discipline instead of fighting it.

The **null instance** (:class:`FedAvg`) accumulates the triples and
finalizes through :func:`fedcrack_tpu.fed.algorithms.fedavg` with exactly
the historical weight gate (``weights if any(w > 0) else None``) — which
is what makes it BITWISE-pinned to the four folds it replaced: same
decoded trees, same weight objects, same native-accumulate expression,
byte-identical globals (test-pinned per plane).

The **robust instances** plug in the literature:

- :class:`TrimmedMean` — coordinate-wise beta-trimmed mean (Yin et al.,
  "Byzantine-Robust Distributed Learning: Towards Optimal Statistical
  Rates", ICML 2018): per coordinate, sort the n client values, drop the
  ``floor(beta * n)`` smallest and largest, mean the rest.
- :class:`CoordinateMedian` — the same paper's coordinate-wise median.
- :class:`Krum` — Krum / Multi-Krum (Blanchard et al., "Machine Learning
  with Adversaries: Byzantine Tolerant Gradient Descent", NeurIPS 2017):
  score each update by the sum of its ``n - f - 2`` smallest squared
  distances to the others; Krum SELECTS the lowest-scoring update
  verbatim, Multi-Krum unweighted-means the ``n - f`` lowest-scoring.

Robust combines deliberately IGNORE the client-reported sample weights: a
Byzantine client self-reports ``num_samples``, so any weight it can
inflate is an attack surface — the whole point of the robust fold is that
no single client controls its own influence. (FedAvg keeps weights; it is
the null instance, pinned to history.)

The **mesh instance** is the same fold shape traced: :func:`mesh_zero_sums`
(init) / :func:`mesh_ordered_fold` (combine, one client at a time in
client-index order via ``all_gather`` + ``fori_loop``) /
:func:`mesh_finish_cohort_mean` (finalize, with the in-mesh empty-cohort
guard). ``parallel/fedavg_mesh.py`` aliases these under its historical
names so every traced program is the identical expression tree
(``groups_bitwise_equal`` unchanged).

Edge tiers refuse non-null algebras loudly (``EdgeAggregator`` ctor): a
trimmed partial of a partial is NOT a trimmed total — robust statistics do
not commute with hierarchical averaging the way the weighted mean does,
so a robust edge would silently change what the root computes. Robust
combines run where the full cohort is visible: the gRPC rounds plane and
the buffered root.

fedlint AGG001 pins the seam statically: a ``fedavg`` call in ``fed/`` or
``parallel/`` outside this module and ``fed/algorithms.py`` is an ERROR —
the fifth copy of the fold never lands.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fedcrack_tpu.fed.algorithms import fedavg

# One triple per contributing update, in the plane's canonical order.
Triple = tuple  # (name: str, weight: float, tree: Any)

# The FedConfig.aggregation vocabulary ("median" is accepted as shorthand
# for "coordinate_median"; from_config canonicalizes).
AGGREGATIONS = (
    "fedavg", "trimmed_mean", "median", "coordinate_median", "krum",
    "multi_krum",
)


class AggregationAlgebra:
    """One aggregation algebra: ``init`` / ``combine`` / ``finalize``.

    The default ``init``/``combine`` accumulate the ordered triples into a
    list — the free monoid, which every instance here folds over, because
    every combine in this family (weighted mean, trimmed mean, median,
    Krum) needs the full cohort to finalize. An instance that CAN stream
    (a plain weighted sum) may override ``init``/``combine`` with a
    constant-space carry; the mesh fold does exactly that, traced.
    """

    name = "abstract"

    def init(self) -> list:
        return []

    def combine(self, acc: list, triple: Triple) -> list:
        acc.append(triple)
        return acc

    def finalize(self, acc: list) -> Any:
        raise NotImplementedError

    def __repr__(self) -> str:  # config surfaces / drill artifacts
        return f"{type(self).__name__}({self.name!r})"


def fold(algebra: AggregationAlgebra, triples: Iterable[Triple]) -> Any:
    """THE ordered fold: run ``triples`` (already in the plane's canonical
    order) through ``algebra``. Every host-plane aggregation routes here —
    fedlint AGG001 makes any other route an ERROR."""
    acc = algebra.init()
    for t in triples:
        acc = algebra.combine(acc, t)
    return algebra.finalize(acc)


class FedAvg(AggregationAlgebra):
    """The null instance: sample-weighted mean, bitwise-pinned to the four
    historical folds. The weight gate is the historical one — weights are
    USED iff any is positive, else the mean is unweighted — and the weight
    OBJECTS pass through untouched (ints on the sync plane, ``ns * (1+s)^-
    alpha`` floats on the buffered plane), so the downstream ``fedavg``
    expression is byte-for-byte the one each plane ran before."""

    name = "fedavg"

    def finalize(self, acc: list) -> Any:
        if not acc:
            raise ValueError("aggregation fold over zero updates")
        trees = [t for (_, _, t) in acc]
        weights = [w for (_, w, _) in acc]
        use = weights if any(w > 0 for w in weights) else None
        return fedavg(trees, use)


def _stacked_leaf_combine(trees: Sequence[Any], leaf_fn: Callable) -> Any:
    """Per-leaf combine over the cohort: stack each leaf position across
    the n trees as float32 and reduce with ``leaf_fn(stacked) ->
    np.ndarray``, casting back to the first tree's leaf dtype. Order-
    independent by construction (the reductions here sort or select per
    coordinate), which is what the permuted-arrival tests pin."""

    def per_leaf(*leaves):
        stacked = np.stack([np.asarray(l, np.float32) for l in leaves])
        out = np.asarray(leaf_fn(stacked), np.float32)
        return out.astype(np.asarray(leaves[0]).dtype)

    return jax.tree_util.tree_map(per_leaf, *trees)


class TrimmedMean(AggregationAlgebra):
    """Coordinate-wise beta-trimmed mean (Yin et al., ICML 2018). Ignores
    client-reported weights (see module docstring). ``trim_fraction`` in
    ``[0, 0.5)`` guarantees at least one survivor per coordinate."""

    name = "trimmed_mean"

    def __init__(self, trim_fraction: float = 0.1):
        if not 0.0 <= trim_fraction < 0.5:
            raise ValueError(
                f"trim_fraction must be in [0, 0.5), got {trim_fraction}"
            )
        self.trim_fraction = float(trim_fraction)

    def finalize(self, acc: list) -> Any:
        if not acc:
            raise ValueError("aggregation fold over zero updates")
        n = len(acc)
        k = int(math.floor(self.trim_fraction * n))

        def leaf_fn(stacked):
            s = np.sort(stacked, axis=0)
            return s[k : n - k].mean(axis=0, dtype=np.float32)

        return _stacked_leaf_combine([t for (_, _, t) in acc], leaf_fn)


class CoordinateMedian(AggregationAlgebra):
    """Coordinate-wise median (Yin et al., ICML 2018). Ignores weights."""

    name = "coordinate_median"

    def finalize(self, acc: list) -> Any:
        if not acc:
            raise ValueError("aggregation fold over zero updates")
        return _stacked_leaf_combine(
            [t for (_, _, t) in acc],
            lambda stacked: np.median(stacked, axis=0),
        )


class Krum(AggregationAlgebra):
    """Krum / Multi-Krum (Blanchard et al., NeurIPS 2017). Each update i
    scores ``sum of its max(1, n - f - 2) smallest squared distances`` to
    the other updates; honest updates cluster, so the poisoned one's
    distances — and score — explode. Krum selects the single lowest-score
    update VERBATIM (bitwise one client's tree); Multi-Krum unweighted-
    means the ``max(1, n - f)`` lowest. Ties break on ``(score, name,
    canonical index)`` so the selection is arrival-order independent.
    Distances accumulate in float64 for cross-platform determinism.
    ``n <= f + 2`` clamps the neighbor count to 1 rather than refusing —
    the drill's 3-client cohorts are exactly this regime and the clamp
    keeps the score ordering (nearest honest neighbor) meaningful."""

    name = "krum"

    def __init__(self, byzantine_f: int = 1, *, multi: bool = False):
        if byzantine_f < 0:
            raise ValueError(f"byzantine_f must be >= 0, got {byzantine_f}")
        self.byzantine_f = int(byzantine_f)
        self.multi = bool(multi)
        if multi:
            self.name = "multi_krum"

    def _scores(self, vecs: list) -> list:
        n = len(vecs)
        closest = max(1, n - self.byzantine_f - 2)
        d2 = np.zeros((n, n), np.float64)
        for i in range(n):
            for j in range(i + 1, n):
                d = float(np.dot(vecs[i] - vecs[j], vecs[i] - vecs[j]))
                d2[i, j] = d2[j, i] = d
        scores = []
        for i in range(n):
            others = np.sort(np.delete(d2[i], i))
            scores.append(float(np.sum(others[:closest])))
        return scores

    def finalize(self, acc: list) -> Any:
        if not acc:
            raise ValueError("aggregation fold over zero updates")
        n = len(acc)
        if n == 1:
            return acc[0][2]
        vecs = [
            np.concatenate(
                [
                    np.asarray(l, np.float64).ravel()
                    for l in jax.tree_util.tree_leaves(t)
                ]
            )
            for (_, _, t) in acc
        ]
        scores = self._scores(vecs)
        order = sorted(range(n), key=lambda i: (scores[i], acc[i][0], i))
        if not self.multi:
            return acc[order[0]][2]
        m = max(1, n - self.byzantine_f)
        # Mean the selected set in CANONICAL index order (not score order)
        # so the summation expression is arrival-order independent.
        selected = sorted(order[:m])
        return fedavg([acc[i][2] for i in selected], None)


def from_config(cfg: Any) -> AggregationAlgebra:
    """The FedConfig -> algebra factory: ``cfg.aggregation`` names the
    combine, ``cfg.trim_fraction`` / ``cfg.byzantine_f`` parameterize it.
    Accepts any object with those attributes (FedConfig, EdgeAggregator
    kwargs bag, a test namespace); missing attributes mean the null
    instance."""
    kind = getattr(cfg, "aggregation", "fedavg") or "fedavg"
    if kind == "fedavg":
        return FedAvg()
    if kind == "trimmed_mean":
        return TrimmedMean(float(getattr(cfg, "trim_fraction", 0.1)))
    if kind in ("median", "coordinate_median"):
        return CoordinateMedian()
    if kind == "krum":
        return Krum(int(getattr(cfg, "byzantine_f", 1)))
    if kind == "multi_krum":
        return Krum(int(getattr(cfg, "byzantine_f", 1)), multi=True)
    raise ValueError(
        f"unknown aggregation {kind!r} (choose from {AGGREGATIONS})"
    )


def quarantine_set(
    scores: dict, names: Sequence[str], quarantine_z: float
) -> dict:
    """The ledger->fold coupling: which of this flush's contributors are
    EXCLUDED from the fold. ``scores`` is the per-client max robust-z the
    r18 ledger just computed (:func:`fedcrack_tpu.health.ledger.
    observe_flush`); a client at or above ``quarantine_z`` is quarantined.
    ``quarantine_z <= 0`` disables (the default — detection without
    response, exactly r18's behavior). A verdict that would quarantine the
    ENTIRE cohort is dropped: robust-z needs a majority reference, and a
    fold over zero updates cannot advance the round — better to take the
    round and let the alert threshold page. Returns ``{name: score}``
    (scores rounded to 6, like the ledger's own norms) for the history's
    ``quarantined`` map."""
    if quarantine_z <= 0.0:
        return {}
    out = {}
    for n in names:
        s = float(scores.get(n, 0.0))
        if s >= quarantine_z:
            out[n] = round(s, 6)
    if out and len(out) >= len(set(names)):
        return {}
    return out


# --------------------------------------------------------------------------
# The mesh instance: the same init/combine/finalize fold shape, traced.
# Relocated verbatim from parallel/fedavg_mesh.py (round 13) so the one
# module owning "how updates combine" owns it on the mesh plane too;
# fedavg_mesh aliases these under its historical names, keeping every
# traced program the identical expression tree (groups_bitwise_equal).
# --------------------------------------------------------------------------


def mesh_ordered_fold(
    tree: Any, weight: jax.Array, init: tuple, *, axis_name: str = "clients"
) -> tuple:
    """Deterministically-ORDERED masked weighted sums over ``axis_name``,
    continuing the partial-sum carry ``init = (num_tree_f32, den_scalar_
    f32)``: each leaf is all_gathered and left-folded into the carry one
    client at a time, in client-index order.

    Why not ``lax.psum``: an all-reduce's float addition order is
    backend/topology-defined (CPU XLA reduces rank-sequentially, a TPU ring
    reduces in ring order), so group-partial psums do NOT compose bitwise —
    ``psum_4(x) != psum_2(x[:2]) + psum_2(x[2:])`` (measured). The fold
    pins ONE expression tree — ``(((0 + w0*x0) + w1*x1) + ...)`` — that is
    identical whether the cohort runs as one C-wide mesh or as sequential
    groups of G continuing the carry (round 13's time-multiplexed cohort
    contract, test-pinned bitwise for groups in {1, 2, 4}). Zero-weight
    padding clients contribute ``±0.0``, which is a bitwise no-op on any
    partial sum reachable from the ``+0.0`` init, so ragged cohorts pad
    clean. Cost vs psum: an all_gather (G x leaf bytes on the ICI) plus a
    serial length-G fold — noise next to the round's epochs x steps scan.
    """
    num, den = init
    gathered = jax.tree_util.tree_map(
        lambda x: lax.all_gather(weight * x.astype(jnp.float32), axis_name),
        tree,
    )
    gw = lax.all_gather(weight, axis_name)

    def body(i, acc):
        acc_num, acc_den = acc
        acc_num = jax.tree_util.tree_map(
            lambda a, g: a + g[i], acc_num, gathered
        )
        return acc_num, acc_den + gw[i]

    return lax.fori_loop(0, gw.shape[0], body, (num, den))


def mesh_zero_sums(tree: Any) -> tuple:
    """The fold's identity carry: f32 zeros per update leaf + a 0 weight."""
    return (
        jax.tree_util.tree_map(
            lambda x: jnp.zeros(jnp.shape(x), jnp.float32), tree
        ),
        jnp.zeros((), jnp.float32),
    )


def mesh_finish_cohort_mean(
    num: Any, total_w: jax.Array, fallback: Any
) -> Any:
    """Divide the ordered sums into the FedAvg mean, with the empty-cohort
    guard: zero total weight returns ``fallback`` (the round's incoming
    global model) unchanged. Elementwise ops only — bitwise deterministic
    regardless of which program (in-round tail, grouped finalize) runs it."""
    denom = jnp.maximum(total_w, 1e-9)
    averaged = jax.tree_util.tree_map(
        lambda s, orig: (s / denom).astype(orig.dtype), num, fallback
    )
    keep = total_w > 0.0
    return jax.tree_util.tree_map(
        lambda avg, orig: jnp.where(keep, avg, orig.astype(avg.dtype)),
        averaged,
        fallback,
    )
