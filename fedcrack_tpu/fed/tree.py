"""Hierarchical aggregation tree: edge tiers between the cohort and the root.

A flat coordinator materializes one full weight blob PER CLIENT before it
can average — O(N) server memory, the hard wall between the reference's
handful of processes and the ROADMAP's 1,000+-client cohorts. Production FL
systems (Bonawitz et al., MLSys 2019) interpose an edge tier: each edge
aggregator owns a shard of the cohort, runs the SAME acceptance gate and
K-of-N quorum the root runs, reduces its shard to ONE sample-weighted
partial average, and streams that single blob upward. Root memory drops to
O(fan-in); total resident blobs at any instant are bounded by one edge's
leaf fan-in plus the root's edge fan-in.

Exactness: a sample-weighted FedAvg is associative over sample-weighted
partial FedAvgs — ``fedavg(all leaves, counts) == fedavg(edge partials,
edge count sums)`` up to float re-association (the edge tier changes the
summation grouping, like any distributed reduction; the 1,024-client smoke
pins the tree-vs-flat agreement numerically and the tree's own trajectory
BITWISE reproducible from the cohort seed).

Every tier routes uploads through the one shared acceptance gate
(:func:`fedcrack_tpu.fed.rounds.decode_and_validate_update` — CRC'd frame
decode, shape/finiteness sanitation), every tier takes K-of-N quorum via
the one shared :func:`fedcrack_tpu.fed.rounds.quorum_target`, and every
tier persists its in-flight round to an atomic statefile so a mid-round
kill resumes with the already-received updates intact (the r8 server
statefile contract, generalized per tier; tools/chaos_drill.py drills the
edge kill→restart). The edge→root hop can re-encode the partial with the
r12 codecs (``update_codec``) — partial aggregates are deltas against the
same broadcast base the leaves trained from, so the root's existing frame
decode accepts them unchanged.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
from typing import Any, Callable, Sequence

import msgpack
import numpy as np

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import buffered as _buffered
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed import aggregation as _aggregation
from fedcrack_tpu.fed.algorithms import sample_cohort
from fedcrack_tpu.fed.rounds import decode_and_validate_update, quorum_target
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.health import ledger as _health_ledger
from fedcrack_tpu.ioutils import atomic_write_bytes
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY

log = logging.getLogger("fedcrack.fed.tree")


def _edge_updates_counter():
    return REGISTRY.counter(
        "edge_updates_total",
        "leaf uploads at the edge tier by outcome",
        labels=("result",),
    )


def _edge_wire_counter():
    return REGISTRY.counter(
        "edge_wire_bytes_total",
        "wire bytes at the edge tier (in = leaf uploads, up = partials "
        "pushed toward the root)",
        labels=("direction",),
    )

EDGE_STATE_FORMAT = 1


def partition_cohort(cohort: Sequence[int], n_edges: int) -> list[np.ndarray]:
    """Deterministic contiguous split of a (sorted) cohort across
    ``n_edges`` edge aggregators — ``np.array_split`` semantics (the first
    ``len % n_edges`` edges take one extra leaf). Deterministic assignment
    is part of the bit-reproducibility contract: the same cohort always
    lands on the same edges, so each edge's partial average reproduces."""
    if n_edges <= 0:
        raise ValueError(f"n_edges must be positive, got {n_edges}")
    arr = np.asarray(cohort, np.int64)
    if arr.size == 0:
        raise ValueError("empty cohort")
    return [s for s in np.array_split(arr, min(n_edges, arr.size))]


class EdgeAggregator:
    """One edge tier node: collects its leaf shard's updates for the
    current round, sanitizes each through the shared acceptance gate,
    holds at most LEAF-FAN-IN decoded blobs, and reduces them to one
    sample-weighted partial average for the hop up.

    The edge deliberately does NOT advance a round counter or broadcast —
    it is a reducer, not a coordinator: the round/base it aggregates for
    comes down from the root (``begin_round``), and what its leaves train
    on next is the ROOT's next broadcast, never the edge's partial (an
    edge that broadcast its own partial would fork the federation's
    trajectory per shard).

    ``state_path`` arms per-tier crash recovery: every accepted or
    rejected offer snapshots the in-flight round through the same atomic
    write-temp + fsync + rename discipline as the server statefile, and
    :meth:`restore` resumes the SAME round with the already-received
    updates intact (drilled by tools/chaos_drill.py EDGE_AGGREGATOR_CRASH).
    """

    def __init__(
        self,
        edge_id: str,
        template: Any,
        *,
        quorum_fraction: float = 1.0,
        sanitize: bool = True,
        state_path: str = "",
        update_codec: str = "null",
        topk_fraction: float = 0.01,
        mode: str = "sync",
        buffer_k: int = 2,
        staleness_alpha: float = 0.5,
        max_staleness: int = 4,
        aggregation: str = "fedavg",
    ):
        if not 0.0 < quorum_fraction <= 1.0:
            raise ValueError(
                f"quorum_fraction must be in (0, 1], got {quorum_fraction}"
            )
        if update_codec not in ("null", "int8", "topk_delta"):
            raise ValueError(f"unknown update_codec {update_codec!r}")
        if aggregation != "fedavg":
            # Robust combines do NOT commute with hierarchical averaging:
            # a trimmed mean of per-edge trimmed partials is not the
            # trimmed mean of the cohort (each edge trims against its own
            # shard's statistics, and the root then re-averages already-
            # censored partials — the Byzantine update an edge fails to
            # trim rides up at full weight, while the root has lost the
            # per-leaf geometry it would need to catch it). Until a
            # composition-safe scheme lands, robust aggregation runs where
            # the full cohort is visible (the gRPC rounds plane and the
            # buffered root); the edge tier refuses loudly rather than
            # silently computing a different federation.
            raise ValueError(
                f"edge tier only supports aggregation='fedavg', got "
                f"{aggregation!r}: a trimmed/robust partial of a partial "
                "is not a robust total — run robust combines at the root "
                "(FedConfig.aggregation)"
            )
        if mode not in ("sync", "buffered"):
            raise ValueError(f"mode must be 'sync' or 'buffered', got {mode!r}")
        if buffer_k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {buffer_k}")
        if staleness_alpha < 0.0 or max_staleness < 0:
            raise ValueError(
                "staleness_alpha and max_staleness must be >= 0, got "
                f"{staleness_alpha}/{max_staleness}"
            )
        self.edge_id = edge_id
        self.template = template
        self.quorum_fraction = quorum_fraction
        self.sanitize = sanitize
        self.state_path = state_path
        self.update_codec = update_codec
        self.topk_fraction = topk_fraction
        # Buffered-async edge tier (round 14, the r13 follow-up): the same
        # FedBuff discipline as the root server (fed/buffered.py), one tier
        # down — leaf updates fold into a K-sized staleness-weighted buffer
        # as they arrive and flush UPSTREAM as one weighted partial, so a
        # straggling leaf never stalls its shard's hop up.
        self.mode = mode
        self.buffer_k = int(buffer_k)
        self.staleness_alpha = float(staleness_alpha)
        self.max_staleness = int(max_staleness)
        self.buffer: list[dict] = []
        # version -> root broadcast blob retained for stale-delta decode
        # (pruned to the max_staleness window on every advance_base), and
        # its decoded-tree cache (one decode per retained version, not per
        # offer — the buffered accept path is the hot loop).
        self.bases: dict[int, bytes] = {}
        self._base_trees: dict[int, Any] = {}
        self.round = 0
        self.base_version = -1
        self.base_blob = b""
        self.leaves: frozenset[str] = frozenset()
        self.received: dict[str, tuple[bytes, int]] = {}
        self.rejected: dict[str, str] = {}
        self.wire_bytes: dict[str, int] = {}
        # Per-leaf health ledger (round 18): the edge feeds the SAME ledger
        # shape as the root — every gate verdict plus flush-time geometry —
        # and persists it in its statefile alongside the round state.
        self.ledger: dict[str, dict] = {}
        # Observability the cohort-scale decision point reads: the most
        # decoded update blobs this edge ever held at once (must stay
        # <= leaf fan-in) and the wire bytes in/up.
        self.peak_resident_blobs = 0
        self.bytes_in = 0
        self.bytes_up = 0
        self._base_tree = None
        # One codec instance for the edge's LIFETIME, like the leaf
        # FedClient's: topk_delta's error-feedback residual is cross-round
        # state — a per-round codec would silently drop each round's
        # unsent partial-delta mass forever instead of re-entering it.
        self._codec = None
        # Trace re-parenting (round 16): the wire context each accepted
        # leaf offer carried, linked onto the edge's flush span; the
        # flush's OWN context rides the hop up so the root re-parents the
        # edge exactly like a client. Observability only — never persisted.
        self.trace_links: dict[str, str] = {}
        self.last_partial_ctx: str = ""
        self._flush_seq = 0

    # -- round lifecycle --

    def begin_round(
        self,
        round_idx: int,
        base_blob: bytes,
        base_version: int,
        leaves: Sequence[Any],
    ) -> None:
        """Arm the edge for one root round: the shard of leaf names it is
        responsible for, and the root's broadcast base (the blob its
        leaves pulled — framed leaf deltas decode against it)."""
        self.round = int(round_idx)
        self.base_blob = bytes(base_blob)
        self.base_version = int(base_version)
        self.leaves = frozenset(str(x) for x in leaves)
        if not self.leaves:
            raise ValueError(f"edge {self.edge_id}: empty leaf shard")
        self.received = {}
        self.rejected = {}
        self.wire_bytes = {}
        self._base_tree = None
        if self.mode == "buffered":
            # Arm the retained-base window; the buffer deliberately
            # survives (it is not per-round state — that is the point).
            self._retain_base()
        self._persist()

    def advance_base(self, round_idx: int, base_blob: bytes, base_version: int) -> None:
        """Buffered mode: the root published a new global — make it the
        current base (new leaf deltas pin to it) while RETAINING the old
        one inside the ``max_staleness`` window, so in-flight leaf updates
        trained on it still decode (staleness-weighted) instead of dying
        on a base mismatch. The buffer carries across."""
        if self.mode != "buffered":
            raise RuntimeError("advance_base is a buffered-mode call")
        self.round = int(round_idx)
        self.base_blob = bytes(base_blob)
        self.base_version = int(base_version)
        self._base_tree = None
        self._retain_base()
        self._persist()

    def _retain_base(self) -> None:
        self.bases = {
            v: b
            for v, b in sorted(self.bases.items())
            if self.base_version - v <= self.max_staleness
        }
        self.bases[self.base_version] = self.base_blob
        self._base_trees = {
            v: t
            for v, t in sorted(self._base_trees.items())
            if v in self.bases
        }

    def _decoded_retained_base(self, version: int):
        tree = self._base_trees.get(version)
        if tree is None:
            tree = tree_from_bytes(self.bases[version], template=self.template)
            self._base_trees[version] = tree
        return tree

    def _decoded_base(self):
        if self._base_tree is None:
            self._base_tree = tree_from_bytes(self.base_blob, template=self.template)
        return self._base_tree

    @property
    def quorum(self) -> int:
        return quorum_target(self.quorum_fraction, len(self.leaves))

    def quorum_met(self) -> bool:
        return len(self.received) >= self.quorum

    def _stamp_trace(self, cname: str, trace_ctx: str) -> None:
        """Remember an accepted offer's wire context for the flush span's
        links; anything unparseable degrades to no link, never an error."""
        if trace_ctx and tracing.TraceContext.from_wire(trace_ctx) is not None:
            self.trace_links[cname] = trace_ctx

    def _emit_flush_span(self, cnames: list[str]) -> str:
        """Re-parent the flushed leaves' contexts onto one
        ``edge.flush_partial`` span and mint this flush's OWN wire context
        (returned, and kept as ``last_partial_ctx``) for the hop up — the
        root then links the edge exactly like a client. Shared by the
        buffered and sync flush paths so the re-parenting idiom cannot
        drift between them."""
        self._flush_seq += 1
        ectx = tracing.TraceContext(
            tracing.version_trace(self.base_version),
            f"edge:{self.edge_id}:flush:{self._flush_seq}",
        )
        links = []
        for name in cnames:
            wire = self.trace_links.pop(name, None)
            if wire is not None:
                links.append(wire)
        with tracing.span(
            "edge.flush_partial",
            trace=ectx.trace,
            ctx=ectx.to_wire(),
            links=sorted(links),
            edge=self.edge_id,
            buffer_fill=len(cnames),
        ):
            pass
        self.last_partial_ctx = ectx.to_wire()
        return self.last_partial_ctx

    def offer(
        self, cname: str, blob: bytes, num_samples: int, trace_ctx: str = ""
    ) -> tuple[bool, str | None]:
        """One leaf's upload. Routes through the SAME
        ``decode_and_validate_update`` gate the root runs — a corrupt
        frame, wrong-shape tree or NaN update is rejected (recorded, never
        averaged) at the edge, before it can cost a hop up. Returns
        ``(accepted, rejection_reason)``."""
        if cname not in self.leaves:
            return False, f"{cname} not in this edge's shard"
        if cname in self.received:
            return False, f"duplicate upload from {cname}"
        decoded, wire_len, _codec, problem, norm = decode_and_validate_update(
            blob,
            num_samples,
            template=self.template,
            base_fn=self._decoded_base,
            base_version=self.base_version,
            sanitize=self.sanitize,
        )
        self.bytes_in += wire_len
        _edge_wire_counter().labels(direction="in").inc(wire_len)
        if problem is not None:
            self.rejected[cname] = problem
            self.ledger = _health_ledger.record_offer(
                self.ledger, cname, outcome="rejected",
                reason_class="sanitation", num_samples=num_samples,
                wire_len=wire_len, round=self.round,
            )
            _edge_updates_counter().labels(result="rejected").inc()
            self._persist()
            return False, problem
        _edge_updates_counter().labels(result="accepted").inc()
        self.received[cname] = (decoded, int(num_samples))
        self.wire_bytes[cname] = wire_len
        self.ledger = _health_ledger.record_offer(
            self.ledger, cname, outcome="accepted", num_samples=num_samples,
            wire_len=wire_len, round=self.round, norm=norm,
        )
        self._stamp_trace(cname, trace_ctx)
        self.peak_resident_blobs = max(self.peak_resident_blobs, len(self.received))
        self._persist()
        return True, None

    def offer_buffered(
        self,
        cname: str,
        blob: bytes,
        num_samples: int,
        base_version: int,
        trace_ctx: str = "",
    ) -> tuple[bool, str | None]:
        """Buffered mode's leaf upload: gated by the SAME
        ``decode_and_validate_update`` — against the base the leaf
        actually trained on (``base_version``, retained in the window) —
        staleness-weighted with the root server's closed form, and folded
        into the buffer. Too-stale or unretained-base offers are recorded
        and refused (the caller resyncs the leaf); sanitation failures are
        refused loudly. Returns ``(accepted, rejection_reason)``."""
        if self.mode != "buffered":
            return False, "edge is not in buffered mode"
        if cname not in self.leaves:
            return False, f"{cname} not in this edge's shard"
        staleness = self.base_version - int(base_version)
        if staleness < 0:
            return self._refuse(
                cname,
                f"future base version {base_version} (edge at {self.base_version})",
                reason_class="stale",
            )
        if staleness > self.max_staleness:
            return self._refuse(
                cname,
                f"too stale: base version {base_version} is {staleness} "
                f"behind (max_staleness={self.max_staleness})",
                reason_class="stale",
                staleness=staleness,
            )
        if int(base_version) not in self.bases:
            return self._refuse(
                cname,
                f"base version {base_version} no longer retained",
                reason_class="stale",
                staleness=staleness,
            )
        decoded, wire_len, codec_name, problem, norm = decode_and_validate_update(
            blob,
            num_samples,
            template=self.template,
            base_fn=lambda: self._decoded_retained_base(int(base_version)),
            base_version=int(base_version),
            sanitize=self.sanitize,
        )
        self.bytes_in += wire_len
        _edge_wire_counter().labels(direction="in").inc(wire_len)
        if problem is not None:
            return self._refuse(
                cname, problem, reason_class="sanitation",
                num_samples=num_samples, wire_len=wire_len,
                staleness=staleness,
            )
        _edge_updates_counter().labels(result="accepted").inc()
        self.ledger = _health_ledger.record_offer(
            self.ledger, cname, outcome="accepted", num_samples=num_samples,
            wire_len=wire_len, round=self.round, staleness=staleness,
            norm=norm,
        )
        self._stamp_trace(cname, trace_ctx)
        self.buffer.append(
            {
                "cname": cname,
                "seq": sum(1 for e in self.buffer if e["cname"] == cname),
                "blob": decoded,
                "ns": int(num_samples),
                "staleness": int(staleness),
                "weight": _buffered.staleness_weight(
                    staleness, self.staleness_alpha
                ),
                "base_version": int(base_version),
                "wire_len": int(wire_len),
                "codec": codec_name,
            }
        )
        self.peak_resident_blobs = max(self.peak_resident_blobs, len(self.buffer))
        self._persist()
        return True, None

    def _refuse(
        self,
        cname: str,
        reason: str,
        *,
        reason_class: str = "other",
        num_samples: int = 0,
        wire_len: int = 0,
        staleness: int = 0,
    ) -> tuple[bool, str]:
        self.rejected[cname] = reason
        self.ledger = _health_ledger.record_offer(
            self.ledger, cname, outcome="rejected", reason_class=reason_class,
            num_samples=num_samples, wire_len=wire_len, round=self.round,
            staleness=staleness,
        )
        _edge_updates_counter().labels(result="rejected").inc()
        self._persist()
        return False, reason

    def buffer_ready(self) -> bool:
        return len(self.buffer) >= self.buffer_k

    def flush_partial(self) -> tuple[bytes, int, dict]:
        """Flush the buffer into ONE staleness-weighted partial for the hop
        up: the same sorted ``(cname, seq)`` fold as the root's buffered
        flush, weighted ``ns * (1 + staleness)^-alpha``. Returns
        ``(blob_or_frame, total_samples, info)`` — ``total_samples`` is the
        effective weight rounded to the wire's integer sample field
        (floored at 1), and ``info`` carries the per-update staleness/
        weight lists for observability. With a non-null ``update_codec``
        the partial re-encodes as a delta against the CURRENT base, with
        the top-k error-feedback residual decayed by the flush's mean
        staleness weight (``ef_decay`` — only the discounted share of the
        dropped mass is owed back; see TopKDeltaCodec). The edge does NOT
        anchor its partial on the base the way the root flush mixes
        against the current global — a partial is an INPUT to the parent
        tier's weighted average, and its staleness discount is carried
        there by the reduced effective sample count."""
        if self.mode != "buffered":
            raise RuntimeError("flush_partial is a buffered-mode call")
        if not self.buffer:
            raise RuntimeError(f"edge {self.edge_id}: flush of an empty buffer")
        avg, entries, counts, eff, trees = _buffered.fold_buffer(
            self.buffer, self.template
        )
        # Health ledger (round 18): score this flush's geometry on the
        # fold's already-decoded trees against the current base.
        self.ledger, _scores = _health_ledger.observe_flush(
            self.ledger,
            [(e["cname"], t) for e, t in zip(entries, trees)],
            self._decoded_base(),
        )
        total_eff = float(sum(eff))
        total_ns = float(sum(counts))
        blob = tree_to_bytes(avg)
        if self.update_codec != "null":
            if self._codec is None:
                from fedcrack_tpu.compress import get_codec

                self._codec = get_codec(
                    self.update_codec,
                    topk_fraction=self.topk_fraction,
                    client_tag=self.edge_id,
                )
            decay = total_eff / total_ns if total_ns > 0 else 1.0
            kwargs = (
                {"ef_decay": decay} if self.update_codec == "topk_delta" else {}
            )
            blob = self._codec.encode_update(
                blob,
                self.base_blob,
                round=self.round,
                base_version=self.base_version,
                **kwargs,
            )
        self.bytes_up += len(blob)
        _edge_wire_counter().labels(direction="up").inc(len(blob))
        REGISTRY.counter(
            "edge_flushes_total", "edge-tier partial aggregations pushed up"
        ).inc()
        # Re-parent the flushed leaves' contexts onto this flush span; its
        # OWN context rides the hop up (info["trace_ctx"] → the relay's
        # "__trace") so the root links the edge like any client.
        flush_ctx = self._emit_flush_span([e["cname"] for e in entries])
        info = {
            "clients": [e["cname"] for e in entries],
            "staleness": [e["staleness"] for e in entries],
            "weights": [e["weight"] for e in entries],
            "buffer_fill": len(entries),
            "effective_samples": total_eff,
            "trace_ctx": flush_ctx,
        }
        self.buffer = []
        self._persist()
        return blob, max(1, int(round(total_eff))), info

    def partial(self) -> tuple[bytes, int]:
        """The shard's sample-weighted partial FedAvg as ONE upload for the
        parent tier: ``(blob_or_frame, total_samples)``. Weighting partials
        by their sample SUM is what makes the tree reduce to the flat
        sample-weighted mean (weighted-mean associativity). With a non-null
        ``update_codec`` the partial re-encodes as a delta frame against
        the round base — the r12 wire contract, so the parent's existing
        frame decode + sanitation accepts it unchanged."""
        if not self.received:
            raise RuntimeError(
                f"edge {self.edge_id}: no accepted updates to aggregate"
            )
        names = sorted(self.received)
        trees = [
            tree_from_bytes(self.received[n][0], template=self.template)
            for n in names
        ]
        counts = [self.received[n][1] for n in names]
        self.ledger, _scores = _health_ledger.observe_flush(
            self.ledger, list(zip(names, trees)), self._decoded_base()
        )
        # The null algebra instance (round 21): bitwise the historical
        # sorted sample-weighted fold. The edge NEVER folds robustly (ctor
        # refusal — see __init__).
        avg = _aggregation.fold(
            _aggregation.FedAvg(), list(zip(names, counts, trees))
        )
        total = int(sum(counts))
        blob = tree_to_bytes(avg)
        if self.update_codec != "null":
            if self._codec is None:
                from fedcrack_tpu.compress import get_codec

                self._codec = get_codec(
                    self.update_codec,
                    topk_fraction=self.topk_fraction,
                    client_tag=self.edge_id,
                )
            blob = self._codec.encode_update(
                blob,
                self.base_blob,
                round=self.round,
                base_version=self.base_version,
            )
        self.bytes_up += len(blob)
        _edge_wire_counter().labels(direction="up").inc(len(blob))
        REGISTRY.counter(
            "edge_flushes_total", "edge-tier partial aggregations pushed up"
        ).inc()
        self._emit_flush_span(list(names))
        return blob, total

    def end_round(self) -> None:
        """Release the round's decoded blobs (the fan-in memory bound is a
        per-round guarantee, not a leak) once the partial is safely up."""
        self.received = {}
        self.wire_bytes = {}
        self._base_tree = None
        self._persist()

    # -- per-tier durable state (the r8 statefile contract, edge-shaped) --

    def _persist(self) -> None:
        if not self.state_path:
            return
        payload = {
            "format": EDGE_STATE_FORMAT,
            "edge_id": self.edge_id,
            "round": self.round,
            "base_version": self.base_version,
            "base_blob": self.base_blob,
            "leaves": sorted(self.leaves),
            # Sorted, like the server statefile: snapshot bytes are a pure
            # function of state, not of upload arrival order.
            "received": {
                name: [blob, int(ns)]
                for name, (blob, ns) in sorted(self.received.items())
            },
            "rejected": {k: v for k, v in sorted(self.rejected.items())},
            "wire_bytes": {k: int(v) for k, v in sorted(self.wire_bytes.items())},
            # Buffered mode (round 14): in-flight buffer + retained bases
            # + the knobs the buffer's SEMANTICS depend on (flush
            # threshold, decay, staleness window) — a restore that fell
            # back to ctor defaults would silently change when the
            # resumed buffer flushes and how its entries weigh.
            # Canonically sorted like everything above; the per-entry
            # wire row is fed/buffered's shared codec. Empty/absent for
            # sync edges and pre-round-14 snapshots.
            "mode": self.mode,
            "buffer_k": int(self.buffer_k),
            "staleness_alpha": float(self.staleness_alpha),
            "max_staleness": int(self.max_staleness),
            "buffer": [
                _buffered.buffer_entry_to_wire(e)
                for e in sorted(
                    self.buffer, key=lambda e: (e["cname"], e["seq"])
                )
            ],
            "bases": {str(int(v)): b for v, b in sorted(self.bases.items())},
            # Health ledger (round 18): canonically-sorted wire rows, the
            # same codec the server statefile uses. Absent pre-round-18.
            "ledger": _health_ledger.ledger_to_wire(self.ledger),
        }
        atomic_write_bytes(self.state_path, msgpack.packb(payload, use_bin_type=True))

    @classmethod
    def restore(
        cls,
        state_path: str,
        template: Any,
        *,
        quorum_fraction: float = 1.0,
        sanitize: bool = True,
        update_codec: str = "null",
        topk_fraction: float = 0.01,
        buffer_k: int = 2,
        staleness_alpha: float = 0.5,
        max_staleness: int = 4,
    ) -> "EdgeAggregator | None":
        """Resume a killed edge from its statefile: same round, same base,
        already-received updates intact. None when the file is missing or
        unreadable (the restarted edge then begins the round fresh and the
        root's quorum/deadline machinery absorbs the loss)."""
        try:
            with open(state_path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError:
            log.exception("edge statefile %s unreadable", state_path)
            return None
        try:
            payload = msgpack.unpackb(blob, raw=False)
            if payload.get("format") != EDGE_STATE_FORMAT:
                raise ValueError(f"unknown edge statefile format {payload.get('format')!r}")
            edge = cls(
                str(payload["edge_id"]),
                template,
                quorum_fraction=quorum_fraction,
                sanitize=sanitize,
                state_path=state_path,
                update_codec=update_codec,
                topk_fraction=topk_fraction,
                mode=str(payload.get("mode", "sync")),
                # The buffer's SEMANTICS (flush threshold, decay,
                # staleness window) restore from the FILE: falling back
                # to the caller's args would silently change when the
                # resumed buffer flushes and how its entries weigh. The
                # args are only the pre-round-14-snapshot default.
                buffer_k=int(payload.get("buffer_k", buffer_k)),
                staleness_alpha=float(
                    payload.get("staleness_alpha", staleness_alpha)
                ),
                max_staleness=int(payload.get("max_staleness", max_staleness)),
            )
            edge.round = int(payload["round"])
            edge.base_version = int(payload["base_version"])
            edge.base_blob = bytes(payload["base_blob"])
            edge.leaves = frozenset(str(x) for x in payload["leaves"])
            edge.received = {
                name: (bytes(pair[0]), int(pair[1]))
                for name, pair in payload["received"].items()
            }
            edge.rejected = dict(payload.get("rejected", {}))
            edge.wire_bytes = {
                k: int(v) for k, v in payload.get("wire_bytes", {}).items()
            }
            edge.buffer = [
                _buffered.buffer_entry_from_wire(e)
                for e in payload.get("buffer", [])
            ]
            edge.bases = {
                int(v): bytes(b)
                for v, b in payload.get("bases", {}).items()
            }
            edge.ledger = _health_ledger.ledger_from_wire(
                payload.get("ledger", [])
            )
            edge.peak_resident_blobs = max(len(edge.received), len(edge.buffer))
            return edge
        except Exception:
            log.exception("edge statefile %s corrupt; starting fresh", state_path)
            return None


@dataclasses.dataclass
class TreeRunResult:
    """What :func:`run_tree_federation` proves, in numbers."""

    state: Any                      # final root ServerState
    n_clients: int
    cohort_size: int
    n_edges: int
    rounds: int
    root_peak_blobs: int            # max |root.received| — must be <= n_edges
    edge_peak_blobs: int            # max over edges — must be <= leaf fan-in
    max_leaf_fan_in: int
    leaf_updates: int               # total leaf uploads offered
    leaf_rejections: int
    bytes_at_root: int              # wire bytes the root actually received
    bytes_flat_equiv: int           # what a flat root would have received
    global_sha256: str              # fingerprint of the final global blob
    cohorts: list[list[int]]        # per-round sampled cohorts (seeded)


def run_tree_federation(
    variables: Any,
    make_update: Callable[[int, int, bytes, int], tuple[bytes, int]],
    *,
    n_clients: int,
    cohort_size: int,
    n_rounds: int,
    n_edges: int,
    cohort_seed: int = 0,
    quorum_fraction: float = 1.0,
    edge_quorum_fraction: float = 1.0,
    update_codec: str = "null",
    topk_fraction: float = 0.01,
    sanitize: bool = True,
    state_dir: str = "",
) -> TreeRunResult:
    """Drive a multi-round federation through a 2-level aggregation tree,
    in-process: the ROOT is the unmodified round state machine
    (``fed.rounds.transition`` — its cohort is the EDGES), each edge an
    :class:`EdgeAggregator` over its shard of the per-round seeded cohort,
    each leaf a simulated client (``make_update(client_idx, round_idx,
    base_blob, base_version) -> (blob, n_samples)``).

    Edges process their shards SEQUENTIALLY and release their decoded
    blobs after the hop up, so peak resident update blobs anywhere in the
    process are ``max(leaf fan-in) + root fan-in`` — the memory shape that
    makes a 1,024-simulated-client round run where a flat coordinator
    would hold 1,024 decoded models. Every quantity the cohort-scale
    decision point reads comes back in :class:`TreeRunResult`.

    Bit-reproducibility: with a deterministic ``make_update``, the entire
    trajectory — cohorts, shard assignment, every edge partial, the root
    average — is a pure function of ``cohort_seed`` (test-pinned via
    ``global_sha256``).
    """
    import os

    if cohort_size < n_edges:
        # partition_cohort would hand out fewer shards than edges and the
        # root's full barrier over n_edges could never close — a
        # misconfiguration, surfaced here instead of as an IndexError
        # mid-round.
        raise ValueError(
            f"cohort_size={cohort_size} < n_edges={n_edges}: every edge "
            "needs at least one leaf (shrink the tree's fan-out)"
        )
    cfg = FedConfig(
        max_rounds=n_rounds,
        cohort_size=n_edges,
        quorum_fraction=quorum_fraction,
        sanitize_updates=sanitize,
        registration_window_s=3600.0,
        update_codec=update_codec,
        topk_fraction=topk_fraction,
    )
    state = R.initial_state(cfg, variables)
    now = 0.0
    for e in range(n_edges):
        now += 1e-3
        state, rep = R.transition(state, R.Ready(cname=f"edge-{e}", now=now))
        assert rep.status == R.SW, rep.status
    assert state.phase == R.PHASE_RUNNING

    edges = [
        EdgeAggregator(
            f"edge-{e}",
            state.template,
            quorum_fraction=edge_quorum_fraction,
            sanitize=sanitize,
            state_path=(
                os.path.join(state_dir, f"edge-{e}.msgpack") if state_dir else ""
            ),
            update_codec=update_codec,
            topk_fraction=topk_fraction,
        )
        for e in range(n_edges)
    ]

    root_peak = 0
    edge_peak = 0
    max_fan_in = 0
    leaf_updates = 0
    leaf_rejections = 0
    bytes_at_root = 0
    bytes_flat = 0
    cohorts: list[list[int]] = []

    for r in range(n_rounds):
        round_no = state.current_round
        base_blob = state.broadcast_blob
        base_version = state.model_version
        cohort = sample_cohort(n_clients, cohort_size, r, cohort_seed)
        cohorts.append([int(x) for x in cohort])
        shards = partition_cohort(cohort, n_edges)
        for e, edge in enumerate(edges):
            shard = [f"client-{int(i)}" for i in shards[e]]
            max_fan_in = max(max_fan_in, len(shard))
            edge.begin_round(round_no, base_blob, base_version, shard)
            for idx, name in zip(shards[e], shard):
                blob, ns = make_update(int(idx), r, base_blob, base_version)
                leaf_updates += 1
                bytes_flat += len(blob)
                accepted, _reason = edge.offer(name, blob, ns)
                if not accepted:
                    leaf_rejections += 1
            edge_peak = max(edge_peak, edge.peak_resident_blobs)
            if not edge.quorum_met():
                # The root's deadline machinery would shrink around a
                # silent edge in a live deployment; the in-process harness
                # surfaces it instead of stalling.
                raise RuntimeError(
                    f"edge-{e} missed quorum round {round_no}: "
                    f"{len(edge.received)}/{edge.quorum}"
                )
            partial_blob, total = edge.partial()
            bytes_at_root += len(partial_blob)
            now += 1e-3
            state, rep = R.transition(
                state,
                R.TrainDone(
                    cname=edge.edge_id,
                    round=round_no,
                    blob=partial_blob,
                    num_samples=total,
                    now=now,
                ),
            )
            if rep.status == R.REJECTED:
                raise RuntimeError(
                    f"root rejected edge-{e}'s partial: {rep.config}"
                )
            # The reply that closed the barrier already emptied `received`;
            # the pre-aggregation peak is then the number of edges that had
            # reported (e + 1).
            closed = rep.status in (R.RESP_ARY, R.FIN)
            root_peak = max(root_peak, e + 1 if closed else len(state.received))
            edge.end_round()
        if state.current_round == round_no:
            raise RuntimeError(f"root round {round_no} failed to close")

    return TreeRunResult(
        state=state,
        n_clients=n_clients,
        cohort_size=cohort_size,
        n_edges=n_edges,
        rounds=n_rounds,
        root_peak_blobs=root_peak,
        edge_peak_blobs=edge_peak,
        max_leaf_fan_in=max_fan_in,
        leaf_updates=leaf_updates,
        leaf_rejections=leaf_rejections,
        bytes_at_root=bytes_at_root,
        bytes_flat_equiv=bytes_flat,
        global_sha256=hashlib.sha256(state.global_blob).hexdigest(),
        cohorts=cohorts,
    )
