"""Fused ResUNet inference forward over the quantized variables pytree.

The model has no single conv chokepoint (models/resunet.py is a Flax module
graph), so the fused plane re-expresses each conv in matmul form and routes
it through :func:`~fedcrack_tpu.kernels.dequant.dequant_matmul` — the int8/
fp8 codes reach the contraction directly, no float32 weight tensor is ever
materialized:

- 3x3 convs (stem, decoder ConvTranspose): im2col via
  ``lax.conv_general_dilated_patches``. Patch channels are (C, kh, kw)-major,
  so the HWIO kernel reshapes as ``transpose(2,0,1,3).reshape(C*9, F)`` —
  per-output-channel scales ride along unchanged (F stays last). A 3x3
  stride-1 SAME ``nn.ConvTranspose`` computes exactly the plain SAME conv of
  the same HWIO kernel (verified bit-exact on this jax), so the decoder needs
  no transposed-conv kernel.
- 1x1 convs (pointwise, decoder residuals, head): plain reshape + matmul.
- encoder residual 1x1 stride-2: a SAME 1x1 stride-2 conv reads exactly the
  ``x[:, ::2, ::2]`` pixels — slice then matmul (bit-exact re-expression).
- depthwise 3x3 (SeparableConv stage 1): O(9*C) weights — nothing to gain
  from fusing the dequant into a grouped conv; expands via ``dequant_codes``
  and runs the stock grouped conv (documented limitation, charged honestly).

Pool/upsample reuse the model's own ops (``max_pool_auto``/``upsample2x``),
BatchNorm applies running statistics inline. Everything accumulates in
float32 regardless of the serve compute dtype — the plane trades weight
bandwidth, not accumulation width.

Parity contract: this forward is a numerical TWIN of the r17 reference
program (dequantize + ``model.apply``), not a bitwise one — BN folding and
matmul-order reassociation move single ulps. The install-time ``quant_gate``
holds it to the same probe-IoU floor as any quantized program, and
tests/test_kernels.py pins per-layer twin error bounds.

Layout limitation: only the reference parameter layouts are supported —
``stem_layout``/``res_layout`` transforms derive folded kernels in-forward
from float32 weights, which contradicts never-materialize; the engine
refuses the combination at build time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.kernels.dequant import dequant_codes, dequant_matmul
from fedcrack_tpu.models.resunet import _BN_EPSILON, upsample2x
from fedcrack_tpu.ops.pooling import max_pool_auto

_DIMS = ("NHWC", "HWIO", "NHWC")


def _codes(leaf) -> tuple[jax.Array, jax.Array]:
    from fedcrack_tpu.serve.quant import QKEY, QKEY_FP8, SKEY

    if not isinstance(leaf, dict):
        raise TypeError(
            f"fused forward wants quantized kernel leaves, got {type(leaf).__name__}"
        )
    return leaf[QKEY] if QKEY in leaf else leaf[QKEY_FP8], leaf[SKEY]


def _bn(x, p, s):
    inv = p["scale"] * lax.rsqrt(s["var"] + _BN_EPSILON)
    return (x - s["mean"]) * inv + p["bias"]


def _conv1x1(x, mod, impl):
    q, s = _codes(mod["kernel"])  # (1, 1, C, F)
    n, h, w, c = x.shape
    y = dequant_matmul(x.reshape(n * h * w, c), q.reshape(c, -1), s, impl=impl)
    return y.reshape(n, h, w, -1) + mod["bias"]


def _conv3x3(x, mod, *, stride, impl):
    q, s = _codes(mod["kernel"])  # (3, 3, C, F)
    c, f = q.shape[2], q.shape[3]
    patches = lax.conv_general_dilated_patches(
        x, (3, 3), (stride, stride), "SAME", dimension_numbers=_DIMS
    )
    n, ho, wo, _ = patches.shape
    q2 = jnp.transpose(q, (2, 0, 1, 3)).reshape(c * 9, f)
    y = dequant_matmul(patches.reshape(n * ho * wo, c * 9), q2, s, impl=impl)
    return y.reshape(n, ho, wo, f) + mod["bias"]


def _sepconv(x, mod, impl):
    dq, ds = _codes(mod["depthwise"]["kernel"])  # (3, 3, 1, C)
    kern = dequant_codes(dq, ds, impl="reference")
    x = lax.conv_general_dilated(
        x,
        kern,
        (1, 1),
        "SAME",
        feature_group_count=x.shape[-1],
        dimension_numbers=_DIMS,
    )
    return _conv1x1(x, mod["pointwise"], impl)


def fused_predict_logits(
    qtree, x: jax.Array, config: ModelConfig, *, impl: str | None = None
) -> jax.Array:
    """Per-pixel logits from the quantized tree — the fused twin of
    ``model.apply(dequantize_variables(qtree), x, train=False)``.

    ``qtree``: the ``{'params', 'batch_stats'}`` pytree produced by
    ``quantize_variables`` / ``quantize_variables_fp8`` (bare tree, not the
    ``QuantizedVariables`` wrapper). ``impl`` threads to every fused matmul
    (``dequant.default_impl()`` when None)."""
    if config.stem_layout != "reference" or config.res_layout != "reference":
        raise ValueError(
            "fused kernel planes support only the reference parameter layouts; "
            f"got stem_layout={config.stem_layout!r} res_layout={config.res_layout!r}"
        )
    p, st = qtree["params"], qtree["batch_stats"]
    x = x.astype(jnp.float32)

    x = _conv3x3(x, p["stem_conv"], stride=2, impl=impl)
    x = _bn(x, p["stem_bn"], st["stem_bn"])
    x = jax.nn.relu(x)
    prev = x

    for i in range(len(config.encoder_features)):
        x = jax.nn.relu(x)
        x = _sepconv(x, p[f"enc{i}_sep1"], impl)
        x = _bn(x, p[f"enc{i}_bn1"], st[f"enc{i}_bn1"])
        x = jax.nn.relu(x)
        x = _sepconv(x, p[f"enc{i}_sep2"], impl)
        x = _bn(x, p[f"enc{i}_bn2"], st[f"enc{i}_bn2"])
        x = max_pool_auto(x)
        # Reference residual: Conv(F, 1x1, stride 2) — reads the ::2 pixels.
        x = x + _conv1x1(prev[:, ::2, ::2, :], p[f"enc{i}_res"], impl)
        prev = x

    for i in range(len(config.decoder_features)):
        x = jax.nn.relu(x)
        x = _conv3x3(x, p[f"dec{i}_convT1"], stride=1, impl=impl)
        x = _bn(x, p[f"dec{i}_bn1"], st[f"dec{i}_bn1"])
        x = jax.nn.relu(x)
        x = _conv3x3(x, p[f"dec{i}_convT2"], stride=1, impl=impl)
        x = _bn(x, p[f"dec{i}_bn2"], st[f"dec{i}_bn2"])
        # Residual conv + add at the LOW resolution (resunet.py's commute).
        x = x + _conv1x1(prev, p[f"dec{i}_res"], impl)
        if i + 1 < len(config.decoder_features):
            x = upsample2x(x)
            prev = x

    logits = _conv1x1(x, p["head"], impl)
    return upsample2x(logits)
