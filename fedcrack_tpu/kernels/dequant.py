"""Pallas fused dequantize kernels: int8/fp8 codes straight into the MXU.

r17's quantized predict is weight-only: the codes live in HBM as int8 but
XLA expands ``q * scale`` to a float32 weight tensor before the conv/matmul,
so every MAC still runs in the serving compute dtype. The kernels here fold
the dequantize into the weight LOAD — each ``(bk, bn)`` code block is cast
to float32 in VMEM, contracted on the MXU with float32 accumulation, and the
per-output-channel scale multiplies the finished accumulator ONCE per output
block (the scale factors out of the K-contraction exactly, so
``(x @ q) * scale == x @ (q * scale)`` up to float reassociation). The same
kernel serves both code dtypes: int8 symmetric codes (r17's
``quantize_leaf``) and fp8 e4m3 codes (``quantize_leaf_fp8``) differ only in
the in-VMEM cast.

Twin discipline (same contract as ops/pallas_bce.py): every kernel has an
interpret-mode CPU twin (``impl="interpret"`` — the Pallas interpreter runs
the SAME kernel body) and a pure-XLA reference (``impl="reference"`` — the
r17 dequantize-then-contract order). Tests pin the fused result within one
per-channel scale of the reference per entry, and deterministic run-to-run.
``default_impl`` picks the compiled kernel on TPU and the interpreter
elsewhere; ``FEDCRACK_KERNEL_IMPL`` overrides for A/B runs.

The training-side transform (``fake_quant_params``) is the straight-through
estimator over the SAME quantize/dequantize math: weights pass through
``dequant_codes`` in-graph, gradients flow to the float32 master copy
(Dettmers et al.'s weight-only fused-compute progression, applied to the
fedavg step). It rides the reference twin — the step runs inside shard_map
where the interpreter is not a supported lowering — so the trajectory claim
is about the quantization math, not the kernel; the kernel's numerics are
pinned by the serve-plane twin tests against the identical math.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

LANE = 128
# 128x128 blocks satisfy every dtype's minimum tile in one shape: f32 (8,128),
# int8/fp8 (32,128). VMEM per grid step: x 64 KiB + q 16 KiB + out 64 KiB.
BLOCK = 128


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(n: int, m: int) -> int:
    return _cdiv(n, m) * m


def default_impl() -> str:
    """Compiled kernel on TPU, Pallas interpreter elsewhere (the CPU twin is
    machinery validation — the speed claim waits on the queued TPU session,
    BASELINE.md "Round 20"). ``FEDCRACK_KERNEL_IMPL`` forces a variant for
    A/B runs (bench.py ``detail.lowp_kernels``)."""
    forced = os.environ.get("FEDCRACK_KERNEL_IMPL")
    if forced:
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "interpret"


def _check_codes(q: jax.Array) -> None:
    kind = jnp.dtype(q.dtype).kind
    # int8 symmetric codes, or any fp8 flavor ('V' pre-numpy-2 ml_dtypes
    # registration, 'f' itemsize 1 after).
    if q.dtype == jnp.int8:
        return
    if jnp.dtype(q.dtype).itemsize == 1 and kind in ("V", "f"):
        return
    raise TypeError(f"dequant kernels want int8/fp8 codes, got {q.dtype}")


# ---- fused dequant-matmul ----


def _matmul_kernel(x_ref, q_ref, s_ref, o_ref, *, k_blocks: int):
    k = pl.program_id(2)
    part = jnp.dot(
        x_ref[:], q_ref[:].astype(jnp.float32), preferred_element_type=jnp.float32
    )

    @pl.when(k == 0)
    def _init():
        o_ref[:] = part

    @pl.when(k > 0)
    def _accumulate():
        o_ref[:] = o_ref[:] + part

    @pl.when(k == k_blocks - 1)
    def _scale():
        o_ref[:] = o_ref[:] * s_ref[0:1, :]


def _dequant_matmul_pallas(
    x: jax.Array, q: jax.Array, scale: jax.Array, interpret: bool
) -> jax.Array:
    m, kk = x.shape
    _, n = q.shape
    bm = min(BLOCK, _round_up(m, 8))
    mp = _round_up(m, bm)
    kp = _round_up(kk, BLOCK)
    np_ = _round_up(n, BLOCK)
    xp = jnp.pad(x.astype(jnp.float32), ((0, mp - m), (0, kp - kk)))
    qp = jnp.pad(q, ((0, kp - kk), (0, np_ - n)))
    # Pad channels with scale 1.0 (dequant of the zero-padded codes stays 0);
    # 8 replicated sublanes keep the block tile-aligned.
    sp = jnp.pad(scale.astype(jnp.float32), (0, np_ - n), constant_values=1.0)
    sp = jnp.broadcast_to(sp[None, :], (8, np_))
    k_blocks = kp // BLOCK

    spec_kw = {} if _VMEM is None else {"memory_space": _VMEM}
    from fedcrack_tpu.jaxcompat import shape_dtype_struct, typeof_vma

    out = pl.pallas_call(
        functools.partial(_matmul_kernel, k_blocks=k_blocks),
        grid=(mp // bm, np_ // BLOCK, k_blocks),
        in_specs=[
            pl.BlockSpec((bm, BLOCK), lambda i, j, k: (i, k), **spec_kw),
            pl.BlockSpec((BLOCK, BLOCK), lambda i, j, k: (k, j), **spec_kw),
            pl.BlockSpec((8, BLOCK), lambda i, j, k: (0, j), **spec_kw),
        ],
        out_specs=pl.BlockSpec((bm, BLOCK), lambda i, j, k: (i, j), **spec_kw),
        out_shape=shape_dtype_struct((mp, np_), jnp.float32, vma=typeof_vma(x)),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:m, :n]


def _dequant_matmul_reference(x, q, scale):
    # The r17 order: expand the float32 weights, then contract.
    return x.astype(jnp.float32) @ (q.astype(jnp.float32) * scale)


def dequant_matmul(
    x: jax.Array, q: jax.Array, scale: jax.Array, *, impl: str | None = None
) -> jax.Array:
    """``[M, K] @ dequant([K, N] codes, [N] scales) -> [M, N]`` float32.

    ``impl``: ``"pallas"`` (compiled TPU kernel), ``"interpret"`` (Pallas
    interpreter, any backend), ``"reference"`` (pure XLA, the r17
    dequantize-then-matmul order). Fused vs reference differ only by the
    scale's association with the K-sum — per entry within one per-channel
    scale (test-pinned, far tighter in practice)."""
    if x.ndim != 2 or q.ndim != 2 or x.shape[1] != q.shape[0]:
        raise ValueError(f"bad matmul shapes: x {x.shape}, q {q.shape}")
    if scale.shape != (q.shape[1],):
        raise ValueError(f"scale {scale.shape} != per-channel ({q.shape[1]},)")
    _check_codes(q)
    impl = impl or default_impl()
    if impl == "pallas":
        return _dequant_matmul_pallas(x, q, scale, interpret=False)
    if impl == "interpret":
        return _dequant_matmul_pallas(x, q, scale, interpret=True)
    if impl == "reference":
        return _dequant_matmul_reference(x, q, scale)
    raise ValueError(f"unknown impl {impl!r}")


# ---- elementwise dequant (weight expansion without a contraction) ----


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[0:1, :]


def _dequant_codes_pallas(q: jax.Array, scale: jax.Array, interpret: bool):
    shape = q.shape
    n = shape[-1]
    r = max(q.size // n, 1)
    q2 = q.reshape(r, n)
    br = min(256, _round_up(r, 32))  # int8 sublane tile
    rp = _round_up(r, br)
    np_ = _round_up(n, BLOCK)
    qp = jnp.pad(q2, ((0, rp - r), (0, np_ - n)))
    sp = jnp.pad(scale.astype(jnp.float32), (0, np_ - n), constant_values=1.0)
    sp = jnp.broadcast_to(sp[None, :], (8, np_))

    spec_kw = {} if _VMEM is None else {"memory_space": _VMEM}
    from fedcrack_tpu.jaxcompat import shape_dtype_struct, typeof_vma

    out = pl.pallas_call(
        _dequant_kernel,
        grid=(rp // br, np_ // BLOCK),
        in_specs=[
            pl.BlockSpec((br, BLOCK), lambda i, j: (i, j), **spec_kw),
            pl.BlockSpec((8, BLOCK), lambda i, j: (0, j), **spec_kw),
        ],
        out_specs=pl.BlockSpec((br, BLOCK), lambda i, j: (i, j), **spec_kw),
        out_shape=shape_dtype_struct((rp, np_), jnp.float32, vma=typeof_vma(q)),
        interpret=interpret,
    )(qp, sp)
    return out[:r, :n].reshape(shape)


def dequant_codes(
    q: jax.Array, scale: jax.Array, *, impl: str = "reference"
) -> jax.Array:
    """Expand ``[..., N]`` codes with per-last-axis scales to float32 —
    traceable twin of ``serve.quant.dequantize_variables``'s leaf expansion,
    shared by the depthwise-conv path (no matmul to fuse into) and the
    training fake-quant transform. Reference impl by default: callers inside
    shard_map (the training step) must not enter the interpreter."""
    if scale.shape != (q.shape[-1],):
        raise ValueError(f"scale {scale.shape} != per-channel ({q.shape[-1]},)")
    _check_codes(q)
    if impl == "reference":
        return q.astype(jnp.float32) * scale
    if impl == "pallas":
        return _dequant_codes_pallas(q, scale, interpret=False)
    if impl == "interpret":
        return _dequant_codes_pallas(q, scale, interpret=True)
    raise ValueError(f"unknown impl {impl!r}")


# ---- training-side straight-through fake-quant ----


def fake_quant_leaf(w: jax.Array) -> jax.Array:
    """Straight-through int8 fake-quant of one weight tensor (traceable).

    Same math as ``serve.quant.quantize_leaf`` + ``dequant_codes``: symmetric
    per-last-axis-channel codes, all-zero channels scale 1.0. The forward
    sees the dequantized int8 projection; the gradient passes straight
    through to the float32 master weights."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=tuple(range(w.ndim - 1)))
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127.0, 127.0).astype(jnp.int8)
    wq = dequant_codes(q, scale, impl="reference")
    return (w32 + jax.lax.stop_gradient(wq - w32)).astype(w.dtype)


def fake_quant_params(params):
    """Apply :func:`fake_quant_leaf` to every channel-structured leaf
    (ndim >= 2) of a params tree — the same leaf set ``quantize_variables``
    quantizes, so the training forward computes with exactly the weights the
    fused serve plane would load. Biases/BN affines pass through."""
    return jax.tree_util.tree_map(
        lambda w: fake_quant_leaf(w) if getattr(w, "ndim", 0) >= 2 else w, params
    )
