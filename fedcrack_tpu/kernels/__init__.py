"""Low-precision kernel plane (round 20): Pallas fused dequantize kernels.

``dequant`` holds the shape-level primitives — a fused dequant-matmul that
feeds int8/fp8 codes to the MXU directly (dequant folded into the load,
float32 accumulation) plus the elementwise dequant twin the training-side
fake-quant transform rides. ``forward`` assembles them into the full fused
ResUNet inference forward that consumes ``serve/quant.py``'s quantized
variables pytree without materializing the float32 weights.

Selection is a serve-plane policy knob (``ServeConfig.kernel_plane``), wired
through ``serve/engine.py`` so every fused program installs through the r17
``quant_gate`` — a numerically-bad kernel refuses loudly and the fleet keeps
serving the reference program.
"""

from fedcrack_tpu.kernels.dequant import (  # noqa: F401
    default_impl,
    dequant_codes,
    dequant_matmul,
    fake_quant_params,
)
