"""fedlint — the repo-native static-analysis + runtime-sanitizer plane.

Rounds 6-10 turned this codebase's headline guarantees into *invariants*:
byte-identical trajectories across the segmented/resident/streamed data
paths, no torn reads across hot-swaps, fsync'd atomic statefiles,
monotonic-clock deadlines, bounded gRPC retries. Every one of them can be
silently re-opened by a single careless line in a later PR — a
``time.time()`` deadline, an unsorted ``os.listdir``, a raw
``open(path, "wb")`` on a checkpoint path. The reference codebase is the
cautionary tale: seven documented accidents (pickle RCE, a commented-out
uploader, a ``grcp.`` typo) that a mechanical checker would have caught.

This package is that checker, in two halves:

- **static** (``engine`` + ``rules/``): an AST-level lint engine with
  repo-specific rule packs — determinism, durability, trace-safety,
  transport, lock-order, dead-code — driven by ``tools/fedlint.py`` and
  pinned at zero non-baselined findings by a tier-1 gate test;
- **runtime** (``sanitizers``): a :class:`RecompileSentry` asserting
  steady-state rounds and serve programs compile exactly once, a
  ``jax.transfer_guard`` wrapper armed around the mesh round and batcher
  dispatch in tier-1 tests, and a debug-mode lock-order monitor that
  records acquisition stacks.

Suppression syntax (checked by the engine, see ``engine.py``)::

    x = time.time()  # fedlint: disable=DET001 -- human-readable record ts

Baseline: ``fedlint_baseline.json`` at the repo root carries the findings
that are accepted-as-is (each entry fingerprinted against the offending
source line, so the baseline goes stale — and the gate fails — the moment
the line changes).
"""

from fedcrack_tpu.analysis.engine import (
    Finding,
    LintEngine,
    ModuleSource,
    Severity,
    load_baseline,
    make_baseline,
)
from fedcrack_tpu.analysis.sanitizers import (
    LockOrderMonitor,
    RecompileError,
    RecompileSentry,
    make_lock,
    no_implicit_transfers,
)

__all__ = [
    "Finding",
    "LintEngine",
    "LockOrderMonitor",
    "ModuleSource",
    "RecompileError",
    "RecompileSentry",
    "Severity",
    "load_baseline",
    "make_baseline",
    "make_lock",
    "no_implicit_transfers",
]
