"""Aggregation-algebra rule pack (round 21).

- **AGG001 aggregation fold outside the algebra**: any ``fedavg(...)``
  call in ``fed/`` or ``parallel/`` outside the two chokepoint modules —
  ``fed/aggregation.py`` (the algebra's own instances) and
  ``fed/algorithms.py`` (the weighted-mean primitive's home) — is an
  ERROR.

  The failure surface this kills is the one round 21 just paid down: the
  repo grew FOUR structurally-identical aggregation folds (rounds-plane
  sorted FedAvg, ``fold_buffer``, the edge ``partial``, the mesh ordered
  cohort fold), and when the r18 health plane needed to gate "how updates
  combine" there was no seam — a flagged update was averaged in at full
  weight on every plane. The folds are now one algebra
  (``fed/aggregation.py``: ordered ``(name, weight, tree)`` triples,
  pluggable combine); a NEW direct ``fedavg`` call in the federation or
  mesh planes is someone minting fold copy number five, invisible to
  ``FedConfig.aggregation``, the quarantine gate, and every robust
  combine. Route it through ``aggregation.fold(...)`` instead. Call sites
  outside ``fed/``/``parallel/`` (benches, tools, tests cross-checking
  the algebra against the primitive) are deliberately out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity

# Where the rule looks: the federation and mesh planes.
SCOPED_DIRS = ("/fed/", "/parallel/")
# The two modules allowed to spell the primitive: the algebra's instances
# and the primitive's own definition.
CHOKEPOINTS = ("fed/aggregation.py", "fed/algorithms.py")


def _is_fedavg_call(node: ast.Call) -> bool:
    """``fedavg(...)`` by Name or any-receiver Attribute (``R.fedavg``,
    ``algorithms.fedavg`` — the aliasing idioms the planes actually used)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "fedavg"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "fedavg"
    return False


class AggregationChokepointRule(Rule):
    id = "AGG001"
    severity = Severity.ERROR
    description = (
        "a direct fedavg(...) call in fed/ or parallel/ is an aggregation "
        "fold outside the algebra — invisible to FedConfig.aggregation, "
        "the quarantine gate, and every robust combine; route it through "
        "fed/aggregation.py's fold(algebra, triples)"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        path = "/" + module.path
        if not any(d in path for d in SCOPED_DIRS):
            return
        if any(path.endswith(c) for c in CHOKEPOINTS):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _is_fedavg_call(node):
                yield self.finding(
                    module,
                    node,
                    "direct fedavg call outside fed/aggregation.py — the "
                    "fifth copy of the fold; use aggregation.fold("
                    "aggregation.FedAvg(), triples) (or from_config) so "
                    "the combine stays pluggable and quarantine-gated",
                )


RULES = (AggregationChokepointRule,)
