"""Observability rule pack (round 15).

- **OBS001 non-catalog metric name**: every ``registry.counter(...)`` /
  ``registry.gauge(...)`` / ``registry.histogram(...)`` call site must name
  its metric with a **string literal** that is ``snake_case`` and carries a
  unit suffix (``_seconds``, ``_bytes``, ``_total``, ``_ratio``,
  ``_versions`` — the staleness unit — or ``_replicas``, the fleet
  population unit). Two failure modes this kills:

  * a *computed* name (f-string, variable, concatenation) makes the metric
    catalog ungreppable — ``grep -r fed_updates_total`` must find every
    producer — and lets label-like variance leak into the name (unbounded
    series, broken dashboards);
  * a free-spelled name (``FedUpdates``, ``updates_count``, no unit) makes
    the Prometheus exposition drift from the documented catalog; the
    registry enforces the same contract at runtime
    (``obs.registry.validate_metric_name``), this rule catches it before
    anything runs.

  The receiver is matched by NAME — a variable/attribute called
  ``registry``/``REGISTRY`` (or containing ``registry``) or the
  conventional short alias ``reg`` — so the rule follows the idiom, not
  the import graph. Calls that pass the name via ``name=`` keyword are
  checked the same way.

- **OBS002 non-catalog span name** (round 16): every ``tracing.span(...)``
  call site must name its span with a **string literal** that is a dotted
  ``plane.verb`` (``client.push``, ``fed.flush``, ``edge.flush_partial``)
  — the OBS001 literal-name contract extended to spans. The plane prefix
  is what ``tools/trace_stitch.py`` reports as ``planes_crossed`` and what
  the soak's span census groups by; a computed or undotted name breaks
  both. The receiver is matched by idiom: the module alias ``tracing``
  (the repo convention) or ``spans``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity

METRIC_METHODS = ("counter", "gauge", "histogram")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# Keep in lockstep with obs.registry.UNIT_SUFFIXES (runtime half of the
# same contract); `_info` is round 20's constant-1 labeled info-gauge unit.
UNIT_SUFFIXES = (
    "_seconds", "_bytes", "_total", "_ratio", "_versions", "_replicas", "_info",
)


def _registry_receiver(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in METRIC_METHODS:
        return False
    recv = func.value
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    else:
        return False
    low = name.lower()
    return "registry" in low or low in ("reg", "_reg")


def _name_arg(call: ast.Call) -> ast.expr | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


class MetricCatalogNameRule(Rule):
    id = "OBS001"
    severity = Severity.ERROR
    description = (
        "registry.counter/gauge/histogram metric name must be a snake_case "
        "string literal with a unit suffix (_seconds/_bytes/_total/_ratio/"
        "_versions/_replicas/_info) — computed or free-spelled names break "
        "the greppable catalog and the exposition's stability"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and _registry_receiver(node)):
                continue
            arg = _name_arg(node)
            if arg is None:
                yield self.finding(module, node, "metric call without a name argument")
                continue
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                yield self.finding(
                    module,
                    arg if hasattr(arg, "lineno") else node,
                    "metric name must be a string LITERAL (computed names "
                    "make the catalog ungreppable and can mint unbounded "
                    "series)",
                )
                continue
            name = arg.value
            if not NAME_RE.match(name):
                yield self.finding(
                    module, arg,
                    f"metric name {name!r} is not snake_case ([a-z][a-z0-9_]*)",
                )
            elif not name.endswith(UNIT_SUFFIXES):
                yield self.finding(
                    module, arg,
                    f"metric name {name!r} lacks a unit suffix {UNIT_SUFFIXES}",
                )


SPAN_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")


def _tracing_receiver(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr != "span":
        return False
    recv = func.value
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    else:
        return False
    low = name.lower()
    return low in ("tracing", "spans") or "tracing" in low


class SpanCatalogNameRule(Rule):
    id = "OBS002"
    severity = Severity.ERROR
    description = (
        "tracing.span(...) span name must be a dotted plane.verb string "
        "literal (e.g. 'client.push', 'fed.flush') — computed or undotted "
        "names break the stitchable span catalog and the plane census"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and _tracing_receiver(node)):
                continue
            arg = _name_arg(node)
            if arg is None:
                yield self.finding(module, node, "span call without a name argument")
                continue
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                yield self.finding(
                    module,
                    arg if hasattr(arg, "lineno") else node,
                    "span name must be a string LITERAL (computed names "
                    "make the span catalog ungreppable)",
                )
                continue
            if not SPAN_NAME_RE.match(arg.value):
                yield self.finding(
                    module, arg,
                    f"span name {arg.value!r} is not a dotted plane.verb "
                    "([a-z][a-z0-9_]* '.' [a-z][a-z0-9_]*)",
                )


RULES = (MetricCatalogNameRule, SpanCatalogNameRule)
