"""Lock-order rule pack.

**LOCK001**: a cycle in the static lock-acquisition graph. Two code paths
acquiring the same pair of locks in opposite orders is the classic
serve-plane deadlock: the batcher worker holds its stats lock while reading
a swap snapshot at the instant the poll thread holds the snapshot lock while
publishing stats. The serving plane owns three locks today (``batcher.py``,
``service.py``, ``hot_swap.py``) and the contract is that the graph over
them — plus ``ckpt/`` and ``obs/`` — stays acyclic.

Graph construction (static, name-based — the runtime twin that records real
acquisition stacks is ``analysis.sanitizers.LockOrderMonitor``):

- **nodes**: every ``threading.Lock()``/``RLock()``/``Condition()``/
  ``make_lock()`` bound to a module-level name or a ``self.<attr>``;
- **edges**: inside a ``with <lock>:`` body, (a) a lexically nested
  ``with <other-lock>:`` and (b) any call whose terminal method name matches
  a method known (transitively) to acquire another lock. Call resolution is
  by name across the analyzed modules — over-approximate on purpose: a
  phantom edge costs nothing unless it closes a cycle, a missed edge hides
  a deadlock.

``build_lock_graph`` is also the ``tools/fedlint.py --lock-graph`` payload:
nodes, edges (with acquisition sites), and any cycles, as one JSON artifact.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Sequence

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import terminal_name

LOCK_CONSTRUCTORS = {"Lock", "RLock", "Condition", "Semaphore",
                     "BoundedSemaphore", "make_lock"}

# Methods on builtin containers: never resolve a call-mediated edge through
# these names (set.add vs StreamingPercentiles.add would otherwise alias).
BUILTIN_METHOD_NAMES = {
    "append", "extend", "pop", "get", "items", "keys", "values", "add",
    "discard", "update", "setdefault", "clear", "remove", "insert", "copy",
    "join", "split", "strip", "format", "encode", "decode", "read", "write",
    "flush", "close", "put", "get_nowait", "put_nowait",
}


@dataclasses.dataclass(frozen=True)
class LockDef:
    node_id: str       # "path::Class.attr" or "path::name"
    path: str
    line: int
    ctor: str          # "Lock" / "RLock" / ...


@dataclasses.dataclass(frozen=True)
class LockEdge:
    src: str
    dst: str
    path: str
    line: int
    via: str           # "nested-with" or "call:<name>"


class _LockGraph:
    def __init__(self) -> None:
        self.locks: dict[str, LockDef] = {}
        self.edges: dict[tuple[str, str], LockEdge] = {}

    def cycles(self) -> list[list[str]]:
        """Strongly connected components of size > 1, plus self-edges on
        non-reentrant locks, as sorted node-id cycles."""
        adj: dict[str, set[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, set()).add(dst)
        out: list[list[str]] = []
        for comp in _sccs(sorted(self.locks), adj):
            if len(comp) > 1:
                out.append(sorted(comp))
        for (src, dst) in sorted(self.edges):
            if src == dst and self.locks.get(src, LockDef("", "", 0, "")).ctor != "RLock":
                out.append([src])
        return out

    def to_json(self) -> dict:
        return {
            "nodes": [dataclasses.asdict(d) for _, d in sorted(self.locks.items())],
            "edges": [dataclasses.asdict(e) for _, e in sorted(self.edges.items())],
            "cycles": self.cycles(),
        }


def _sccs(nodes: Sequence[str], adj: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan's algorithm, iterative (lint inputs are small but recursion
    limits are not ours to spend)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    out: list[list[str]] = []

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _enclosing_class(module: ModuleSource, node: ast.AST) -> str | None:
    for anc in module.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc.name
    return None


def _lock_expr_id(module: ModuleSource, expr: ast.expr,
                  class_locks: dict[tuple[str, str], str],
                  module_locks: dict[str, str],
                  enclosing_class: str | None) -> str | None:
    """Resolve a with/acquire target to a known lock node id."""
    target = expr
    if isinstance(target, ast.Call) and isinstance(target.func, ast.Attribute) \
            and target.func.attr == "acquire":
        target = target.func.value
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name) \
            and target.value.id == "self" and enclosing_class is not None:
        return class_locks.get((enclosing_class, target.attr))
    if isinstance(target, ast.Name):
        return module_locks.get(target.id)
    return None


def build_lock_graph(modules: Sequence[ModuleSource]) -> _LockGraph:
    graph = _LockGraph()
    # (module, class or None, attr/name) discovery + function inventory.
    class_locks_by_mod: dict[str, dict[tuple[str, str], str]] = {}
    module_locks_by_mod: dict[str, dict[str, str]] = {}
    funcs: list[tuple[ModuleSource, ast.AST, str, str | None]] = []
    name_index: dict[str, list[int]] = {}

    for module in modules:
        class_locks: dict[tuple[str, str], str] = {}
        module_locks: dict[str, str] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            if not (isinstance(val, ast.Call) and terminal_name(val) in LOCK_CONSTRUCTORS):
                continue
            ctor = terminal_name(val) or ""
            for t in node.targets:
                if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    cls = _enclosing_class(module, node)
                    if cls is not None:
                        node_id = f"{module.path}::{cls}.{t.attr}"
                        class_locks[(cls, t.attr)] = node_id
                        graph.locks[node_id] = LockDef(node_id, module.path, node.lineno, ctor)
                elif isinstance(t, ast.Name) and _enclosing_class(module, node) is None:
                    node_id = f"{module.path}::{t.id}"
                    module_locks[t.id] = node_id
                    graph.locks[node_id] = LockDef(node_id, module.path, node.lineno, ctor)
        class_locks_by_mod[module.path] = class_locks
        module_locks_by_mod[module.path] = module_locks
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = _enclosing_class(module, node)
                funcs.append((module, node, node.name, cls))
                name_index.setdefault(node.name, []).append(len(funcs) - 1)

    # Per function: directly acquired locks + called names.
    direct: list[set[str]] = []
    calls: list[set[str]] = []
    for module, fn, _, cls in funcs:
        acquired: set[str] = set()
        called: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_expr_id(
                        module, item.context_expr,
                        class_locks_by_mod[module.path],
                        module_locks_by_mod[module.path], cls,
                    )
                    if lid is not None:
                        acquired.add(lid)
            elif isinstance(node, ast.Call):
                term = terminal_name(node)
                if term is not None and term not in BUILTIN_METHOD_NAMES:
                    called.add(term)
                lid = _lock_expr_id(
                    module, node,
                    class_locks_by_mod[module.path],
                    module_locks_by_mod[module.path], cls,
                )
                if lid is not None:
                    acquired.add(lid)
        direct.append(acquired)
        calls.append(called)

    # Fixpoint: locks reachable through the name-resolved call graph.
    reach = [set(s) for s in direct]
    changed = True
    while changed:
        changed = False
        for i in range(len(funcs)):
            for n in calls[i]:
                for j in name_index.get(n, ()):
                    if not reach[j] <= reach[i]:
                        reach[i] |= reach[j]
                        changed = True

    # Edges: held lock -> lock acquired inside the with body.
    for i, (module, fn, _, cls) in enumerate(funcs):
        for node in ast.walk(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = [
                lid for item in node.items
                if (lid := _lock_expr_id(
                    module, item.context_expr,
                    class_locks_by_mod[module.path],
                    module_locks_by_mod[module.path], cls,
                )) is not None
            ]
            if not held:
                continue
            # `with a, b:` acquires in item order: a -> b.
            for k in range(len(held) - 1):
                for later in held[k + 1:]:
                    _add_edge(graph, held[k], later, module.path, node.lineno,
                              "nested-with")
            for inner in ast.walk(ast.Module(body=node.body, type_ignores=[])):
                if isinstance(inner, (ast.With, ast.AsyncWith)):
                    for item in inner.items:
                        lid = _lock_expr_id(
                            module, item.context_expr,
                            class_locks_by_mod[module.path],
                            module_locks_by_mod[module.path], cls,
                        )
                        if lid is not None:
                            for h in held:
                                _add_edge(graph, h, lid, module.path,
                                          inner.lineno, "nested-with")
                elif isinstance(inner, ast.Call):
                    term = terminal_name(inner)
                    if term is None or term in BUILTIN_METHOD_NAMES:
                        continue
                    for j in name_index.get(term, ()):
                        for lid in reach[j]:
                            for h in held:
                                _add_edge(graph, h, lid, module.path,
                                          inner.lineno, f"call:{term}")
    return graph


def _add_edge(graph: _LockGraph, src: str, dst: str, path: str, line: int,
              via: str) -> None:
    key = (src, dst)
    if key not in graph.edges:
        graph.edges[key] = LockEdge(src, dst, path, line, via)


class LockOrderRule(Rule):
    id = "LOCK001"
    severity = Severity.ERROR
    description = (
        "cycle in the static lock-acquisition graph: two paths take the "
        "same locks in opposite orders (or a non-reentrant lock re-enters "
        "itself) — the serve-plane deadlock class"
    )
    paths = ("/serve/", "/ckpt/", "/obs/", "/native/", "/transport/")
    project_scope = True

    def check_project(self, modules: Sequence[ModuleSource]) -> Iterable[Finding]:
        graph = build_lock_graph(modules)
        by_path = {m.path: m for m in modules}
        for cycle in graph.cycles():
            # Anchor the finding at the first edge participating in the cycle.
            members = set(cycle)
            anchor = None
            for key, edge in sorted(graph.edges.items()):
                if edge.src in members and edge.dst in members:
                    anchor = edge
                    break
            if anchor is None:
                d = graph.locks[cycle[0]]
                anchor = LockEdge(cycle[0], cycle[0], d.path, d.line, "self")
            module = by_path.get(anchor.path)
            line_text = module.line_text(anchor.line) if module else ""
            yield Finding(
                rule=self.id,
                severity=self.severity,
                path=anchor.path,
                line=anchor.line,
                col=0,
                message=(
                    "lock-order cycle " + " -> ".join(cycle + [cycle[0]])
                    + f" (via {anchor.via}): acquire these locks in one "
                    "global order everywhere"
                ),
                source_line=line_text,
            )


RULES = (LockOrderRule,)
