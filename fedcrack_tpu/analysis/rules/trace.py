"""Trace-safety rule pack.

**TRACE001**: host operations inside functions that XLA traces. A
``.item()``, ``float(...)``, ``np.*`` call or ``print`` inside a
``jit``/``shard_map``/``scan``-transformed function either fails at trace
time or — worse — silently forces a device→host transfer and a pipeline
stall every step (the implicit-transfer class that torpedoes round wall;
the runtime twin is ``analysis.sanitizers.no_implicit_transfers``).

A function counts as *traced* when it is

- decorated with ``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)`` /
  ``shard_map`` / ``jax.remat`` / ``checkpoint``; or
- passed **by name** to a ``jit(...)`` / ``shard_map(...)`` /
  ``lax.scan(...)`` / ``pjit``/``remat`` call anywhere in the module; or
- lexically nested inside a traced function (closures over the carry).

Scoped to the mesh round and serve planes (``parallel/``,
``serve/engine.py``) where every hot function is traced; host-side drivers
legitimately mix numpy with device code.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import call_name, terminal_name

TRANSFORM_NAMES = {"jit", "pjit", "shard_map", "scan", "remat", "checkpoint"}

HOST_CALLS = {"print", "input", "breakpoint"}
HOST_CASTS = {"float", "int", "bool"}
HOST_MODULES = {"np", "numpy"}


def _decorator_is_transform(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(...)
        if terminal_name(dec) == "partial" and dec.args:
            return terminal_name(dec.args[0]) in TRANSFORM_NAMES
        return terminal_name(dec) in TRANSFORM_NAMES
    return terminal_name(dec) in TRANSFORM_NAMES


class TracedHostOpRule(Rule):
    id = "TRACE001"
    severity = Severity.ERROR
    description = (
        "host op (.item()/float()/np.*/print) inside a jit/shard_map/scan-"
        "transformed function: trace-time failure or an implicit transfer "
        "stalling every step"
    )
    paths = ("/parallel/", "/serve/engine.py")

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        traced = self._traced_functions(module)
        reported: set[tuple[int, int]] = set()
        for fn in traced:
            for f in self._host_ops(module, fn):
                key = (f.line, f.col)
                if key not in reported:
                    reported.add(key)
                    yield f

    def _traced_functions(self, module: ModuleSource) -> list[ast.AST]:
        funcs = [
            n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        by_name: dict[str, list[ast.AST]] = {}
        for fn in funcs:
            by_name.setdefault(fn.name, []).append(fn)
        traced: set[ast.AST] = set()
        # Decorated.
        for fn in funcs:
            if any(_decorator_is_transform(d) for d in fn.decorator_list):
                traced.add(fn)
        # Passed by name to a transform call.
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node) not in TRANSFORM_NAMES:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    traced.update(by_name[arg.id])
        # Lexical nesting: a def inside a traced def is traced.
        changed = True
        while changed:
            changed = False
            for fn in funcs:
                if fn in traced:
                    continue
                for anc in module.ancestors(fn):
                    if anc in traced:
                        traced.add(fn)
                        changed = True
                        break
        return [fn for fn in funcs if fn in traced]

    def _host_ops(self, module: ModuleSource, fn: ast.AST) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            term = terminal_name(node)
            if term == "item" and isinstance(node.func, ast.Attribute) and not node.args:
                yield self.finding(
                    module, node,
                    ".item() forces a device->host transfer inside a traced "
                    "function",
                )
            elif name in HOST_CALLS:
                yield self.finding(
                    module, node,
                    f"{name}() is a host side effect inside a traced function "
                    "— use jax.debug.print / host_callback if intentional",
                )
            elif name in HOST_CASTS and node.args and not isinstance(
                node.args[0], ast.Constant
            ):
                yield self.finding(
                    module, node,
                    f"{name}() on a traced value forces concretization — use "
                    "jnp casts (x.astype) instead",
                )
            elif name is not None and name.split(".")[0] in HOST_MODULES:
                yield self.finding(
                    module, node,
                    f"{name}() runs on host inside a traced function — use "
                    "the jnp equivalent",
                )
            elif name in ("jax.device_get", "jax.device_put"):
                yield self.finding(
                    module, node,
                    f"{name}() inside a traced function is a transfer in the "
                    "hot loop",
                )


RULES = (TracedHostOpRule,)
