"""Durability rule pack.

**DUR001**: a raw ``open(path, "w"/"wb")`` write landing on a
checkpoint/statefile/model path bypasses ``ioutils.atomic_write_bytes`` —
a crash mid-write leaves a torn file where the r8 contract promises "the
old complete file or the new complete file, never a torn one".

A write-mode ``open`` is flagged when any of these hold:

- the module lives under ``ckpt/`` (everything there is durable state);
- the path expression's source mentions a durable-state name
  (state/ckpt/best/weights);
- the ``with`` body writes the output of a known tree/state serializer
  (``tree_to_bytes``, ``server_state_to_bytes``, ``packb``, ...) — bytes
  whose only consumer is a later restore, i.e. a checkpoint by any name.

Scratch/report writes (json.dump of a bench artifact, log sinks) are not
flagged; orbax manages its own temp-dir + rename protocol and never calls
plain ``open``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import call_name, terminal_name

DURABLE_PATH_HINTS = ("state", "ckpt", "checkpoint", "best", "weights")

SERIALIZER_CALLS = {
    "tree_to_bytes", "server_state_to_bytes", "packb", "msgpack_serialize",
    "SerializeToString", "to_bytes",
}

WRITE_MODES = ("w", "wb", "w+", "wb+", "w+b")


def _open_write_mode(call: ast.Call) -> bool:
    if call_name(call) not in ("open", "io.open", "os.fdopen"):
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and mode in WRITE_MODES


class AtomicWriteRule(Rule):
    id = "DUR001"
    severity = Severity.ERROR
    description = (
        "raw open(.., 'w'/'wb') on a checkpoint/statefile/model path: "
        "route through ioutils.atomic_write_bytes (write-temp + fsync + "
        "rename)"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        in_ckpt = "/ckpt/" in "/" + module.path
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and _open_write_mode(node)):
                continue
            why = None
            if in_ckpt:
                why = "module is under ckpt/"
            elif node.args and self._durable_path_expr(module, node.args[0]):
                why = "path names durable state"
            elif self._writes_serialized_tree(module, node):
                why = "writes serialized tree/state bytes"
            if why is not None:
                yield self.finding(
                    module, node,
                    f"torn-write hazard ({why}): use "
                    "ioutils.atomic_write_bytes so a crash leaves the old "
                    "complete file or the new one, never a torn file",
                )

    @staticmethod
    def _durable_path_expr(module: ModuleSource, expr: ast.expr) -> bool:
        try:
            text = ast.unparse(expr).lower()
        except Exception:
            return False
        return any(h in text for h in DURABLE_PATH_HINTS)

    @staticmethod
    def _writes_serialized_tree(module: ModuleSource, open_call: ast.Call) -> bool:
        """``with open(...) as f: f.write(<serializer>(...))`` — find the
        enclosing With and scan its body for serializer-fed writes."""
        with_stmt = None
        for anc in module.ancestors(open_call):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                if any(
                    item.context_expr is open_call or open_call in ast.walk(item.context_expr)
                    for item in anc.items
                ):
                    with_stmt = anc
                break
            if isinstance(anc, ast.stmt):
                break
        if with_stmt is None:
            return False
        for node in ast.walk(with_stmt):
            if (
                isinstance(node, ast.Call)
                and terminal_name(node) == "write"
                and node.args
                and isinstance(node.args[0], ast.Call)
                and terminal_name(node.args[0]) in SERIALIZER_CALLS
            ):
                return True
        return False


RULES = (AtomicWriteRule,)
