"""Determinism rule pack.

The repo's headline invariant is byte-identical trajectories; each rule
here targets one way a PR silently breaks that:

- **DET001 wall-clock**: ``time.time()`` / ``datetime.now()`` reads. Wall
  clocks jump (NTP slew, suspend); every deadline, interval or retry budget
  must use ``time.monotonic()``. Human-readable record timestamps are the
  one legitimate use — keep them, with a ``# fedlint: disable=DET001``
  stating so (hot_swap records, obs JSONL ``ts``).
- **DET002 unseeded randomness**: module-level ``random.*`` /
  ``np.random.*`` draws share hidden global state; two runs (or two
  threads) diverge. Use ``random.Random(seed)`` /
  ``np.random.default_rng(seed)`` / ``jax.random.key(seed)``.
- **DET003 unsorted directory listing**: ``os.listdir`` / ``glob.glob``
  order is filesystem-dependent (the classic cross-host trajectory split
  when file order feeds sample order). Wrap in ``sorted(...)``.
- **DET004 unordered iteration into serialization**: in ``fed/``, ``ckpt/``
  and ``serve/`` — where iteration order lands in wire bytes, statefiles,
  or aggregation — iterating a set, or a dict view that feeds a
  serializer/hasher, must go through ``sorted(...)`` (set order is
  hash-randomized across processes; dict order is arrival order, which a
  federation does not control).
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import (
    assigned_names,
    call_name,
    terminal_name,
    wrapped_in_sorted,
)

WALL_CLOCK_CALLS = {
    "time.time",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "date.today",
    "datetime.date.today",
}

# Constructors / seeding entry points on the random modules that are fine.
SEEDED_RANDOM_OK = {"Random", "SystemRandom", "default_rng", "RandomState",
                    "Generator", "SeedSequence", "PCG64", "Philox"}

LISTING_CALLS = {"os.listdir", "glob.glob", "glob.iglob", "os.scandir"}
LISTING_METHODS = {"glob", "rglob", "iterdir"}  # pathlib.Path

# Terminal call names whose arguments become bytes/hashes: iteration order
# inside them IS the output.
SERIALIZATION_SINKS = {
    "packb", "pack", "dumps", "dump", "msgpack_serialize", "tree_to_bytes",
    "server_state_to_bytes", "sha256", "sha1", "md5", "blake2b", "crc32c",
    "SerializeToString",
}


class WallClockRule(Rule):
    id = "DET001"
    severity = Severity.ERROR
    description = (
        "wall-clock read (time.time/datetime.now): deadlines and intervals "
        "must use time.monotonic(); human-readable timestamps need a "
        "suppression stating so"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and call_name(node) in WALL_CLOCK_CALLS:
                yield self.finding(
                    module,
                    node,
                    f"{call_name(node)}() is a wall clock — use "
                    "time.monotonic() for deadline/interval math; if this is "
                    "a human-readable record timestamp, suppress with a "
                    "reason",
                )


class UnseededRandomRule(Rule):
    id = "DET002"
    severity = Severity.ERROR
    description = (
        "module-level random draw (random.*/np.random.*): hidden global "
        "state breaks reproducibility — use a seeded generator object"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            parts = name.split(".")
            if parts[0] == "random" and len(parts) == 2:
                if parts[1] not in SEEDED_RANDOM_OK:
                    yield self.finding(
                        module, node,
                        f"{name}() draws from the process-global RNG — use "
                        "random.Random(seed)",
                    )
            elif parts[0] in ("np", "numpy") and len(parts) >= 3 and parts[1] == "random":
                if parts[2] not in SEEDED_RANDOM_OK:
                    yield self.finding(
                        module, node,
                        f"{name}() draws from numpy's global RNG — use "
                        "np.random.default_rng(seed)",
                    )


class UnsortedListingRule(Rule):
    id = "DET003"
    severity = Severity.ERROR
    description = (
        "os.listdir/glob without sorted(): filesystem order is "
        "host-dependent and leaks into sample/checkpoint order"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            is_listing = name in LISTING_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in LISTING_METHODS
                and name is not None
                and not name.startswith(("re.", "fnmatch."))
            )
            if is_listing and not wrapped_in_sorted(module, node):
                yield self.finding(
                    module, node,
                    f"{name or node.func.attr}() returns filesystem order — "
                    "wrap in sorted(...)",
                )


class OrderedSerializationRule(Rule):
    id = "DET004"
    severity = Severity.ERROR
    description = (
        "unordered set/dict iteration feeding serialization, aggregation "
        "or hashing in fed/, ckpt/, serve/"
    )
    paths = ("/fed/", "/ckpt/", "/serve/")

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        # Scopes: module body + each function body, walked independently so
        # "which names feed a sink" stays local.
        scopes: list[ast.AST] = [module.tree]
        scopes.extend(
            n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        seen: set[tuple[int, int]] = set()
        for scope in scopes:
            for f in self._check_scope(module, scope):
                key = (f.line, f.col)
                if key not in seen:
                    seen.add(key)
                    yield f

    @staticmethod
    def _scope_walk(scope: ast.AST) -> Iterable[ast.AST]:
        """Walk ``scope`` WITHOUT descending into nested function scopes —
        a name bound in one function must not taint a same-named variable
        in another (nested functions are scopes of their own in ``check``)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _check_scope(self, module: ModuleSource, scope: ast.AST) -> Iterable[Finding]:
        set_vars: set[str] = set()
        sink_fed_vars: set[str] = set()
        # Pass 1: names bound to sets, and names passed to serializer sinks.
        for node in self._scope_walk(scope):
            if isinstance(node, ast.Assign):
                val = node.value
                is_set = isinstance(val, ast.Set) or (
                    isinstance(val, ast.Call)
                    and terminal_name(val) in ("set", "frozenset")
                )
                if is_set:
                    for t in node.targets:
                        set_vars.update(assigned_names(t))
            if isinstance(node, ast.Call) and terminal_name(node) in SERIALIZATION_SINKS:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        sink_fed_vars.add(arg.id)
        # Pass 2: offending iterations.
        for node in self._scope_walk(scope):
            for it, kind in self._iterations(node):
                if wrapped_in_sorted(module, it):
                    continue
                if kind == "set" or self._is_set_expr(it, set_vars):
                    yield self.finding(
                        module, it,
                        "iterating a set: order is hash-randomized across "
                        "processes — wrap in sorted(...)",
                    )
                elif kind == "dictview" and self._feeds_sink(
                    module, it, sink_fed_vars
                ):
                    yield self.finding(
                        module, it,
                        "dict-view iteration feeding a serializer/hash: "
                        "order is arrival order — wrap in sorted(...)",
                    )

    @staticmethod
    def _iterations(node: ast.AST) -> list[tuple[ast.expr, str]]:
        iters: list[ast.expr] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            iters.extend(gen.iter for gen in node.generators)
        out = []
        for it in iters:
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                    and it.func.attr in ("items", "keys", "values") and not it.args:
                out.append((it, "dictview"))
            elif isinstance(it, ast.Set) or (
                isinstance(it, ast.Call)
                and terminal_name(it) in ("set", "frozenset")
            ):
                out.append((it, "set"))
            else:
                out.append((it, "other"))
        return out

    @staticmethod
    def _is_set_expr(node: ast.expr, set_vars: set[str]) -> bool:
        return isinstance(node, ast.Name) and node.id in set_vars

    @staticmethod
    def _feeds_sink(module: ModuleSource, node: ast.AST, sink_fed: set[str]) -> bool:
        """The iteration lexically sits inside a sink call's arguments, or
        inside the RHS of an assignment to a name later passed to a sink."""
        for anc in module.ancestors(node):
            if isinstance(anc, ast.Call) and terminal_name(anc) in SERIALIZATION_SINKS:
                return True
            if isinstance(anc, ast.Assign):
                for t in anc.targets:
                    if set(assigned_names(t)) & sink_fed:
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        return False


RULES = (WallClockRule, UnseededRandomRule, UnsortedListingRule,
         OrderedSerializationRule)
