"""Elastic-fleet rule pack (round 22).

- **FLEET001 replica-set mutation outside the fleet chokepoints**: any
  statement in ``serve/`` that mutates a router/fleet replica set —
  ``.replicas.append/extend/insert/pop/remove/clear(...)``, ``del
  x.replicas[i]``, or a call to the lifecycle verbs
  ``add_replica``/``remove_replica``/``kill_replica``/``grow_slot`` —
  outside ``serve/fleet.py`` and ``serve/autoscaler.py`` is an ERROR.

  The failure surface is the r17 one-lock two-phase invariant: the fleet
  manager's slot list and the router's replica list must grow and shrink
  in lockstep (a replica the router dispatches to MUST have a committed
  weights slot, and a drained replica must leave through the reroute path
  so zero accepted requests drop). Round 22 made the set dynamic — the
  autoscaler resizes it live — which is exactly when a convenience
  mutation in the router, the service front door, or a new serve module
  would desynchronize the two lists and produce a replica serving without
  weights (or dropping queued futures). All replica-set surgery therefore
  lives behind ``ServeFleet.add_replica``/``remove_replica`` (fleet.py)
  and the controller that calls them (autoscaler.py). Constructing the
  initial list (plain ``Assign``) stays legal everywhere — the router's
  ``__init__`` receives the list it routes over; it just may not reshape
  it. Code outside ``serve/`` (drills, benches, tests driving
  ``kill_replica`` as the crash hook) is deliberately out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity

# Where the rule looks: the serving plane only.
SCOPED_DIRS = ("/serve/",)
# The two modules allowed to reshape a replica set: the fleet (owner of
# both lists and the slot commit) and the autoscaler (the controller).
CHOKEPOINTS = ("serve/fleet.py", "serve/autoscaler.py")

# Mutating list methods on a `.replicas` attribute.
_LIST_MUTATORS = frozenset(
    {"append", "extend", "insert", "pop", "remove", "clear"}
)
# Lifecycle verbs that ARE replica-set surgery wherever they're invoked.
_LIFECYCLE_VERBS = frozenset(
    {"add_replica", "remove_replica", "kill_replica", "grow_slot"}
)


def _is_replica_set_mutation(node: ast.AST) -> str | None:
    """A human-readable description of the mutation, or None."""
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # x.replicas.append(...) etc.
            if (
                fn.attr in _LIST_MUTATORS
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "replicas"
            ):
                return f".replicas.{fn.attr}(...)"
            if fn.attr in _LIFECYCLE_VERBS:
                return f".{fn.attr}(...)"
        elif isinstance(fn, ast.Name) and fn.id in _LIFECYCLE_VERBS:
            return f"{fn.id}(...)"
    if isinstance(node, ast.Delete):
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Attribute)
                and tgt.value.attr == "replicas"
            ):
                return "del .replicas[...]"
    # x.replicas[i] = ... (slot surgery through subscript assignment).
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Attribute)
                and tgt.value.attr == "replicas"
            ):
                return ".replicas[...] = ..."
    return None


class FleetChokepointRule(Rule):
    id = "FLEET001"
    severity = Severity.ERROR
    description = (
        "replica-set mutation in serve/ outside serve/fleet.py and "
        "serve/autoscaler.py — the router's replica list and the fleet "
        "manager's weights slots must resize in lockstep under the "
        "two-phase commit; route it through ServeFleet.add_replica/"
        "remove_replica"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        path = "/" + module.path
        if not any(d in path for d in SCOPED_DIRS):
            return
        if any(path.endswith(c) for c in CHOKEPOINTS):
            return
        for node in ast.walk(module.tree):
            what = _is_replica_set_mutation(node)
            if what is not None:
                yield self.finding(
                    module,
                    node,
                    f"{what} outside serve/fleet.py — a replica set "
                    "reshaped without its weights slot (or its drain "
                    "reroute) desynchronizes the two-phase commit; use "
                    "ServeFleet.add_replica / remove_replica",
                )


RULES = (FleetChokepointRule,)
