"""fedlint rule registry.

Each rule pack module exports ``RULES``; ``all_rules()`` instantiates the
full set in a stable order. ``rules_by_id`` powers the CLI's ``--rules``
filter and ``--list-rules``.
"""

from __future__ import annotations

from fedcrack_tpu.analysis.engine import Rule


def all_rules() -> list[Rule]:
    from fedcrack_tpu.analysis.rules import (
        agg_plane,
        async_plane,
        compress,
        deadcode,
        determinism,
        durability,
        fleet_plane,
        health_plane,
        kernel_plane,
        locks,
        obs_plane,
        privacy_plane,
        serve_plane,
        trace,
        transport,
    )

    out: list[Rule] = []
    for pack in (
        determinism, durability, trace, transport, compress, async_plane,
        obs_plane, health_plane, agg_plane, locks, deadcode, serve_plane,
        kernel_plane, fleet_plane, privacy_plane,
    ):
        out.extend(cls() for cls in pack.RULES)
    return out


def rules_by_id() -> dict[str, Rule]:
    return {r.id: r for r in all_rules()}
