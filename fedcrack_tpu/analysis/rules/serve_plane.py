"""Serving-plane rule pack.

- **SERVE001 cache key misses model version**: the round-19 video plane
  caches per-tile inference output across frames. Any such cache whose key
  does not include the model version SURVIVES a hot swap: after new weights
  install, lookups keep answering from tiles computed under the old model —
  the silent-staleness class the (model_version, content-hash) key exists to
  make impossible. The rule statically pins that invariant over ``serve/``:
  every tile/stream cache LOOKUP (a ``[...]`` read or ``.get(...)`` on a
  cache-named receiver) must use a key expression that references the model
  version — directly, or through a local variable whose assignment does
  (the ``key = (version, digest)`` idiom). Writes and deletes are exempt:
  an entry stored under a bad key is unreachable if every read is gated.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity


def _enclosing_function(module: ModuleSource, node: ast.AST):
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _recv_name(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _is_cache_recv(expr: ast.AST) -> bool:
    name = _recv_name(expr)
    return name is not None and "cache" in name.lower()


def _mentions_version(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and "version" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "version" in n.attr.lower():
            return True
    return False


def _key_is_versioned(key: ast.AST, scope: ast.AST) -> bool:
    """True when the key expression references the model version, directly
    or via a local name whose assignment in ``scope`` does (the
    ``key = (version, digest)`` idiom)."""
    if _mentions_version(key):
        return True
    if isinstance(key, ast.Name):
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == key.id:
                        if _mentions_version(n.value):
                            return True
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                if isinstance(n.target, ast.Name) and n.target.id == key.id:
                    if _mentions_version(n.value):
                        return True
    return False


def _cache_lookup(node: ast.AST):
    """(receiver, key_expr) when ``node`` READS a cache-named container:
    ``cache[key]`` under Load, or ``cache.get(key[, default])``."""
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if _is_cache_recv(node.value):
            return node.value, node.slice
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and node.args
        and _is_cache_recv(node.func.value)
    ):
        return node.func.value, node.args[0]
    return None, None


class CacheKeyMissesModelVersionRule(Rule):
    id = "SERVE001"
    severity = Severity.ERROR
    description = (
        "tile/stream cache lookup whose key never references the model "
        "version: the cache survives a hot swap and serves tiles computed "
        "under the OLD weights (silent staleness)"
    )
    paths = ("/serve/",)

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            recv, key = _cache_lookup(node)
            if recv is None:
                continue
            scope = _enclosing_function(module, node) or module.tree
            if _key_is_versioned(key, scope):
                continue
            yield self.finding(
                module,
                node,
                f"cache lookup on {_recv_name(recv)!r} keyed without the "
                "model version: entries computed under old weights survive "
                "a hot swap — key on (model_version, content hash) like "
                "serve/stream.py, or trace the key through an assignment "
                "that includes the version",
            )


RULES = (CacheKeyMissesModelVersionRule,)
