"""Shared AST matchers for the fedlint rule packs."""

from __future__ import annotations

import ast


def dotted_name(node: ast.AST) -> str | None:
    """'time.time' for Attribute(Name('time'), 'time'); None for anything
    that is not a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def terminal_name(node: ast.AST) -> str | None:
    """The last component of a call target: 'packb' for msgpack.packb(...),
    'dumps' for json.dumps(...)."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def wrapped_in_sorted(module, node: ast.AST) -> bool:
    """Whether ``node`` sits (at any depth, within its statement) inside a
    ``sorted(...)`` call — the canonical order-fixing wrapper."""
    for anc in module.ancestors(node):
        if isinstance(anc, ast.Call) and terminal_name(anc) == "sorted":
            return True
        if isinstance(anc, ast.stmt):
            break
    return False


def assigned_names(target: ast.expr) -> list[str]:
    """Flat Name targets of an assignment ('x' for x = ..., both for
    x, y = ...)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for elt in target.elts:
            out.extend(assigned_names(elt))
        return out
    return []
