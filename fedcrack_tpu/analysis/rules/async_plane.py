"""Async-federation rule pack (round 14).

- **ASYNC001 unordered iteration in a buffered flush / staleness path**:
  the buffered aggregator's headline invariant is that a flush is a pure
  function of the buffer CONTENTS — never of cross-client arrival order
  (the sorted ``(cname, seq)`` fold, the same ordered-fold discipline the
  r13 cohort plane pinned). The hazard is one careless iteration: a
  ``dict``-view or ``set`` walked inside a flush/staleness code path feeds
  ``fedavg``/serialization in arrival (or hash-randomized) order and the
  "bit-identical resume / sync-degeneration" contracts silently die.
  DET004 already polices dict-views that LEXICALLY feed a serializer in
  ``fed/``; this rule extends it to the new plane with a stricter scope:
  inside any ``fed/`` function whose name marks it as buffer-flush or
  staleness machinery (``flush``/``buffer``/``stale`` in the name), EVERY
  unsorted dict-view or set iteration is an ERROR — in those functions
  iteration order IS aggregation/serialization order, so there is no
  benign case to carve out.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import (
    assigned_names,
    terminal_name,
    wrapped_in_sorted,
)

# Function names that mark the buffered-aggregation / staleness plane.
ASYNC_FUNC_PAT = re.compile(r"flush|buffer|stale", re.IGNORECASE)


def _scope_walk(scope: ast.AST) -> Iterable[ast.AST]:
    """Walk without descending into nested function scopes (each matching
    function is checked on its own)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _iterations(node: ast.AST) -> list[tuple[ast.expr, str]]:
    iters: list[ast.expr] = []
    if isinstance(node, (ast.For, ast.AsyncFor)):
        iters.append(node.iter)
    elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
        iters.extend(gen.iter for gen in node.generators)
    out = []
    for it in iters:
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in ("items", "keys", "values")
            and not it.args
        ):
            out.append((it, "dictview"))
        elif isinstance(it, ast.Set) or (
            isinstance(it, ast.Call) and terminal_name(it) in ("set", "frozenset")
        ):
            out.append((it, "set"))
        else:
            out.append((it, "other"))
    return out


class BufferedFlushOrderRule(Rule):
    id = "ASYNC001"
    severity = Severity.ERROR
    description = (
        "unsorted dict/set iteration inside a buffer-flush/staleness code "
        "path in fed/: arrival order must never reach aggregation or "
        "serialization (extends DET004 to the async plane)"
    )
    paths = ("/fed/",)

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for fn in ast.walk(module.tree):
            if isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and ASYNC_FUNC_PAT.search(fn.name):
                yield from self._check_fn(module, fn)

    def _check_fn(self, module: ModuleSource, fn: ast.AST) -> Iterable[Finding]:
        set_vars: set[str] = set()
        for node in _scope_walk(fn):
            if isinstance(node, ast.Assign):
                val = node.value
                if isinstance(val, ast.Set) or (
                    isinstance(val, ast.Call)
                    and terminal_name(val) in ("set", "frozenset")
                ):
                    for t in node.targets:
                        set_vars.update(assigned_names(t))
        for node in _scope_walk(fn):
            for it, kind in _iterations(node):
                if wrapped_in_sorted(module, it):
                    continue
                is_set_name = isinstance(it, ast.Name) and it.id in set_vars
                if kind in ("dictview", "set") or is_set_name:
                    yield self.finding(
                        module,
                        it,
                        f"unsorted {'set' if kind == 'set' or is_set_name else 'dict-view'} "
                        f"iteration inside {getattr(fn, 'name', '?')}(): in a "
                        "buffer-flush/staleness path iteration order IS "
                        "aggregation/serialization order — wrap in sorted(...)",
                    )


RULES = (BufferedFlushOrderRule,)
