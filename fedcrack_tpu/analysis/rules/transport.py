"""Transport rule pack.

The reference codebase's transport accidents were mechanical: a ``grcp.``
typo that only failed on the error path it guarded, and retry loops that
re-asked the server questions it had already refused to answer. Both are
statically checkable:

- **TRANS001 unaudited retry**: an ``except`` handler catching
  ``grpc.RpcError`` inside a retry loop that never consults
  ``NON_RETRYABLE_CODES`` retries *every* status code — including the ones a
  retry can never fix (bad request, bad credentials). The r8 retry audit
  made the decision explicit; this rule keeps it that way for every future
  call site.
- **TRANS002 unknown status code**: ``grpc.StatusCode.<NAME>`` where NAME is
  not a real gRPC status code. Python resolves the attribute only when the
  error path runs — exactly the ``grcp.``-typo class the paper's reference
  shipped: the bug hides until the one retry that needed it.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import dotted_name, terminal_name

# The complete grpc.StatusCode enum (grpc/_common.py; stable since gRPC 1.0).
GRPC_STATUS_CODES = frozenset(
    {
        "OK",
        "CANCELLED",
        "UNKNOWN",
        "INVALID_ARGUMENT",
        "DEADLINE_EXCEEDED",
        "NOT_FOUND",
        "ALREADY_EXISTS",
        "PERMISSION_DENIED",
        "RESOURCE_EXHAUSTED",
        "FAILED_PRECONDITION",
        "ABORTED",
        "OUT_OF_RANGE",
        "UNIMPLEMENTED",
        "INTERNAL",
        "UNAVAILABLE",
        "DATA_LOSS",
        "UNAUTHENTICATED",
    }
)

RETRY_REGISTRY_NAME = "NON_RETRYABLE_CODES"


def _catches_rpc_error(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(terminal_name(x) == "RpcError" for x in types)


class UnauditedRetryRule(Rule):
    id = "TRANS001"
    severity = Severity.ERROR
    description = (
        "grpc.RpcError handler inside a retry loop never consults "
        "NON_RETRYABLE_CODES: non-retryable codes burn the whole backoff "
        "schedule re-asking a server that already refused"
    )
    paths = ("/transport/", "/serve/")

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.ExceptHandler) and _catches_rpc_error(node)):
                continue
            if not self._inside_loop(module, node):
                continue
            consults = any(
                isinstance(n, ast.Name) and n.id == RETRY_REGISTRY_NAME
                for stmt in node.body
                for n in ast.walk(stmt)
            )
            if not consults:
                yield self.finding(
                    module,
                    node,
                    "RpcError retry handler must check the code against "
                    f"{RETRY_REGISTRY_NAME} and raise immediately on a match "
                    "(a retry cannot fix INVALID_ARGUMENT or UNAUTHENTICATED)",
                )

    @staticmethod
    def _inside_loop(module: ModuleSource, node: ast.AST) -> bool:
        for anc in module.ancestors(node):
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False


class UnknownStatusCodeRule(Rule):
    id = "TRANS002"
    severity = Severity.ERROR
    description = (
        "grpc.StatusCode.<NAME> where NAME is not a gRPC status code: the "
        "AttributeError hides until the error path that needed it runs"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Attribute):
                continue
            name = dotted_name(node)
            if name is None:
                continue
            parts = name.split(".")
            # grpc.StatusCode.X or (from grpc import StatusCode) StatusCode.X
            if len(parts) >= 2 and parts[-2] == "StatusCode":
                member = parts[-1]
                if member not in GRPC_STATUS_CODES:
                    yield self.finding(
                        module,
                        node,
                        f"StatusCode.{member} is not a gRPC status code — "
                        "this AttributeError only fires on the error path "
                        "that references it",
                    )


RULES = (UnauditedRetryRule, UnknownStatusCodeRule)
