"""Federation-health rule pack (round 18).

- **HEALTH001 client-labeled metric outside the ledger chokepoint**: any
  ``registry.counter/gauge/histogram(...)`` call site whose ``labels=``
  tuple contains a per-client axis (``client``, ``cname``, ``client_id``,
  ``client_name``) must live in ``health/ledger.py`` — the ONE module
  whose export path (:func:`fedcrack_tpu.health.ledger.client_label` /
  ``export_anomaly_metrics``) bounds the label's cardinality
  (``MAX_CLIENT_LABELS`` + ``_overflow`` collapse, max-aggregated).

  The failure mode this kills is the classic federation-metrics leak: a
  well-meaning ``fed_whatever_total`` labeled by client name looks fine on
  a 3-client devbox and mints one Prometheus series per enrolled client in
  production — unbounded cardinality, exactly what the r15 registry's
  bounded-label discipline exists to prevent, except the registry cannot
  know which label VALUES are unbounded; only the lint layer can see that
  the label NAME is a client axis. Anyone who needs a client-resolved
  metric routes it through the ledger's helper instead of minting a new
  family. Same receiver idiom as OBS001 (``registry``/``REGISTRY``/
  ``reg`` by name) so the two rules cover the same call sites.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules.obs_plane import _registry_receiver

# Label names that resolve to one series PER CLIENT — the unbounded axis.
CLIENT_LABELS = frozenset({"client", "cname", "client_id", "client_name"})
# The one module allowed to mint client-labeled families: its export path
# bounds cardinality by construction (client_label / MAX_CLIENT_LABELS).
CHOKEPOINT = "health/ledger.py"


def _client_label_names(call: ast.Call) -> list[str]:
    """Literal label names in the call's ``labels=`` that are client axes.
    Non-literal label expressions are OBS001's problem (computed names);
    this rule only judges what it can read."""
    for kw in call.keywords:
        if kw.arg != "labels":
            continue
        if not isinstance(kw.value, (ast.Tuple, ast.List)):
            return []
        return [
            elt.value
            for elt in kw.value.elts
            if isinstance(elt, ast.Constant)
            and isinstance(elt.value, str)
            and elt.value.lower() in CLIENT_LABELS
        ]
    return []


class ClientLabelChokepointRule(Rule):
    id = "HEALTH001"
    severity = Severity.ERROR
    description = (
        "a metric family labeled by client name mints one series per "
        "enrolled client (unbounded cardinality) — route it through "
        "health/ledger.py's bounded export (client_label / "
        "export_anomaly_metrics) instead of a new registry family"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        if module.path.endswith(CHOKEPOINT):
            return
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and _registry_receiver(node)):
                continue
            for label in _client_label_names(node):
                yield self.finding(
                    module,
                    node,
                    f"metric labeled by client axis {label!r} outside "
                    f"{CHOKEPOINT} — per-client series are unbounded; use "
                    "health.ledger.export_anomaly_metrics/client_label "
                    "(MAX_CLIENT_LABELS + _overflow) instead",
                )


RULES = (ClientLabelChokepointRule,)
