"""Compressed-transport rule pack.

- **COMP001 frame decode bypasses sanitation**: a compressed-update frame
  that decodes cleanly (magic + CRC + manifest) can still carry anything a
  poisoned trainer produces — NaN deltas, adversarial values — because the
  CRC proves transport integrity, not semantic safety. Every decode path
  that feeds FedAvg must therefore route its reconstruction through
  ``fed.serialization.validate_update`` (the same gate raw uploads take).
  The rule statically pins that invariant over ``fed/`` and ``compress/``:
  any function calling a frame decoder (``decode_update``/``decode_frame``)
  must also reference ``validate_update`` in the same function scope. The
  decoder layer itself (functions NAMED as a frame decoder, which compose
  the lower-level parses) is exempt — it returns trees, it does not feed
  the aggregator.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import terminal_name

FRAME_DECODERS = frozenset({"decode_update", "decode_frame"})
SANITATION_GATE = "validate_update"


def _enclosing_function(module: ModuleSource, node: ast.AST):
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _references(scope: ast.AST, name: str) -> bool:
    for n in ast.walk(scope):
        if isinstance(n, ast.Name) and n.id == name:
            return True
        if isinstance(n, ast.Attribute) and n.attr == name:
            return True
    return False


class FrameDecodeBypassesSanitationRule(Rule):
    id = "COMP001"
    severity = Severity.ERROR
    description = (
        "compressed-frame decode feeding FedAvg never touches "
        "serialization.validate_update: a CRC-valid frame can still carry "
        "NaN/poisoned deltas into the global average"
    )
    paths = ("/fed/", "/compress/")

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node)
            if name not in FRAME_DECODERS:
                continue
            fn = _enclosing_function(module, node)
            if fn is None:
                # Module-level decode: check the whole module for the gate.
                scope: ast.AST = module.tree
            elif fn.name in FRAME_DECODERS:
                continue  # the decoder layer composing its own parses
            else:
                scope = fn
            if not _references(scope, SANITATION_GATE):
                yield self.finding(
                    module,
                    node,
                    f"{name}() reconstruction must pass through "
                    f"serialization.{SANITATION_GATE} before it can reach "
                    "FedAvg (the CRC proves transport integrity, not that "
                    "the decoded tree is safe to average)",
                )


RULES = (FrameDecodeBypassesSanitationRule,)
