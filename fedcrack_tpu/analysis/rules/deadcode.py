"""Dead-code rule pack.

Mechanical hygiene with a real failure mode behind it: the reference
codebase shipped a fully written log uploader whose call site was commented
out — dead code that LOOKED like a feature. Unused imports and unreachable
branches are where that class of accident hides.

- **DEAD001 unused import**: an imported name never referenced in the
  module. ``__init__.py`` files are exempt (imports there ARE the API), as
  are ``import x as x`` re-exports and names listed in ``__all__``.
- **DEAD002 unreachable code**: statements after an unconditional
  ``return``/``raise``/``break``/``continue`` in the same block, and
  branches guarded by a constant-false test.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity

_WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


class UnusedImportRule(Rule):
    id = "DEAD001"
    severity = Severity.WARNING
    description = "imported name never used in the module"

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        if module.path.endswith("__init__.py"):
            return
        imported: list[tuple[str, ast.AST, str]] = []  # (name, node, spelled)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    imported.append((name, node, alias.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    if alias.asname == alias.name:
                        continue  # explicit re-export idiom
                    name = alias.asname or alias.name
                    imported.append((name, node, alias.name))
        if not imported:
            return
        used: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                # root of a dotted chain is a Name and caught above; nothing
                # extra needed, but keep attribute names out of `used`.
                pass
        used |= self._string_referenced(module)
        for name, node, spelled in imported:
            if name not in used:
                yield self.finding(
                    module, node,
                    f"'{spelled}' imported but unused",
                )

    @staticmethod
    def _string_referenced(module: ModuleSource) -> set[str]:
        """Names referenced from string contexts that behave like code:
        ``__all__`` entries and string annotations."""
        out: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
                if "__all__" in targets:
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Constant) and isinstance(c.value, str):
                            out.add(c.value)
            ann = []
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ann = [a.annotation for a in node.args.args + node.args.kwonlyargs
                       if a.annotation is not None]
                if node.returns is not None:
                    ann.append(node.returns)
            elif isinstance(node, ast.AnnAssign):
                ann = [node.annotation]
            for a in ann:
                for c in ast.walk(a):
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        out.update(_WORD.findall(c.value))
        return out


def _is_terminal(stmt: ast.stmt) -> bool:
    return isinstance(stmt, (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _const_false(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and not test.value


class UnreachableRule(Rule):
    id = "DEAD002"
    severity = Severity.WARNING
    description = (
        "unreachable statement (after return/raise/break/continue, or under "
        "a constant-false test)"
    )

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            for field in ("body", "orelse", "finalbody"):
                block = getattr(node, field, None)
                if not isinstance(block, list):
                    continue
                for prev, stmt in zip(block, block[1:]):
                    if _is_terminal(prev) and isinstance(stmt, ast.stmt):
                        yield self.finding(
                            module, stmt,
                            f"unreachable: follows a {type(prev).__name__.lower()}",
                        )
                        break  # one finding per block is enough
            if isinstance(node, (ast.If, ast.While)) and _const_false(node.test):
                yield self.finding(
                    module, node,
                    f"{'if' if isinstance(node, ast.If) else 'while'} guarded "
                    "by a constant-false test: the body never runs",
                )


RULES = (UnusedImportRule, UnreachableRule)
