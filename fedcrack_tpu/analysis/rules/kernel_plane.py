"""Kernel-plane rule pack (round 20).

- **KERN001 pallas_call without a CPU twin**: every Pallas kernel in this
  repo must be testable off-TPU. The contract (ops/pallas_bce.py, round 5;
  kernels/dequant.py, round 20) is that the module owning a
  ``pl.pallas_call`` ships a twin of the compiled kernel that runs on any
  backend, in one of two idiomatic forms:

  * an **interpret-mode path** — some ``pallas_call`` site in the module
    takes an ``interpret=`` keyword, so the same kernel body runs under the
    Pallas interpreter on CPU and can be pinned against a reference;
  * a **reference twin** — a function in the module whose name carries
    ``reference``/``_ref``/``jnp`` implementing the same math in plain XLA.

  A module that compiles a kernel without either is untestable until a TPU
  shows up: its numerics can silently drift from the math the rest of the
  codebase assumes (the exact failure class the round-17 quant gate exists
  to catch at install time, and the round-20 property sweeps catch in CI).
  The rule fires one ERROR per ``pallas_call`` site in such a module.

  Matching is by idiom, not import graph: any call spelled
  ``<anything>.pallas_call(...)`` or a bare ``pallas_call(...)`` counts as
  a kernel launch; docstring mentions and attribute reads without a call do
  not fire.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity

# Function-name fragments that mark a plain-XLA reference twin of a kernel.
_TWIN_NAME_FRAGMENTS = ("reference", "_ref", "jnp")


def _is_pallas_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "pallas_call"
    if isinstance(func, ast.Name):
        return func.id == "pallas_call"
    return False


def _has_interpret_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "interpret" for kw in call.keywords)


def _module_has_twin(module: ModuleSource) -> bool:
    """True when the module ships a CPU twin for its kernels: an
    ``interpret=`` keyword on any pallas_call site, or a function whose
    name marks a plain-XLA reference implementation."""
    for node in ast.walk(module.tree):
        if _is_pallas_call(node) and _has_interpret_kwarg(node):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            low = node.name.lower()
            if any(frag in low for frag in _TWIN_NAME_FRAGMENTS):
                return True
    return False


class PallasKernelWithoutTwinRule(Rule):
    id = "KERN001"
    severity = Severity.ERROR
    description = (
        "pallas_call in a module with neither an interpret-mode path "
        "(interpret= kwarg on some pallas_call site) nor a reference twin "
        "function — the kernel is untestable off-TPU and its numerics can "
        "drift unpinned"
    )
    paths = ("/fedcrack_tpu/",)

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        has_twin: bool | None = None  # computed lazily; most modules have 0 kernels
        for node in ast.walk(module.tree):
            if not _is_pallas_call(node):
                continue
            if has_twin is None:
                has_twin = _module_has_twin(module)
            if has_twin:
                return
            yield self.finding(
                module,
                node,
                "pallas_call without a CPU twin in this module: add an "
                "interpret= kwarg threaded to an interpreter path (the "
                "ops/pallas_bce.py idiom) or a plain-XLA reference "
                "function, and pin them against each other in tests",
            )


RULES = (PallasKernelWithoutTwinRule,)
