"""Privacy-plane rule pack (round 23).

- **PRIV001 unseeded randomness in the privacy plane**: inside
  ``privacy/`` every random draw must trace to an EXPLICIT seed. The
  package's whole contract is that masks and noise are replayable — a
  chaos-retried round reproduces bit-identical DP noise, a restarted
  server reconstructs a dropped masker's pads from its enroll-time seed,
  and the secagg drill pins the unmasked average bit-for-bit. One
  ambient-entropy draw (``default_rng()`` with no seed, ``os.urandom``,
  ``uuid4``, a wall-clock fed into a key) silently breaks all three: the
  retry double-noises, the recovery subtracts the WRONG pad (corrupting
  the global, not just a metric), and nothing fails loudly because the
  bytes are still well-formed.

  DET002 already flags module-level ``random.*``/``np.random.*`` draws
  repo-wide; PRIV001 tightens the net where it matters most — argless
  generator CONSTRUCTION (``default_rng()``, ``Philox()``,
  ``random.Random()``: seeded-looking, OS-entropy-backed) and
  nondeterministic entropy sources (``os.urandom``, ``secrets.*``,
  ``uuid.uuid1/4``, wall clocks) anywhere in ``privacy/``, severity
  ERROR, no legitimate suppression expected.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fedcrack_tpu.analysis.engine import Finding, ModuleSource, Rule, Severity
from fedcrack_tpu.analysis.rules._ast_util import call_name

# Generator constructors that are deterministic ONLY when given a seed/key:
# called with no arguments at all they pull OS entropy — the silent
# non-reproducibility PRIV001 exists to kill.
SEEDABLE_CONSTRUCTORS = {
    "default_rng",
    "Random",
    "RandomState",
    "Philox",
    "PCG64",
    "SFC64",
    "MT19937",
    "SeedSequence",
}

# Calls that are nondeterministic entropy BY DESIGN — never acceptable in
# the privacy plane, seeded or not (there is nothing to seed).
ENTROPY_SOURCES = {
    "os.urandom",
    "secrets.token_bytes",
    "secrets.token_hex",
    "secrets.token_urlsafe",
    "secrets.randbits",
    "secrets.randbelow",
    "secrets.choice",
    "uuid.uuid1",
    "uuid.uuid4",
    "time.time",
    "time.time_ns",
    "datetime.now",
    "datetime.datetime.now",
    "datetime.utcnow",
    "datetime.datetime.utcnow",
}


class UnseededPrivacyRandomRule(Rule):
    id = "PRIV001"
    severity = Severity.ERROR
    description = (
        "unseeded/ambient randomness inside privacy/: masks and DP noise "
        "must derive from explicit seeds or replay breaks silently "
        "(double-drawn noise, wrong recovered pads)"
    )
    paths = ("privacy/",)

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name in ENTROPY_SOURCES:
                yield self.finding(
                    module,
                    node,
                    f"{name}() is nondeterministic entropy — the privacy "
                    "plane's masks/noise must derive from explicit seeds "
                    "(sha256-rooted, like privacy.secagg.client_seed)",
                )
                continue
            tail = name.split(".")[-1]
            if (
                tail in SEEDABLE_CONSTRUCTORS
                and not node.args
                and not node.keywords
            ):
                yield self.finding(
                    module,
                    node,
                    f"{name}() constructed without a seed pulls OS entropy "
                    "— pass an explicit seed/key so masks and noise replay "
                    "bit-identically",
                )


RULES = [UnseededPrivacyRandomRule]
