"""Runtime sanitizers — the dynamic twins of the static rule packs.

Three contracts the lint engine can only approximate statically get a
runtime assertion here:

- :class:`RecompileSentry` (twin of TRACE001's intent): steady-state rounds
  and per-bucket serve programs must compile exactly once. A stray python
  float in a carry, a shape drifting by one, or a weights pytree whose
  structure changes across a hot-swap silently triggers a retrace — turning
  the pointer-flip swap into a multi-second XLA pause. The sentry watches
  ``jax.jit`` cache sizes and fails loudly on unexpected growth.
- :func:`no_implicit_transfers` (twin of TRACE001): arms
  ``jax.transfer_guard("disallow")`` so any *implicit* host<->device
  transfer inside the guarded span raises instead of stalling the pipeline.
  Explicit ``device_put``/``device_get`` (the staged paths) still work —
  exactly the discipline the mesh round and batcher dispatch claim to have.
- :class:`LockOrderMonitor` + :func:`make_lock` (twin of LOCK001): a
  lockdep-style order recorder. Locks built through ``make_lock(name)`` are
  plain ``threading.Lock`` objects in production; with a monitor installed
  (tests, or ``FEDCRACK_LOCK_DEBUG=1``) every acquisition records the
  per-thread held stack, and acquiring A-then-B after B-then-A was ever
  observed raises :class:`LockOrderViolation` with both acquisition stacks —
  catching the inversion even when the timing never actually deadlocks.
"""

from __future__ import annotations

import contextlib
import os
import threading
import traceback
from typing import Any, Iterator


class RecompileError(AssertionError):
    """A watched jit function compiled when the contract said it must not."""


class RecompileSentry:
    """Asserts jit-cache stability over watched functions.

    Usage::

        sentry = RecompileSentry()
        sentry.watch("serve.predict", engine._fn)
        engine.warmup(variables)          # compiles (one entry per bucket)
        sentry.mark()                     # steady state begins here
        ... serve traffic / hot-swap ...
        sentry.assert_steady()            # zero recompiles since mark()

    or as a span::

        with sentry.expect(compiles=0):
            batcher-driven traffic

    Counting uses the jit wrapper's ``_cache_size()`` (one entry per traced
    (shapes, dtypes, shardings) signature — jax>=0.4 exposes it on the
    ``jax.jit`` return value). ``supported()`` reports availability so tests
    can skip on exotic builds instead of failing.
    """

    def __init__(self) -> None:
        self._watched: dict[str, Any] = {}
        self._marks: dict[str, int] = {}

    @staticmethod
    def supported(fn: Any = None) -> bool:
        if fn is not None:
            return hasattr(fn, "_cache_size")
        import jax

        probe = jax.jit(lambda x: x)
        return hasattr(probe, "_cache_size")

    def watch(self, name: str, fn: Any) -> None:
        if not hasattr(fn, "_cache_size"):
            raise TypeError(
                f"{name}: object has no _cache_size(); pass the jax.jit "
                "wrapper itself (e.g. engine._fn), not a bound method"
            )
        self._watched[name] = fn
        self._marks[name] = fn._cache_size()

    def counts(self) -> dict[str, int]:
        return {name: fn._cache_size() for name, fn in self._watched.items()}

    def mark(self) -> None:
        """Steady state begins now: subsequent deltas are violations."""
        self._marks = self.counts()

    def deltas(self) -> dict[str, int]:
        return {
            name: count - self._marks[name]
            for name, count in self.counts().items()
        }

    def assert_steady(self) -> None:
        grew = {n: d for n, d in self.deltas().items() if d != 0}
        if grew:
            raise RecompileError(
                f"unexpected recompiles since mark(): {grew} — a shape, "
                "dtype, or pytree-structure drift is retracing a program "
                "the contract says compiles exactly once"
            )

    @contextlib.contextmanager
    def expect(self, compiles: int = 0) -> Iterator["RecompileSentry"]:
        before = self.counts()
        yield self
        after = self.counts()
        total = sum(after.values()) - sum(before.values())
        if total != compiles:
            per_fn = {n: after[n] - before[n] for n in after
                      if after[n] != before[n]}
            raise RecompileError(
                f"expected exactly {compiles} compiles in this span, "
                f"observed {total} ({per_fn or 'none'})"
            )


@contextlib.contextmanager
def no_implicit_transfers() -> Iterator[None]:
    """Raise on any implicit host<->device transfer inside the span.

    Explicit ``jax.device_put`` / ``jax.device_get`` remain allowed — the
    guarded code is exactly the staged discipline the mesh round and the
    batcher dispatch promise. No-op on jax builds without transfer_guard.
    """
    import jax

    guard = getattr(jax, "transfer_guard", None)
    if guard is None:
        yield
        return
    with guard("disallow"):
        yield


# ---- lock-order runtime monitor ----


class LockOrderViolation(RuntimeError):
    """Two locks were acquired in both orders — a latent deadlock."""


class LockOrderMonitor:
    """Records lock-acquisition order edges with stacks; raises on inversion.

    The check runs BEFORE blocking on the real lock, so a would-be deadlock
    surfaces as an exception with both stacks instead of a hang.
    """

    def __init__(self) -> None:
        self._held = threading.local()
        self._edges: dict[tuple[str, str], str] = {}
        self._edge_lock = threading.Lock()

    def _stack(self) -> list[str]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def on_acquire(self, name: str) -> None:
        held = self._stack()
        if not held:
            # Leaf acquisition (the common case — every current lock in the
            # repo): no edge to record, so skip the stack capture entirely.
            held.append(name)
            return
        stack_txt = "".join(traceback.format_stack(limit=12))
        for h in held:
            if h == name:
                continue
            edge, rev = (h, name), (name, h)
            with self._edge_lock:
                if rev in self._edges and edge not in self._edges:
                    raise LockOrderViolation(
                        f"lock order inversion: acquiring {name!r} while "
                        f"holding {h!r}, but the opposite order was recorded "
                        f"earlier.\n--- this acquisition ---\n{stack_txt}"
                        f"--- earlier {rev[0]!r}->{rev[1]!r} ---\n"
                        f"{self._edges[rev]}"
                    )
                self._edges.setdefault(edge, stack_txt)
        held.append(name)

    def on_release(self, name: str) -> None:
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> set[tuple[str, str]]:
        with self._edge_lock:
            return set(self._edges)


class _MonitoredLock:
    """threading.Lock plus order recording. API-compatible with the subset
    the repo uses (context manager, acquire/release, locked)."""

    def __init__(self, name: str, monitor: LockOrderMonitor):
        self._name = name
        self._monitor = monitor
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._monitor.on_acquire(self._name)
        got = self._lock.acquire(blocking, timeout)
        if not got:
            self._monitor.on_release(self._name)
        return got

    def release(self) -> None:
        self._lock.release()
        self._monitor.on_release(self._name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


_monitor: LockOrderMonitor | None = None
_monitor_lock = threading.Lock()


def install_monitor() -> LockOrderMonitor:
    """Turn on lock-order monitoring for locks created AFTER this call."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = LockOrderMonitor()
        return _monitor


def uninstall_monitor() -> None:
    global _monitor
    with _monitor_lock:
        _monitor = None


def make_lock(name: str):
    """The serve plane's lock factory. Plain ``threading.Lock()`` unless a
    monitor is installed (or ``FEDCRACK_LOCK_DEBUG=1``), in which case the
    lock records acquisition order under ``name``. Production overhead of
    debug-off mode: one module-global read at construction time, zero per
    acquisition."""
    mon = _monitor
    if mon is None and os.environ.get("FEDCRACK_LOCK_DEBUG"):
        mon = install_monitor()
    if mon is None:
        return threading.Lock()
    return _MonitoredLock(name, mon)
