"""The fedlint core: module loading, the finding/severity model, per-line
suppressions, the checked-in baseline, and the per-file result cache.

Rules are small objects (see ``rules/``) with an ``id``, a ``severity`` and
a ``check(module) -> findings`` method; project-scope rules (the lock-order
graph) additionally see every module at once via ``check_project``. The
engine parses each file exactly once into a :class:`ModuleSource` (AST +
source lines + a parent map) and hands that to every rule, so adding a rule
costs one AST walk, not one parse.

Suppressions: a ``# fedlint: disable=RULE[,RULE...]`` comment suppresses
matching findings on its own line, or — when the comment is the whole line —
on the next line. ``disable=all`` suppresses every rule. A file-wide
``# fedlint: disable-file=RULE`` anywhere in the file suppresses the rule
for the entire file. Suppressions are for findings with a *reason*; put the
reason after ``--`` in the comment.

Baseline: a JSON file of fingerprinted findings accepted as-is. The
fingerprint hashes (rule, path, stripped source line) — NOT the line
number — so re-indenting or moving code keeps the baseline valid, while
*changing* the offending line invalidates it and resurfaces the finding.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import hashlib
import json
import os
import tokenize
from typing import Any, Iterable, Sequence

ENGINE_VERSION = 1

DEFAULT_EXCLUDES = ("_pb2.py",)  # generated modules are not ours to lint


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    severity: Severity
    path: str          # repo-relative, '/'-separated
    line: int          # 1-based
    col: int           # 0-based
    message: str
    source_line: str = ""

    def fingerprint(self) -> str:
        """Stable identity for baseline matching: rule + path + the stripped
        offending line (line numbers drift; the code itself is the claim)."""
        key = f"{self.rule}:{self.path}:{self.source_line.strip()}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "source_line": self.source_line,
            "fingerprint": self.fingerprint(),
        }

    @classmethod
    def from_json(cls, d: dict) -> "Finding":
        return cls(
            rule=d["rule"],
            severity=Severity[d["severity"]],
            path=d["path"],
            line=int(d["line"]),
            col=int(d["col"]),
            message=d["message"],
            source_line=d.get("source_line", ""),
        )

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class ModuleSource:
    """One parsed module: AST, raw lines, and lazy derived views shared by
    every rule (parent map, per-line suppressions)."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self._parents: dict[ast.AST, ast.AST] | None = None
        self._suppressed: dict[int, set[str]] | None = None
        self._file_suppressed: set[str] | None = None

    # -- derived views --

    def parent_map(self) -> dict[ast.AST, ast.AST]:
        if self._parents is None:
            parents: dict[ast.AST, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        parents = self.parent_map()
        while node in parents:
            node = parents[node]
            yield node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # -- suppressions --

    def _scan_suppressions(self) -> None:
        per_line: dict[int, set[str]] = {}
        file_wide: set[str] = set()
        try:
            tokens = tokenize.generate_tokens(iter(self.lines_for_tokenize()).__next__)
            comments = [
                (t.start[0], t.string) for t in tokens if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError):
            # Unparseable token stream (the AST parsed, so this is rare);
            # fall back to a per-line textual scan.
            comments = [
                (i + 1, line[line.index("#"):])
                for i, line in enumerate(self.lines)
                if "#" in line
            ]
        for lineno, text in comments:
            body = text.lstrip("#").strip()
            if not body.startswith("fedlint:"):
                continue
            directive = body[len("fedlint:"):].strip()
            for clause in directive.split(";"):
                clause = clause.strip()
                if clause.startswith("disable-file="):
                    rules = clause[len("disable-file="):]
                    file_wide.update(self._parse_rules(rules))
                elif clause.startswith("disable="):
                    rules = clause[len("disable="):]
                    parsed = self._parse_rules(rules)
                    stripped = self.line_text(lineno).strip()
                    target = lineno
                    if stripped.startswith("#"):
                        target = lineno + 1  # standalone comment guards the next line
                    per_line.setdefault(target, set()).update(parsed)
        self._suppressed = per_line
        self._file_suppressed = file_wide

    def lines_for_tokenize(self) -> list[str]:
        return [line + "\n" for line in self.lines]

    @staticmethod
    def _parse_rules(spec: str) -> set[str]:
        # "DET001,DET002 -- reason text" -> {"DET001", "DET002"}
        spec = spec.split("--")[0]
        return {r.strip() for r in spec.split(",") if r.strip()}

    def is_suppressed(self, finding: Finding) -> bool:
        if self._suppressed is None:
            self._scan_suppressions()
        assert self._suppressed is not None and self._file_suppressed is not None
        if {"all", finding.rule} & self._file_suppressed:
            return True
        rules = self._suppressed.get(finding.line, ())
        return "all" in rules or finding.rule in rules


class Rule:
    """Base rule. Subclasses set ``id``/``severity``/``description`` and
    implement ``check`` (per module) or ``check_project`` (all modules).

    ``paths``: optional path-fragment filter — the rule only sees modules
    whose repo-relative path contains one of the fragments (e.g. the
    ordered-iteration rule is scoped to ``fed/``, ``ckpt/``, ``serve/``
    where iteration order feeds serialization/aggregation).
    """

    id: str = ""
    severity: Severity = Severity.ERROR
    description: str = ""
    paths: tuple[str, ...] = ()   # empty = every module
    project_scope: bool = False   # True -> check_project(modules) once

    def applies_to(self, path: str) -> bool:
        if not self.paths:
            return True
        p = "/" + path.replace(os.sep, "/")
        return any(frag in p for frag in self.paths)

    def check(self, module: ModuleSource) -> Iterable[Finding]:
        return ()

    def check_project(self, modules: Sequence[ModuleSource]) -> Iterable[Finding]:
        return ()

    def finding(
        self, module: ModuleSource, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            severity=self.severity,
            path=module.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            source_line=module.line_text(line),
        )


# ---- baseline ----


def make_baseline(findings: Iterable[Finding]) -> dict:
    """Baseline payload for a set of findings: fingerprint -> count (the
    same line can legitimately fire twice, e.g. two calls on one line)."""
    entries: dict[str, dict] = {}
    for f in findings:
        fp = f.fingerprint()
        e = entries.setdefault(
            fp, {"rule": f.rule, "path": f.path, "line": f.source_line.strip(),
                 "count": 0}
        )
        e["count"] += 1
    return {"version": ENGINE_VERSION, "entries": entries}


def load_baseline(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != ENGINE_VERSION:
        raise ValueError(f"unknown baseline version {payload.get('version')!r}")
    return payload


def apply_baseline(findings: list[Finding], baseline: dict) -> list[Finding]:
    """Drop findings covered by the baseline, count-limited per fingerprint
    (so a NEW duplicate of a baselined line still surfaces)."""
    budget = {fp: e["count"] for fp, e in baseline.get("entries", {}).items()}
    out = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            continue
        out.append(f)
    return out


# ---- engine ----


class LintEngine:
    """Loads modules, runs rules, applies suppressions + baseline.

    ``cache_dir``: optional per-file findings cache (keyed on path + mtime +
    size + the rule-set version) — per-module rules only; project-scope
    rules always run, their inputs are cross-file.
    """

    def __init__(
        self,
        rules: Sequence[Rule] | None = None,
        cache_dir: str | None = None,
    ):
        if rules is None:
            from fedcrack_tpu.analysis.rules import all_rules

            rules = all_rules()
        self.rules = list(rules)
        self.cache_dir = cache_dir
        self._cache: dict[str, Any] | None = None

    # -- module loading --

    @staticmethod
    def iter_python_files(
        root: str, excludes: Sequence[str] = DEFAULT_EXCLUDES
    ) -> list[str]:
        if os.path.isfile(root):
            return [root]
        out = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                if any(name.endswith(ex) for ex in excludes):
                    continue
                out.append(os.path.join(dirpath, name))
        return out

    def load_modules(
        self, paths: Sequence[str], rel_to: str | None = None
    ) -> list[ModuleSource]:
        modules = []
        for root in paths:
            for fp in self.iter_python_files(root):
                rel = os.path.relpath(fp, rel_to) if rel_to else fp
                with open(fp, encoding="utf-8") as f:
                    modules.append(ModuleSource(rel, f.read()))
        return modules

    # -- cache --

    def _cache_key(self) -> str:
        return f"v{ENGINE_VERSION}:" + ",".join(sorted(r.id for r in self.rules))

    def _cache_path(self) -> str:
        assert self.cache_dir is not None
        return os.path.join(self.cache_dir, "cache.json")

    def _load_cache(self) -> dict:
        if self._cache is None:
            self._cache = {}
            if self.cache_dir is not None:
                try:
                    with open(self._cache_path(), encoding="utf-8") as f:
                        payload = json.load(f)
                    if payload.get("key") == self._cache_key():
                        self._cache = payload.get("files", {})
                except (OSError, ValueError):
                    pass
        return self._cache

    def _save_cache(self) -> None:
        if self.cache_dir is None or self._cache is None:
            return
        os.makedirs(self.cache_dir, exist_ok=True)
        with open(self._cache_path(), "w", encoding="utf-8") as f:
            json.dump({"key": self._cache_key(), "files": self._cache}, f)

    @staticmethod
    def _stat_sig(path: str) -> list[int] | None:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return [int(st.st_mtime_ns), st.st_size]

    # -- running --

    def lint_source(self, source: str, path: str = "<memory>") -> list[Finding]:
        """Lint one in-memory module (the fixture-test entry point).
        Per-module rules only; suppressions applied, no baseline."""
        module = ModuleSource(path, source)
        findings: list[Finding] = []
        for rule in self.rules:
            if rule.project_scope or not rule.applies_to(module.path):
                continue
            findings.extend(rule.check(module))
        findings = [f for f in findings if not module.is_suppressed(f)]
        return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))

    def lint_modules(
        self,
        modules: Sequence[ModuleSource],
        abs_paths: dict[str, str] | None = None,
    ) -> list[Finding]:
        """Run every rule over ``modules``; suppressions applied, no
        baseline. ``abs_paths`` (module path -> filesystem path) enables the
        cache for per-module rules."""
        cache = self._load_cache() if self.cache_dir is not None else None
        findings: list[Finding] = []
        by_path = {m.path: m for m in modules}
        for module in modules:
            sig = None
            if cache is not None and abs_paths and module.path in abs_paths:
                sig = self._stat_sig(abs_paths[module.path])
                entry = cache.get(module.path)
                if sig is not None and entry is not None and entry["sig"] == sig:
                    findings.extend(
                        Finding.from_json(d) for d in entry["findings"]
                    )
                    continue
            mod_findings: list[Finding] = []
            for rule in self.rules:
                if rule.project_scope or not rule.applies_to(module.path):
                    continue
                mod_findings.extend(rule.check(module))
            mod_findings = [
                f for f in mod_findings if not by_path[f.path].is_suppressed(f)
            ]
            if cache is not None and sig is not None:
                cache[module.path] = {
                    "sig": sig,
                    "findings": [f.to_json() for f in mod_findings],
                }
            findings.extend(mod_findings)
        for rule in self.rules:
            if not rule.project_scope:
                continue
            scoped = [m for m in modules if rule.applies_to(m.path)]
            for f in rule.check_project(scoped):
                owner = by_path.get(f.path)
                if owner is None or not owner.is_suppressed(f):
                    findings.append(f)
        if cache is not None:
            self._save_cache()
        return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))

    def lint_paths(
        self,
        paths: Sequence[str],
        rel_to: str | None = None,
        baseline: dict | None = None,
    ) -> list[Finding]:
        abs_paths = {}
        for root in paths:
            for fp in self.iter_python_files(root):
                rel = os.path.relpath(fp, rel_to) if rel_to else fp
                abs_paths[rel.replace(os.sep, "/")] = fp
        modules = self.load_modules(paths, rel_to=rel_to)
        findings = self.lint_modules(modules, abs_paths=abs_paths)
        if baseline is not None:
            findings = apply_baseline(findings, baseline)
        return findings
