"""Version tolerance for the small set of JAX APIs that moved recently.

The data plane targets current JAX (``jax.shard_map`` with varying-axes
tracking), but CI images and tunnels pin older releases where the same
machinery lives under ``jax.experimental.shard_map`` and the vma system
(``lax.pcast``) does not exist yet. Rather than sprinkling try/excepts at
every call site, the handful of moved names resolve here once:

- :func:`shard_map` — ``jax.shard_map`` when present (0.5+), else the
  experimental module's implementation (identical call signature for the
  ``mesh``/``in_specs``/``out_specs`` keywords this repo uses). The
  experimental path runs with ``check_rep=False``: its pre-vma replication
  checker is conservative (there is no ``pcast`` to teach it that a scan
  carry re-replicates), and every replicated out_spec this repo emits is
  replicated by construction — psum/pmean over the relevant axis right
  before the return (fedavg_mesh, spatial) — which current JAX's vma
  checker verifies for real in CI.
- :func:`pcast_varying` — ``lax.pcast(..., to="varying")`` when the vma
  system exists; identity otherwise (pre-vma shard_map has no varying-axes
  tracking, so there is nothing to promote and the scan carry is already
  stable).
- :func:`typeof_vma` / :func:`shape_dtype_struct` — the vma of an abstract
  value (``jax.typeof``) and a ``ShapeDtypeStruct`` carrying one; both
  degrade to vma-less behavior where the system doesn't exist.
- :func:`is_distributed_initialized` — ``jax.distributed.is_initialized``
  when present, else the 0.4.x ``global_state.client`` probe. Resolved
  DYNAMICALLY so tests that monkeypatch ``jax.distributed.is_initialized``
  (with ``raising=False``) are honored on every version.
- :func:`ensure_cpu_devices` — best-effort "run on the virtual n-device CPU
  host platform" on any JAX version (``jax_num_cpu_devices`` where it
  exists, the ``XLA_FLAGS`` host-device-count flag where it doesn't),
  tolerating already-initialized backends. The single home for an idiom
  that conftest, ``__graft_entry__``, measure_baseline and the multihost
  test workers previously each hand-rolled.
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Sequence

import jax
from jax import lax

_raw_shard_map = getattr(jax, "shard_map", None)
if _raw_shard_map is None:  # pragma: no cover - exercised on older JAX images
    from jax.experimental.shard_map import shard_map as _raw_shard_map

try:
    _PRE_VMA_SHARD_MAP = "check_rep" in inspect.signature(_raw_shard_map).parameters
except (TypeError, ValueError):  # pragma: no cover - unsignaturable builtin
    _PRE_VMA_SHARD_MAP = not hasattr(lax, "pcast")

if _PRE_VMA_SHARD_MAP:  # pragma: no cover - exercised on older JAX images

    def shard_map(f, **kwargs):
        # check_vma is the current-JAX spelling; pre-vma shard_map (whether
        # importable as jax.shard_map or only from jax.experimental) calls
        # the weaker analog check_rep — and it must default OFF here: its
        # conservative checker has no pcast to learn that a scan carry
        # re-replicates, and check_rep=True would ALSO flip the AD
        # psum-insertion behavior out from under psum_if_no_auto below.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        else:
            kwargs.setdefault("check_rep", False)
        return _raw_shard_map(f, **kwargs)

else:
    shard_map = _raw_shard_map


# Does jax.grad INSIDE shard_map auto-insert the psum that keeps the
# gradient of an axis-unvarying input consistent across shards? Under the
# vma system it does; under a pre-vma shard_map run with check_rep=False
# (how the wrapper above always runs it) the cotangent stays shard-LOCAL,
# and every in-mesh gradient step must insert the psum itself (fedavg_mesh,
# spatial) or silently train on 1/n-weighted shard-local gradients whenever
# an inner data-parallel axis is wider than one shard. Keyed on the SAME
# probe as the wrapper so the two decisions can never disagree (a JAX
# window with public jax.shard_map but no vma system gets the wrapper AND
# the explicit psum together).
AD_PSUMS_UNVARYING_COTANGENTS = not _PRE_VMA_SHARD_MAP


def psum_if_no_auto(tree: Any, axes: Sequence[str]) -> Any:
    """Explicit replacement for the vma AD psum on pre-vma JAX: psum the
    gradient tree over ``axes``; identity where AD already did it."""
    if AD_PSUMS_UNVARYING_COTANGENTS or not axes:
        return tree
    return lax.psum(tree, tuple(axes))


def pcast_varying(x: Any, axes: Sequence[str]) -> Any:
    """Promote ``x`` to varying over ``axes`` where vma tracking exists;
    no-op on pre-vma JAX."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    return x


def typeof_vma(x: Any) -> frozenset:
    """The varying-manual-axes set of ``x``'s abstract value; empty where
    the vma system (``jax.typeof``) doesn't exist."""
    if hasattr(jax, "typeof"):
        return getattr(jax.typeof(x), "vma", frozenset())
    return frozenset()


def shape_dtype_struct(shape, dtype, vma: frozenset = frozenset()):
    """``jax.ShapeDtypeStruct`` carrying ``vma`` where supported (required
    for pallas_call outputs under check_vma shard_map); plain struct
    otherwise."""
    if vma and hasattr(jax, "typeof"):
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # pragma: no cover - vma kwarg not accepted
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def ensure_cpu_devices(n: int | None = None) -> None:
    """Best-effort: route this process onto the CPU host platform with ``n``
    virtual devices (``n=None`` leaves the device count alone).

    Must run before first backend use to take effect; once backends are
    initialized the config updates raise RuntimeError and this becomes a
    no-op (callers that need a hard guarantee should check
    ``len(jax.devices())`` afterwards — which itself initializes the
    backend, so only do that LAST). On JAX without ``jax_num_cpu_devices``
    the count rides the ``XLA_FLAGS`` host-device flag, which XLA reads at
    backend initialization — still in the future at that point, or the
    config update would have raised RuntimeError instead of AttributeError.
    """
    try:
        if n is not None:
            # Count first: it is the update that raises RuntimeError once
            # backends are initialized, leaving jax_platforms untouched.
            jax.config.update("jax_num_cpu_devices", n)
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backends already initialized; run where we are
    except AttributeError:
        if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass


def fp8_dtypes():
    """``(weight_dtype, grad_dtype)`` — fp8 e4m3 for weights, e5m2 for
    gradients (the Micikevicius et al. split the kernel plane follows) —
    or ``None`` where this jax build ships neither."""
    import jax.numpy as jnp

    e4m3 = getattr(jnp, "float8_e4m3fn", None)
    e5m2 = getattr(jnp, "float8_e5m2", None)
    if e4m3 is None or e5m2 is None:  # pragma: no cover - ancient jax
        return None
    return (e4m3, e5m2)


_FP8_PROBE: bool | None = None


def fp8_supported() -> bool:
    """Whether fp8 codes actually round-trip on this backend (dtypes exist
    AND a tiny cast runs) — probed once, cached. The kernel plane resolves
    ``kernel_plane="fp8"`` through this: unsupported degrades to the r17
    int8 reference path bit-exactly (engine.py). Tests monkeypatch this
    function to pin the degraded path, so callers must resolve it
    DYNAMICALLY (``jaxcompat.fp8_supported()``, never a cached import)."""
    global _FP8_PROBE
    if _FP8_PROBE is None:
        dts = fp8_dtypes()
        if dts is None:  # pragma: no cover - ancient jax
            _FP8_PROBE = False
        else:
            try:
                import jax.numpy as jnp
                import numpy as np

                got = np.asarray(
                    jnp.asarray([1.0, -2.5], jnp.float32)
                    .astype(dts[0])
                    .astype(jnp.float32)
                )
                _FP8_PROBE = bool(np.all(np.isfinite(got)))
            except Exception:  # pragma: no cover - backend refuses fp8
                _FP8_PROBE = False
    return _FP8_PROBE


def is_distributed_initialized() -> bool:
    """Whether this process runs inside an initialized jax.distributed job.
    Reads ``jax.distributed.is_initialized`` dynamically (monkeypatchable);
    falls back to the 0.4.x ``global_state.client`` probe."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    state = getattr(jax.distributed, "global_state", None)  # pragma: no cover
    return getattr(state, "client", None) is not None  # pragma: no cover


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent XLA compilation cache at ``cache_dir`` (the
    round-17 cold-start killer: replica boots, CI sessions and repeat bench
    runs reuse compiled programs instead of paying XLA again — BENCH_r03
    died at rc 124 on exactly that wall).

    The entry-size/compile-time floors are dropped to 0 so even the tiny
    CPU-smoke programs cache (the knobs exist on 0.4.x under these names;
    older builds without them still get the directory cache). Returns
    whether the cache directory was accepted."""
    import os

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # pragma: no cover - ancient jax without the knob
        return False
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # pragma: no cover - knob renamed/missing
            pass
    # The cache singleton initializes lazily at the FIRST compile; a process
    # that already compiled something (tests, a warm harness) latched it in
    # the disabled state — reset so the new directory takes effect.
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # pragma: no cover - internal API moved
        pass
    return True
