"""Per-client update ledger + robust anomaly scoring (round 18).

Every aggregation tier (root sync rounds, FedBuff buffered offers, edge
aggregators) already routes uploads through the ONE shared acceptance gate
(``fed.rounds.decode_and_validate_update``). The ledger is the bounded,
deterministic rolling record of what each client did at that gate — offers,
accepted/rejected-by-class counts, resyncs, sample counts, wire bytes — plus
the update GEOMETRY sanitation cannot see: the L2 norm of each accepted
update (computed at the gate, from the already-decoded tree) and its cosine
to the cohort-mean update (computed once per flush, over the same decoded
trees the fold averages).

Anomaly score: at each flush, a robust z-score over the flush cohort's
norms and cosines — ``z = |x - median| / (1.4826 * MAD + eps)`` per signal,
score = max over the two signals, capped at :data:`SCORE_CAP`. Median/MAD
(not mean/std) so one adversary cannot drag the baseline it is judged
against; ``eps`` scales with the median so honest float jitter never flags.
A finite, shape-correct update scaled by x1000 (chaos ``SCALED_UPDATE``)
passes sanitation but lands a score orders of magnitude past
:data:`ANOMALY_ALERT` — the measured bridge to the ROADMAP's trust-plane
item (Blanchard et al.'s Krum threat model).

Everything here is a pure function over plain dicts (copy-on-write, like
the round machines): the ledger lives as a field on the immutable server
state, persists canonically-sorted in the r8 statefile
(:func:`ledger_to_wire`), and exports as bounded-cardinality metrics
(:func:`export_anomaly_metrics` — the ONE place a client name may become a
metric label; fedlint HEALTH001 enforces the chokepoint) plus deterministic
JSONL (:func:`write_ledger_jsonl`).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable, Mapping

import numpy as np

# Rolling window of per-flush (norm, cosine) samples kept per client — the
# statefile carries the ledger, so the record must stay O(1) per client.
LEDGER_WINDOW = 8
# Robust-z alert threshold: the classic |z| >= 3.5 outlier cutoff
# (Iglewicz & Hoaglin). configs/slo_health.json mirrors it.
ANOMALY_ALERT = 3.5
# Scores are capped so a zero-MAD cohort cannot mint astronomically large
# (but still finite) exposition values.
SCORE_CAP = 1e6
# Bounded metric-label cardinality: at most this many distinct client label
# values; everyone past the cap (sorted order) collapses into "_overflow".
MAX_CLIENT_LABELS = 32

_REJECT_KEYS = ("not_in_cohort", "stale", "sanitation", "other")
_OUTCOMES = ("accepted", "rejected", "resync")


def new_record() -> dict:
    """One client's empty ledger record (fixed key set — the wire codec and
    the JSONL export both iterate it in this order)."""
    return {
        "offers": 0,
        "accepted": 0,
        "resyncs": 0,
        "samples": 0,
        "wire_bytes": 0,
        "rejected": {},            # reason class -> count
        "last_round": 0,
        "last_staleness": 0,
        "norms": [],               # last LEDGER_WINDOW update L2 norms
        "cosines": [],             # last LEDGER_WINDOW cosines-to-cohort-mean
        "anomaly": 0.0,            # robust z at the most recent flush
        "flags": 0,                # flushes where anomaly >= ANOMALY_ALERT
        # Round 21: flushes this client was EXCLUDED from by the ledger-
        # coupled quarantine (FedConfig.quarantine_z). Outside the
        # `conservation` identity by design: a quarantined update passed
        # the acceptance gate (its offer is already counted "accepted");
        # quarantine is a flush-time fold decision, not a gate verdict.
        "quarantined": 0,
    }


def _flat(tree: Any) -> np.ndarray:
    import jax

    leaves = [
        np.asarray(leaf, np.float32).ravel()
        for leaf in jax.tree_util.tree_leaves(tree)
    ]
    return np.concatenate(leaves) if leaves else np.zeros(0, np.float32)


def update_norm(tree: Any, base_tree: Any) -> float:
    """L2 norm of (update - base) over every leaf — the gate-time geometry
    sample. Deterministic: pure numpy over the decoded trees, rounded so
    the persisted ledger bytes are stable."""
    delta = _flat(tree) - _flat(base_tree)
    return round(float(np.linalg.norm(delta)), 6)


def record_offer(
    ledger: Mapping[str, dict],
    cname: str,
    *,
    outcome: str,
    reason_class: str | None = None,
    num_samples: int = 0,
    wire_len: int = 0,
    staleness: int = 0,
    round: int = 0,
    norm: float | None = None,
) -> dict:
    """Fold one gate verdict into the ledger (copy-on-write; the input
    mapping is never mutated). ``outcome`` is 'accepted' | 'rejected' |
    'resync'; rejected offers carry a bounded ``reason_class`` (the r15
    label-cardinality discipline — never the raw reason string)."""
    if outcome not in _OUTCOMES:
        raise ValueError(f"unknown ledger outcome {outcome!r}")
    out = dict(ledger)
    rec = dict(out.get(cname) or new_record())
    rec["offers"] += 1
    rec["last_round"] = int(round)
    if outcome == "accepted":
        rec["accepted"] += 1
        rec["samples"] += max(0, int(num_samples))
        rec["wire_bytes"] += max(0, int(wire_len))
        rec["last_staleness"] = int(staleness)
        if norm is not None:
            # np.round, not round(): the `round` kwarg shadows the builtin.
            rec["norms"] = (
                list(rec["norms"]) + [float(np.round(float(norm), 6))]
            )[-LEDGER_WINDOW:]
    elif outcome == "resync":
        rec["resyncs"] += 1
    else:
        key = reason_class if reason_class in _REJECT_KEYS else "other"
        rejected = dict(rec["rejected"])
        rejected[key] = rejected.get(key, 0) + 1
        rec["rejected"] = rejected
    out[cname] = rec
    return out


def record_quarantine(ledger: Mapping[str, dict], cname: str) -> dict:
    """Fold one flush-time quarantine decision into the ledger (copy-on-
    write): the named client's accepted-but-excluded counter. Called by the
    round machines right after :func:`observe_flush` hands them the scores
    that crossed ``FedConfig.quarantine_z``."""
    out = dict(ledger)
    rec = dict(out.get(cname) or new_record())
    rec["quarantined"] = int(rec.get("quarantined", 0)) + 1
    out[cname] = rec
    return out


def cohort_geometry(
    items: Iterable[tuple[str, Any]], base_tree: Any
) -> list[tuple[str, float, float]]:
    """Per-update (name, norm, cosine-to-cohort-mean-delta) over one flush's
    decoded trees, all against one base (the global the flush averages onto).
    Deterministic: items are processed in the given order but the mean is
    order-independent; callers pass the fold's sorted order."""
    items = list(items)
    if not items:
        return []
    base = _flat(base_tree)
    deltas = [_flat(tree) - base for _, tree in items]
    mean = np.mean(np.stack(deltas), axis=0)
    mean_norm = float(np.linalg.norm(mean))
    out = []
    for (name, _), delta in zip(items, deltas):
        norm = float(np.linalg.norm(delta))
        if norm > 0.0 and mean_norm > 0.0:
            cos = float(np.dot(delta, mean) / (norm * mean_norm))
        else:
            # A zero update agrees perfectly with a zero mean and carries no
            # direction against a non-zero one.
            cos = 1.0 if norm == mean_norm else 0.0
        out.append((name, round(norm, 6), round(max(-1.0, min(1.0, cos)), 6)))
    return out


def robust_z(values: list[float]) -> list[float]:
    """Median/MAD z-scores, eps-guarded and capped (see module docstring).
    A 0- or 1-element window scores 0.0 — there is no cohort to deviate
    from."""
    if len(values) < 2:
        return [0.0] * len(values)
    arr = np.asarray(values, np.float64)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    denom = 1.4826 * mad + max(1e-6, 1e-3 * abs(med))
    return [
        round(min(SCORE_CAP, abs(v - med) / denom), 6) for v in arr.tolist()
    ]


def observe_flush(
    ledger: Mapping[str, dict],
    items: Iterable[tuple[str, Any]],
    base_tree: Any,
) -> tuple[dict, dict]:
    """The per-flush geometry pass: cosines vs the cohort-mean update,
    robust-z anomaly scores across THIS flush's updates, windows appended.
    Returns ``(new_ledger, {cname: score})``. One client may contribute
    several buffered entries to a flush; its score is the max over them."""
    geometry = cohort_geometry(items, base_tree)
    if not geometry:
        return dict(ledger), {}
    z_norm = robust_z([g[1] for g in geometry])
    z_cos = robust_z([g[2] for g in geometry])
    scores: dict[str, float] = {}
    cosines: dict[str, list[float]] = {}
    for (name, _norm, cos), zn, zc in zip(geometry, z_norm, z_cos):
        score = round(max(zn, zc), 6)
        scores[name] = max(score, scores.get(name, 0.0))
        cosines.setdefault(name, []).append(cos)
    out = dict(ledger)
    for name in sorted(scores):
        rec = dict(out.get(name) or new_record())
        rec["cosines"] = (list(rec["cosines"]) + cosines[name])[-LEDGER_WINDOW:]
        rec["anomaly"] = scores[name]
        if scores[name] >= ANOMALY_ALERT:
            rec["flags"] += 1
        out[name] = rec
    return out, scores


# ---- persistence (the r8 canonical-statefile discipline) ----

def ledger_to_wire(ledger: Mapping[str, dict]) -> list:
    """Canonical wire rows, sorted by client name with a fixed positional
    field order — statefile bytes stay a pure function of the state."""
    rows = []
    for name in sorted(ledger):
        rec = ledger[name]
        rows.append([
            str(name),
            int(rec["offers"]),
            int(rec["accepted"]),
            int(rec["resyncs"]),
            int(rec["samples"]),
            int(rec["wire_bytes"]),
            int(rec["last_round"]),
            int(rec["last_staleness"]),
            float(rec["anomaly"]),
            int(rec["flags"]),
            [[k, int(rec["rejected"][k])] for k in sorted(rec["rejected"])],
            [float(x) for x in rec["norms"]],
            [float(x) for x in rec["cosines"]],
            # Field 14 (round 21); readers accept 13-field r18 rows.
            int(rec.get("quarantined", 0)),
        ])
    return rows


def ledger_from_wire(rows: Iterable) -> dict:
    out: dict[str, dict] = {}
    for row in rows or []:
        rec = new_record()
        (
            name, rec["offers"], rec["accepted"], rec["resyncs"],
            rec["samples"], rec["wire_bytes"], rec["last_round"],
            rec["last_staleness"], rec["anomaly"], rec["flags"],
            rejected, norms, cosines,
        ) = row[:13]
        # r18 statefiles carry 13-field rows; round 21 appended the
        # quarantined counter (missing = 0 via new_record).
        if len(row) > 13:
            rec["quarantined"] = int(row[13])
        rec["rejected"] = {str(k): int(v) for k, v in rejected}
        rec["norms"] = [float(x) for x in norms]
        rec["cosines"] = [float(x) for x in cosines]
        rec["anomaly"] = float(rec["anomaly"])
        out[str(name)] = rec
    return out


# ---- bounded-cardinality export (the HEALTH001 chokepoint) ----

def client_label(cname: str, rank: int) -> str:
    """The bounded label value for one client: its own name while the
    family stays under :data:`MAX_CLIENT_LABELS` children, '_overflow'
    past it. ``rank`` is the client's position in the sorted ledger."""
    return str(cname) if rank < MAX_CLIENT_LABELS else "_overflow"


def export_anomaly_metrics(ledger: Mapping[str, dict], registry=None) -> None:
    """Set the anomaly gauges from the ledger — the ONE sanctioned path
    from a client name to a metric label (fedlint HEALTH001). Cardinality
    is bounded by construction: sorted clients past MAX_CLIENT_LABELS
    share the '_overflow' child (max-aggregated). The unlabeled max gauge
    exists for watchdog ceiling rules: the 'value' stat SUMS children
    matching a label subset, so a label-free rule over the per-client
    gauge would add scores instead of bounding them."""
    from fedcrack_tpu.obs.registry import REGISTRY

    reg = registry if registry is not None else REGISTRY
    per_client = reg.gauge(
        "fed_client_anomaly_score_ratio",
        "robust z-score (median/MAD over the flush cohort's update norm and "
        "cosine-to-mean) of each client's latest flushed update; >= 3.5 "
        "flags an outlier sanitation cannot see",
        labels=("client",),
    )
    values: dict[str, float] = {}
    for rank, name in enumerate(sorted(ledger)):
        label = client_label(name, rank)
        score = float(ledger[name].get("anomaly", 0.0))
        values[label] = max(score, values.get(label, 0.0))
    for label in sorted(values):
        per_client.labels(client=label).set(values[label])
    reg.gauge(
        "fed_client_anomaly_max_ratio",
        "max per-client anomaly score at the latest flush (unlabeled "
        "ceiling series for configs/slo_health.json)",
    ).set(max(values.values()) if values else 0.0)


def write_ledger_jsonl(ledger: Mapping[str, dict], path: str) -> int:
    """Deterministic JSONL dump: one sorted line per client, sorted keys,
    no timestamps — two ledgers with equal state produce byte-identical
    files. Returns the number of rows written."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    rows = 0
    with open(path, "w", encoding="utf-8") as f:
        for name in sorted(ledger):
            rec = ledger[name]
            line = {"client": str(name)}
            for key in sorted(rec):
                value = rec[key]
                if key == "rejected":
                    value = {k: int(value[k]) for k in sorted(value)}
                line[key] = value
            f.write(json.dumps(line, sort_keys=True) + "\n")
            rows += 1
    return rows


def read_ledger_jsonl(path: str) -> dict:
    """Inverse of :func:`write_ledger_jsonl` (tools/health_report.py)."""
    out: dict[str, dict] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            name = row.pop("client")
            rec = new_record()
            rec.update(row)
            out[name] = rec
    return out


def conservation(ledger: Mapping[str, dict]) -> dict:
    """The end-of-soak audit's ledger-conservation check: per client,
    offers == accepted + rejected + resyncs (every gate verdict accounted
    exactly once). Returns {'clients': n, 'violations': [names]}."""
    violations = []
    for name in sorted(ledger):
        rec = ledger[name]
        rejected = sum(int(v) for v in rec["rejected"].values())
        if rec["offers"] != rec["accepted"] + rejected + rec["resyncs"]:
            violations.append(name)
    return {"clients": len(ledger), "violations": violations}
