"""Canary evaluation of new global versions (round 18).

Every hot swap installs weights the serving path will trust completely; the
canary is the held-out quality check that runs OFF that path, against the
same pinned probe oracle the int8 quant gate uses (``serve/quant.py``):
seeded synthetic crack batches at every bucket size, masks compared by IoU.

The REFERENCE is the first version this evaluator sees (typically the boot
weights, installed before traffic): every later version's probe masks are
IoU'd against the reference masks, and the min over buckets becomes the
``model_canary_iou_ratio`` gauge — a time-series a watchdog regression rule
(``configs/slo_health.json``) can bound, with the standard breach contract
(flight-recorder dump, exit 3). A poisoned flush that drags the global
average (chaos ``SCALED_UPDATE``) shows up here as an IoU cliff even though
every averaged update individually passed sanitation.

Contract with the swap path (test-pinned): :meth:`evaluate` is called from
the version manager's POLL thread after the pointer flip, wrapped so a
raising canary can never fail or block an install — the serving path never
pays for it, and ``recompiles_since_warmup`` stays 0 (probe batches reuse
the engine's compiled bucket programs at ``max_batch``).
"""

from __future__ import annotations

import logging
from typing import Any

from fedcrack_tpu.obs import flight
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY

log = logging.getLogger("fedcrack.health.canary")


class CanaryEvaluator:
    """Pinned probe-set IoU tracking across installed global versions.

    Deterministic: the probe batches are seeded (``probe_seed``), buckets
    are evaluated in the engine's fixed bucket order, and the reference is
    whatever version is evaluated first — same install sequence, same
    history. Not thread-safe against concurrent evaluate() calls; the
    version manager's single poll thread is the intended caller.
    """

    def __init__(
        self,
        engine: Any,
        *,
        probe_batch: int | None = None,
        probe_seed: int | None = None,
        history_cap: int = 256,
        registry: Any = None,
        metrics: Any = None,
    ):
        cfg = engine.serve_config
        self.engine = engine
        self.probe_batch = (
            cfg.quant_probe_batch if probe_batch is None else int(probe_batch)
        )
        self.probe_seed = (
            cfg.quant_probe_seed if probe_seed is None else int(probe_seed)
        )
        self._history_cap = history_cap
        self._registry = registry if registry is not None else REGISTRY
        self._metrics = metrics
        self.reference_version: int | None = None
        self._reference_probs: dict[int, Any] = {}
        self.history: list[dict] = []
        self.last: dict | None = None

    def evaluate(self, version: int, device_variables: Any) -> dict:
        """Probe one installed version against the pinned reference.

        ``device_variables`` is the already-prepared payload the swap
        installed (plain tree or QuantizedVariables — the engine routes).
        Returns the eval record; also appends it to ``history``, sets the
        gauge, emits one ``health.canary`` span joined to the version's
        flush lineage, and feeds the flight ring."""
        from fedcrack_tpu.serve.quant import mask_iou, probe_images

        version = int(version)
        fctx = tracing.flush_context(version)
        with tracing.span(
            "health.canary",
            trace=fctx.trace,
            remote_parent=fctx.to_wire(),
            version=version,
        ) as span_handle:
            per_bucket: dict[int, float] = {}
            is_reference = self.reference_version is None
            for size in self.engine.bucket_sizes:
                batch = probe_images(
                    size,
                    min(self.probe_batch, self.engine.max_batch),
                    self.probe_seed,
                )
                probs = self.engine.predict_bucket(device_variables, batch)
                if is_reference:
                    self._reference_probs[size] = probs
                    per_bucket[size] = 1.0
                else:
                    per_bucket[size] = mask_iou(
                        self._reference_probs[size], probs
                    )
            if is_reference:
                self.reference_version = version
            iou = min(per_bucket.values())
            if span_handle is not None:
                span_handle.set(iou=round(iou, 6), reference=is_reference)
        self._registry.gauge(
            "model_canary_iou_ratio",
            "min-over-buckets mask IoU of the installed global version vs "
            "the pinned canary reference on the seeded probe set (1.0 = "
            "identical masks; a regression rule in configs/slo_health.json "
            "bounds it)",
        ).set(iou)
        record = {
            "version": version,
            "iou": round(iou, 6),
            "per_bucket": {str(k): round(v, 6) for k, v in per_bucket.items()},
            "reference_version": int(self.reference_version),
            "probe_batch": self.probe_batch,
            "probe_seed": self.probe_seed,
        }
        self.history.append(record)
        del self.history[: max(0, len(self.history) - self._history_cap)]
        self.last = record
        flight.note(
            "health.canary", version=version, iou=record["iou"],
            reference_version=record["reference_version"],
        )
        if self._metrics is not None:
            self._metrics.log("canary_eval", **record)
        log.info(
            "canary eval v%d: iou=%.4f (reference v%d)",
            version, iou, self.reference_version,
        )
        return record

    def audit(self) -> dict:
        """The end-of-soak 'canary steady' verdict: every eval finite in
        [0, 1] (NOT an IoU floor — tiny randomly-initialized soak models
        produce unstable masks; thresholds belong to the watchdog rules an
        operator arms deliberately)."""
        ious = [h["iou"] for h in self.history]
        return {
            "evals": len(self.history),
            "reference_version": self.reference_version,
            "min_iou": min(ious) if ious else None,
            "all_finite_unit": all(0.0 <= i <= 1.0 for i in ious),
        }
