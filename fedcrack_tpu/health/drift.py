"""Serve-side input/prediction drift detection (round 18).

The serve->train flywheel needs a signal saying "what the fleet is seeing
no longer looks like what the model was trained/validated on". This module
profiles served traffic per bucket — input intensity distribution (plus
scalar mean/std), prediction confidence and entropy histograms, and the
``tools/quantify.py`` contour-derived crack-fraction distribution — and
compares a live profile against a FROZEN reference captured at install
time via the population stability index:

    PSI = sum_i (p_i - q_i) * ln(p_i / q_i)

over eps-smoothed bin fractions (fixed bins on [0, 1], so two profiles are
always comparable). The usual reading: < 0.1 stable, 0.1-0.25 drifting,
> 0.25 shifted — but the number is exported per (bucket, signal) as the
``serve_drift_psi_ratio`` gauge and thresholds belong to watchdog rules.

Everything is OFF the serving hot path: the monitor consumes request
results AFTER their futures resolve (the soak's load loop, a sidecar, or a
batch job), never inside the batcher. Deterministic: fixed bin edges,
accumulation is order-independent (counts and sums), outputs rounded.

The contour stats reuse :func:`fedcrack_tpu.tools.quantify.quantify_mask`,
whose cv2 import is gated — without OpenCV the crack_fraction signal is
simply absent from profiles and comparisons (never a crash, never a fake
zero).
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Mapping

import numpy as np

N_BINS = 10
_EDGES = np.linspace(0.0, 1.0, N_BINS + 1)
# Histogram signals a profile may carry (crack_fraction only with cv2).
SIGNALS = ("input", "confidence", "entropy", "crack_fraction")


def _hist(values: np.ndarray) -> list[int]:
    """Fixed-bin counts over [0, 1]; values are clipped in (drift past the
    domain must still land in the edge bins, not vanish)."""
    clipped = np.clip(np.asarray(values, np.float64).ravel(), 0.0, 1.0)
    counts, _ = np.histogram(clipped, bins=_EDGES)
    return [int(c) for c in counts]


def psi(
    ref_counts: Any, cur_counts: Any, eps: float = 1e-4
) -> float:
    """Population stability index between two same-length count vectors.

    Closed form over eps-smoothed fractions: both distributions are
    normalized to sum 1 AFTER adding ``eps`` per bin, so empty bins never
    divide by zero and PSI(x, x) == 0 exactly."""
    ref = np.asarray(ref_counts, np.float64)
    cur = np.asarray(cur_counts, np.float64)
    if ref.shape != cur.shape:
        raise ValueError(f"bin count mismatch: {ref.shape} vs {cur.shape}")
    p = (ref + eps) / float(np.sum(ref + eps))
    q = (cur + eps) / float(np.sum(cur + eps))
    return round(float(np.sum((q - p) * np.log(q / p))), 6)


def _crack_fractions(probs: np.ndarray) -> list[float] | None:
    """Per-image contour-derived crack fraction via tools/quantify.py, or
    None when OpenCV is unavailable (the signal is then omitted)."""
    try:
        from fedcrack_tpu.tools.quantify import quantify_mask
    except Exception:
        return None
    out = []
    try:
        for img_probs in probs:
            mask_u8 = (
                np.clip(np.asarray(img_probs, np.float32), 0.0, 1.0) * 255.0
            ).astype(np.uint8)
            stats = quantify_mask(mask_u8[..., 0] if mask_u8.ndim == 3 else mask_u8)
            out.append(float(stats.crack_fraction))
    except Exception:
        # quantify_mask imports cv2 lazily; ImportError surfaces here.
        return None
    return out


class DriftMonitor:
    """Accumulates per-bucket traffic profiles; compares against a frozen
    reference profile.

    Thread-safety: ``observe`` does plain adds on python ints/lists under
    no lock — call it from ONE consumer (the soak's load loop resolves
    futures in its own thread; that thread observes)."""

    def __init__(self, reference: Mapping | None = None):
        self.reference = dict(reference) if reference else None
        self._buckets: dict[int, dict] = {}

    @staticmethod
    def _empty_bucket() -> dict:
        return {
            "n_images": 0,
            "input_sum": 0.0,
            "input_sumsq": 0.0,
            "input_n": 0,
            "hist": {s: [0] * N_BINS for s in SIGNALS if s != "crack_fraction"},
            "crack_hist": None,  # [0]*N_BINS once cv2 produced a sample
        }

    def observe(self, images_u8: np.ndarray, probs: np.ndarray) -> None:
        """Fold one answered request/batch into the live profile.

        ``images_u8``: [B, S, S, 3] (or [S, S, 3]) uint8 inputs;
        ``probs``: matching [B, S, S, 1] (or [S, S, 1]) float probabilities.
        Bucket key is the spatial size S."""
        images = np.asarray(images_u8)
        p = np.asarray(probs, np.float32)
        if images.ndim == 3:
            images = images[None]
        if p.ndim == 3:
            p = p[None]
        size = int(images.shape[1])
        b = self._buckets.setdefault(size, self._empty_bucket())
        x = images.astype(np.float64) / 255.0
        b["n_images"] += int(images.shape[0])
        b["input_sum"] += float(np.sum(x))
        b["input_sumsq"] += float(np.sum(x * x))
        b["input_n"] += int(x.size)
        pc = np.clip(p, 1e-7, 1.0 - 1e-7)
        confidence = np.maximum(pc, 1.0 - pc)
        # Bernoulli entropy normalized to [0, 1] by ln 2.
        entropy = -(
            pc * np.log(pc) + (1.0 - pc) * np.log(1.0 - pc)
        ) / math.log(2.0)
        for signal, values in (
            ("input", x), ("confidence", confidence), ("entropy", entropy)
        ):
            counts = _hist(values)
            b["hist"][signal] = [
                a + c for a, c in zip(b["hist"][signal], counts)
            ]
        fractions = _crack_fractions(p)
        if fractions is not None:
            if b["crack_hist"] is None:
                b["crack_hist"] = [0] * N_BINS
            counts = _hist(np.asarray(fractions))
            b["crack_hist"] = [a + c for a, c in zip(b["crack_hist"], counts)]

    # ---- profiles ----

    def profile(self) -> dict:
        """The canonical (sorted, rounded) profile dict — JSON-safe, what
        the statefile-adjacent artifacts persist and PSI compares."""
        buckets = {}
        for size in sorted(self._buckets):
            b = self._buckets[size]
            n = max(1, b["input_n"])
            mean = b["input_sum"] / n
            var = max(0.0, b["input_sumsq"] / n - mean * mean)
            hist = {s: list(b["hist"][s]) for s in sorted(b["hist"])}
            if b["crack_hist"] is not None:
                hist["crack_fraction"] = list(b["crack_hist"])
            buckets[str(size)] = {
                "n_images": b["n_images"],
                "input_mean": round(mean, 6),
                "input_std": round(math.sqrt(var), 6),
                "hist": hist,
            }
        return {"bins": N_BINS, "buckets": buckets}

    @classmethod
    def capture_reference(
        cls, engine: Any, device_variables: Any, *, n: int | None = None,
        seed: int | None = None,
    ) -> dict:
        """The frozen install-time reference: the pinned probe set (same
        oracle as the canary/quant gate) pushed through the engine at every
        bucket size, profiled once. Pure function of (weights, seed)."""
        from fedcrack_tpu.serve.quant import probe_images

        cfg = engine.serve_config
        n = cfg.quant_probe_batch if n is None else int(n)
        seed = cfg.quant_probe_seed if seed is None else int(seed)
        monitor = cls()
        for size in engine.bucket_sizes:
            batch = probe_images(size, min(n, engine.max_batch), seed)
            probs = engine.predict_bucket(device_variables, batch)
            monitor.observe(batch, probs)
        return monitor.profile()

    def compare(self, reference: Mapping | None = None) -> dict:
        """Per-(bucket, signal) PSI of the live profile vs the reference.
        Only (bucket, signal) pairs present in BOTH profiles compare —
        missing traffic or a cv2-less crack signal is absence, not drift.
        Returns {'<bucket>/<signal>': psi}."""
        ref = reference if reference is not None else self.reference
        if not ref:
            return {}
        current = self.profile()
        out: dict[str, float] = {}
        for size in sorted(current["buckets"]):
            if size not in ref.get("buckets", {}):
                continue
            cur_hist = current["buckets"][size]["hist"]
            ref_hist = ref["buckets"][size]["hist"]
            for signal in sorted(set(cur_hist) & set(ref_hist)):
                out[f"{size}/{signal}"] = psi(
                    ref_hist[signal], cur_hist[signal]
                )
        return out


def export_drift_metrics(psis: Mapping[str, float], registry=None) -> None:
    """Per-(bucket, signal) PSI gauges — cardinality bounded by
    construction (buckets x 4 signals)."""
    from fedcrack_tpu.obs.registry import REGISTRY

    reg = registry if registry is not None else REGISTRY
    gauge = reg.gauge(
        "serve_drift_psi_ratio",
        "population stability index of live serve traffic vs the frozen "
        "install-time reference profile, per (bucket, signal); < 0.1 "
        "stable, > 0.25 shifted",
        labels=("bucket", "signal"),
    )
    for key in sorted(psis):
        bucket, signal = key.split("/", 1)
        gauge.labels(bucket=bucket, signal=signal).set(float(psis[key]))


def write_drift_json(
    path: str, *, reference: Mapping | None, current: Mapping | None,
    psis: Mapping[str, float] | None,
) -> None:
    """The soak's drift artifact: reference + live profile + comparison in
    one deterministic JSON document (sorted keys, no timestamps)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    doc = {
        "reference": dict(reference) if reference else None,
        "current": dict(current) if current else None,
        "psi": {k: float(v) for k, v in (psis or {}).items()},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
