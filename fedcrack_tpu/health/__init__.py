"""Federation health plane (round 18): model/cohort quality observability.

Rounds 15-16 built the *operational* telemetry (latency, bytes, versions,
recompiles); nothing observed whether the MODEL or the COHORT is healthy. A
sanitation-passing but adversarially-scaled update averages in silently, a
global version that regresses held-out IoU hot-swaps into the fleet
unnoticed, and the serve plane had no drift signal for the serve->train
flywheel. This package is the quality layer over the same pipes:

- :mod:`fedcrack_tpu.health.ledger` — the per-client update ledger fed by
  every aggregation tier's acceptance gate, plus robust (median/MAD)
  anomaly scoring over update geometry at each flush.
- :mod:`fedcrack_tpu.health.canary` — pinned held-out probe evaluation of
  every new global version, off the serving hot path.
- :mod:`fedcrack_tpu.health.drift` — per-bucket serve-input/prediction
  profiles compared via population stability index against a frozen
  install-time reference.
"""

from fedcrack_tpu.health.ledger import (  # noqa: F401
    ANOMALY_ALERT,
    LEDGER_WINDOW,
    cohort_geometry,
    export_anomaly_metrics,
    ledger_from_wire,
    ledger_to_wire,
    new_record,
    observe_flush,
    record_offer,
    update_norm,
    write_ledger_jsonl,
)
