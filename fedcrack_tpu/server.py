"""Federation coordinator entry point: ``python -m fedcrack_tpu.server``.

The reference equivalent is ``python fl_server.py`` (fl_server.py:229-232):
build the global model, then serve. Configuration comes from flags or a JSON
config file instead of editing module globals (SURVEY.md §5.6).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
from typing import Any

import jax

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.train.local import create_train_state
from fedcrack_tpu.transport.service import FedServer


def build_config(argv: list[str] | None = None) -> tuple[FedConfig, Any]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="JSON FedConfig file (flags override it)")
    p.add_argument("--rounds", type=int, help="max federation rounds")
    p.add_argument("--cohort", type=int, help="target cohort size")
    p.add_argument("--port", type=int)
    p.add_argument("--host")
    p.add_argument("--registration-window", type=float, dest="registration_window_s")
    p.add_argument("--round-deadline", type=float, dest="round_deadline_s")
    p.add_argument(
        "--quorum-fraction",
        type=float,
        dest="quorum_fraction",
        help="aggregate at ceil(f * cohort) received updates instead of the "
        "full barrier (Bonawitz et al.); stragglers are re-synced, the "
        "round deadline stays as backstop; 1.0 = full barrier",
    )
    p.add_argument(
        "--state-path",
        dest="state_path",
        help="mid-round durable server state (atomic msgpack snapshot of "
        "cohort/phase/received): a server killed mid-round resumes the "
        "SAME round with the already-received updates intact",
    )
    p.add_argument(
        "--mode",
        dest="mode",
        help="federation mode: sync (barrier rounds, the default) or "
        "buffered (FedBuff async aggregation — updates fold into a "
        "K-sized staleness-weighted buffer as they arrive; no round "
        "barrier, clients loop pull->train->push continuously)",
    )
    p.add_argument(
        "--buffer-k",
        type=int,
        dest="buffer_k",
        help="buffered mode: flush to a new global version after this many "
        "accepted updates (FedBuff's K); buffer_k = cohort with "
        "staleness-alpha 0 reproduces sync FedAvg bit-exactly",
    )
    p.add_argument(
        "--staleness-alpha",
        type=float,
        dest="staleness_alpha",
        help="buffered mode: polynomial staleness decay exponent — an "
        "update s versions stale weighs ns * (1+s)^-alpha (FedAsync); "
        "0 disables decay",
    )
    p.add_argument(
        "--max-staleness",
        type=int,
        dest="max_staleness",
        help="buffered mode: updates staler than this many versions are "
        "rejected into the history and the sender re-synced; also bounds "
        "the retained past-broadcast window for delta decode",
    )
    p.add_argument("--fedprox-mu", type=float, dest="fedprox_mu")
    p.add_argument(
        "--pos-weight",
        type=float,
        dest="pos_weight",
        help="crack-pixel BCE weight for every client's local fit (>1 "
        "counters the foreground imbalance; 1 = reference's plain BCE)",
    )
    p.add_argument(
        "--aggregation",
        dest="aggregation",
        help="how accepted updates combine (fed/aggregation.py): fedavg "
        "(sample-weighted mean, the default), trimmed_mean, median/"
        "coordinate_median, krum, multi_krum — the robust combines ignore "
        "client-reported sample counts",
    )
    p.add_argument(
        "--trim-fraction",
        type=float,
        dest="trim_fraction",
        help="trimmed_mean's beta: drop floor(beta*n) per coordinate from "
        "each tail; [0, 0.5)",
    )
    p.add_argument(
        "--byzantine-f",
        type=int,
        dest="byzantine_f",
        help="krum/multi_krum's assumed Byzantine count f",
    )
    p.add_argument(
        "--quarantine-z",
        type=float,
        dest="quarantine_z",
        help="exclude a client from the fold when its flush-time robust-z "
        "anomaly score reaches this threshold (0 disables; 3.5 matches "
        "the ledger's alert line)",
    )
    p.add_argument(
        "--secagg",
        dest="secagg",
        action="store_const",
        const=True,
        default=None,
        help="pairwise-mask secure aggregation (privacy plane, round 23): "
        "the cohort uploads fixed-point masked updates whose masks cancel "
        "exactly in the fold; a dropped masker is recovered from its "
        "enroll-time seed. Requires aggregation=fedavg, quarantine_z=0 "
        "and update_codec=null (validated loudly)",
    )
    p.add_argument(
        "--secagg-bits",
        type=int,
        dest="secagg_bits",
        help="fixed-point fractional bits for masked uploads (default 24)",
    )
    p.add_argument(
        "--dp-clip-norm",
        type=float,
        dest="dp_clip_norm",
        help="DP-SGD per-step L2 clip norm C for the cohort's local fits "
        "(0 disables the DP twin; required > 0 when noise is on)",
    )
    p.add_argument(
        "--dp-noise-multiplier",
        type=float,
        dest="dp_noise_multiplier",
        help="DP-SGD Gaussian noise multiplier sigma: per-step noise is "
        "N(0, (sigma*C)^2); drives the RDP accountant's per-client "
        "epsilon in round history",
    )
    p.add_argument(
        "--dp-sample-rate",
        type=float,
        dest="dp_sample_rate",
        help="accountant's per-step subsampling rate q (default 0.01)",
    )
    p.add_argument(
        "--dp-delta",
        type=float,
        dest="dp_delta",
        help="accountant's target delta (default 1e-5)",
    )
    p.add_argument(
        "--dp-steps-per-round",
        type=int,
        dest="dp_steps_per_round",
        help="noise steps the accountant charges each contributor per "
        "round close (default 0 = local_epochs)",
    )
    p.add_argument(
        "--dp-seed",
        type=int,
        dest="dp_seed",
        help="root seed of the per-(client, round, leaf) DP noise key "
        "chain (kept in the persisted config; clients pass their own "
        "--dp-seed, which must match for a coherent replay story)",
    )
    p.add_argument(
        "--dp-epsilon-budget",
        type=float,
        dest="dp_epsilon_budget",
        help="refuse further rounds once any client's accounted epsilon "
        "reaches this budget (0 = unlimited)",
    )
    p.add_argument(
        "--privacy-summary",
        dest="privacy_summary_path",
        help="write the final privacy summary (per-client epsilon, secagg "
        "roster facts) as JSON here at federation end",
    )
    p.add_argument(
        "--server-optimizer",
        dest="server_optimizer",
        help="FedOpt server update: avg (plain FedAvg), momentum/fedavgm, "
        "adam/fedadam, yogi/fedyogi",
    )
    p.add_argument("--server-lr", type=float, dest="server_lr")
    p.add_argument("--server-momentum", type=float, dest="server_momentum")
    p.add_argument(
        "--wire-dtype",
        dest="wire_dtype",
        help="weight payload dtype on the control plane: float32 or "
        "bfloat16 (halves upload+broadcast bytes; server math stays f32)",
    )
    p.add_argument(
        "--update-codec",
        dest="update_codec",
        help="compressed update transport (fedcrack_tpu/compress): null "
        "(today's raw bytes, bit-exact), int8 (quantized round delta), or "
        "topk_delta (top-k sparsified delta with client-side error "
        "feedback); advertised to the cohort in-band at enroll",
    )
    p.add_argument(
        "--topk-fraction",
        type=float,
        dest="topk_fraction",
        help="topk_delta keep fraction per leaf (default 0.01 = ~50x fewer "
        "upload bytes before framing)",
    )
    p.add_argument(
        "--max-message-mb",
        type=int,
        dest="max_message_mb",
        help="gRPC send/receive cap in MiB, both directions (the reference "
        "hardcoded 512 for full-weight pickles); startup asserts the "
        "worst-case weight message under the configured codec fits",
    )
    p.add_argument("--seed", type=int, help="PRNG seed for the initial global model")
    p.add_argument(
        "--ckpt-dir",
        dest="ckpt_dir",
        help="orbax checkpoint directory; when it already holds a checkpoint "
        "the federation resumes from the latest round (SURVEY.md §5.4)",
    )
    p.add_argument(
        "--metrics",
        dest="metrics_path",
        help="JSONL file for structured per-round metrics (SURVEY.md §5.5)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        dest="metrics_port",
        default=0,
        help="serve the live metric registry as Prometheus text format on "
        "http://127.0.0.1:<port>/metrics (round 15 telemetry plane); "
        "0 disables, -1 binds an ephemeral port (logged)",
    )
    p.add_argument(
        "--spans-path",
        dest="spans_path",
        help="JSONL trace-span sink (fed.flush / client.push correlation "
        "spans); empty disables span recording",
    )
    p.add_argument(
        "--tb-dir",
        dest="tb_dir",
        help="TensorBoard event-file directory: per-round metrics become "
        "real TB scalars (the reference's TensorBoard workflow, "
        "client_fit_model.py:153-154)",
    )
    p.add_argument(
        "--eval-synthetic",
        type=int,
        default=0,
        help="evaluate the global model each round on N generated samples "
        "(the reference designed per-round server-side eval but never "
        "enabled it, fl_server.py:27-37)",
    )
    p.add_argument("--eval-image-dir", help="server-side eval images")
    p.add_argument("--eval-mask-dir", help="server-side eval masks")
    p.add_argument(
        "--best-path",
        dest="best_path",
        help="keep the best global model by server-side eval loss here "
        "(msgpack + .json metrics sidecar) — the federated analog of the "
        "reference's best-val ModelCheckpoint (test/Segmentation.py:177-179); "
        "requires --eval-*",
    )
    p.add_argument(
        "--logs-dir",
        dest="logs_dir",
        help="sink directory for client-uploaded log files (reference 'L' "
        "path, fl_server.py:84-89); empty keeps uploads in memory",
    )
    p.add_argument(
        "--init-weights",
        dest="init_weights",
        help="seed the global model from a msgpack pytree (e.g. produced by "
        "`python -m fedcrack_tpu.tools.h5_import crack_segmentation.h5 out.msgpack`)",
    )
    p.add_argument(
        "--auth-token",
        dest="auth_token",
        help="shared enrollment token: every client message must carry it "
        "or is REJECTED (the reference accepted anyone reaching the port)",
    )
    p.add_argument(
        "--allow-insecure-token",
        dest="allow_insecure_token",
        action="store_const",
        const=True,
        default=None,
        help="accept --auth-token over a plaintext channel (the secret then "
        "travels in cleartext on every message; loopback/testing only)",
    )
    p.add_argument("--tls-cert", dest="tls_cert", help="server TLS certificate (PEM)")
    p.add_argument("--tls-key", dest="tls_key", help="server TLS private key (PEM)")
    p.add_argument(
        "--tls-ca",
        dest="tls_ca",
        help="CA bundle (PEM); on the server this also demands client "
        "certificates (mTLS)",
    )
    args = p.parse_args(argv)

    # Flags merge into the RAW config dict before FedConfig construction:
    # __post_init__ validation (TLS pairing, plaintext-token refusal) must
    # see the final merged config, or a flag meant to resolve a validation
    # error (--allow-insecure-token, --tls-*) could never rescue a config
    # file that fails it.
    if args.config:
        with open(args.config) as f:
            raw = json.load(f)
    else:
        raw = {}
    overrides = {}
    for flag, field in [
        ("rounds", "max_rounds"),
        ("cohort", "cohort_size"),
        ("port", "port"),
        ("host", "host"),
        ("registration_window_s", "registration_window_s"),
        ("round_deadline_s", "round_deadline_s"),
        ("quorum_fraction", "quorum_fraction"),
        ("state_path", "state_path"),
        ("mode", "mode"),
        ("buffer_k", "buffer_k"),
        ("staleness_alpha", "staleness_alpha"),
        ("max_staleness", "max_staleness"),
        ("fedprox_mu", "fedprox_mu"),
        ("pos_weight", "pos_weight"),
        ("aggregation", "aggregation"),
        ("trim_fraction", "trim_fraction"),
        ("byzantine_f", "byzantine_f"),
        ("quarantine_z", "quarantine_z"),
        ("secagg", "secagg"),
        ("secagg_bits", "secagg_bits"),
        ("dp_clip_norm", "dp_clip_norm"),
        ("dp_noise_multiplier", "dp_noise_multiplier"),
        ("dp_sample_rate", "dp_sample_rate"),
        ("dp_delta", "dp_delta"),
        ("dp_steps_per_round", "dp_steps_per_round"),
        ("dp_seed", "dp_seed"),
        ("dp_epsilon_budget", "dp_epsilon_budget"),
        ("server_optimizer", "server_optimizer"),
        ("server_lr", "server_lr"),
        ("server_momentum", "server_momentum"),
        ("wire_dtype", "wire_dtype"),
        ("update_codec", "update_codec"),
        ("topk_fraction", "topk_fraction"),
        ("max_message_mb", "max_message_mb"),
        ("ckpt_dir", "ckpt_dir"),
        ("seed", "seed"),
        ("metrics_path", "metrics_path"),
        ("tb_dir", "tb_dir"),
        ("logs_dir", "logs_dir"),
        ("init_weights", "init_weights"),
        ("best_path", "best_path"),
        ("auth_token", "auth_token"),
        ("allow_insecure_token", "allow_insecure_token"),
        ("tls_cert", "tls_cert"),
        ("tls_key", "tls_key"),
        ("tls_ca", "tls_ca"),
    ]:
        val = getattr(args, flag)
        if val is not None:
            overrides[field] = val
    raw.update(overrides)
    cfg = FedConfig.from_dict(raw)
    shown = json.loads(cfg.to_json())
    if shown.get("auth_token"):
        shown["auth_token"] = "<redacted>"  # the secret must not hit logs
    logging.info("config: %s", shown)
    return cfg, args


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    cfg, args = build_config(argv)
    # Build + serialize the initial global model (the reference delegates
    # this to the missing model_evaluate module, SURVEY.md §2.5).
    state = create_train_state(jax.random.key(cfg.seed), cfg.model, cfg.learning_rate)
    variables = state.variables
    eval_fn = None
    if args.eval_synthetic or (args.eval_image_dir and args.eval_mask_dir):
        from fedcrack_tpu.data.pipeline import dataset_from_source
        from fedcrack_tpu.fed.serialization import tree_from_bytes
        from fedcrack_tpu.train.local import evaluate, recalibrate_batch_stats

        eval_dataset = dataset_from_source(
            args.eval_synthetic,
            args.eval_image_dir,
            args.eval_mask_dir,
            img_size=cfg.model.img_size,
            batch_size=cfg.data.batch_size,
            seed=cfg.seed + 1,  # never the clients' train fixtures
            drop_last=False,
        )

        def eval_fn(blob: bytes) -> dict:
            st = state.replace_variables(
                tree_from_bytes(blob, template=state.variables)
            )
            # A freshly averaged global model carries mixed, under-converged
            # BN running stats (momentum 0.99 needs ~500 steps); re-estimate
            # them from the eval images (labels never enter calibration) so
            # the reported loss/IoU reflects the params, not stale moments.
            st = recalibrate_batch_stats(st, eval_dataset, cfg.model)
            return evaluate(st, eval_dataset, pos_weight=cfg.pos_weight)

    if cfg.best_path and eval_fn is None:
        logging.warning(
            "--best-path %s is set but server-side eval is off (no --eval-*): "
            "no best model will ever be written",
            cfg.best_path,
        )
    if cfg.init_weights:
        from fedcrack_tpu.fed.serialization import tree_from_bytes

        with open(cfg.init_weights, "rb") as f:
            variables = tree_from_bytes(f.read(), template=variables)
        logging.info("seeded global model from %s", cfg.init_weights)
    checkpointer = None
    if cfg.ckpt_dir:
        from fedcrack_tpu.ckpt import FedCheckpointer

        checkpointer = FedCheckpointer(cfg.ckpt_dir)
    metrics = None
    if cfg.metrics_path or cfg.tb_dir:
        from fedcrack_tpu.obs import MetricsLogger

        metrics = MetricsLogger(
            cfg.metrics_path or os.devnull, tb_dir=cfg.tb_dir or None
        )
    exporter = None
    if args.metrics_port:
        from fedcrack_tpu.obs.promexp import start_exporter

        exporter = start_exporter(args.metrics_port)
        if exporter is not None:
            logging.info("metrics: %s", exporter.url)
    if args.spans_path:
        from fedcrack_tpu.obs import spans as tracing

        tracing.install(args.spans_path)
    server = FedServer(
        cfg, variables, checkpointer=checkpointer, metrics=metrics, eval_fn=eval_fn
    )
    final = asyncio.run(server.serve_until_finished())
    if exporter is not None:
        exporter.stop()
    for entry in server.eval_history:
        logging.info("server eval %s", entry)
    if metrics is not None:
        metrics.close()
    if args.privacy_summary_path or cfg.dp_noise_multiplier > 0 or cfg.secagg:
        from fedcrack_tpu.fed.rounds import privacy_summary

        summary = privacy_summary(final)
        logging.info("privacy summary: %s", summary)
        if args.privacy_summary_path:
            from fedcrack_tpu.ioutils import atomic_write_bytes

            atomic_write_bytes(
                args.privacy_summary_path,
                json.dumps(summary, sort_keys=True, indent=2).encode("utf-8"),
            )
            logging.info("privacy summary -> %s", args.privacy_summary_path)
    logging.info(
        "federation finished: %d rounds, final cohort %s",
        len(final.history),
        sorted(final.cohort),
    )
    for entry in final.history:
        logging.info("round %s: clients=%s", entry["round"], entry["clients"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
