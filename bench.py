"""Benchmark: FedAvg round wall-clock, mesh data plane vs host control plane.

The north-star metric (BASELINE.md): federated round wall-clock with the
round executed as ONE compiled XLA program (local-SGD scan + in-mesh FedAvg,
``fedcrack_tpu.parallel``) versus the reference's architecture reproduced in
this repo — Python-driven per-step dispatch with per-batch host transfers,
weights serialized to bytes and averaged on the host (the gRPC weight-shipping
plane of fl_server.py:92-105 / fl_client.py:63, minus the network).

Prints ONE JSON line: value = mesh-plane round wall-clock (ms);
vs_baseline = host-plane time / mesh-plane time (higher is better, >1 means
the TPU-native plane wins).

Run shape: flagship 128x128 U-Net, batch 16 (reference: client_fit_model.py:55-56),
32 steps, 1 local epoch, as many mesh clients as the host exposes devices.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


STEPS = 32
BATCH = 16
SEED = 0


def _median_time(fn, reps: int = 3) -> float:
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> None:
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.algorithms import fedavg
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
    from fedcrack_tpu.parallel import build_federated_round, make_mesh, stack_client_data
    from fedcrack_tpu.train.local import create_train_state, train_step

    config = ModelConfig()  # 128x128x3 — the reference's training shape
    n_clients = max(1, jax.device_count())
    per_client = [
        synth_crack_batch(STEPS * BATCH, img_size=config.img_size, seed=SEED + i)
        for i in range(n_clients)
    ]
    state0 = create_train_state(jax.random.key(SEED), config)
    variables = state0.variables
    n_samples = np.full(n_clients, float(STEPS * BATCH), np.float32)
    active = np.ones(n_clients, np.float32)

    # ---- mesh plane: the whole round is one program ----
    mesh = make_mesh(n_clients, 1)
    round_fn = build_federated_round(mesh, config, learning_rate=1e-3, local_epochs=1)
    stacked_images, stacked_masks = stack_client_data(per_client, STEPS, BATCH)
    # Per-client shards live on their chips before the round starts (the
    # data plane's contract: the input pipeline stages local data round-start,
    # overlapped with the previous round) — the timed region measures the
    # round program itself, not re-shipping the same bytes through PCIe
    # every repetition.
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_sharding = NamedSharding(mesh, P("clients", None, "batch"))
    stacked_images = jax.device_put(stacked_images, data_sharding)
    stacked_masks = jax.device_put(stacked_masks, data_sharding)

    # Rounds are CHAINED (each consumes the previous round's output) and
    # synced via a host readback of the round metrics, not just
    # block_until_ready: through remote-device tunnels the latter has been
    # observed to return before the program finishes, and repeating one
    # identical call would let any result caching fake the timing. Chained
    # rounds are also what a real federation runs. The loss depends on every
    # step, so its readback is a full-program barrier.
    mesh_vars = {"v": variables}

    def mesh_round():
        new_vars, metrics = round_fn(
            mesh_vars["v"], stacked_images, stacked_masks, active, n_samples
        )
        mesh_vars["v"] = new_vars
        float(np.asarray(metrics["loss"])[0])
        return new_vars

    # ---- host plane: reference architecture (per-step dispatch + byte
    # shipping + host-side average), minus the actual TCP socket ----
    # Chained across reps like the mesh plane; tree_to_bytes is a real
    # device->host readback, so each round is fully synced.
    mu0 = np.float32(0.0)
    host_vars = {"v": variables}

    def host_round():
        blob = tree_to_bytes(host_vars["v"])  # server -> client broadcast
        uploads = []
        for c in range(n_clients):
            received = tree_from_bytes(blob, template=variables)
            st = state0.replace_variables(received)
            st = st.replace(opt_state=st.tx.init(st.params))
            images, masks = per_client[c]
            for s in range(STEPS):
                batch = (
                    images[s * BATCH : (s + 1) * BATCH],
                    masks[s * BATCH : (s + 1) * BATCH],
                )
                st, _ = train_step(st, batch, received["params"], mu0)
            jax.block_until_ready(st.params)
            uploads.append(tree_to_bytes(st.variables))  # client -> server
        trees = [tree_from_bytes(b, template=variables) for b in uploads]
        avg = fedavg(trees, weights=list(n_samples))
        jax.block_until_ready(avg)
        host_vars["v"] = jax.device_get(avg)
        return avg

    # Warm up both programs (first TPU compile is slow and cached after).
    # The mesh plane warms twice: the first call consumes the host pytree,
    # the second compiles the committed-device-input signature the timed
    # chained reps use.
    mesh_round()
    mesh_round()
    host_round()

    mesh_s = _median_time(mesh_round)
    host_s = _median_time(host_round)

    print(
        json.dumps(
            {
                "metric": (
                    f"FedAvg round wall-clock, one-program mesh plane "
                    f"({n_clients} client(s), 128x128, b{BATCH}, {STEPS} steps) "
                    f"vs host/gRPC-style plane"
                ),
                "value": round(mesh_s * 1000.0, 2),
                "unit": "ms",
                "vs_baseline": round(host_s / mesh_s, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
