"""Benchmark: per-step time + MFU sweep, and the FedAvg-round architecture ratio.

Round 1 published one wall-clock number at one shape; this bench makes the perf
story measurable (VERDICT.md round-1 items 1-2):

1. **Sweep**: single-chip per-step time and MFU for
   {float32, bfloat16} x {128, 256} — the reference's training shape
   (client_fit_model.py:55-56), BASELINE config 3's 256 px crop, and BASELINE
   config 5's bf16 compute. Every point is timed at TWO scan lengths and the
   per-step time is the slope of that fit, so the fixed per-call dispatch
   cost (~100 ms through a remote-device tunnel) is separated out instead of
   silently inflating per-step numbers. MFU comes from an analytic FLOPs
   model of the U-Net cross-checked against XLA's HLO cost analysis
   (obs/flops.py, tests/test_flops.py), against the chip's bf16 MXU peak —
   slope-based MFU matches the device-busy time in profiler traces.
2. **Decomposed baseline**: the host plane (the reference's architecture —
   Python-dispatched per-step execution + serialized weight shipping + host
   FedAvg, fl_server.py:92-105 / fl_client.py:63, minus the TCP socket) is
   reported as total wall-clock AND split into per-step compute,
   serialization, aggregation, and dispatch overhead, so the mesh-vs-host
   ratio is stated both tunnel-inclusive ("vs_baseline", what a user of each
   architecture experiences end to end) and per-step-compute-only
   ("vs_baseline_compute_only" in detail, the architecture-independent floor).

Prints ONE JSON line: value = flagship bf16 one-program round wall-clock (ms);
vs_baseline = measured host-plane / mesh-plane round time at equal (float32)
dtype; everything else under "detail".

Env knobs (smoke testing; defaults are the real bench):
FEDCRACK_BENCH_STEPS=32 FEDCRACK_BENCH_BATCH=16 FEDCRACK_BENCH_REPS=3
FEDCRACK_BENCH_SIZES=128,256 FEDCRACK_BENCH_FIT_FACTOR=4
FEDCRACK_PEAK_TFLOPS=<override chip peak>.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

STEPS = int(os.environ.get("FEDCRACK_BENCH_STEPS", "32"))
BATCH = int(os.environ.get("FEDCRACK_BENCH_BATCH", "16"))
REPS = int(os.environ.get("FEDCRACK_BENCH_REPS", "3"))
SIZES = tuple(
    int(s) for s in os.environ.get("FEDCRACK_BENCH_SIZES", "128,256").split(",")
)
SEED = 0

# Reference-scale round (the reference's actual workload: 10 local epochs x
# ~388 steps of batch 16 over 6213 images, client_fit_model.py:166,76).
# "auto" runs it on TPU only — at 3,880 steps a CPU smoke run would take
# hours; "1"/"0" force it on/off.
REF_EPOCHS = int(os.environ.get("FEDCRACK_BENCH_REF_EPOCHS", "10"))
REF_STEPS = int(os.environ.get("FEDCRACK_BENCH_REF_STEPS", "388"))
REF_SCALE = os.environ.get("FEDCRACK_BENCH_REF_SCALE", "auto")


def _median_time(fn, reps: int = REPS) -> float:
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


# Longer-round multiplier for the dispatch-correction fit (see _time_mesh_round);
# the two-point slope needs the rounds to differ, so 2 is the floor.
FIT_FACTOR = max(2, int(os.environ.get("FEDCRACK_BENCH_FIT_FACTOR", "4")))


def _make_mesh_round(config, n_clients, variables, per_client, steps):
    """Chained, readback-synced one-program round at this config's shape.

    Rounds are CHAINED (each consumes the previous round's output) and synced
    via a host readback of the round metrics, not just block_until_ready:
    through remote-device tunnels the latter has been observed to return
    before the program finishes, and repeating one identical call would let
    result caching fake the timing. The loss depends on every step, so its
    readback is a full-program barrier.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedcrack_tpu.parallel import build_federated_round, make_mesh, stack_client_data

    mesh = make_mesh(n_clients, 1)
    round_fn = build_federated_round(mesh, config, learning_rate=1e-3, local_epochs=1)
    # stack_client_data cycles each client's samples, so one synthesized set
    # serves both the standard and the FIT_FACTOR-longer round.
    images, masks = stack_client_data(per_client, steps, BATCH)
    # Per-client shards live on their chips before the round starts (the data
    # plane's contract: the input pipeline stages local data round-start,
    # overlapped with the previous round) — the timed region measures the
    # round program, not re-shipping the same bytes through PCIe per rep.
    sharding = NamedSharding(mesh, P("clients", None, "batch"))
    images = jax.device_put(images, sharding)
    masks = jax.device_put(masks, sharding)
    active = np.ones(n_clients, np.float32)
    n_samples = np.full(n_clients, float(steps * BATCH), np.float32)
    state = {"v": variables}

    def mesh_round():
        new_vars, metrics = round_fn(state["v"], images, masks, active, n_samples)
        state["v"] = new_vars
        float(np.asarray(metrics["loss"])[0])
        return new_vars

    return mesh_round


def _time_mesh_round(config, n_clients, variables, per_client, steps):
    """Median wall-clock of the chained round at ``steps`` scan length."""
    mesh_round = _make_mesh_round(config, n_clients, variables, per_client, steps)
    # Warm twice: first call consumes the host pytree, second compiles the
    # committed-device-input signature the timed chained reps use.
    mesh_round()
    mesh_round()
    return _median_time(mesh_round)


def _measure_host_plane(n_clients, variables, per_client, state0):
    """The reference architecture, decomposed. Returns (total_s, parts)."""
    from fedcrack_tpu.fed.algorithms import fedavg
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
    from fedcrack_tpu.train.local import train_step

    mu0 = np.float32(0.0)
    host_vars = {"v": variables}

    def host_round():
        blob = tree_to_bytes(host_vars["v"])  # server -> client broadcast
        uploads = []
        for c in range(n_clients):
            received = tree_from_bytes(blob, template=variables)
            st = state0.replace_variables(received)
            st = st.replace(opt_state=st.tx.init(st.params))
            images, masks = per_client[c]
            for s in range(STEPS):
                batch = (
                    images[s * BATCH : (s + 1) * BATCH],
                    masks[s * BATCH : (s + 1) * BATCH],
                )
                st, _ = train_step(st, batch, received["params"], mu0)
            jax.block_until_ready(st.params)
            uploads.append(tree_to_bytes(st.variables))  # client -> server
        trees = [tree_from_bytes(b, template=variables) for b in uploads]
        avg = fedavg(trees, weights=[float(STEPS * BATCH)] * n_clients)
        jax.block_until_ready(avg)
        host_vars["v"] = jax.device_get(avg)
        return avg

    host_round()  # warm-up: compiles train_step at this shape
    total_s = _median_time(host_round)

    # Serialization cost, measured on the same pytree: per round the host
    # plane serializes 1 broadcast + C uploads and parses 2C blobs
    # (client receive + server receive).
    blob = tree_to_bytes(variables)
    to_s = _median_time(lambda: tree_to_bytes(variables))
    from_s = _median_time(lambda: tree_from_bytes(blob, template=variables))
    ser_s = to_s * (1 + n_clients) + from_s * (2 * n_clients)

    trees = [tree_from_bytes(blob, template=variables) for _ in range(n_clients)]
    fedavg_s = _median_time(
        lambda: jax.block_until_ready(fedavg(trees, weights=[1.0] * n_clients))
    )
    return total_s, {
        "serialization_ms": ser_s * 1e3,
        "host_fedavg_ms": fedavg_s * 1e3,
        # raw per-operation costs, so reconstructions at OTHER client counts
        # (the 1-client reference-scale round) can rebuild serialization for
        # their own shape instead of inheriting this n_clients' total
        "to_bytes_s_raw": to_s,
        "from_bytes_s_raw": from_s,
        "fedavg_s_raw": fedavg_s,
    }


def _bench_reference_scale(img: int, dtype: str, device) -> dict:
    """One-program federated round at the reference's true workload:
    REF_EPOCHS local epochs over REF_STEPS batches of BATCH, single client,
    uint8 transport staging.

    Decomposition reported:
    - ``staging_ms``: host->device transfer of one epoch's uint8 data,
      synced via an on-device element readback (tunnel-safe barrier);
    - ``round_ms``: the chained round program on pre-staged data — at
      ~REF_EPOCHS*REF_STEPS steps the fixed dispatch cost is <2% of the
      round, so the naive per-step division is finally honest;
    - ``round_plus_restage_ms``: the round dispatched asynchronously while
      the NEXT round's data stages concurrently (double buffering) — the
      production overlap pattern; ``staging_hidden_frac`` is how much of
      the staging cost the overlap hides.
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import build_federated_round, make_mesh
    from fedcrack_tpu.train.local import create_train_state

    config = ModelConfig(img_size=img, compute_dtype=dtype)
    state0 = create_train_state(jax.random.key(SEED), config)
    mesh = make_mesh(1, 1)
    round_fn = build_federated_round(
        mesh, config, learning_rate=1e-3, local_epochs=REF_EPOCHS
    )
    # One epoch of uint8 transport data. 512 distinct syntheses cycled to
    # the full epoch: timing is value-independent, and 6k unique 256 px
    # syntheses would dominate host time for no fidelity gain.
    n_unique = min(512, REF_STEPS * BATCH)
    imgs_f, msks_f = synth_crack_batch(n_unique, img_size=img, seed=SEED)
    imgs_u8 = np.clip(np.rint(imgs_f * 255.0), 0, 255).astype(np.uint8)
    msks_u8 = msks_f.astype(np.uint8)
    need = REF_STEPS * BATCH
    idx = np.resize(np.arange(n_unique), need)
    images = np.ascontiguousarray(
        imgs_u8[idx].reshape(1, REF_STEPS, BATCH, img, img, 3)
    )
    masks = np.ascontiguousarray(
        msks_u8[idx].reshape(1, REF_STEPS, BATCH, img, img, 1)
    )
    sharding = NamedSharding(mesh, P("clients", None, "batch"))

    def stage():
        si = jax.device_put(images, sharding)
        sm = jax.device_put(masks, sharding)
        # On-device element readback: the computation must wait for the
        # transfer, and the scalar fetch is a real tunnel round-trip
        # (block_until_ready alone has been observed returning early).
        float(jnp.asarray(si[0, 0, 0, 0, 0, 0], jnp.float32))
        float(jnp.asarray(sm[0, 0, 0, 0, 0, 0], jnp.float32))
        return si, sm

    active = np.ones(1, np.float32)
    n_samp = np.full(1, float(need), np.float32)
    state = {"v": state0.variables}
    si, sm = stage()

    def run_round(imgs_dev, msks_dev):
        new_vars, metrics = round_fn(state["v"], imgs_dev, msks_dev, active, n_samp)
        state["v"] = new_vars
        float(np.asarray(metrics["loss"])[0])

    # Deep warmup + settle: through the tunnel, residual streaming from the
    # initial 400 MB+ staging contaminates the next few calls — a single
    # warmup run measured a 3,880-step round at 15.8 s where the settled
    # value is 8.2 s (isolated in bench_runs/r03_refscale_isolation.json).
    for _ in range(3):
        run_round(si, sm)
    time.sleep(2.0)
    reps = max(1, min(REPS, 3))
    round_s = _median_time(lambda: run_round(si, sm), reps=reps)
    stage_s = _median_time(lambda: stage(), reps=2)
    time.sleep(2.0)  # drain staging traffic before the overlap phase

    def overlapped():
        # Dispatch the round (async), stage the next round's buffers while
        # the device computes, then barrier both.
        new_vars, metrics = round_fn(state["v"], si, sm, active, n_samp)
        state["v"] = new_vars
        si2 = jax.device_put(images, sharding)
        sm2 = jax.device_put(masks, sharding)
        float(jnp.asarray(si2[0, 0, 0, 0, 0, 0], jnp.float32))
        float(jnp.asarray(sm2[0, 0, 0, 0, 0, 0], jnp.float32))
        float(np.asarray(metrics["loss"])[0])

    overlapped()  # warm the overlap path
    overlap_s = _median_time(overlapped, reps=reps)

    total_steps = REF_EPOCHS * REF_STEPS
    step_s = round_s / total_steps
    flops = train_step_flops(config, BATCH)
    util = mfu(step_s, flops, device)
    hidden = (stage_s + round_s - overlap_s) / stage_s if stage_s > 0 else None
    return {
        "img_size": img,
        "dtype": dtype,
        "epochs": REF_EPOCHS,
        "steps_per_epoch": REF_STEPS,
        "batch": BATCH,
        "total_steps": total_steps,
        "staging_bytes": int(images.nbytes + masks.nbytes),
        "round_s_raw": round_s,
        "staging_s_raw": stage_s,
        "staging_ms": round(stage_s * 1e3, 2),
        "round_ms": round(round_s * 1e3, 2),
        "per_step_ms": round(step_s * 1e3, 3),
        "round_plus_restage_ms": round(overlap_s * 1e3, 2),
        "staging_hidden_frac": None if hidden is None else round(max(0.0, min(1.0, hidden)), 3),
        "mfu": None if util is None else round(util, 4),
    }


def main() -> None:
    # Smoke-test hook: this image pre-imports jax at interpreter startup with
    # the axon (real TPU tunnel) platform, so a JAX_PLATFORMS=cpu env override
    # is swallowed; the runtime config API still works before first backend use.
    if os.environ.get("FEDCRACK_BENCH_FORCE_CPU"):
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialized; run where we are
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.obs.flops import device_peak_flops, mfu, train_step_flops
    from fedcrack_tpu.train.local import create_train_state

    n_clients = max(1, jax.device_count())
    device = jax.devices()[0]
    peak = device_peak_flops(device)

    # ---- sweep: per-step time + MFU, {f32, bf16} x SIZES, mesh plane ----
    # Each point is timed at two scan lengths (STEPS and FIT_FACTOR*STEPS);
    # the slope of that fit is the true per-step time and the intercept is
    # the fixed per-call dispatch cost (through a remote-device tunnel the
    # intercept is ~100 ms, which at 32 steps would inflate per-step time
    # ~2.5x — dividing one round's wall-clock by its step count is a lie).
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    sweep = {}
    flagship_per_client = None
    f32_state0 = None
    for img in SIZES:
        per_client_img = [
            synth_crack_batch(STEPS * BATCH, img_size=img, seed=SEED + i)
            for i in range(n_clients)
        ]
        for dtype in ("float32", "bfloat16"):
            config = ModelConfig(img_size=img, compute_dtype=dtype)
            state0 = create_train_state(jax.random.key(SEED), config)
            if img == SIZES[0] and dtype == "float32":
                f32_state0 = state0
                flagship_per_client = per_client_img
            short_s = _time_mesh_round(
                config, n_clients, state0.variables, per_client_img, STEPS
            )
            long_s = _time_mesh_round(
                config, n_clients, state0.variables, per_client_img,
                FIT_FACTOR * STEPS,
            )
            slope_s = (long_s - short_s) / ((FIT_FACTOR - 1) * STEPS)
            # A non-positive slope means timing noise swamped the fit: report
            # the point as unmeasurable (None) rather than publishing a
            # garbage per-step time / absurd MFU as if it were real.
            fit_ok = slope_s > 0.0
            step_s = slope_s if fit_ok else None
            flops = train_step_flops(config, BATCH)
            sweep[f"{dtype}_{img}"] = {
                "dtype": dtype,
                "img_size": img,
                # raw (unrounded) seconds: every derived ratio reads these,
                # so display rounding never leaks into the arithmetic
                "round_s_raw": short_s,
                "per_step_s_raw": step_s,
                "round_ms": round(short_s * 1e3, 2),
                "per_step_ms": round(step_s * 1e3, 3) if fit_ok else None,
                "naive_per_step_ms": round(short_s / STEPS * 1e3, 3),
                "dispatch_intercept_ms": (
                    round(max(0.0, short_s - STEPS * step_s) * 1e3, 2)
                    if fit_ok
                    else None
                ),
                "flops_per_step": flops,
                "mfu": (
                    round(mfu(step_s, flops, device), 4)
                    if fit_ok and peak is not None
                    else None
                ),
            }

    f32_key = f"float32_{SIZES[0]}"
    bf16_key = f"bfloat16_{SIZES[0]}"
    mesh_f32_s = sweep[f32_key]["round_s_raw"]
    mesh_bf16_s = sweep[bf16_key]["round_s_raw"]

    def _step_s(point):
        """Slope-based per-step seconds (raw), falling back to naive when
        the fit failed (the fallback overstates compute, so derived ratios
        degrade conservatively rather than crashing)."""
        if point["per_step_s_raw"] is not None:
            return point["per_step_s_raw"]
        return point["round_s_raw"] / STEPS

    # Dispatch-free round times (slope x steps): the apples-to-apples basis
    # for any ratio whose other side excludes dispatch.
    mesh_f32_compute_s = STEPS * _step_s(sweep[f32_key])
    mesh_bf16_compute_s = STEPS * _step_s(sweep[bf16_key])

    # ---- reference-scale rounds (the reference's real workload) ----
    reference_scale = {}
    run_ref = REF_SCALE == "1" or (
        REF_SCALE == "auto" and getattr(device, "platform", "") == "tpu"
    )
    if run_ref:
        points = [(SIZES[0], "float32"), (SIZES[0], "bfloat16")]
        if len(SIZES) > 1:
            points.append((SIZES[1], "bfloat16"))
        for img, dtype in points:
            reference_scale[f"{dtype}_{img}"] = _bench_reference_scale(
                img, dtype, device
            )

    # ---- host plane (reference architecture) at the reference's shape ----
    host_total_s, host_parts = _measure_host_plane(
        n_clients, f32_state0.variables, flagship_per_client, f32_state0
    )
    # Compute-only reconstruction of a host round: the same SGD step costs
    # what the mesh plane's scan charges per step (identical XLA program);
    # everything above that is the host architecture's own overhead.
    compute_s = n_clients * STEPS * _step_s(sweep[f32_key])
    ser_s = host_parts["serialization_ms"] / 1e3
    agg_s = host_parts["host_fedavg_ms"] / 1e3
    dispatch_s = max(0.0, host_total_s - compute_s - ser_s - agg_s)
    compute_only_s = compute_s + ser_s + agg_s

    detail = {
        "sweep": sweep,
        "host_plane": {
            "dtype": "float32",
            "img_size": SIZES[0],
            "round_ms": round(host_total_s * 1e3, 2),
            "per_step_compute_ms": round(_step_s(sweep[f32_key]) * 1e3, 3),
            "serialization_ms": round(host_parts["serialization_ms"], 2),
            "host_fedavg_ms": round(host_parts["host_fedavg_ms"], 2),
            "dispatch_overhead_ms": round(dispatch_s * 1e3, 2),
            "note": (
                "dispatch_overhead is per-step Python dispatch + host<->device "
                "transfer round-trips; through a remote-device tunnel it is "
                "dominated by tunnel latency and is NOT a compute advantage"
            ),
        },
        # Same-architecture-work ratio, dispatch excluded on BOTH sides: host
        # round rebuilt from its compute + serialization + aggregation parts,
        # over the mesh round's slope-based (dispatch-free) time.
        "vs_baseline_compute_only": round(compute_only_s / mesh_f32_compute_s, 3),
        # Measured end-to-end ratio against the bf16 flagship.
        "vs_baseline_vs_flagship": round(host_total_s / mesh_bf16_s, 3),
        # From slopes, so the dispatch intercept doesn't dilute the dtype win;
        # None unless BOTH fits succeeded (mixing a dispatch-inflated naive
        # fallback on one side only would fabricate a speedup).
        "bf16_speedup_over_f32": (
            round(mesh_f32_compute_s / mesh_bf16_compute_s, 3)
            if sweep[f32_key]["per_step_ms"] is not None
            and sweep[bf16_key]["per_step_ms"] is not None
            else None
        ),
        "device_kind": getattr(device, "device_kind", "unknown"),
        "peak_tflops_bf16": None if peak is None else peak / 1e12,
        "n_clients": n_clients,
        "steps": STEPS,
        "batch": BATCH,
    }

    # Headline at the small sweep scale (CPU smoke / ref-scale disabled).
    metric = (
        f"flagship one-program FedAvg round wall-clock "
        f"({n_clients} client(s), {SIZES[0]}x{SIZES[0]}, bf16 compute, "
        f"b{BATCH}, {STEPS} steps); vs_baseline = host/gRPC-style plane "
        f"over mesh plane at equal float32 dtype, tunnel-inclusive "
        f"(see detail for compute-only ratio, MFU sweep, decomposition)"
    )
    value = sweep[bf16_key]["round_ms"]
    vs_baseline = round(host_total_s / mesh_f32_s, 3)

    if reference_scale:
        # Headline restated AT THE REFERENCE'S SCALE (round-2 verdict #1):
        # 10 epochs x ~388 steps per round. The host plane at that scale is
        # reconstructed from measured components — per-step compute slope,
        # per-step dispatch overhead from the measured 32-step host round,
        # serialization, host FedAvg — because driving 3,880 Python-dispatched
        # steps through the tunnel per rep is minutes per measurement for no
        # added information. Both the tunnel-inclusive ratio and the
        # dispatch-free compute-only floor are reported.
        total_steps = REF_EPOCHS * REF_STEPS
        per_step_overhead_s = dispatch_s / max(1, n_clients * STEPS)
        ref_f32 = reference_scale[f"float32_{SIZES[0]}"]
        ref_bf16 = reference_scale[f"bfloat16_{SIZES[0]}"]
        # 1-client serialization shape: 1 broadcast + 1 upload serialized,
        # 1 client parse + 1 server parse (NOT this run's n_clients total).
        ser_ref_s = 2 * host_parts["to_bytes_s_raw"] + 2 * host_parts["from_bytes_s_raw"]
        agg_ref_s = host_parts["fedavg_s_raw"]
        host_ref_s = (
            total_steps * (_step_s(sweep[f32_key]) + per_step_overhead_s)
            + ser_ref_s
            + agg_ref_s
        )
        host_ref_compute_s = (
            total_steps * _step_s(sweep[f32_key]) + ser_ref_s + agg_ref_s
        )
        detail["reference_scale"] = reference_scale
        detail["host_ref_reconstructed_s"] = round(host_ref_s, 3)
        detail["vs_baseline_ref_compute_only"] = round(
            host_ref_compute_s / ref_f32["round_s_raw"], 3
        )
        metric = (
            f"reference-scale one-program FedAvg round wall-clock "
            f"(1 client, {SIZES[0]}x{SIZES[0]}, bf16 compute, b{BATCH}, "
            f"{REF_EPOCHS} epochs x {REF_STEPS} steps = {total_steps} steps, "
            f"uint8 staging); vs_baseline = reconstructed host/gRPC-style "
            f"plane over measured mesh round at equal float32 dtype, "
            f"tunnel-inclusive (detail.vs_baseline_ref_compute_only is the "
            f"dispatch-free floor; detail.reference_scale has the "
            f"staging/compute/overlap decomposition)"
        )
        value = ref_bf16["round_ms"]
        vs_baseline = round(host_ref_s / ref_f32["round_s_raw"], 3)

    print(
        json.dumps(
            {
                "metric": metric,
                "value": value,
                "unit": "ms",
                "vs_baseline": vs_baseline,
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
