"""Benchmark: per-step time + MFU sweep, host-plane decomposition, and the
reference-scale one-program round — under a wall-clock budget.

Round 3's lesson (VERDICT.md round-3 item 1): a bench that only proves its
claims given unbounded time proves nothing under a driver — `BENCH_r03.json`
was an empty timeout. This bench is budget-aware:

- **Sections run value-first**: the {f32, bf16} sweep at the flagship size
  always runs, the reference-scale points (the headline) run IMMEDIATELY
  after it, and the host plane runs after those — round 4's lesson
  (VERDICT round-4 weak #1): under a congested tunnel the host plane cost
  240 s and starved the headline sections out of the driver's budget, so
  the headline now outranks it. The host plane, batch-scaling curve, and
  secondary-size sweep are each gated on a cost estimate fitting the
  remaining budget (the host plane degrades to fewer reps before skipping).
- **`FEDCRACK_BENCH_BUDGET_S`** (default 780 s) is the wall-clock budget.
  When a section doesn't fit, it is SKIPPED and recorded under
  `detail.skipped` with the estimate that excluded it — the JSON always
  prints with everything that WAS measured.
- **SIGTERM/SIGINT safety net**: if the driver kills the run anyway, the
  handler prints the partial JSON before exiting, so even a timeout captures
  every completed section.
- **Exit-code contract** (changed in round 5; the round-3 docs said rc 0 on
  TERM): an interrupted-but-emitted run exits **128+signum** (143 on TERM,
  130 on INT) with the partial JSON already printed and its payload marked
  ``interrupted: <SIGNAME>``. Drivers must treat 128+signum WITH a parsed
  JSON line as "partial artifact", not "failed run" — rc 0 now means only a
  run that completed inside its own budget. (The driver's own timeout
  killing us with SIGKILL still yields rc 137 and whatever was flushed.)
- Expensive measurements are shared: the f32 reference-scale point reuses
  the bf16 point's staged uint8 buffers (transport data is dtype-independent)
  and its staging timings; the sweep's long-scan arrays are tiled from the
  short-scan arrays ON DEVICE (no second tunnel transfer); both dtypes at a
  sweep size share one staged data set.

Measurement design (unchanged from round 3, validated in bench_runs/):

1. **Sweep**: per-step time is the slope of a two-scan-length fit, so the
   fixed per-call dispatch cost (~100 ms through a remote-device tunnel)
   is separated out. MFU from an analytic FLOPs model cross-checked against
   XLA's HLO cost analysis (obs/flops.py).
2. **Host plane**: the reference's architecture (Python-dispatched steps +
   serialized weight shipping + host FedAvg, fl_server.py:92-105 /
   fl_client.py:63) measured and decomposed into compute / serialization /
   aggregation / dispatch.
3. **Reference scale**: the reference's true workload — REF_EPOCHS x
   REF_STEPS steps of batch BATCH (client_fit_model.py:166,76) — as one
   program, with uint8 staging and the double-buffered next-round overlap
   driven through `parallel.driver.run_mesh_federation` (the production
   component, not a bench-local loop).
4. **Input pipeline** (round 5): the reference's synchronous per-batch cv2
   decode cost (client_fit_model.py:30-43 runs 16 imread+resize per step
   inside fit), measured on this host and folded into the host-plane
   reconstruction as a separate labeled term — the decode-inclusive
   co-located ratio the round-4 verdict asked for.
5. **Batch curve** (round 5): bf16 flagship per-step/MFU at batch {32, 64}
   from on-device regrouped sweep data — evidence for/against the
   width-bound MFU-ceiling claim (batch 16 stays the parity headline).
6. **Layout A/B** (round 6): the model-graph layout transforms
   (space-to-depth stem, channel-packed residual projections —
   models/resunet.py, exact re-expressions of the same math) vs the
   reference layout, interleaved over shared staged data at the flagship
   size (bf16 + f32) and each secondary size (bf16), with MFU charged on
   canonical reference-topology FLOPs for every variant. Variants via
   FEDCRACK_BENCH_LAYOUTS; artifact schema matches tools/ab_pallas_bce
   (per-variant dicts under "impls", ratios as sibling keys).
7. **Resident-pool A/B** (round 9, detail.resident_pool): streamed
   per-round slab restaging vs the device-resident sample pool with
   index-only uploads (parallel.driver data_placement="resident"), over
   byte-identical batches — the max(compute, staging) roofline collapsing
   to the compute term, with the production driver's RoundRecords pinning
   per-round staged bytes to the gather plan's kilobytes.
8. **Serving SLO** (round 10, detail.serving): the serving plane
   (fedcrack_tpu/serve — compiled per-bucket predict, dynamic
   micro-batching, hot-swap manager, gRPC front door) under tools/load_gen
   closed-loop traffic across every bucket, with one LIVE hot-swap
   installed mid-run — throughput img/s, latency p50/p95/p99, swap
   load/pause, zero-drop accounting.
9. **Update-compression A/B** (round 12, detail.update_compression): the
   three upload codecs (fedcrack_tpu/compress — null / int8 quantized
   delta / top-k sparsified delta with error feedback) priced on REAL
   frame bytes for one reference-scale round delta (encode/decode wall,
   bytes ratio vs the dense blob, null pinned byte-identical), plus the
   mesh twins' crack-IoU trajectory vs the NullCodec oracle with the
   driver's RoundRecord.bytes_per_round counter per codec.

Output contract (round 9): the full payload prints as one JSON line (value =
flagship one-program round wall-clock (ms) at reference scale when measured,
sweep scale otherwise; vs_baseline = host-plane / mesh-plane round time at
equal float32 dtype) and is ALSO written to ``FEDCRACK_BENCH_OUT`` (default
/tmp/fedcrack_bench_payload.json); the FINAL stdout line is a compact
single-line summary (headline metrics + artifact path, no detail tree) that
survives tail-capture — BENCH_r05.json's ``"parsed": null`` was the
monolithic payload line getting truncated. Parse the last line; follow its
``artifact`` pointer (or the second-to-last line) for the full detail.

Env knobs (smoke testing; defaults are the real bench):
FEDCRACK_BENCH_BUDGET_S=780 FEDCRACK_BENCH_STEPS=32 FEDCRACK_BENCH_BATCH=16
FEDCRACK_BENCH_REPS=3 FEDCRACK_BENCH_SIZES=128,256 FEDCRACK_BENCH_FIT_FACTOR=4
FEDCRACK_BENCH_REF_SCALE=auto|1|0 FEDCRACK_BENCH_REF_EPOCHS=10
FEDCRACK_BENCH_REF_STEPS=388 FEDCRACK_BENCH_REF_256=1 (opt-in: the ~10 min
bf16/256 reference-scale point) FEDCRACK_PEAK_TFLOPS=<override chip peak>
FEDCRACK_BENCH_LAYOUTS=reference,s2d,s2d_full,respack,s2d+respack (layout
A/B variants; first is the ratio denominator)
FEDCRACK_BENCH_CHAOS=0 (skip the mid-round kill→restart recovery drill,
detail.chaos_recovery) FEDCRACK_BENCH_OUT=<full-payload artifact path>
(default /tmp/fedcrack_bench_payload.json; "" disables the file write)
FEDCRACK_BENCH_SERVING=0 (skip the serving-plane section)
FEDCRACK_BENCH_SERVE_SIZES=128,256 FEDCRACK_BENCH_SERVE_REQUESTS=128
FEDCRACK_BENCH_SERVE_MAX_BATCH=8 FEDCRACK_BENCH_SERVE_CONCURRENCY=8
FEDCRACK_BENCH_SERVE_FLEET=0 (skip the round-17 fleet/quant section)
FEDCRACK_BENCH_FLEET_REPLICAS=1,2 FEDCRACK_BENCH_FLEET_REQUESTS=64
FEDCRACK_BENCH_FLEET_SHED_RATE=40 (ramp-profile base rate, rps)
FEDCRACK_BENCH_ELASTIC=0 (skip the round-22 elastic-fleet diurnal A/B +
shadow-delivery section, detail.elastic_fleet)
FEDCRACK_BENCH_ELASTIC_REQUESTS=120 FEDCRACK_BENCH_ELASTIC_RATE=24
FEDCRACK_BENCH_COMPRESSION=0 (skip the update-compression A/B)
FEDCRACK_BENCH_COMPRESSION_ROUNDS=3 (mesh-twin trajectory rounds).
FEDCRACK_BENCH_OBSERVABILITY=0 (skip the round-15 concurrent mini-soak)
FEDCRACK_BENCH_SOAK_S=8 (the soak's traffic wall in seconds)
FEDCRACK_BENCH_HEALTH=0 (skip the round-18 federation-health drill,
detail.federation_health)
FEDCRACK_BENCH_ROBUST=0 (skip the round-21 robust-aggregation A/B drill,
detail.robust_aggregation)
FEDCRACK_BENCH_LOWP=0 (skip the round-20 low-precision kernel A/B,
detail.lowp_kernels) FEDCRACK_BENCH_LOWP_IMG=64 (its bucket size)
FEDCRACK_BENCH_LOWP_CALLS=2 (predict calls at the short length; the long
length is FIT_FACTOR x this)
FEDCRACK_BENCH_PRIVACY=0 (skip the round-23 privacy section,
detail.privacy) FEDCRACK_BENCH_PRIVACY_ROUNDS=2 (DP utility A/B rounds)
FEDCRACK_BENCH_PRIVACY_SIGMAS=0.5,1.1 (noise multipliers beside the off
arm)
"""

from __future__ import annotations

import json
import os
import signal
import time

import jax
import numpy as np

STEPS = int(os.environ.get("FEDCRACK_BENCH_STEPS", "32"))
BATCH = int(os.environ.get("FEDCRACK_BENCH_BATCH", "16"))
REPS = int(os.environ.get("FEDCRACK_BENCH_REPS", "3"))
SIZES = tuple(
    int(s) for s in os.environ.get("FEDCRACK_BENCH_SIZES", "128,256").split(",")
)
SEED = 0

# Reference-scale round (the reference's actual workload: 10 local epochs x
# ~388 steps of batch 16 over 6213 images, client_fit_model.py:166,76).
# "auto" runs it on TPU only — at 3,880 steps a CPU smoke run would take
# hours; "1"/"0" force it on/off.
REF_EPOCHS = int(os.environ.get("FEDCRACK_BENCH_REF_EPOCHS", "10"))
REF_STEPS = int(os.environ.get("FEDCRACK_BENCH_REF_STEPS", "388"))
REF_SCALE = os.environ.get("FEDCRACK_BENCH_REF_SCALE", "auto")
REF_256 = os.environ.get("FEDCRACK_BENCH_REF_256", "0") == "1"
# Segment count for the epoch-segmented execution A/B (round 7) and the
# chunked 256 px reference-scale point: K device-resident-carry programs of
# REF_EPOCHS/K epochs each (parallel.fedavg_mesh.SegmentedRound —
# bit-identical to the monolithic scan). Default: one segment per epoch.
SEGMENTS = int(os.environ.get("FEDCRACK_BENCH_SEGMENTS", str(REF_EPOCHS)))

# ---- artifact schema contract -----------------------------------------------
# Consumers (the driver's JSON parse, BASELINE.md updates, cross-round
# comparisons) key on these names; tests/test_bench.py::test_detail_schema_*
# guard them so a rename breaks CI instead of silently breaking artifact
# readers. Every key is OPTIONAL in any given run (budget gating skips
# sections) but, when present, must carry the declared type.
DETAIL_SCHEMA: dict = {
    "sweep": dict,
    "skipped": list,
    "budget": dict,
    "reference_scale": dict,
    "layout_ab": dict,
    "segmented_pipeline": dict,
    "resident_pool": dict,
    "host_plane": dict,
    "batch_curve": dict,
    "input_pipeline": dict,
    "chaos_recovery": dict,
    "serving": dict,
    "serve_fleet": dict,
    "elastic_fleet": dict,
    "update_compression": dict,
    "cohort_scale": dict,
    "async_federation": dict,
    "observability": dict,
    "federation_health": dict,
    "robust_aggregation": dict,
    "video_serving": dict,
    "lowp_kernels": dict,
    "privacy": dict,
}
# Typed keys of detail.observability (round 15): the concurrent mini-soak's
# contract — the self-scrape must cover all five instrumented planes and
# the end-of-soak invariant audit must hold (zero torn versions, EF mass
# conserved, bit-identical statefile restore, steady watermarks).
OBSERVABILITY_SCHEMA: dict = {
    "traffic_wall_s": (int, float),
    "storm_fired": bool,
    "federation": dict,
    "serve": dict,
    "scrape": dict,
    "spans": dict,
    "audit": dict,
}
# Required keys of detail.observability.audit — the gate bench readers and
# the tier-1 guard test read.
OBSERVABILITY_AUDIT_SCHEMA: dict = {
    "torn_versions": int,
    "zero_torn_versions": bool,
    "serve_healthy": bool,
    "ef_mass_conserved": bool,
    "statefile_restore_bit_identical": bool,
    "watermarks_steady": bool,
    "recompiles_since_warmup": int,
    "clean": bool,
}
# Additive round-16 arms of detail.observability — distributed tracing and
# the SLO watchdog. Typed (and sub-schema'd) whenever PRESENT; presence
# itself is required only from round 16 on (the committed r15 artifact
# predates them — the dedicated r16 artifact test pins presence AND the
# ≥3-planes single-trace chain).
OBSERVABILITY_R16_SCHEMA: dict = {
    "tracing": dict,
    "watchdog": dict,
}
# Required keys of detail.observability.tracing: the stitched-trace summary
# (tools/trace_stitch.py over the soak's span JSONL) — `complete` means one
# trace id followed client train → push → flush → swap → first served
# batch, `planes_crossed` lists the span-name planes on that chain.
OBSERVABILITY_TRACING_SCHEMA: dict = {
    "records": int,
    "traces": int,
    "chains": int,
    "n_complete": int,
    "complete": bool,
    "trace": (str, type(None)),
    "planes_crossed": list,
    "stages": list,
}
# Required keys of detail.observability.watchdog: the machine-checked SLO
# audit (obs/watchdog.py) — every rule evaluated, zero breaches = clean.
OBSERVABILITY_WATCHDOG_SCHEMA: dict = {
    "rules_evaluated": int,
    "rules": list,
    "evaluations": int,
    "never_determinate": list,
    "all_rules_evaluated": bool,
    "breaches": list,
    "clean": bool,
}
# Typed keys of detail.federation_health (round 18): the SCALED_UPDATE
# chaos drill — FedAvg's sanitation gate ACCEPTS the norm-bounded-but-
# scaled update (it is finite and well-formed), the per-client ledger's
# robust-z anomaly score flags it, the canary IoU falls off a cliff on the
# poisoned install, and the health SLO watchdog turns that into a breach +
# flight dump + exit-3 verdict. Three sub-blocks, one per plane.
FEDERATION_HEALTH_SCHEMA: dict = {
    "ledger": dict,
    "canary": dict,
    "watchdog": dict,
}
FEDERATION_HEALTH_LEDGER_SCHEMA: dict = {
    "fault_fired": str,
    "poisoned_accepted": bool,
    "honest_accepted": bool,
    "nothing_rejected": bool,
    "global_drag_matches_fedavg": bool,
    "anomaly_scores": dict,
    "alert_threshold": (int, float),
    "poisoned_flagged": bool,
    "honest_below_alert": bool,
    "flagged_flushes": int,
}
FEDERATION_HEALTH_CANARY_SCHEMA: dict = {
    "reference_iou": (int, float),
    "poisoned_iou": (int, float),
    "iou_cliff": bool,
    "swap_still_installed": bool,
    "recompiles_since_warmup": int,
}
FEDERATION_HEALTH_WATCHDOG_SCHEMA: dict = {
    "rules": list,
    "breached": list,
    "both_signals_breached": bool,
    "flight_dumped": bool,
    "breach_exit_code": int,
    "would_exit": int,
}
# Typed keys of detail.robust_aggregation (round 21): the r18
# SCALED_UPDATE scenario as a 4-arm A/B over real gRPC — identical
# poisoned cohort, the only delta being FedConfig.aggregation /
# quarantine_z — plus a 7-client colluding-minority variant and the
# health-report join proving the quarantine exclusion is visible there.
ROBUST_AGGREGATION_SCHEMA: dict = {
    "scale_factor": (int, float),
    "honest_mean": (int, float),
    "reference_iou": (int, float),
    "arms": dict,
    "fedavg_cliffed": bool,
    "robust_arms_hold": bool,
    "drag_reduced_10x": bool,
    "colluding": dict,
    "health_report": dict,
    "drill_s": (int, float),
}
# Keys every arm of detail.robust_aggregation.arms must carry (the
# quarantine arm adds its NOT_WAIT-resync extras on top; robust arms add
# drag_reduction_vs_fedavg — extras are allowed, absences are not).
ROBUST_AGGREGATION_ARM_SCHEMA: dict = {
    "aggregation": str,
    "quarantine_z": (int, float),
    "global_avg": (int, float),
    "drag": (int, float),
    "quarantined": dict,
    "canary_iou": (int, float),
    "serve_factor": (int, float),
}
ROBUST_AGGREGATION_HEALTH_SCHEMA: dict = {
    "schema_violations": list,
    "quarantines": int,
    "quarantined_clients": list,
    "exclusion_visible": bool,
}
# Typed keys of detail.privacy (round 23): the privacy plane's cost model —
# the DP-SGD utility/epsilon trade at 2-3 noise levels on the mesh twin
# (identical data/seeds, the only delta being the noise multiplier), the
# secagg masking overhead vs the plaintext wire (host math: fixed-point
# encode + pairwise pads, with the unmasked mean pinned EXACT against the
# plaintext weighted sum), and the real-gRPC dropped-masker drill.
PRIVACY_SCHEMA: dict = {
    "rounds": int,
    "dp_utility": dict,
    "secagg_overhead": dict,
    "secagg_drill": dict,
    "bench_s": (int, float),
}
# Keys every arm of detail.privacy.dp_utility must carry. `epsilon` is
# None only on the off arm (no noise, nothing to account).
PRIVACY_DP_ARM_SCHEMA: dict = {
    "noise_multiplier": (int, float),
    "clip_norm": (int, float),
    "epsilon": (int, float, type(None)),
    "val_iou": (int, float),
    "val_loss": (int, float),
    "weight_drift_vs_off": (int, float),
}
PRIVACY_SECAGG_OVERHEAD_SCHEMA: dict = {
    "n_params": int,
    "cohort": int,
    "bits": int,
    "plaintext_bytes": int,
    "masked_bytes": int,
    "wire_ratio": (int, float),
    "mask_ms": (int, float),
    "unmask_ms": (int, float),
    "exact_vs_plaintext": bool,
}
# The real-gRPC drill pins the section cannot ship without.
PRIVACY_DRILL_SCHEMA: dict = {
    "fault_fired": bool,
    "dropout_recovered": bool,
    "exact_average_bit_for_bit": bool,
    "torn_rounds": int,
}
# Typed keys of detail.async_federation (round 14): the buffered-async
# contract — the chaos straggler-storm sync-vs-buffered A/B at equal wall,
# the bit-exact sync-degeneration pin, the mid-buffer kill→restart drill,
# and the equal-wall trajectory simulation (the CPU proxy; real-model IoU
# at equal wall is TPU measurement item 7).
ASYNC_FEDERATION_SCHEMA: dict = {
    "storm": dict,
    "sync_equivalence": dict,
    "recovery": dict,
    "trajectory": dict,
}
# Per-arm keys of detail.async_federation.storm.{sync,buffered}.
ASYNC_STORM_ARM_SCHEMA: dict = {
    "wall_s": (int, float),
    "accepted_updates": int,
    "global_versions": int,
    "updates_per_sec": (int, float),
    "versions_per_min": (int, float),
}
# Typed keys of detail.cohort_scale (round 13): the time-multiplexed-cohort
# + hierarchical-tree contract — the group-count sweep's wall scaling, the
# 1,024-simulated-client tree round's memory/byte accounting, and the
# tree-vs-flat A/B.
COHORT_SCALE_SCHEMA: dict = {
    "groups": dict,
    "tree": dict,
    "flat": dict,
}
# Per-point keys of detail.cohort_scale.groups.*.
COHORT_GROUP_SCHEMA: dict = {
    "round_wall_s": (int, float),
    "group_dispatches": int,
}
# Typed keys of detail.update_compression (round 12): the compressed-
# transport A/B contract — real wire bytes + codec timings at reference
# scale, and the mesh-twin crack-IoU trajectory vs the NullCodec oracle.
COMPRESSION_SCHEMA: dict = {
    "dense_update_bytes": int,
    "rounds": int,
    "wire": dict,
    "trajectory": dict,
}
# Per-codec keys of detail.update_compression.wire.*.
COMPRESSION_WIRE_SCHEMA: dict = {
    "bytes_per_round": int,
    "ratio_vs_null": (int, float, type(None)),
    "encode_ms": (int, float),
    "decode_ms": (int, float),
}
# Typed keys of detail.serving (round 10): the serving-plane SLO contract —
# throughput, latency percentiles, zero-drop accounting and the hot-swap
# record that BASELINE.md "Serving SLO" reads.
SERVING_SCHEMA: dict = {
    "throughput_rps": (int, float, type(None)),
    "latency_ms": dict,
    "requests": dict,
    "batcher": dict,
    "swap": (dict, type(None)),
    "dropped": int,
}
# Typed keys of detail.serve_fleet (round 17): the fleet scale-out +
# quantized-predict contract — the replicas x {bf16,int8} throughput/p95
# grid, the fleet-wide two-phase swap (pause + zero torn versions), the
# admission-control shed run under a ramp arrival profile, and the int8
# install gate's verdict.
SERVE_FLEET_SCHEMA: dict = {
    "buckets": list,
    "max_batch": int,
    "grid": dict,
    "swap": dict,
    "shed": dict,
    "quant_gate": (dict, type(None)),
}
# Per-arm keys of detail.serve_fleet.grid.*. `served_quant` records whether
# the arm ACTUALLY served the quantized program (the grid's int8 fleets
# install under a relaxed measurement floor; a false here on an int8 arm
# means even that floor refused and the numbers are the bf16 fallback).
SERVE_FLEET_ARM_SCHEMA: dict = {
    "replicas": int,
    "quant": str,
    "served_quant": bool,
    "requests": int,
    "completed": int,
    "throughput_rps": (int, float, type(None)),
    "p50_ms": (int, float, type(None)),
    "p95_ms": (int, float, type(None)),
}
# Typed keys of detail.elastic_fleet (round 22): the SLO-driven autoscaler
# + shadow-delivery contract — the 3-arm diurnal A/B (static-max holds the
# profile by burning replicas, static-min sheds at the peak, the autoscaled
# arm holds p95 with zero sheds and zero drops at STRICTLY lower
# replica-seconds than static-max), the autoscaler's full action audit,
# and the shadow-replica verdicts (one promote, one rollback, each with
# the deciding iou/psi/latency deltas).
ELASTIC_FLEET_SCHEMA: dict = {
    "profile": str,
    "rate_rps": (int, float),
    "requests": int,
    "slo_p95_ms": (int, float),
    "queue_bound": int,
    "arms": dict,
    "autoscaler": dict,
    "autoscaled_cheaper_than_static_max": bool,
    "autoscaled_held_slo": bool,
    "static_min_shed": bool,
    "shadow": dict,
}
# Per-arm keys of detail.elastic_fleet.arms.*. `replica_seconds` is the
# cost integral: live-replicas x wall for the autoscaled arm (the
# controller's meter), replicas x wall for the static arms. `replicas_*`
# come from load_gen's --metrics-url sampler polling the live
# serve_fleet_replicas gauge — `replicas_varied` True on the autoscaled
# arm is the wire-level proof the fleet actually resized mid-profile.
ELASTIC_ARM_SCHEMA: dict = {
    "replicas_band": list,
    "completed": int,
    "shed": int,
    "dropped": int,
    "p95_ms": (int, float, type(None)),
    "wall_s": (int, float),
    "replica_seconds": (int, float),
    "replicas_min": (int, type(None)),
    "replicas_max": (int, type(None)),
    "replicas_varied": bool,
}
# Required keys of detail.elastic_fleet.shadow: the progressive-delivery
# pins. Each record is a ShadowController verdict — iou vs the production
# payload's canary, drift PSI on the shared probe batch, the shadow-lane
# latency factor, and the reasons that decided it.
ELASTIC_SHADOW_SCHEMA: dict = {
    "promote": dict,
    "rollback": dict,
    "promoted": bool,
    "rolled_back": bool,
}
# Typed keys of detail.video_serving (round 19): the frame-coherent video
# contract — the stateless-vs-cached-session A/B over a seeded
# >=90%-overlap sequence, the per-frame byte-identity audit spanning a
# live mid-sequence hot swap, the effective-throughput model
# (img/s-equiv ~= stateless / changed-tile-fraction), the serve_stream_*
# exposition check, and the StreamPredict gRPC smoke.
VIDEO_SERVING_SCHEMA: dict = {
    "frame": dict,
    "stateless": dict,
    "session": dict,
    "effective_speedup": (int, float, type(None)),
    "effective_img_per_s": (int, float, type(None)),
    "speedup_target_met": bool,
    "identity": dict,
    "swap": dict,
    "metrics_in_exposition": bool,
    "grpc_smoke": (dict, type(None)),
}
# Typed keys of detail.lowp_kernels (round 20): the kernel-plane A/B — the
# r17 reference plane (dequantize-then-matmul in XLA) vs the fused-int8
# Pallas plane (dequant fused into the matmul's K loop; the Pallas
# INTERPRETER off-TPU) vs fp8 where the backend has the dtypes, on the
# round-5 interleaved two-length template. Off-TPU the artifact's value is
# the parity + gate columns (twin correctness); the timing columns become
# a perf claim only on a real TPU (ROADMAP TPU measurement item 10).
LOWP_KERNELS_SCHEMA: dict = {
    "img": int,
    "interpret_mode": bool,
    "fp8_supported": bool,
    "flops_per_forward_canonical": (int, float),
    "impls": dict,
    "speedup_vs_reference": dict,
}
# Per-variant keys of detail.lowp_kernels.impls.*. `parity_max_abs_diff`
# is vs the reference plane's probabilities on the same probe batch (0.0
# for the reference arm by construction); `gate` is the r17 two-phase
# install gate's full verdict for THIS plane's program.
LOWP_IMPL_SCHEMA: dict = {
    "round_s_short": (int, float),
    "round_s_long": (int, float),
    "per_step_ms": (int, float, type(None)),
    "mfu": (int, float, type(None)),
    "parity_max_abs_diff": (int, float),
    "gate": dict,
}
# Per-point keys of detail.reference_scale.* and the per-arm dicts of
# detail.segmented_pipeline.*: the staging/overlap decomposition contract.
REF_POINT_SCHEMA: dict = {
    "round_ms": (int, float),
    "round_plus_restage_ms": (int, float, type(None)),
    "staging_hidden_frac": (int, float, type(None)),
}


def validate_detail(detail: dict) -> list:
    """Schema-contract violations in an emitted ``detail`` payload (empty =
    clean). Pure checks — shared by the bench itself and the tier-1 guard
    test so the contract cannot drift from the code that writes it."""
    bad = []
    for key, typ in DETAIL_SCHEMA.items():
        if key in detail and not isinstance(detail[key], typ):
            bad.append(f"detail[{key!r}] is {type(detail[key]).__name__}, wants {typ}")
    for name, point in (detail.get("reference_scale") or {}).items():
        for key, typs in REF_POINT_SCHEMA.items():
            if key in point and not isinstance(point[key], typs):
                bad.append(f"reference_scale[{name!r}][{key!r}]: {type(point[key]).__name__}")
    for name, ab in (detail.get("segmented_pipeline") or {}).items():
        for arm in ("monolithic", "segmented"):
            for key, typs in REF_POINT_SCHEMA.items():
                val = (ab.get(arm) or {}).get(key)
                if val is not None and not isinstance(val, typs):
                    bad.append(f"segmented_pipeline[{name!r}][{arm}][{key!r}]")
    for name, ab in (detail.get("resident_pool") or {}).items():
        for arm in ("streamed", "resident"):
            for key, typs in REF_POINT_SCHEMA.items():
                val = (ab.get(arm) or {}).get(key)
                if val is not None and not isinstance(val, typs):
                    bad.append(f"resident_pool[{name!r}][{arm}][{key!r}]")
    serving = detail.get("serving")
    if isinstance(serving, dict) and "error" not in serving:
        for key, typs in SERVING_SCHEMA.items():
            if key not in serving:
                bad.append(f"serving[{key!r}] missing")
            elif not isinstance(serving[key], typs):
                bad.append(f"serving[{key!r}]: {type(serving[key]).__name__}")
    fleet = detail.get("serve_fleet")
    if isinstance(fleet, dict) and "error" not in fleet:
        for key, typs in SERVE_FLEET_SCHEMA.items():
            if key not in fleet:
                bad.append(f"serve_fleet[{key!r}] missing")
            elif not isinstance(fleet[key], typs):
                bad.append(f"serve_fleet[{key!r}]: {type(fleet[key]).__name__}")
        grid = fleet.get("grid")
        for name, point in (grid if isinstance(grid, dict) else {}).items():
            if not isinstance(point, dict):
                # Report, never TypeError — the r12 wire-map contract.
                bad.append(f"serve_fleet.grid[{name!r}]: {type(point).__name__}")
                continue
            for key, typs in SERVE_FLEET_ARM_SCHEMA.items():
                if key not in point:
                    bad.append(f"serve_fleet.grid[{name!r}][{key!r}] missing")
                elif not isinstance(point[key], typs):
                    bad.append(
                        f"serve_fleet.grid[{name!r}][{key!r}]: "
                        f"{type(point[key]).__name__}"
                    )
    elastic = detail.get("elastic_fleet")
    if isinstance(elastic, dict) and "error" not in elastic:
        for key, typs in ELASTIC_FLEET_SCHEMA.items():
            if key not in elastic:
                bad.append(f"elastic_fleet[{key!r}] missing")
            elif not isinstance(elastic[key], typs):
                bad.append(f"elastic_fleet[{key!r}]: {type(elastic[key]).__name__}")
        arms = elastic.get("arms")
        if isinstance(arms, dict) and not arms:
            bad.append("elastic_fleet['arms'] is empty")
        for name, point in (arms if isinstance(arms, dict) else {}).items():
            if not isinstance(point, dict):
                # Report, never TypeError — the r12 wire-map contract.
                bad.append(f"elastic_fleet.arms[{name!r}]: {type(point).__name__}")
                continue
            for key, typs in ELASTIC_ARM_SCHEMA.items():
                if key not in point:
                    bad.append(f"elastic_fleet.arms[{name!r}][{key!r}] missing")
                elif not isinstance(point[key], typs):
                    bad.append(
                        f"elastic_fleet.arms[{name!r}][{key!r}]: "
                        f"{type(point[key]).__name__}"
                    )
        shadow = elastic.get("shadow")
        if isinstance(shadow, dict):
            for key, typs in ELASTIC_SHADOW_SCHEMA.items():
                if key not in shadow:
                    bad.append(f"elastic_fleet.shadow[{key!r}] missing")
                elif not isinstance(shadow[key], typs):
                    bad.append(
                        f"elastic_fleet.shadow[{key!r}]: "
                        f"{type(shadow[key]).__name__}"
                    )
    comp = detail.get("update_compression")
    if isinstance(comp, dict) and "error" not in comp:
        for key, typs in COMPRESSION_SCHEMA.items():
            if key not in comp:
                bad.append(f"update_compression[{key!r}] missing")
            elif not isinstance(comp[key], typs):
                bad.append(f"update_compression[{key!r}]: {type(comp[key]).__name__}")
        wire = comp.get("wire")
        for name, point in (wire if isinstance(wire, dict) else {}).items():
            if not isinstance(point, dict):
                # Same contract as the wire map itself: a malformed artifact
                # is REPORTED, never a TypeError aborting validation.
                bad.append(
                    f"update_compression.wire[{name!r}]: {type(point).__name__}"
                )
                continue
            for key, typs in COMPRESSION_WIRE_SCHEMA.items():
                if key not in point:
                    bad.append(f"update_compression.wire[{name!r}][{key!r}] missing")
                elif not isinstance(point[key], typs):
                    bad.append(
                        f"update_compression.wire[{name!r}][{key!r}]: "
                        f"{type(point[key]).__name__}"
                    )
    asyncf = detail.get("async_federation")
    if isinstance(asyncf, dict) and "error" not in asyncf:
        for key, typs in ASYNC_FEDERATION_SCHEMA.items():
            if key not in asyncf:
                bad.append(f"async_federation[{key!r}] missing")
            elif not isinstance(asyncf[key], typs):
                bad.append(
                    f"async_federation[{key!r}]: {type(asyncf[key]).__name__}"
                )
        storm = asyncf.get("storm")
        for arm in ("sync", "buffered"):
            point = (storm if isinstance(storm, dict) else {}).get(arm)
            if not isinstance(point, dict):
                bad.append(
                    f"async_federation.storm[{arm!r}]: "
                    f"{type(point).__name__}"
                )
                continue
            for key, typs in ASYNC_STORM_ARM_SCHEMA.items():
                if key not in point:
                    bad.append(
                        f"async_federation.storm[{arm!r}][{key!r}] missing"
                    )
                elif not isinstance(point[key], typs):
                    bad.append(
                        f"async_federation.storm[{arm!r}][{key!r}]: "
                        f"{type(point[key]).__name__}"
                    )
    obsy = detail.get("observability")
    if isinstance(obsy, dict) and "error" not in obsy:
        for key, typs in OBSERVABILITY_SCHEMA.items():
            if key not in obsy:
                bad.append(f"observability[{key!r}] missing")
            elif not isinstance(obsy[key], typs):
                bad.append(f"observability[{key!r}]: {type(obsy[key]).__name__}")
        audit = obsy.get("audit")
        if isinstance(audit, dict):
            for key, typs in OBSERVABILITY_AUDIT_SCHEMA.items():
                if key not in audit:
                    bad.append(f"observability.audit[{key!r}] missing")
                elif not isinstance(audit[key], typs):
                    bad.append(
                        f"observability.audit[{key!r}]: "
                        f"{type(audit[key]).__name__}"
                    )
        scrape_block = obsy.get("scrape")
        if isinstance(scrape_block, dict):
            planes = scrape_block.get("planes_covered")
            if not isinstance(planes, dict):
                bad.append(
                    f"observability.scrape['planes_covered']: "
                    f"{type(planes).__name__}"
                )
        for key, typs in OBSERVABILITY_R16_SCHEMA.items():
            if key not in obsy:
                continue  # additive from round 16; r15 artifacts predate it
            if not isinstance(obsy[key], typs):
                bad.append(f"observability[{key!r}]: {type(obsy[key]).__name__}")
                continue
            sub_schema = (
                OBSERVABILITY_TRACING_SCHEMA
                if key == "tracing"
                else OBSERVABILITY_WATCHDOG_SCHEMA
            )
            for sub, styps in sub_schema.items():
                if sub not in obsy[key]:
                    bad.append(f"observability.{key}[{sub!r}] missing")
                elif not isinstance(obsy[key][sub], styps):
                    bad.append(
                        f"observability.{key}[{sub!r}]: "
                        f"{type(obsy[key][sub]).__name__}"
                    )
    health = detail.get("federation_health")
    if isinstance(health, dict) and "error" not in health:
        for key, typs in FEDERATION_HEALTH_SCHEMA.items():
            if key not in health:
                bad.append(f"federation_health[{key!r}] missing")
            elif not isinstance(health[key], typs):
                bad.append(
                    f"federation_health[{key!r}]: {type(health[key]).__name__}"
                )
        for block_key, sub_schema in (
            ("ledger", FEDERATION_HEALTH_LEDGER_SCHEMA),
            ("canary", FEDERATION_HEALTH_CANARY_SCHEMA),
            ("watchdog", FEDERATION_HEALTH_WATCHDOG_SCHEMA),
        ):
            block = health.get(block_key)
            if not isinstance(block, dict):
                continue
            for key, typs in sub_schema.items():
                if key not in block:
                    bad.append(
                        f"federation_health.{block_key}[{key!r}] missing"
                    )
                elif not isinstance(block[key], typs):
                    bad.append(
                        f"federation_health.{block_key}[{key!r}]: "
                        f"{type(block[key]).__name__}"
                    )
    robust = detail.get("robust_aggregation")
    if isinstance(robust, dict) and "error" not in robust:
        for key, typs in ROBUST_AGGREGATION_SCHEMA.items():
            if key not in robust:
                bad.append(f"robust_aggregation[{key!r}] missing")
            elif not isinstance(robust[key], typs):
                bad.append(
                    f"robust_aggregation[{key!r}]: "
                    f"{type(robust[key]).__name__}"
                )
        arms = robust.get("arms")
        if isinstance(arms, dict):
            for arm_name in sorted(arms):
                arm = arms[arm_name]
                if not isinstance(arm, dict):
                    # Report, never TypeError: a non-dict arm is its own
                    # violation, not a crash inside the validator.
                    bad.append(
                        f"robust_aggregation.arms[{arm_name!r}]: "
                        f"{type(arm).__name__}"
                    )
                    continue
                for key, typs in ROBUST_AGGREGATION_ARM_SCHEMA.items():
                    if key not in arm:
                        bad.append(
                            f"robust_aggregation.arms[{arm_name!r}]"
                            f"[{key!r}] missing"
                        )
                    elif not isinstance(arm[key], typs):
                        bad.append(
                            f"robust_aggregation.arms[{arm_name!r}]"
                            f"[{key!r}]: {type(arm[key]).__name__}"
                        )
        hp = robust.get("health_report")
        if isinstance(hp, dict):
            for key, typs in ROBUST_AGGREGATION_HEALTH_SCHEMA.items():
                if key not in hp:
                    bad.append(
                        f"robust_aggregation.health_report[{key!r}] missing"
                    )
                elif not isinstance(hp[key], typs):
                    bad.append(
                        f"robust_aggregation.health_report[{key!r}]: "
                        f"{type(hp[key]).__name__}"
                    )
    privacy = detail.get("privacy")
    if isinstance(privacy, dict) and "error" not in privacy:
        for key, typs in PRIVACY_SCHEMA.items():
            if key not in privacy:
                bad.append(f"privacy[{key!r}] missing")
            elif not isinstance(privacy[key], typs):
                bad.append(f"privacy[{key!r}]: {type(privacy[key]).__name__}")
        dp_arms = privacy.get("dp_utility")
        if isinstance(dp_arms, dict):
            if not dp_arms:
                bad.append("privacy['dp_utility'] is empty")
            for arm_name in sorted(dp_arms):
                arm = dp_arms[arm_name]
                if not isinstance(arm, dict):
                    bad.append(
                        f"privacy.dp_utility[{arm_name!r}]: "
                        f"{type(arm).__name__}"
                    )
                    continue
                for key, typs in PRIVACY_DP_ARM_SCHEMA.items():
                    if key not in arm:
                        bad.append(
                            f"privacy.dp_utility[{arm_name!r}]"
                            f"[{key!r}] missing"
                        )
                    elif not isinstance(arm[key], typs):
                        bad.append(
                            f"privacy.dp_utility[{arm_name!r}]"
                            f"[{key!r}]: {type(arm[key]).__name__}"
                        )
        overhead = privacy.get("secagg_overhead")
        if isinstance(overhead, dict):
            for key, typs in PRIVACY_SECAGG_OVERHEAD_SCHEMA.items():
                if key not in overhead:
                    bad.append(f"privacy.secagg_overhead[{key!r}] missing")
                elif not isinstance(overhead[key], typs):
                    bad.append(
                        f"privacy.secagg_overhead[{key!r}]: "
                        f"{type(overhead[key]).__name__}"
                    )
        drill = privacy.get("secagg_drill")
        if isinstance(drill, dict):
            for key, typs in PRIVACY_DRILL_SCHEMA.items():
                if key not in drill:
                    bad.append(f"privacy.secagg_drill[{key!r}] missing")
                elif not isinstance(drill[key], typs):
                    bad.append(
                        f"privacy.secagg_drill[{key!r}]: "
                        f"{type(drill[key]).__name__}"
                    )
    cohort = detail.get("cohort_scale")
    if isinstance(cohort, dict) and "error" not in cohort:
        for key, typs in COHORT_SCALE_SCHEMA.items():
            if key not in cohort:
                bad.append(f"cohort_scale[{key!r}] missing")
            elif not isinstance(cohort[key], typs):
                bad.append(f"cohort_scale[{key!r}]: {type(cohort[key]).__name__}")
        groups = cohort.get("groups")
        for name, point in (groups if isinstance(groups, dict) else {}).items():
            if not isinstance(point, dict):
                bad.append(f"cohort_scale.groups[{name!r}]: {type(point).__name__}")
                continue
            for key, typs in COHORT_GROUP_SCHEMA.items():
                if key not in point:
                    bad.append(f"cohort_scale.groups[{name!r}][{key!r}] missing")
                elif not isinstance(point[key], typs):
                    bad.append(
                        f"cohort_scale.groups[{name!r}][{key!r}]: "
                        f"{type(point[key]).__name__}"
                    )
    video = detail.get("video_serving")
    if isinstance(video, dict) and "error" not in video:
        for key, typs in VIDEO_SERVING_SCHEMA.items():
            if key not in video:
                bad.append(f"video_serving[{key!r}] missing")
            elif not isinstance(video[key], typs):
                bad.append(f"video_serving[{key!r}]: {type(video[key]).__name__}")
    lowp = detail.get("lowp_kernels")
    if isinstance(lowp, dict) and "error" not in lowp:
        for key, typs in LOWP_KERNELS_SCHEMA.items():
            if key not in lowp:
                bad.append(f"lowp_kernels[{key!r}] missing")
            elif not isinstance(lowp[key], typs):
                bad.append(f"lowp_kernels[{key!r}]: {type(lowp[key]).__name__}")
        impls = lowp.get("impls")
        if isinstance(impls, dict) and not impls:
            bad.append("lowp_kernels['impls'] is empty")
        for name, point in (impls if isinstance(impls, dict) else {}).items():
            if not isinstance(point, dict):
                # Report, never TypeError — the r12 wire-map contract.
                bad.append(f"lowp_kernels.impls[{name!r}]: {type(point).__name__}")
                continue
            for key, typs in LOWP_IMPL_SCHEMA.items():
                if key not in point:
                    bad.append(f"lowp_kernels.impls[{name!r}][{key!r}] missing")
                elif not isinstance(point[key], typs):
                    bad.append(
                        f"lowp_kernels.impls[{name!r}][{key!r}]: "
                        f"{type(point[key]).__name__}"
                    )
        if isinstance(impls, dict) and len(impls) >= 2:
            speed = lowp.get("speedup_vs_reference")
            if isinstance(speed, dict):
                for name, val in speed.items():
                    if not isinstance(val, (int, float)):
                        bad.append(
                            f"lowp_kernels.speedup_vs_reference[{name!r}]: "
                            f"{type(val).__name__}"
                        )
    return bad

# Default sized from measured section costs on the TPU-tunnel host (round 4):
# sweep_128 ~260 s + ref bf16 ~233 s + ref f32 ~132 s + host ~75 s ≈ 700 s on
# a warm compilation cache (big-program cache loads still ship executables
# through the ~30 MB/s tunnel — they are not free). 780 keeps both
# reference-scale points inside the budget warm (they run right after the
# sweep, so congestion degrades the TAIL sections — host plane, batch curve,
# 256 sweep — not the headline), and degrades to a sweep-only r02-level
# artifact when cold.
BUDGET_S = float(os.environ.get("FEDCRACK_BENCH_BUDGET_S", "780"))
_START = time.monotonic()

# XLA compile cost for a program this bench has never run on this host (no
# persistent-cache entry): measured 40-90 s per 128/256 px round program
# through the tunnel. Cost estimates for OPTIONAL sections must assume cold —
# round 4's first budget cut assumed warm and blew a wall-clock timeout
# inside the 256 sweep instead of skipping it.
COMPILE_EST_S = 60.0

# Mid-round kill→restart recovery drill (tools/chaos_drill): host-only,
# tiny weights, seconds — times the durable-statefile crash-recovery path
# (round 8). "0" opts out.
CHAOS = os.environ.get("FEDCRACK_BENCH_CHAOS", "1") == "1"

# Compressed update transport A/B (round 12, detail.update_compression):
# real wire bytes + encode/decode timings for the three codecs against one
# reference-scale round delta (host-side, seconds), and the mesh twins'
# crack-IoU trajectory vs the NullCodec oracle over
# FEDCRACK_BENCH_COMPRESSION_ROUNDS rounds of a small federation. "0" opts
# out.
COMPRESSION = os.environ.get("FEDCRACK_BENCH_COMPRESSION", "1") == "1"
COMPRESSION_ROUNDS = int(os.environ.get("FEDCRACK_BENCH_COMPRESSION_ROUNDS", "3"))

# Cohort-scale section (round 13, detail.cohort_scale): the group-count
# sweep over the time-multiplexed cohort round (wall ~linear in
# ceil(C/G) group dispatches, trajectory bitwise equal across splits), and
# the 1,024-simulated-client round through the 2-level aggregation tree
# with root-memory/byte accounting plus a flat-root A/B. "0" opts out.
COHORT = os.environ.get("FEDCRACK_BENCH_COHORT", "1") == "1"
COHORT_TREE_CLIENTS = int(os.environ.get("FEDCRACK_BENCH_COHORT_CLIENTS", "1024"))
COHORT_TREE_FANOUT = int(os.environ.get("FEDCRACK_BENCH_COHORT_FANOUT", "32"))

# Async-federation section (round 14, detail.async_federation): the chaos
# straggler-storm sync-vs-buffered A/B (real gRPC, seeded heavy-tail
# delays, equal wall), the bit-exact sync-degeneration pin (buffer_k=N,
# alpha=0 == sync FedAvg, sha-compared), the buffered mid-buffer
# kill→restart drill, and a deterministic equal-wall trajectory
# simulation. "0" opts out.
ASYNC = os.environ.get("FEDCRACK_BENCH_ASYNC", "1") == "1"

# Observability section (round 15, detail.observability): the concurrent
# mini-soak — buffered federation + edge shard + serve/hot-swap + driver
# leg under a rolling chaos schedule, self-scraped through a live /metrics
# endpoint, ending in the invariant audit. "0" opts out;
# FEDCRACK_BENCH_SOAK_S sizes the traffic wall.
OBSERVABILITY = os.environ.get("FEDCRACK_BENCH_OBSERVABILITY", "1") == "1"
SOAK_S = float(os.environ.get("FEDCRACK_BENCH_SOAK_S", "8"))
ASYNC_SEED = int(os.environ.get("FEDCRACK_BENCH_ASYNC_SEED", "0"))

# Federation-health section (round 18, detail.federation_health): the
# SCALED_UPDATE chaos drill — a sanitation-passing scaled update that
# FedAvg accepts, the per-client ledger's robust-z anomaly flag, the
# canary IoU cliff on the poisoned install, and the health SLO watchdog's
# breach → flight dump → exit-3 verdict. Host + tiny engine, seconds.
# "0" opts out.
HEALTH = os.environ.get("FEDCRACK_BENCH_HEALTH", "1") == "1"

# Robust-aggregation section (round 21, detail.robust_aggregation): the
# SCALED_UPDATE scenario as a 4-arm A/B over real gRPC (fedavg /
# trimmed_mean / krum / fedavg+quarantine — the only delta being
# FedConfig.aggregation), the per-arm canary IoU on one shared tiny
# engine, a 7-client colluding-minority variant, and the health-report
# join over the quarantine arm's ledger. Host + tiny engine, seconds.
# "0" opts out.
ROBUST = os.environ.get("FEDCRACK_BENCH_ROBUST", "1") == "1"

# Privacy section (round 23, detail.privacy): the DP-SGD utility/epsilon
# trade on the mesh twin (off vs FEDCRACK_BENCH_PRIVACY_SIGMAS noise arms,
# identical data/seeds), the secagg fixed-point masking overhead vs the
# plaintext wire with an EXACT unmask pin, and the real-gRPC
# dropped-masker drill. Tiny model; wall is the per-arm mesh compiles.
# "0" opts out.
PRIVACY = os.environ.get("FEDCRACK_BENCH_PRIVACY", "1") == "1"
PRIVACY_ROUNDS = int(os.environ.get("FEDCRACK_BENCH_PRIVACY_ROUNDS", "2"))
PRIVACY_SIGMAS = tuple(
    float(s)
    for s in os.environ.get(
        "FEDCRACK_BENCH_PRIVACY_SIGMAS", "0.5,1.1"
    ).split(",")
    if s.strip()
)

# Low-precision kernel A/B (round 20, detail.lowp_kernels): the quantized
# predict program per kernel plane — reference (the r17 dequantize-then-
# matmul XLA program), fused_int8 (the Pallas dequant-fused plane; the
# interpreter off-TPU), fp8 where the backend has the dtypes — interleaved
# on the r5 two-length template, plus per-plane numerics parity and the
# install gate's verdict. Tiny engine off-TPU, seconds. "0" opts out.
LOWP = os.environ.get("FEDCRACK_BENCH_LOWP", "1") == "1"
LOWP_IMG = int(os.environ.get("FEDCRACK_BENCH_LOWP_IMG", "64"))
LOWP_CALLS = int(os.environ.get("FEDCRACK_BENCH_LOWP_CALLS", "2"))

# Serving-plane SLO section (round 10, detail.serving): boots the full
# serve stack in-process (engine + micro-batcher + hot-swap manager + gRPC
# front door), drives it with tools/load_gen over >= 2 buckets, installs a
# live hot-swap at ~1/3 completions, and reports throughput / latency
# percentiles / swap pause. "0" opts out.
SERVING = os.environ.get("FEDCRACK_BENCH_SERVING", "1") == "1"
SERVE_SIZES = tuple(
    int(s)
    for s in os.environ.get("FEDCRACK_BENCH_SERVE_SIZES", "128,256").split(",")
    if s.strip()
)
SERVE_REQUESTS = int(os.environ.get("FEDCRACK_BENCH_SERVE_REQUESTS", "128"))
SERVE_MAX_BATCH = int(os.environ.get("FEDCRACK_BENCH_SERVE_MAX_BATCH", "8"))
SERVE_CONCURRENCY = int(os.environ.get("FEDCRACK_BENCH_SERVE_CONCURRENCY", "8"))

# Serve-fleet section (round 17, detail.serve_fleet): the replicas x
# {bf16,int8} in-process router grid (throughput + p50/p95 per arm), a
# fleet-wide two-phase swap with torn-version accounting, the gRPC-front-
# door shed run under a load_gen ramp profile against a tight queue bound,
# and the int8 install gate's probe-IoU verdict. "0" opts out.
SERVE_FLEET = os.environ.get("FEDCRACK_BENCH_SERVE_FLEET", "1") == "1"
FLEET_REPLICAS = tuple(
    int(s)
    for s in os.environ.get("FEDCRACK_BENCH_FLEET_REPLICAS", "1,2").split(",")
    if s.strip()
)
FLEET_REQUESTS = int(os.environ.get("FEDCRACK_BENCH_FLEET_REQUESTS", "64"))
FLEET_SHED_RATE = float(os.environ.get("FEDCRACK_BENCH_FLEET_SHED_RATE", "40"))

# Elastic-fleet section (round 22, detail.elastic_fleet): the 3-arm diurnal
# A/B — static-max vs static-min vs autoscaled — through the real gRPC
# front door with load_gen's diurnal profile and its --metrics-url replica
# sampler, plus the shadow-replica progressive-delivery pins (one candidate
# auto-promoted, one deliberately-degraded candidate auto-rolled-back).
# The model is deliberately tiny and every dispatch chaos-throttled: the
# section certifies the CONTROL LOOP (scale before shed, drain without
# drops, strictly fewer replica-seconds than static-max), not model
# throughput. "0" opts out.
ELASTIC = os.environ.get("FEDCRACK_BENCH_ELASTIC", "1") == "1"
ELASTIC_REQUESTS = int(os.environ.get("FEDCRACK_BENCH_ELASTIC_REQUESTS", "120"))
ELASTIC_RATE = float(os.environ.get("FEDCRACK_BENCH_ELASTIC_RATE", "24"))

# Video-serving section (round 19, detail.video_serving): the frame-coherent
# session A/B — stateless predict_tiled vs the per-stream tile-cached
# session over one seeded correlated sequence (>=90% frame-to-frame
# overlap), per-frame byte-identity audit across a live mid-sequence hot
# swap, the serve_stream_* registry exposition, and a StreamPredict gRPC
# smoke via load_gen --profile video. Tiny weights: the section certifies
# cache semantics and the effective-throughput model, not model quality.
# "0" opts out. The default motion fraction (0.04 -> 8 changed rows at 192)
# keeps the moving band inside ~2 of the 7 tile rows, so the steady-state
# changed-tile fraction stays well under 1/3 and the >=3x effective-speedup
# target is geometric, not timing-dependent.
VIDEO = os.environ.get("FEDCRACK_BENCH_VIDEO", "1") == "1"
VIDEO_FRAMES = int(os.environ.get("FEDCRACK_BENCH_VIDEO_FRAMES", "20"))
VIDEO_FRAME_SIZE = int(os.environ.get("FEDCRACK_BENCH_VIDEO_FRAME_SIZE", "192"))
VIDEO_MOTION_FRACTION = float(
    os.environ.get("FEDCRACK_BENCH_VIDEO_MOTION_FRACTION", "0.04")
)

# Longer-round multiplier for the dispatch-correction fit; the two-point
# slope needs the rounds to differ, so 2 is the floor.
FIT_FACTOR = max(2, int(os.environ.get("FEDCRACK_BENCH_FIT_FACTOR", "4")))

# Model-graph layout variants for the interleaved layout A/B (round 6):
# "reference", "s2d" (bit-exact width-folded space-to-depth stem),
# "s2d_full" (fully collapsed stride-1 stem, ~1 ulp), "respack" (channel-
# packed encoder residual projections, bit-exact); combine with "+"
# (e.g. "s2d+respack"). The first variant is the ratio denominator and
# should stay "reference".
LAYOUTS = tuple(
    s.strip()
    for s in os.environ.get(
        "FEDCRACK_BENCH_LAYOUTS", "reference,s2d,s2d_full,respack,s2d+respack"
    ).split(",")
    if s.strip()
)

CLIENTS_AX, BATCH_AX = "clients", "batch"


def _elapsed() -> float:
    return time.monotonic() - _START


def _remaining() -> float:
    return BUDGET_S - _elapsed()


# ---- partial-output machinery ------------------------------------------------
# The payload is rebuilt after every completed section; _emit prints it exactly
# once — at normal completion, or from the SIGTERM/SIGINT handler if the
# driver's own timeout fires first (rc will be 124 then, but the JSON line
# still carries every section that finished).
_OUT: dict = {"emitted": False, "payload": None}

# Where _emit writes the FULL payload as a file (best-effort; "" disables).
# The monolithic stdout payload line can run to hundreds of KB, and
# tail-capturing drivers truncate it (BENCH_r05.json shows "parsed": null
# for exactly that reason) — so the final stdout line is a COMPACT summary
# (headline metrics + this artifact path) that always survives, with the
# full payload printed on the line before it AND written here.
BENCH_OUT = os.environ.get("FEDCRACK_BENCH_OUT", "/tmp/fedcrack_bench_payload.json")


def compact_summary(payload: dict, artifact_path: str | None = None) -> dict:
    """The guaranteed-parseable final stdout line: headline metrics plus a
    pointer to the full-payload artifact, NO detail tree. Stays well under
    any sane line-capture limit regardless of how many sections ran —
    tier-1-tested (tests/test_bench.py) so it cannot regrow a payload."""
    detail = payload.get("detail") or {}
    out = {
        "compact": True,
        "metric": payload.get("metric"),
        "value": payload.get("value"),
        "unit": payload.get("unit"),
        "vs_baseline": payload.get("vs_baseline"),
        "sections": sorted(k for k in detail if k in DETAIL_SCHEMA and k != "skipped"),
        "skipped_n": len(detail.get("skipped") or []),
        "artifact": artifact_path,
    }
    if payload.get("interrupted"):
        out["interrupted"] = payload["interrupted"]
    if payload.get("schema_violations"):
        out["schema_violations_n"] = len(payload["schema_violations"])
    return out


def _set_payload(metric, value, vs_baseline, detail) -> None:
    _OUT["payload"] = {
        "metric": metric,
        "value": value,
        "unit": "ms",
        "vs_baseline": vs_baseline,
        "detail": detail,
    }


def _emit() -> None:
    if not _OUT["emitted"] and _OUT["payload"] is not None:
        _OUT["emitted"] = True
        try:
            # Self-check against the declared artifact schema at write time:
            # a violating payload still emits (a flagged artifact beats a
            # dead run) but carries the violations where consumers and the
            # committed-artifact guard test will surface them.
            bad = validate_detail(_OUT["payload"].get("detail") or {})
            if bad:
                _OUT["payload"]["schema_violations"] = bad
        except Exception:
            pass  # the schema self-check must never kill the artifact
        artifact_path = None
        try:
            if BENCH_OUT:
                with open(BENCH_OUT, "w") as f:
                    json.dump(_OUT["payload"], f)
                artifact_path = BENCH_OUT
        except Exception:
            artifact_path = None  # a read-only fs must never kill the emit
        print(json.dumps(_OUT["payload"]), flush=True)
        # FINAL stdout line: the compact summary — the one line a
        # tail-capturing driver is guaranteed to get whole.
        try:
            print(json.dumps(compact_summary(_OUT["payload"], artifact_path)), flush=True)
        except Exception:
            pass


def _install_signal_net() -> None:
    def handler(signum, frame):
        # Mark the artifact as interrupted (a run killed mid-section must be
        # distinguishable from one where later sections simply never ran) and
        # exit 128+signum so the rc says so too.
        if _OUT["payload"] is not None:
            try:
                name = signal.Signals(signum).name
            except ValueError:
                name = str(signum)
            _OUT["payload"]["interrupted"] = name
        _emit()
        os._exit(128 + signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, handler)
        except (ValueError, OSError):
            pass  # non-main thread / exotic platform: budget checks still cover us


# Install at import time, not in main(): a TERM that lands while jax is still
# initializing the backend would otherwise take the process down with the
# default disposition and zero output.
_install_signal_net()


# ---- transfer/synthesis rate tracking (feeds the cost estimates) -------------
_XFER = {"bytes": 0.0, "s": 0.0}
_SYNTH = {"bytes": 0.0, "s": 0.0}


def _est_stage_s(nbytes: float) -> float:
    bw = _XFER["bytes"] / _XFER["s"] if _XFER["s"] > 0 else 25e6
    return nbytes / max(bw, 1e6)


def _est_synth_s(nbytes: float) -> float:
    rate = _SYNTH["bytes"] / _SYNTH["s"] if _SYNTH["s"] > 0 else 60e6
    return nbytes / max(rate, 1e6)


def _synth(n: int, img: int, seed: int):
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    t0 = time.perf_counter()
    out = synth_crack_batch(n, img_size=img, seed=seed)
    _SYNTH["s"] += time.perf_counter() - t0
    _SYNTH["bytes"] += out[0].nbytes + out[1].nbytes
    return out


def _stage_timed(images, masks, mesh):
    """stage_round_data with the transfer rate recorded for estimates."""
    from fedcrack_tpu.parallel import stage_round_data

    t0 = time.perf_counter()
    si, sm = stage_round_data(images, masks, mesh)
    dt = time.perf_counter() - t0
    _XFER["s"] += dt
    _XFER["bytes"] += images.nbytes + masks.nbytes
    return si, sm, dt


def _stage_timed_chunks(images, masks, mesh, n_chunks: int):
    """Chunked staging with the transfer rate recorded: one device_put +
    barrier per step-range chunk (``data.pipeline.split_epoch_slab``), so no
    single transfer exceeds 1/n_chunks of the epoch slab — the grain the
    segmented round consumes, and the tunnel-safe form of the 1.6 GB 256 px
    epoch (round-5 isolation logs: the remote helper dies on the monolithic
    transfer + 3,880-step program)."""
    from fedcrack_tpu.data.pipeline import split_epoch_slab
    from fedcrack_tpu.parallel import stage_round_data

    t0 = time.perf_counter()
    ic, mc = split_epoch_slab(images, masks, n_chunks)
    pairs = [stage_round_data(i, m, mesh) for i, m in zip(ic, mc)]
    dt = time.perf_counter() - t0
    _XFER["s"] += dt
    _XFER["bytes"] += images.nbytes + masks.nbytes
    return tuple(p[0] for p in pairs), tuple(p[1] for p in pairs), dt


def _fits(est_s: float, reserve_s: float = 15.0) -> bool:
    """Does a section with this cost estimate fit the remaining budget?
    1.2x slack for estimate error plus a flat reserve for the final JSON."""
    return _remaining() > est_s * 1.2 + reserve_s


def _skip(skips: list, section: str, est_s: float, reason: str) -> None:
    skips.append(
        {
            "section": section,
            "est_s": round(est_s, 1),
            "remaining_s": round(_remaining(), 1),
            "reason": reason,
        }
    )


def _median_time(fn, reps: int = REPS) -> float:
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _make_round_runner(round_fn, variables, si, sm, active, n_samples):
    """Chained, readback-synced round at pre-staged data.

    Rounds are CHAINED (each consumes the previous round's output) and synced
    via a host readback of the round metrics, not just block_until_ready:
    through remote-device tunnels the latter has been observed to return
    before the program finishes, and repeating one identical call would let
    result caching fake the timing. The loss depends on every step, so its
    readback is a full-program barrier.
    """
    state = {"v": variables}

    def run():
        new_vars, metrics = round_fn(state["v"], si, sm, active, n_samples)
        state["v"] = new_vars
        float(np.asarray(metrics["loss"])[0])
        return new_vars

    return run


def _tile_steps(x, factor: int, mesh):
    """Cycle a staged [C, steps, B, ...] array to factor x steps ON DEVICE —
    value-identical to stack_client_data's host-side cycling for whole
    multiples, without shipping the duplicated bytes through the tunnel."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(CLIENTS_AX, None, BATCH_AX))
    out = jax.jit(
        lambda a: jnp.concatenate([a] * factor, axis=1), out_shardings=sharding
    )(x)
    jax.block_until_ready(out)
    return out


def _sweep_size(
    img: int, mesh, n_clients: int, device, peak, sweep: dict, checkpoint=None
):
    """Both dtypes at one crop size; returns the per-client float32 sample
    arrays (the host plane reuses them), the f32 initial state, and the
    staged short-scan device arrays (the batch curve regroups them on device
    instead of re-shipping bytes). ``checkpoint`` (if given) is called after
    each completed point so a mid-sweep TERM still ships the points that
    finished."""
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import build_federated_round, stack_client_data
    from fedcrack_tpu.train.local import create_train_state

    per_client = [
        _synth(STEPS * BATCH, img, SEED + i) for i in range(n_clients)
    ]
    images, masks = stack_client_data(per_client, STEPS, BATCH)
    # One staged data set serves both dtypes (values are dtype-independent);
    # the long-scan arrays are tiled on device from the short ones.
    si, sm, _ = _stage_timed(images, masks, mesh)
    si_long = _tile_steps(si, FIT_FACTOR, mesh)
    sm_long = _tile_steps(sm, FIT_FACTOR, mesh)
    active = np.ones(n_clients, np.float32)

    f32_state0 = None
    for dtype in ("float32", "bfloat16"):
        config = ModelConfig(img_size=img, compute_dtype=dtype)
        state0 = create_train_state(jax.random.key(SEED), config)
        if dtype == "float32":
            f32_state0 = state0
        round_fn = build_federated_round(
            mesh, config, learning_rate=1e-3, local_epochs=1
        )

        def timed(steps, data_i, data_m):
            n_samp = np.full(n_clients, float(steps * BATCH), np.float32)
            run = _make_round_runner(
                round_fn, state0.variables, data_i, data_m, active, n_samp
            )
            # Warm twice: first call consumes the host pytree, second
            # compiles the committed-device-input signature the timed
            # chained reps use.
            run()
            run()
            return _median_time(run)

        short_s = timed(STEPS, si, sm)
        long_s = timed(FIT_FACTOR * STEPS, si_long, sm_long)
        slope_s = (long_s - short_s) / ((FIT_FACTOR - 1) * STEPS)
        # A non-positive slope means timing noise swamped the fit: report
        # the point as unmeasurable (None) rather than publishing a garbage
        # per-step time / absurd MFU as if it were real.
        fit_ok = slope_s > 0.0
        step_s = slope_s if fit_ok else None
        flops = train_step_flops(config, BATCH)
        sweep[f"{dtype}_{img}"] = {
            "dtype": dtype,
            "img_size": img,
            # raw (unrounded) seconds: every derived ratio reads these,
            # so display rounding never leaks into the arithmetic
            "round_s_raw": short_s,
            "per_step_s_raw": step_s,
            "round_ms": round(short_s * 1e3, 2),
            "per_step_ms": round(step_s * 1e3, 3) if fit_ok else None,
            "naive_per_step_ms": round(short_s / STEPS * 1e3, 3),
            "dispatch_intercept_ms": (
                round(max(0.0, short_s - STEPS * step_s) * 1e3, 2)
                if fit_ok
                else None
            ),
            "flops_per_step": flops,
            "mfu": (
                round(mfu(step_s, flops, device), 4)
                if fit_ok and peak is not None
                else None
            ),
        }
        if checkpoint is not None:
            checkpoint()
    return per_client, f32_state0, (si, sm)


def _step_s(point) -> float:
    """Slope-based per-step seconds (raw), falling back to naive when the
    fit failed (the fallback overstates compute, so derived ratios degrade
    conservatively rather than crashing)."""
    if point["per_step_s_raw"] is not None:
        return point["per_step_s_raw"]
    return point["round_s_raw"] / STEPS


def _measure_host_plane(n_clients, variables, per_client, state0, reps=REPS):
    """The reference architecture, decomposed. Returns (total_s, parts).
    ``reps`` shrinks the median sample when the remaining budget is tight
    (a 1-rep host round beats a skipped host plane; the artifact records
    the rep count used)."""
    from fedcrack_tpu.fed.algorithms import fedavg
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
    from fedcrack_tpu.train.local import train_step

    mu0 = np.float32(0.0)
    host_vars = {"v": variables}

    def host_round():
        blob = tree_to_bytes(host_vars["v"])  # server -> client broadcast
        uploads = []
        for c in range(n_clients):
            received = tree_from_bytes(blob, template=variables)
            st = state0.replace_variables(received)
            st = st.replace(opt_state=st.tx.init(st.params))
            images, masks = per_client[c]
            for s in range(STEPS):
                batch = (
                    images[s * BATCH : (s + 1) * BATCH],
                    masks[s * BATCH : (s + 1) * BATCH],
                )
                st, _ = train_step(st, batch, received["params"], mu0)
            jax.block_until_ready(st.params)
            uploads.append(tree_to_bytes(st.variables))  # client -> server
        trees = [tree_from_bytes(b, template=variables) for b in uploads]
        avg = fedavg(trees, weights=[float(STEPS * BATCH)] * n_clients)
        jax.block_until_ready(avg)
        host_vars["v"] = jax.device_get(avg)
        return avg

    host_round()  # warm-up: compiles train_step at this shape
    total_s = _median_time(host_round, reps=reps)

    # Serialization cost, measured on the same pytree: per round the host
    # plane serializes 1 broadcast + C uploads and parses 2C blobs
    # (client receive + server receive).
    blob = tree_to_bytes(variables)
    to_s = _median_time(lambda: tree_to_bytes(variables))
    from_s = _median_time(lambda: tree_from_bytes(blob, template=variables))
    ser_s = to_s * (1 + n_clients) + from_s * (2 * n_clients)

    trees = [tree_from_bytes(blob, template=variables) for _ in range(n_clients)]
    fedavg_s = _median_time(
        lambda: jax.block_until_ready(fedavg(trees, weights=[1.0] * n_clients))
    )
    return total_s, {
        "serialization_ms": ser_s * 1e3,
        "host_fedavg_ms": fedavg_s * 1e3,
        # raw per-operation costs, so reconstructions at OTHER client counts
        # (the 1-client reference-scale round) can rebuild serialization for
        # their own shape instead of inheriting this n_clients' total
        "to_bytes_s_raw": to_s,
        "from_bytes_s_raw": from_s,
        "fedavg_s_raw": fedavg_s,
    }


def _batch_curve(
    img: int, mesh, n_clients, device, peak, si, sm, curve: dict, checkpoint=None
):
    """bf16 per-step time + MFU at batch {32, 64} (batch 16 is the sweep's
    flagship point). Substantiates BASELINE.md's width-bound-ceiling claim:
    if the model's 32-256-lane widths are the bottleneck, larger batches
    occupy more MXU rows at the same lane width and MFU should rise.

    Data is the flagship sweep's staged float32 arrays regrouped ON DEVICE
    ([C, S, B, ...] -> [C, S/f, f*B, ...]) — same bytes, same total samples
    per round, zero extra tunnel transfer. Batch 16 remains the parity
    headline (the reference's batch, client_fit_model.py:55-56); this curve
    is a non-parity appendix."""
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import build_federated_round
    from fedcrack_tpu.train.local import create_train_state

    from jax.sharding import NamedSharding, PartitionSpec as P

    config = ModelConfig(img_size=img, compute_dtype="bfloat16")
    state0 = create_train_state(jax.random.key(SEED), config)
    round_fn = build_federated_round(mesh, config, learning_rate=1e-3, local_epochs=1)
    active = np.ones(n_clients, np.float32)
    sharding = NamedSharding(mesh, P(CLIENTS_AX, None, BATCH_AX))

    for b in (32, 64):
        factor = b // BATCH
        if factor < 1 or b % BATCH:
            continue  # smoke-test batch overrides can make this degenerate
        steps_b = STEPS // factor
        if steps_b < 2 or steps_b * factor != STEPS:
            continue  # regroup must preserve element count (steps override)

        def regroup(a):
            out = jax.jit(
                lambda t: t.reshape(t.shape[0], steps_b, b, *t.shape[3:]),
                out_shardings=sharding,
            )(a)
            jax.block_until_ready(out)
            return out

        bi, bm = regroup(si), regroup(sm)
        bi_long = _tile_steps(bi, FIT_FACTOR, mesh)
        bm_long = _tile_steps(bm, FIT_FACTOR, mesh)
        n_samp = np.full(n_clients, float(steps_b * b), np.float32)

        def timed(data_i, data_m):
            run = _make_round_runner(
                round_fn, state0.variables, data_i, data_m, active, n_samp
            )
            run()
            run()
            return _median_time(run)

        short_s = timed(bi, bm)
        long_s = timed(bi_long, bm_long)
        slope_s = (long_s - short_s) / ((FIT_FACTOR - 1) * steps_b)
        fit_ok = slope_s > 0.0
        flops = train_step_flops(config, b)
        curve[f"bfloat16_{img}_b{b}"] = {
            "dtype": "bfloat16",
            "img_size": img,
            "batch": b,
            "steps": steps_b,
            "round_s_raw": short_s,
            "per_step_s_raw": slope_s if fit_ok else None,
            "round_ms": round(short_s * 1e3, 2),
            "per_step_ms": round(slope_s * 1e3, 3) if fit_ok else None,
            "per_sample_ms": round(slope_s / b * 1e3, 4) if fit_ok else None,
            "flops_per_step": flops,
            "mfu": (
                round(mfu(slope_s, flops, device), 4)
                if fit_ok and peak is not None
                else None
            ),
        }
        del bi, bm, bi_long, bm_long
        if checkpoint is not None:
            checkpoint()


def _layout_config(img: int, dtype: str, variant: str):
    """ModelConfig for a layout-A/B variant token (see ``LAYOUTS``)."""
    from fedcrack_tpu.configs import ModelConfig

    kw: dict = {}
    for tok in variant.split("+"):
        if tok == "reference":
            pass
        elif tok in ("s2d", "s2d_full"):
            kw["stem_layout"] = tok
        elif tok == "respack":
            kw["res_layout"] = "packed"
        else:
            raise ValueError(f"unknown layout variant token {tok!r}")
    return ModelConfig(img_size=img, compute_dtype=dtype, **kw)


def _layout_ab(
    img: int,
    mesh,
    n_clients: int,
    device,
    peak,
    si,
    sm,
    out: dict,
    *,
    dtype: str = "bfloat16",
    round_s_hint: float,
    skips: list,
    checkpoint=None,
):
    """Interleaved A/B of the model-graph layout transforms at one crop size.

    The transforms (ModelConfig.stem_layout / res_layout) are exact
    re-expressions of the same math (models/resunet.py), so the ONLY honest
    question is wall-clock — measured with the same discipline as the
    round-5 Pallas-BCE A/B: every variant's round program is built in one
    process over the SAME staged reference-layout data (the transforms pack
    on device — what a flag flip costs in production), timed at two scan
    lengths with the variants' reps INTERLEAVED (A,B,C,A,B,C,...) so tunnel
    drift hits all variants equally, slope = per-step time. MFU is charged
    on CANONICAL (reference-topology) FLOPs for every variant — the
    zero-extended kernels' structural-zero MACs are not achievement
    (obs/flops.py) — so the MFU column moves only when wall-clock does.

    Variants are added value-first and budget-gated INDIVIDUALLY: when the
    remaining budget cannot fund the next variant, it is recorded under
    ``skipped`` and the section publishes what it measured (a 2-variant A/B
    beats a skipped section). Artifact schema matches tools/ab_pallas_bce:
    per-variant dicts under ``impls``, derived ratios as sibling keys.
    """
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import build_federated_round
    from fedcrack_tpu.train.local import create_train_state

    variant_est = (2 + REPS) * (1 + FIT_FACTOR) * max(round_s_hint, 1e-3) + 2 * COMPILE_EST_S
    if not _fits(variant_est * 2):
        # Not even a 2-variant comparison fits — record one skip and spend
        # nothing (not even the long-scan tiling below).
        _skip(
            skips,
            f"layout_ab_{dtype}_{img}",
            variant_est * 2,
            "estimate exceeds remaining budget",
        )
        return

    si_long = _tile_steps(si, FIT_FACTOR, mesh)
    sm_long = _tile_steps(sm, FIT_FACTOR, mesh)
    active = np.ones(n_clients, np.float32)
    n_samp = np.full(n_clients, float(STEPS * BATCH), np.float32)
    n_samp_long = np.full(n_clients, float(FIT_FACTOR * STEPS * BATCH), np.float32)
    # One initial state serves every variant: parameter trees are
    # layout-invariant (the transforms derive kernels in-forward).
    state0 = create_train_state(jax.random.key(SEED), _layout_config(img, dtype, "reference"))

    # Per-variant build + warm, value-first, individually budget-gated. The
    # FIRST variant is priced cold (COMPILE_EST_S is real through the
    # tunnel); every later variant is priced off the first one's MEASURED
    # build+warm cost — on a warm persistent cache that is seconds, so a
    # second driver run funds the full variant set where the cold estimate
    # alone would starve it (same self-correcting-estimate pattern as
    # _est_stage_s/_est_synth_s).
    runners: dict[str, tuple] = {}
    measured_variant_s = None
    for variant in LAYOUTS:
        est = variant_est if measured_variant_s is None else measured_variant_s
        if not _fits(est * (1 if runners else 2)):
            # The first gate prices TWO variants: a single measured variant
            # has no comparison and would waste its budget.
            _skip(
                skips,
                f"layout_ab_{dtype}_{img}_{variant}",
                est,
                "estimate exceeds remaining budget",
            )
            continue
        t0v = time.monotonic()
        config = _layout_config(img, dtype, variant)
        round_fn = build_federated_round(mesh, config, learning_rate=1e-3, local_epochs=1)
        short = _make_round_runner(round_fn, state0.variables, si, sm, active, n_samp)
        long = _make_round_runner(
            round_fn, state0.variables, si_long, sm_long, active, n_samp_long
        )
        for r in (short, long):
            r()  # compile (host-pytree signature)
            r()  # committed-device-input signature the timed reps use
        runners[variant] = (short, long)
        # build+warm just executed 2 short + 2 long rounds (+ any compile);
        # the interleaved phase adds REPS x (short + long) on top.
        build_warm_s = time.monotonic() - t0v
        measured_variant_s = build_warm_s * (1.0 + REPS / 2.0)

    if len(runners) < 2:
        for variant in runners:
            _skip(
                skips,
                f"layout_ab_{dtype}_{img}",
                variant_est,
                "fewer than 2 variants funded; no comparison possible",
            )
        return

    # Interleaved timed reps: one short pass over all variants, then one
    # long pass, per rep — drift lands on every variant equally.
    shorts: dict[str, list] = {v: [] for v in runners}
    longs: dict[str, list] = {v: [] for v in runners}
    for _ in range(REPS):
        for v, (short, _long) in runners.items():
            shorts[v].append(_median_time(short, 1))
        for v, (_short, long) in runners.items():
            longs[v].append(_median_time(long, 1))

    flops = train_step_flops(_layout_config(img, dtype, "reference"), BATCH)
    impls = {}
    for v in runners:
        short_s = float(np.median(shorts[v]))
        long_s = float(np.median(longs[v]))
        slope = (long_s - short_s) / ((FIT_FACTOR - 1) * STEPS)
        fit_ok = slope > 0.0
        util = mfu(slope, flops, device) if fit_ok and peak is not None else None
        impls[v] = {
            "round_s_short": short_s,
            "round_s_long": long_s,
            "per_step_ms": round(slope * 1e3, 4) if fit_ok else None,
            "mfu": None if util is None else round(util, 4),
        }
    point = {
        "impls": impls,
        "flops_per_step_canonical": flops,
        "note": (
            "MFU charged on canonical (reference-layout) FLOPs for every "
            "variant; staged data is the shared reference-layout arrays "
            "(transforms pack on device — the production flag-flip cost)"
        ),
    }
    ref = impls.get("reference", {})
    if ref.get("per_step_ms"):
        point["speedup_vs_reference"] = {
            v: round(ref["per_step_ms"] / p["per_step_ms"], 4)
            for v, p in impls.items()
            if v != "reference" and p["per_step_ms"]
        }
    out[f"{dtype}_{img}"] = point
    del si_long, sm_long
    if checkpoint is not None:
        checkpoint()


def _bench_lowp_kernels(device, skips: list) -> dict | None:
    """Low-precision kernel-plane A/B (round 20, detail.lowp_kernels).

    One quantized model, one predict program per kernel plane: the r17
    reference (dequantize the int8 codes, then matmul in XLA), the
    round-20 fused-int8 Pallas plane (dequant fused into the matmul's K
    loop — the Pallas INTERPRETER off-TPU: numerics-true, wall-clock-
    meaningless there), and the fp8 plane where the backend has fp8
    dtypes. Discipline is the round-5 Pallas-BCE A/B: every variant's
    engine is built over the SAME weights, timed at two call counts with
    the variants' reps INTERLEAVED so drift hits all arms equally, slope =
    per-forward time; MFU is charged on canonical reference-topology FLOPs
    (obs/flops.py — bit-width changes bytes per MAC, not MACs).

    Each variant additionally records its numerics parity vs the reference
    plane's probabilities and the r17 two-phase install gate's verdict for
    ITS program — off-TPU those columns ARE the artifact's value (twin
    correctness, measured not assumed); the timing columns only become a
    perf claim on a real TPU (ROADMAP TPU measurement item 10). Variants
    are budget-gated individually; fp8 absence on this backend is recorded
    as ``fp8_supported: false``, not a skip (ambient truth, not a budget
    decision). A gate refusal is an honest artifact, not a failure.
    """
    import dataclasses

    from fedcrack_tpu import jaxcompat
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.obs.flops import mfu, resunet_forward_flops
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import quant as quant_mod
    from fedcrack_tpu.serve.engine import InferenceEngine

    on_tpu = getattr(device, "platform", "") == "tpu"
    img = LOWP_IMG
    if on_tpu:
        model_config = ModelConfig(img_size=img, compute_dtype="bfloat16")
    else:
        # The interpreter executes kernel bodies in Python — the full-width
        # model would burn minutes proving nothing this one doesn't.
        model_config = ModelConfig(
            img_size=img,
            stem_features=8,
            encoder_features=(16, 32),
            decoder_features=(32, 16),
        )
    base_cfg = ServeConfig(
        bucket_sizes=(img,),
        max_batch=4,
        max_delay_ms=5.0,
        tile_overlap=0,
        quant="int8",
    )
    variables = init_variables(jax.random.key(SEED), model_config)
    batch = quant_mod.probe_images(img, 4, SEED)
    fp8_ok = bool(jaxcompat.fp8_supported())
    variants = ["reference", "fused_int8"] + (["fp8"] if fp8_ok else [])

    k_short = max(1, LOWP_CALLS)
    k_long = FIT_FACTOR * k_short

    # Per-variant build + gate + warm, reference first (it is the parity
    # oracle AND the speedup denominator — without it the section has no
    # comparison, so the first budget gate prices TWO variants). Later
    # variants are priced off the first one's measured cost (the
    # self-correcting-estimate pattern of _layout_ab).
    runners: dict[str, tuple] = {}
    impls: dict[str, dict] = {}
    probs_ref = None
    variant_est = COMPILE_EST_S + 10.0
    measured_variant_s = None
    for variant in variants:
        est = variant_est if measured_variant_s is None else measured_variant_s
        if not _fits(est * (1 if runners else 2)):
            _skip(
                skips,
                f"lowp_kernels_{variant}",
                est,
                "estimate exceeds remaining budget",
            )
            continue
        t0v = time.monotonic()
        cfg_v = dataclasses.replace(base_cfg, kernel_plane=variant)
        engine = InferenceEngine(model_config, cfg_v)
        ref_payload = engine.prepare(variables)
        q_payload = engine.prepare_quantized(
            quant_mod.quantize_for_plane(variables, engine.effective_kernel_plane)
        )
        gate = quant_mod.quant_gate(engine, ref_payload, q_payload)

        def run_calls(n, _engine=engine, _q=q_payload):
            for _ in range(n):
                _engine.predict_bucket(_q, batch)

        probs = engine.predict_bucket(q_payload, batch)  # warm + parity sample
        t0c = time.perf_counter()
        run_calls(1)  # second warm call — the committed-signature path
        per_call_hint = time.perf_counter() - t0c
        if variant == "reference":
            probs_ref = probs
        parity = (
            0.0
            if variant == "reference"
            else float(
                np.max(
                    np.abs(
                        np.asarray(probs, np.float64)
                        - np.asarray(probs_ref, np.float64)
                    )
                )
            )
        )
        impls[variant] = {
            "parity_max_abs_diff": parity,
            "gate": gate.to_json(),
            "effective_kernel_plane": engine.effective_kernel_plane,
        }
        runners[variant] = run_calls
        build_warm_s = time.monotonic() - t0v
        measured_variant_s = (
            build_warm_s + REPS * (k_short + k_long) * per_call_hint
        )

    if len(runners) < 2:
        for variant in runners:
            _skip(
                skips,
                "lowp_kernels",
                variant_est,
                "fewer than 2 variants funded; no comparison possible",
            )
        return None

    # Interleaved timed reps: one short pass over all variants, then one
    # long pass, per rep — drift lands on every variant equally.
    shorts: dict[str, list] = {v: [] for v in runners}
    longs: dict[str, list] = {v: [] for v in runners}
    for _ in range(REPS):
        for v, run_calls in runners.items():
            shorts[v].append(_median_time(lambda r=run_calls: r(k_short), 1))
        for v, run_calls in runners.items():
            longs[v].append(_median_time(lambda r=run_calls: r(k_long), 1))

    flops = resunet_forward_flops(model_config, int(batch.shape[0]))
    for v in runners:
        short_s = float(np.median(shorts[v]))
        long_s = float(np.median(longs[v]))
        slope = (long_s - short_s) / (k_long - k_short)
        fit_ok = slope > 0.0
        util = mfu(slope, flops, device) if fit_ok else None
        impls[v].update(
            round_s_short=short_s,
            round_s_long=long_s,
            per_step_ms=round(slope * 1e3, 4) if fit_ok else None,
            mfu=None if util is None else round(util, 4),
        )
    ref = impls.get("reference", {})
    speedup = {}
    if ref.get("per_step_ms"):
        speedup = {
            v: round(ref["per_step_ms"] / p["per_step_ms"], 4)
            for v, p in impls.items()
            if v != "reference" and p.get("per_step_ms")
        }
    return {
        "img": img,
        "interpret_mode": not on_tpu,
        "fp8_supported": fp8_ok,
        "calls_short": k_short,
        "calls_long": k_long,
        "flops_per_forward_canonical": flops,
        "impls": impls,
        "speedup_vs_reference": speedup,
        "note": (
            "MFU charged on canonical reference-topology FLOPs for every "
            "plane; off-TPU the fused arms run the Pallas interpreter — "
            "parity + gate columns are the claim there, timing is not"
        ),
    }


def _measure_input_pipeline(img: int) -> dict | None:
    """The reference's synchronous per-step input cost, measured on this host.

    The reference decodes its batch INSIDE the training loop: 16 x
    cv2.imread + cvtColor(BGR2RGB) + resize for images and 16 x imread +
    resize + binarize for masks, in ``__getitem__``, before EVERY step of
    every epoch (client_fit_model.py:30-43; keras Sequence with no
    prefetch workers wired, SURVEY.md §3.3). The host-plane reconstruction
    used to charge the reference ZERO for this (VERDICT round-4 weak #4);
    this section measures it so the co-located ratio can include it as a
    separate, labeled term.

    Measured variants: the reference's verbatim cv2 sequence (when cv2 is
    importable — the reference hard-requires it) and this framework's own
    ``data.pipeline.load_example`` decode (cv2 or PIL+native, whichever
    backend this host has). Source resolutions 227 and 448 px bracket
    public crack-segmentation datasets (SDNET2018-style 256-class patches
    to khanhha-style 448 tiles); the CHARGED term is the cheapest measured
    variant at the smallest source — a conservative lower bound.
    """
    import tempfile

    try:
        import cv2
    except Exception:
        cv2 = None

    out: dict = {"batch": BATCH, "target_px": img, "variants": {}}
    with tempfile.TemporaryDirectory() as td:
        for src in (227, 448):
            imgs_f, masks_f = _synth(BATCH, src, SEED)
            u8 = np.clip(imgs_f * 255.0, 0, 255).astype(np.uint8)
            m8 = (masks_f[..., 0] > 0.5).astype(np.uint8) * 255
            img_paths, mask_paths = [], []
            for i in range(BATCH):
                ip = os.path.join(td, f"img_{src}_{i}.jpg")
                mp = os.path.join(td, f"mask_{src}_{i}.png")
                if cv2 is not None:
                    cv2.imwrite(ip, cv2.cvtColor(u8[i], cv2.COLOR_RGB2BGR))
                    cv2.imwrite(mp, m8[i])
                else:
                    from PIL import Image

                    Image.fromarray(u8[i]).save(ip, quality=95)
                    Image.fromarray(m8[i]).save(mp)
                img_paths.append(ip)
                mask_paths.append(mp)

            variants = {}
            if cv2 is not None:

                def ref_step():
                    np.array(
                        [
                            cv2.resize(
                                cv2.cvtColor(cv2.imread(p, -1), cv2.COLOR_BGR2RGB),
                                (img, img),
                            )
                            for p in img_paths
                        ]
                    ) / 255
                    np.expand_dims(
                        np.array(
                            [
                                (cv2.resize(cv2.imread(p, -1), (img, img)) > 0).astype(
                                    np.uint8
                                )
                                for p in mask_paths
                            ]
                        ),
                        -1,
                    )

                ref_step()
                variants["reference_cv2"] = _median_time(ref_step, reps=5)

            from fedcrack_tpu.data.pipeline import load_example

            def our_step():
                for ip, mp in zip(img_paths, mask_paths):
                    load_example(ip, mp, img_size=img, transport_dtype="uint8")

            our_step()
            variants["framework_load_sample"] = _median_time(our_step, reps=5)
            out["variants"][f"src{src}"] = {
                k: {
                    "batch_ms": round(v * 1e3, 2),
                    "per_image_ms": round(v / BATCH * 1e3, 3),
                }
                for k, v in variants.items()
            }

    candidates = [
        v * 1e-3
        for by_src in out["variants"].values()
        for v in [x["batch_ms"] for x in by_src.values()]
    ]
    if not candidates:
        return None
    out["charged_per_step_s_raw"] = min(candidates)
    out["charged_per_step_ms"] = round(out["charged_per_step_s_raw"] * 1e3, 2)
    out["note"] = (
        "charged term = cheapest measured variant (conservative bound for "
        "the reference's per-step input cost); the mesh plane decodes each "
        "image once into a uint8 pool and restages it overlapped "
        "(parallel.driver), so its per-step input cost is ~0"
    )
    return out


def _ref_host_arrays(img: int):
    """One epoch of uint8 transport data in the round layout, PLUS the
    deduplicated unique pool it was cycled from (the resident-pool A/B
    gathers from that pool by the same cycling plan, so both arms train on
    byte-identical batches). 512 distinct syntheses cycled to the full
    epoch: timing is value-independent, and 6k unique syntheses would
    dominate host time for no fidelity gain — but the STAGED volume is the
    epoch's real data volume (unique data would ship the same bytes)."""
    from fedcrack_tpu.data.pipeline import to_uint8_transport
    from fedcrack_tpu.parallel import stack_client_data

    n_unique = min(512, REF_STEPS * BATCH)
    imgs_f, msks_f = _synth(n_unique, img, SEED)
    imgs_u8, msks_u8 = to_uint8_transport(imgs_f, msks_f)
    # stack_client_data cycles the unique pool to the full epoch length.
    images, masks = stack_client_data([(imgs_u8, msks_u8)], REF_STEPS, BATCH)
    return images, masks, (imgs_u8, msks_u8)


def _bench_reference_scale(
    img: int,
    dtype: str,
    device,
    mesh,
    *,
    full: bool = True,
    reuse: dict | None = None,
    segments: int = 0,
):
    """One-program federated round at the reference's true workload:
    REF_EPOCHS local epochs over REF_STEPS batches of BATCH, single client,
    uint8 transport staging.

    Decomposition reported:
    - ``staging_ms``: host->device transfer of one epoch's uint8 data,
      synced via an on-device element readback (tunnel-safe barrier);
    - ``round_ms``: the chained round program on pre-staged data — at
      ~REF_EPOCHS*REF_STEPS steps the fixed dispatch cost is <2% of the
      round, so the naive per-step division is finally honest;
    - ``round_plus_restage_ms``: rounds driven through
      ``parallel.driver.run_mesh_federation`` with per-round restaging
      overlapped against the in-flight round (double buffering) — the
      production overlap pattern; ``staging_hidden_frac`` is how much of
      the staging cost the overlap hides.

    ``full=False`` measures only the round time and inherits staging/overlap
    numbers from ``reuse`` (the flagship point): the staged uint8 bytes are
    dtype-independent, so re-measuring transfers for the f32 ratio point
    would spend tunnel minutes re-learning the same number.

    ``segments > 0`` runs the round through the epoch-segmented execution
    (``build_federated_round_segments``, bit-identical weights): each
    compiled program is REF_STEPS*REF_EPOCHS/segments steps — the chunked
    form that compiles at 256 px where the 3,880-step monolith fails
    (VERDICT r5 #6) — and ``run_mesh_federation`` streams the restage one
    chunk per in-flight segment.

    Returns ``(point_dict, reuse_dict)``; point_dict is None if the budget
    ran out after warmup (the partial JSON then omits this point).
    """
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import (
        build_federated_round,
        build_federated_round_segments,
        run_mesh_federation,
    )
    from fedcrack_tpu.train.local import create_train_state

    config = ModelConfig(img_size=img, compute_dtype=dtype)
    state0 = create_train_state(jax.random.key(SEED), config)
    if segments:
        round_fn = build_federated_round_segments(
            mesh, config, learning_rate=1e-3, local_epochs=REF_EPOCHS,
            segments=segments,
        )
    else:
        round_fn = build_federated_round(
            mesh, config, learning_rate=1e-3, local_epochs=REF_EPOCHS
        )
    if reuse is None:
        images, masks, pool_u8 = _ref_host_arrays(img)
        if segments:
            si, sm, init_stage_s = _stage_timed_chunks(images, masks, mesh, segments)
        else:
            si, sm, init_stage_s = _stage_timed(images, masks, mesh)
        reuse = {
            "images": images,
            "masks": masks,
            "pool": pool_u8,
            "si": si,
            "sm": sm,
            "stage_s": init_stage_s,
            "overlap": None,
        }
    images, masks = reuse["images"], reuse["masks"]
    si, sm = reuse["si"], reuse["sm"]

    active = np.ones(1, np.float32)
    n_samp = np.full(1, float(REF_STEPS * BATCH), np.float32)
    run = _make_round_runner(round_fn, state0.variables, si, sm, active, n_samp)

    # Warmup + settle: through the tunnel, residual streaming from the
    # initial 400 MB+ staging contaminates the next calls — an under-warmed
    # 3,880-step round reads 15.8 s where the settled value is 8.2 s
    # (bench_runs/r03_refscale_isolation.json). Two warm rounds (compile/
    # host-pytree consumption + committed signature) + a 2 s drain settle it;
    # warm-round wall-clocks are recorded so a contaminated measurement is
    # visible in the artifact rather than silent.
    warm_walls = []
    for _ in range(2):
        t0 = time.perf_counter()
        run()
        warm_walls.append(round(time.perf_counter() - t0, 3))
    time.sleep(2.0)

    reps = max(1, min(REPS, 3))
    round_est = warm_walls[-1] * reps
    if _remaining() < round_est + 10.0:
        return None, reuse  # budget died mid-point; emit without this entry
    round_s = _median_time(run, reps=reps)

    total_steps = REF_EPOCHS * REF_STEPS
    step_s = round_s / total_steps
    flops = train_step_flops(config, BATCH)
    util = mfu(step_s, flops, device)
    point = {
        "img_size": img,
        "dtype": dtype,
        "epochs": REF_EPOCHS,
        "steps_per_epoch": REF_STEPS,
        "batch": BATCH,
        "total_steps": total_steps,
        "segments": segments,
        "staging_bytes": int(images.nbytes + masks.nbytes),
        "warm_round_walls_s": warm_walls,
        "round_s_raw": round_s,
        "round_ms": round(round_s * 1e3, 2),
        "per_step_ms": round(step_s * 1e3, 3),
        "mfu": None if util is None else round(util, 4),
    }

    if full:
        if segments:
            stage_s = _median_time(
                lambda: _stage_timed_chunks(images, masks, mesh, segments), reps=2
            )
        else:
            stage_s = _median_time(lambda: _stage_timed(images, masks, mesh), reps=2)
        time.sleep(2.0)  # drain staging traffic before the overlap phase
        # Double-buffered multi-round federation through the PACKAGE driver:
        # data_fn re-returns the epoch arrays, so every round restages while
        # the previous round computes — per-round wall is max(round, staging)
        # plus the unhidden residue.
        overlap_rounds = reps + 1
        timeline = None
        if _remaining() > (overlap_rounds * max(stage_s, round_s)) * 1.2 + 10.0:
            _, records = run_mesh_federation(
                round_fn,
                state0.variables,
                lambda r: (images, masks, active, n_samp),
                overlap_rounds,
                mesh,
            )
            walls = [r.wall_clock_s for r in records[:-1]]  # last round: no restage
            overlap_s = float(np.median(walls[1:] if len(walls) > 2 else walls))
            if segments and len(records) > 1:
                # Segmented path: the driver's per-segment host timeline
                # (dispatch + the next-round chunk transfer that rode under
                # each segment) from a post-compile overlapped round.
                timeline = list(records[1].segments)
        else:
            overlap_s = None
        reuse = dict(reuse, stage_s=stage_s, overlap=overlap_s)
        hidden = (
            (stage_s + round_s - overlap_s) / stage_s
            if (overlap_s is not None and stage_s > 0)
            else None
        )
        point.update(
            {
                "round_plus_restage_ms": (
                    None if overlap_s is None else round(overlap_s * 1e3, 2)
                ),
                "staging_hidden_frac": (
                    None if hidden is None else round(max(0.0, min(1.0, hidden)), 3)
                ),
            }
        )
        if timeline is not None:
            point["segment_timeline"] = timeline
    else:
        # Staging cost is dtype-independent (same uint8 bytes) and inherited;
        # the overlap decomposition is NOT re-derived here — it would mix the
        # flagship's overlapped wall with this dtype's round time.
        stage_s = reuse["stage_s"]
        point["staging_shared_with_flagship"] = True
    point.update(
        {
            "staging_s_raw": stage_s,
            "staging_ms": round(stage_s * 1e3, 2),
        }
    )
    return point, reuse


def _bench_segmented_pipeline(
    img: int,
    dtype: str,
    device,
    mesh,
    reuse: dict,
    mono_point: dict,
    *,
    with_overlap: bool = True,
):
    """Monolithic vs epoch-segmented round execution at reference scale
    (round 7's deliverable): the same REF_EPOCHS x REF_STEPS trajectory run
    as K= SEGMENTS device-resident-carry programs with chunk-grain streamed
    restaging, against the monolithic one-program round already measured in
    ``reference_scale``. The weights are bit-identical by construction
    (test-pinned), so the ONLY honest question is the pipeline: dispatch
    overhead of K programs vs 1, and how much of the restage hides under
    compute at segment grain vs round grain (``staging_hidden_frac``).

    Reuses the monolithic point's staged buffers and host arrays (same
    uint8 bytes); ``with_overlap=False`` measures only the compute round
    (the f32 arm mirrors the monolithic f32 point's asymmetry). Returns
    None when the budget dies mid-measurement.
    """
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.parallel import (
        build_federated_round_segments,
        run_mesh_federation,
    )
    from fedcrack_tpu.train.local import create_train_state

    k = SEGMENTS if SEGMENTS > 0 and REF_EPOCHS % SEGMENTS == 0 else REF_EPOCHS
    config = ModelConfig(img_size=img, compute_dtype=dtype)
    state0 = create_train_state(jax.random.key(SEED), config)
    seg_round = build_federated_round_segments(
        mesh, config, learning_rate=1e-3, local_epochs=REF_EPOCHS, segments=k
    )
    images, masks = reuse["images"], reuse["masks"]
    si, sm = reuse["si"], reuse["sm"]
    active = np.ones(1, np.float32)
    n_samp = np.full(1, float(REF_STEPS * BATCH), np.float32)
    run = _make_round_runner(seg_round, state0.variables, si, sm, active, n_samp)

    warm_walls = []
    for _ in range(2):
        t0 = time.perf_counter()
        run()
        warm_walls.append(round(time.perf_counter() - t0, 3))
    time.sleep(2.0)
    reps = max(1, min(REPS, 3))
    if _remaining() < warm_walls[-1] * reps + 10.0:
        return None
    seg_round_s = _median_time(run, reps=reps)

    stage_s = reuse.get("stage_s")
    overlap_s = None
    timeline = None
    if with_overlap and stage_s:
        overlap_rounds = reps + 1
        if _remaining() > (overlap_rounds * max(stage_s, seg_round_s)) * 1.2 + 10.0:
            _, records = run_mesh_federation(
                seg_round,
                state0.variables,
                lambda r: (images, masks, active, n_samp),
                overlap_rounds,
                mesh,
            )
            walls = [r.wall_clock_s for r in records[:-1]]
            overlap_s = float(np.median(walls[1:] if len(walls) > 2 else walls))
            if len(records) > 1:
                timeline = list(records[1].segments)

    hidden = (
        (stage_s + seg_round_s - overlap_s) / stage_s
        if (overlap_s is not None and stage_s)
        else None
    )
    segmented = {
        "round_ms": round(seg_round_s * 1e3, 2),
        "per_step_ms": round(seg_round_s / (REF_EPOCHS * REF_STEPS) * 1e3, 3),
        "warm_round_walls_s": warm_walls,
        "round_plus_restage_ms": (
            None if overlap_s is None else round(overlap_s * 1e3, 2)
        ),
        "staging_hidden_frac": (
            None if hidden is None else round(max(0.0, min(1.0, hidden)), 3)
        ),
    }
    if timeline is not None:
        segmented["segment_timeline"] = timeline
    out = {
        "segments": k,
        "segment_epochs": REF_EPOCHS // k,
        "img_size": img,
        "dtype": dtype,
        "monolithic": {
            "round_ms": mono_point["round_ms"],
            "round_plus_restage_ms": mono_point.get("round_plus_restage_ms"),
            "staging_hidden_frac": mono_point.get("staging_hidden_frac"),
        },
        "segmented": segmented,
        "round_speedup_mono_over_seg": round(
            mono_point["round_s_raw"] / seg_round_s, 4
        ),
        "note": (
            "same trajectory bit-for-bit (SegmentedRound exactness contract); "
            "the comparison is pure pipeline — K-program dispatch overhead vs "
            "chunk-grain staged-transfer streaming"
        ),
    }
    mono_wall = mono_point.get("round_plus_restage_ms")
    seg_wall = segmented["round_plus_restage_ms"]
    if mono_wall and seg_wall:
        out["round_plus_restage_speedup"] = round(mono_wall / seg_wall, 4)
    return out


def _bench_resident_pool(img: int, dtype: str, device, mesh, reuse: dict, mono_point: dict):
    """Streamed vs device-resident data plane at reference scale (round 9).

    The streamed arm (the monolithic point already measured in
    ``reference_scale``) re-stages the full uint8 epoch slab every round;
    the resident arm stages the deduplicated sample pool ONCE
    (``data.pipeline.SamplePool``) and per round ships only the
    ``[1, epochs, steps, batch]`` int32 gather plan — the round program
    assembles batches on device by ``jnp.take``. Both arms train on
    byte-identical batches (the gather plan cycles the same unique pool the
    streamed slab was assembled from; trajectory equality is test-pinned in
    tests/test_resident.py), so the ONLY honest question is the pipeline:
    per-round wall with the staging term collapsed from the slab's seconds
    to the plan's kilobytes — the roofline dropping from
    max(compute, staging) to the compute term (BASELINE.md "Resident data
    plane"). The overlapped arm runs through the production driver
    (``run_mesh_federation(data_placement="resident")``), whose
    ``RoundRecord``s also pin the per-round driver-staged bytes
    (indices only after round 0).

    Returns None when the budget dies mid-measurement.
    """
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.pipeline import SamplePool
    from fedcrack_tpu.parallel import (
        build_federated_round,
        run_mesh_federation,
        stage_round_indices,
    )
    from fedcrack_tpu.train.local import create_train_state

    pool_u8 = reuse.get("pool")
    if pool_u8 is None:
        return None
    pool = SamplePool(pool_u8[0][None], pool_u8[1][None])
    n_unique = pool.n_samples
    config = ModelConfig(img_size=img, compute_dtype=dtype)
    state0 = create_train_state(jax.random.key(SEED), config)
    round_fn = build_federated_round(
        mesh, config, learning_rate=1e-3, local_epochs=REF_EPOCHS,
        data_placement="resident",
    )
    # Gather plan reproducing the streamed arm's cycled slab byte for byte:
    # stack_client_data cycles via np.resize(arange(n_unique)), tiled over
    # the epochs axis exactly like the slab is reused per local epoch.
    plan = np.resize(np.arange(n_unique, dtype=np.int32), REF_STEPS * BATCH)
    idx = np.ascontiguousarray(
        np.broadcast_to(
            plan.reshape(1, 1, REF_STEPS, BATCH),
            (1, REF_EPOCHS, REF_STEPS, BATCH),
        ).astype(np.int32)
    )

    t0 = time.perf_counter()
    pool_dev = pool.stage(mesh)
    pool_stage_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    idx_dev = stage_round_indices(idx, mesh)
    idx_stage_s = time.perf_counter() - t0

    active = np.ones(1, np.float32)
    n_samp = np.full(1, float(REF_STEPS * BATCH), np.float32)
    run = _make_round_runner(round_fn, state0.variables, pool_dev, idx_dev, active, n_samp)
    warm_walls = []
    for _ in range(2):
        t0 = time.perf_counter()
        run()
        warm_walls.append(round(time.perf_counter() - t0, 3))
    time.sleep(2.0)
    reps = max(1, min(REPS, 3))
    if _remaining() < warm_walls[-1] * reps + 10.0:
        return None
    res_round_s = _median_time(run, reps=reps)

    # Overlapped rounds through the production driver: per-round wall with
    # only the next plan staging under the in-flight round, and the honest
    # staged-bytes accounting straight off the RoundRecords.
    overlap_s = None
    driver_staged = None
    max_live = None
    overlap_rounds = reps + 1
    if _remaining() > overlap_rounds * res_round_s * 1.2 + 10.0:
        _, records = run_mesh_federation(
            round_fn,
            state0.variables,
            lambda r: (idx, active, n_samp),
            overlap_rounds,
            mesh,
            data_placement="resident",
            sample_pool=pool,
        )
        walls = [r.wall_clock_s for r in records[:-1]]
        overlap_s = float(np.median(walls[1:] if len(walls) > 2 else walls))
        driver_staged = [int(r.staged_bytes) for r in records]
        max_live = max(int(r.max_live_staged_bytes) for r in records)

    slab_bytes = int(reuse["images"].nbytes + reuse["masks"].nbytes)
    slab_stage_s = reuse.get("stage_s")
    hidden = (
        (idx_stage_s + res_round_s - overlap_s) / idx_stage_s
        if (overlap_s is not None and idx_stage_s > 0)
        else None
    )
    out = {
        "img_size": img,
        "dtype": dtype,
        "epochs": REF_EPOCHS,
        "steps_per_epoch": REF_STEPS,
        "pool_unique_samples": n_unique,
        "pool_bytes": pool.nbytes,
        "pool_stage_ms": round(pool_stage_s * 1e3, 2),
        "slab_bytes": slab_bytes,
        "idx_bytes_per_round": int(idx.nbytes),
        "staged_bytes_ratio": round(idx.nbytes / slab_bytes, 8),
        "driver_staged_bytes_per_round": driver_staged,
        "max_live_staged_bytes": max_live,
        "streamed": {
            "round_ms": mono_point["round_ms"],
            "round_plus_restage_ms": mono_point.get("round_plus_restage_ms"),
            "staging_hidden_frac": mono_point.get("staging_hidden_frac"),
            "staging_ms": mono_point.get("staging_ms"),
        },
        "resident": {
            "round_ms": round(res_round_s * 1e3, 2),
            "warm_round_walls_s": warm_walls,
            "round_plus_restage_ms": (
                None if overlap_s is None else round(overlap_s * 1e3, 2)
            ),
            "staging_hidden_frac": (
                None if hidden is None else round(max(0.0, min(1.0, hidden)), 3)
            ),
            "staging_ms": round(idx_stage_s * 1e3, 3),
        },
        "roofline": {
            "streamed_floor_s": round(
                max(mono_point["round_s_raw"], slab_stage_s or 0.0), 3
            ),
            "resident_floor_s": round(res_round_s, 3),
            "note": (
                "streamed wall >= max(compute, slab staging); resident wall "
                ">= compute — the index upload is kilobytes, so the staging "
                "roofline term vanishes (pool charged once)"
            ),
        },
        "note": (
            "identical data both arms: the resident gather plan cycles the "
            "same deduplicated pool the streamed slab was assembled from, so "
            "every batch is byte-identical; pool staged once (pool_stage_ms), "
            "indices per round (idx_bytes_per_round)"
        ),
    }
    streamed_wall = mono_point.get("round_plus_restage_ms")
    resident_wall = out["resident"]["round_plus_restage_ms"]
    if streamed_wall and resident_wall:
        out["round_plus_restage_speedup"] = round(streamed_wall / resident_wall, 4)
    return out


def _bench_serving(device) -> dict:
    """Serving-plane SLO measurement (round 10, detail.serving).

    The full production stack in one process: ``InferenceEngine`` (one
    compiled program per bucket), ``MicroBatcher`` (dynamic micro-batching),
    ``ModelVersionManager`` (hot swap), the gRPC ``ServePlane/Predict``
    front door, and ``tools/load_gen`` driving it closed-loop over every
    bucket size. At ~1/3 completions a new model version is installed
    through the manager (the request-boundary barrier) — ``swap`` records
    the load cost and the served-plane pause, and ``versions_observed``
    proves the swap was live mid-run. Weights are seed-initialized: serving
    throughput/latency are weight-independent, and the swap semantics are
    what the section certifies (bit-identity is test-pinned in
    tests/test_serve.py, not re-proven here).
    """
    import dataclasses

    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ModelVersionManager,
        ServeServer,
        ServeServerThread,
        ServeService,
    )
    from fedcrack_tpu.tools.load_gen import run_load

    dtype = "bfloat16" if getattr(device, "platform", "") == "tpu" else "float32"
    serve_config = ServeConfig(
        bucket_sizes=tuple(sorted(SERVE_SIZES)),
        max_batch=SERVE_MAX_BATCH,
        max_delay_ms=5.0,
        tile_overlap=min(16, min(SERVE_SIZES) - 16) if min(SERVE_SIZES) > 16 else 0,
        compute_dtype=dtype,
        port=0,
    )
    model_config = ModelConfig(img_size=max(SERVE_SIZES), compute_dtype=dtype)
    var_v0 = init_variables(jax.random.key(SEED), model_config)
    var_v1 = init_variables(jax.random.key(SEED + 1), model_config)

    t0 = time.perf_counter()
    engine = InferenceEngine(model_config, serve_config)
    manager = ModelVersionManager(engine, var_v0, initial_version=0)
    engine.warmup(manager.snapshot()[1])
    warmup_s = time.perf_counter() - t0

    batcher = MicroBatcher(engine, manager)
    server = ServeServer(ServeService(engine, batcher, manager), port=0)
    swap_at = max(1, SERVE_REQUESTS // 3)
    state = {"fired": False, "n": 0}

    def on_complete():
        state["n"] += 1
        if not state["fired"] and state["n"] >= swap_at:
            state["fired"] = True
            # Direct install (pre-decoded weights): the statefile/checkpoint
            # READ path is unit-tested; paying a multi-second msgpack decode
            # under the load's GIL here would only blur the swap timing.
            manager.install(1, var_v1)

    try:
        with ServeServerThread(server) as thread:
            summary = run_load(
                f"127.0.0.1:{thread.port}",
                mode="closed",
                n_requests=SERVE_REQUESTS,
                concurrency=SERVE_CONCURRENCY,
                sizes=serve_config.bucket_sizes,
                seed=SEED,
                on_complete=on_complete,
            )
    finally:
        batcher.close()
        manager.stop()

    stats = batcher.stats()
    swap = None
    if manager.last_swap is not None:
        gaps = stats.get("swap_gaps_ms") or []
        swap = {
            **manager.last_swap,
            "gap_ms": gaps[0] if gaps else None,
            "triggered_after_n": swap_at,
        }
    # Throughput in images/s == requests/s here (one image per request);
    # recomputed over the serving phase only via the load_gen wall.
    return {
        "dtype": dtype,
        "buckets": list(serve_config.bucket_sizes),
        "max_batch": serve_config.max_batch,
        "max_delay_ms": serve_config.max_delay_ms,
        "concurrency": SERVE_CONCURRENCY,
        "warmup_s": round(warmup_s, 3),
        "requests": {
            "total": summary["n_requests"],
            "completed": summary["completed"],
            "rejected": summary["rejected"],
            "per_size": summary["per_size"],
            "versions_observed": summary["versions_observed"],
        },
        "dropped": summary["dropped"],
        "throughput_rps": summary["throughput_rps"],
        "wall_s": summary["wall_s"],
        "latency_ms": summary["latency_ms"],
        "server_latency_ms": summary["server_latency_ms"],
        "batcher": {
            k: stats[k]
            for k in (
                "batches",
                "batch_retries",
                "deadline_missed",
                "per_bucket",
                "versions_served",
            )
        },
        "swap": swap,
        "note": (
            "closed-loop gRPC load over every bucket; one live hot-swap "
            "installed mid-run at the request-boundary barrier — "
            "versions_observed spanning two versions with dropped == 0 is "
            "the serve-while-training claim"
        ),
    }


def _bench_video_serving(device) -> dict:
    """Frame-coherent video serving (round 19, detail.video_serving).

    One seeded correlated sequence (a moving full-width noise band over a
    static base frame, ``VIDEO_MOTION_FRACTION`` of the rows per step —
    >=90% frame-to-frame overlap) served two ways on the SAME engine:

    - **stateless**: ``engine.predict_tiled`` per frame — every tile
      recomputed, the r10 contract and the byte-identity oracle;
    - **session**: a ``StreamSession`` behind ``StreamSessionManager`` —
      only tiles whose bytes changed run on device, keyed on
      (model_version, content hash).

    Mid-sequence a new model version installs through the SAME
    ``ModelVersionManager`` the still path uses — the swap frame must be a
    full re-run on the new version (old-version entries are unreachable by
    key and purged), and its bytes must match stateless-under-v1. The
    audit compares EVERY frame byte-for-byte against the per-version
    stateless oracle, so ``identity.ok`` is the cached==stateless claim
    measured, not assumed.

    ``effective_speedup`` is tile accounting over steady-state frames
    (frame 0 is by construction a cold full re-run):
    tiles_total / tiles_computed ~= 1 / changed-tile-fraction — the
    BASELINE.md effective-throughput model. It is seeded-deterministic;
    the measured walls corroborate it but carry CPU timing noise.
    """
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ModelVersionManager,
        ServeServer,
        ServeServerThread,
        ServeService,
    )
    from fedcrack_tpu.serve.stream import StreamSessionManager
    from fedcrack_tpu.tools.load_gen import make_frame_sequence, run_load

    dtype = "bfloat16" if getattr(device, "platform", "") == "tpu" else "float32"
    size = VIDEO_FRAME_SIZE
    serve_config = ServeConfig(
        bucket_sizes=(16, 32),
        max_batch=8,
        max_delay_ms=5.0,
        tile_overlap=4,
        compute_dtype=dtype,
        port=0,
    )
    model_config = ModelConfig(
        img_size=max(serve_config.bucket_sizes),
        stem_features=4,
        encoder_features=(8,),
        decoder_features=(8, 4),
        compute_dtype=dtype,
    )
    var_v0 = init_variables(jax.random.key(SEED), model_config)
    var_v1 = init_variables(jax.random.key(SEED + 1), model_config)

    t0 = time.perf_counter()
    engine = InferenceEngine(model_config, serve_config)
    manager = ModelVersionManager(engine, var_v0, initial_version=0)
    engine.warmup(manager.snapshot()[1])
    warmup_s = time.perf_counter() - t0

    n_frames = max(4, VIDEO_FRAMES)
    frames = make_frame_sequence(n_frames, size, VIDEO_MOTION_FRACTION, seed=SEED)
    band = int(round(VIDEO_MOTION_FRACTION * size))

    # ---- stateless arm: the oracle AND the timing baseline ----
    v0 = manager.snapshot()[1]
    t0 = time.perf_counter()
    stateless_probs = [engine.predict_tiled(v0, f) for f in frames]
    stateless_wall = time.perf_counter() - t0
    stateless_bytes = [np.asarray(p).tobytes() for p in stateless_probs]

    # ---- session arm through the manager (metrics in a private registry,
    # so the exposition check sees exactly this run's counters) ----
    registry = MetricsRegistry()
    smgr = StreamSessionManager(engine, manager, registry=registry)
    session = smgr.open("bench", height=size, width=size)
    swap_at = max(1, (2 * n_frames) // 3)
    results = []
    t0 = time.perf_counter()
    for i, frame in enumerate(frames):
        if i == swap_at:
            # Direct install (pre-decoded weights), same rationale as the
            # r10 serving section: the decode path is unit-tested and would
            # only blur the timing.
            manager.install(1, var_v1)
        result = session.process_frame(frame)
        smgr.record(result)
        results.append(result)
    session_wall = time.perf_counter() - t0

    # ---- byte-identity audit (untimed): every frame vs the stateless
    # oracle under the version the session actually pinned ----
    v1 = manager.snapshot()[1]
    mismatches = 0
    swap_info: dict = {}
    for i, (frame, result) in enumerate(zip(frames, results)):
        if result.model_version == 0:
            ref = stateless_bytes[i]
        else:
            ref = np.asarray(engine.predict_tiled(v1, frame)).tobytes()
        identical = np.asarray(result.probs).tobytes() == ref
        if not identical:
            mismatches += 1
        if i == swap_at:
            swap_info = {
                "frame": i,
                "model_version": result.model_version,
                "full_rerun_on_swap": result.tiles_computed == result.tiles_total,
                "stale_entries_purged": result.evicted,
                "identity_after_swap": bool(identical),
            }

    tiles_total = sum(r.tiles_total for r in results)
    tiles_computed = sum(r.tiles_computed for r in results)
    cache_hits = sum(r.cache_hits for r in results)
    steady = results[1:]
    st_total = sum(r.tiles_total for r in steady)
    st_computed = sum(r.tiles_computed for r in steady)
    effective_speedup = (st_total / st_computed) if st_computed else None
    stateless_ips = n_frames / stateless_wall if stateless_wall > 0 else None
    session_ips = n_frames / session_wall if session_wall > 0 else None
    effective_ips = (
        round(stateless_ips * effective_speedup, 3)
        if stateless_ips and effective_speedup
        else None
    )

    expo = registry.exposition()
    wanted = (
        "serve_stream_sessions_total",
        "serve_stream_frames_total",
        "serve_stream_cache_hits_total",
        "serve_stream_cache_misses_total",
        "serve_stream_full_rerun_total",
        "serve_stream_frame_seconds",
        "serve_stream_cache_hit_ratio",
        "serve_stream_effective_speedup_ratio",
    )
    metrics_ok = all(name in expo for name in wanted)
    smgr.close("bench")

    # ---- gRPC smoke: the full StreamPredict front door under
    # load_gen --profile video (mixed still + video traffic) ----
    grpc_smoke = None
    batcher = MicroBatcher(engine, manager)
    front_smgr = StreamSessionManager(engine, manager)
    server = ServeServer(
        ServeService(engine, batcher, manager, stream_manager=front_smgr),
        port=0,
    )
    try:
        with ServeServerThread(server) as thread:
            summary = run_load(
                f"127.0.0.1:{thread.port}",
                profile="video",
                n_requests=4,
                concurrency=2,
                sizes=(max(serve_config.bucket_sizes),),
                seed=SEED,
                streams=1,
                frames_per_stream=6,
                motion_fraction=VIDEO_MOTION_FRACTION,
                video_size=2 * max(serve_config.bucket_sizes),
                audit_every=2,
            )
        video = summary["video"]
        grpc_smoke = {
            "frames_completed": video["frames_completed"],
            "frames_dropped": video["dropped"],
            "stills_completed": summary["completed"],
            "stills_dropped": summary["dropped"],
            "hit_ratio": video["hit_ratio"],
            "effective_speedup": video["effective_speedup"],
            "audit": video["audit"],
        }
    except Exception as e:  # the smoke must not void the in-process A/B
        grpc_smoke = {"error": repr(e)}
    finally:
        batcher.close()
        manager.stop()

    return {
        "dtype": dtype,
        "warmup_s": round(warmup_s, 3),
        "frame": {
            "size": size,
            "frames": n_frames,
            "motion_fraction": VIDEO_MOTION_FRACTION,
            "motion_rows": band,
            "overlap_fraction": round(1.0 - band / size, 4),
            "tile": max(serve_config.bucket_sizes),
            "tile_overlap": serve_config.tile_overlap,
            "tiles_per_frame": results[0].tiles_total,
        },
        "stateless": {
            "wall_s": round(stateless_wall, 3),
            "img_per_s": round(stateless_ips, 3) if stateless_ips else None,
        },
        "session": {
            "wall_s": round(session_wall, 3),
            "img_per_s": round(session_ips, 3) if session_ips else None,
            "wall_speedup": (
                round(stateless_wall / session_wall, 3) if session_wall > 0 else None
            ),
            "tiles_total": tiles_total,
            "tiles_computed": tiles_computed,
            "cache_hits": cache_hits,
            "hit_ratio": round(cache_hits / tiles_total, 4) if tiles_total else 0.0,
            "steady_state": {
                "frames": len(steady),
                "tiles_total": st_total,
                "tiles_computed": st_computed,
            },
        },
        "effective_speedup": (
            round(effective_speedup, 3) if effective_speedup else None
        ),
        "effective_img_per_s": effective_ips,
        "speedup_target_met": bool(
            effective_speedup is not None and effective_speedup >= 3.0
        ),
        "identity": {
            "frames_checked": len(results),
            "mismatches": mismatches,
            "ok": mismatches == 0,
        },
        "swap": swap_info,
        "metrics_in_exposition": metrics_ok,
        "grpc_smoke": grpc_smoke,
        "note": (
            "cached-session bytes == stateless predict_tiled bytes on every "
            "frame, across a live mid-sequence hot swap; effective_speedup "
            "is steady-state tiles_total/tiles_computed — the "
            "1/(changed-tile-fraction) throughput model, seeded and "
            "timing-independent"
        ),
    }


def _bench_serve_fleet(device) -> dict:
    """Serve-fleet scale-out + quantized predict (round 17,
    detail.serve_fleet).

    Four measurements over one model:

    - **grid**: replicas x {bf16,int8} closed-loop throughput and p50/p95
      through the in-process router (the gRPC overhead is the r10 serving
      section's number; this grid isolates the replica/quant levers).
    - **swap**: a fleet-wide two-phase install under concurrent load —
      commit pause (the fleet lock hold) and the torn-version count over
      post-commit requests (the zero-torn claim, measured not assumed).
    - **shed**: the full gRPC front door + load_gen ramp profile against a
      tight queue bound — shed counts by reason and per-phase client
      latency (admission control proven by overload, not by unit test).
    - **quant_gate**: the int8 install gate's probe-IoU verdict (a refusal
      is an honest artifact, not a failure: the fleet serves bf16 then).
    """
    import dataclasses
    import threading

    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs.metrics import StreamingPercentiles
    from fedcrack_tpu.serve import (
        InferenceEngine,
        ServeFleet,
        ServeServer,
        ServeServerThread,
        ServeService,
    )
    from fedcrack_tpu.tools.load_gen import make_images, run_load

    dtype = "bfloat16" if getattr(device, "platform", "") == "tpu" else "float32"
    buckets = tuple(sorted(SERVE_SIZES))
    base_cfg = ServeConfig(
        bucket_sizes=buckets,
        max_batch=SERVE_MAX_BATCH,
        max_delay_ms=5.0,
        tile_overlap=min(16, min(buckets) - 16) if min(buckets) > 16 else 0,
        compute_dtype=dtype,
        port=0,
    )
    model_config = ModelConfig(img_size=max(buckets), compute_dtype=dtype)
    var_v0 = init_variables(jax.random.key(SEED), model_config)
    var_v1 = init_variables(jax.random.key(SEED + 1), model_config)
    images = make_images(FLEET_REQUESTS, buckets, SEED)

    def drive(fleet, imgs, concurrency=SERVE_CONCURRENCY):
        """Closed-loop router load: C threads, one request in flight each."""
        from queue import Empty, Queue

        jobs: Queue = Queue()
        for img in imgs:
            jobs.put(img)
        versions: list[int] = []
        vlock = threading.Lock()

        def worker():
            while True:
                try:
                    img = jobs.get_nowait()
                except Empty:
                    return
                res = fleet.submit(img).result(timeout=300)
                with vlock:
                    versions.append(res.model_version)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, versions

    engines: dict[str, InferenceEngine] = {}
    grid: dict[str, dict] = {}
    for quant, arm in (("none", "bf16"), ("int8", "int8")):
        cfg_q = dataclasses.replace(base_cfg, quant=quant)
        engines[quant] = InferenceEngine(model_config, cfg_q)
        if quant == "int8":
            # The grid measures the int8 PROGRAM's throughput, so its
            # fleets install under a relaxed MEASUREMENT floor; the
            # production-floor verdict is the separate quant_gate record
            # below (a refusal there is an honest artifact, but it must
            # not silently turn the int8 arms into bf16 re-measurements).
            cfg_q = dataclasses.replace(cfg_q, quant_iou_floor=0.5)
        for n in FLEET_REPLICAS:
            fleet = ServeFleet(
                model_config,
                dataclasses.replace(cfg_q, replicas=n),
                var_v0,
                shared_engine=engines[quant],
            )
            try:
                from fedcrack_tpu.serve.quant import QuantizedVariables

                served_quant = isinstance(
                    fleet.manager.snapshot_for(0)[1], QuantizedVariables
                )
                wall, versions = drive(fleet, images)
                pooled = StreamingPercentiles(8192)
                for r in fleet.replicas:
                    pooled.merge(r.batcher.latency)
            finally:
                fleet.close()
            grid[f"r{n}_{arm}"] = {
                "replicas": n,
                "quant": arm,
                "served_quant": served_quant,
                "requests": len(images),
                "completed": len(versions),
                "wall_s": round(wall, 3),
                "throughput_rps": round(len(versions) / wall, 3) if wall else None,
                "p50_ms": pooled.percentile(50.0),
                "p95_ms": pooled.percentile(95.0),
            }

    # The production-floor gate verdict (ServeConfig defaults): what an
    # operator's install would do with THESE weights on THIS host.
    from fedcrack_tpu.serve.quant import quant_gate as run_quant_gate
    from fedcrack_tpu.serve.quant import quantize_variables

    eng_q = engines["int8"]
    quant_gate = run_quant_gate(
        eng_q,
        eng_q.prepare(var_v0),
        eng_q.prepare_quantized(quantize_variables(var_v0)),
    ).to_json()

    # ---- fleet-wide two-phase swap under load (max replicas, int8 cfg:
    # the swap re-runs the gate, so a refused quantization swaps bf16) ----
    n_max = max(FLEET_REPLICAS)
    swap_fleet = ServeFleet(
        model_config,
        dataclasses.replace(base_cfg, quant="int8", replicas=n_max),
        var_v0,
        shared_engine=engines["int8"],
    )
    try:
        half = images[: max(1, len(images) // 2)]
        _, pre_versions = drive(swap_fleet, half)
        swap_fleet.install(1, var_v1)
        _, post_versions = drive(swap_fleet, half)
        torn = sum(1 for v in post_versions if v != 1)
        swap = {
            "replicas": n_max,
            "pause_ms": (swap_fleet.manager.last_swap or {}).get("pause_ms"),
            "prepare_ms": (swap_fleet.manager.last_swap or {}).get("load_ms"),
            "pre_commit_versions": sorted(set(pre_versions)),
            "post_commit_versions": sorted(set(post_versions)),
            "torn_versions": torn,
            "zero_torn": torn == 0,
        }
    finally:
        swap_fleet.close()

    # ---- admission control: gRPC front door + ramp arrival profile vs a
    # tight queue bound — the 2x phase MUST shed, the artifact shows where ----
    shed_cfg = dataclasses.replace(
        base_cfg, quant="none", replicas=n_max, queue_bound=4
    )
    shed_fleet = ServeFleet(
        model_config, shed_cfg, var_v0, shared_engine=engines["none"]
    )
    server = ServeServer(
        ServeService(shed_fleet.engine, shed_fleet.router, shed_fleet.manager),
        port=0,
    )
    try:
        with ServeServerThread(server) as thread:
            shed_summary = run_load(
                f"127.0.0.1:{thread.port}",
                mode="open",
                profile="ramp",
                n_requests=max(32, FLEET_REQUESTS),
                rate_rps=FLEET_SHED_RATE,
                concurrency=SERVE_CONCURRENCY,
                sizes=(min(buckets),),
                seed=SEED,
            )
    finally:
        shed_fleet.close()
    shed = {
        "profile": "ramp",
        "rate_rps": FLEET_SHED_RATE,
        "queue_bound": shed_cfg.queue_bound,
        "total": shed_summary["shed"],
        "by_reason": shed_fleet.router.shed_counts(),
        "completed": shed_summary["completed"],
        "dropped": shed_summary["dropped"],
        "per_phase": shed_summary["per_phase"],
    }

    return {
        "dtype": dtype,
        "buckets": list(buckets),
        "max_batch": base_cfg.max_batch,
        "concurrency": SERVE_CONCURRENCY,
        "grid": grid,
        "swap": swap,
        "shed": shed,
        "quant_gate": quant_gate,
        "note": (
            "in-process router grid isolates the replica/quant levers "
            "(gRPC overhead is detail.serving's number); int8 grid arms "
            "install under a relaxed measurement floor so they measure the "
            "quantized PROGRAM (served_quant says what actually ran) while "
            "quant_gate is the production-floor verdict; zero_torn is "
            "measured over post-commit requests; CPU-smoke ratios are "
            "machinery validation — decisive img/s queue behind the "
            "ROADMAP TPU session"
        ),
    }


def _bench_elastic_fleet(device) -> dict:
    """Elastic serve fleet (round 22, detail.elastic_fleet).

    Two halves over one deliberately tiny model (dispatches chaos-throttled
    to 80 ms so capacity is REPLICA-bound, not model-bound — the section
    certifies the control loop, never CPU throughput):

    - **diurnal A/B**: the same seeded compressed-day arrival profile
      (night/morning/peak/evening at 0.2x/1x/1.8x/0.8x of the base rate)
      through the real gRPC front door three times — static-max (burns
      ``max`` replicas all day), static-min (one replica: the 1.8x peak
      MUST overrun its queue bound and shed), and autoscaled (starts at
      min, the FleetAutoscaler grows/shrinks the fleet live from the
      registry's own exposition). load_gen's ``--metrics-url`` sampler
      polls ``serve_fleet_replicas`` through each run — the autoscaled
      arm's ``replicas_varied`` is wire-level proof the fleet resized.
      The claims: autoscaled holds p95 with shed == 0 and dropped == 0 at
      STRICTLY lower replica-seconds than static-max; static-min sheds.
    - **shadow delivery**: a ShadowController stages one candidate that
      matches production (mirrored live traffic, canary IoU 1.0, zero
      drift → auto-PROMOTE installs it) and one deliberately degraded
      candidate (zeroed weights → IoU cliff + PSI blowout → auto-ROLLBACK,
      never installed, clients never see a shadow answer). Both full
      verdict records land in the artifact.
    """
    import dataclasses
    import threading

    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs.promexp import MetricsExporter
    from fedcrack_tpu.obs.registry import REGISTRY
    from fedcrack_tpu.serve import (
        FleetAutoscaler,
        InferenceEngine,
        ServeFleet,
        ServeServer,
        ServeServerThread,
        ServeService,
        ShadowController,
    )
    from fedcrack_tpu.tools.load_gen import make_images, run_load

    model_config = ModelConfig(
        img_size=16,
        stem_features=4,
        encoder_features=(8,),
        decoder_features=(8, 4),
    )
    slo_ms = 1500.0
    base_cfg = ServeConfig(
        bucket_sizes=(16,),
        max_batch=2,
        max_delay_ms=5.0,
        tile_overlap=4,
        # 16 open-loop client streams bound in-flight requests at 16, so a
        # bound of 10 is reachable by a one-replica backlog at the 1.8x
        # peak (static-min MUST shed) while the autoscaler's queue trigger
        # (2 x live <= 6) fires well inside it (autoscaled must NOT).
        queue_bound=10,
        slo_p95_ms=slo_ms,
        port=0,
    )
    v0 = init_variables(jax.random.key(SEED), model_config)
    engine = InferenceEngine(model_config, base_cfg)

    class _SlowBatches:
        """Stretch every dispatch so a replica's service rate is the
        throttle (max_batch/0.08s ~ 25 rps), making the 1.8x peak a real
        capacity cliff a tiny CPU model would otherwise never feel."""

        def on_batch(self, bucket, batch_index, attempt):
            time.sleep(0.08)

    exporter = MetricsExporter(REGISTRY)
    metrics_url = f"http://127.0.0.1:{exporter.start()}/metrics"
    arms: dict[str, dict] = {}
    auto_audit: dict = {}

    def run_arm(name: str, *, replicas: int, min_r: int = 0, max_r: int = 0):
        cfg = dataclasses.replace(
            base_cfg,
            replicas=replicas,
            min_replicas=min_r,
            max_replicas=max_r,
            scale_interval_s=0.05,
            scale_cooldown_s=0.15,
            scale_up_queue_depth=2,
            scale_down_idle_evals=6,
        )
        fleet = ServeFleet(
            model_config, cfg, v0, shared_engine=engine, chaos=_SlowBatches()
        )
        server = ServeServer(
            ServeService(fleet.engine, fleet.router, fleet.manager), port=0
        )
        autoscaler = None
        try:
            if min_r > 0:
                autoscaler = FleetAutoscaler(fleet)
                autoscaler.start()
            with ServeServerThread(server) as thread:
                summary = run_load(
                    f"127.0.0.1:{thread.port}",
                    mode="open",
                    profile="diurnal",
                    n_requests=ELASTIC_REQUESTS,
                    rate_rps=ELASTIC_RATE,
                    concurrency=16,
                    sizes=(16,),
                    seed=SEED,
                    metrics_url=metrics_url,
                    metrics_interval_s=0.25,
                )
            replica_seconds = (
                autoscaler.replica_seconds()
                if autoscaler is not None
                else replicas * summary["wall_s"]
            )
        finally:
            if autoscaler is not None:
                autoscaler.stop()
            fleet.close()
        fleet_block = summary.get("fleet") or {}
        fleet_block.pop("track", None)  # per-sample detail; keep artifact lean
        arms[name] = {
            "replicas_band": [min_r or replicas, max_r or replicas],
            "completed": summary["completed"],
            "shed": summary["shed"],
            "dropped": summary["dropped"],
            "p95_ms": (summary["latency_ms"] or {}).get("p95"),
            "wall_s": summary["wall_s"],
            "replica_seconds": round(replica_seconds, 3),
            "replicas_min": fleet_block.get("replicas_min"),
            "replicas_max": fleet_block.get("replicas_max"),
            "replicas_varied": bool(fleet_block.get("replicas_varied")),
            "per_phase": summary["per_phase"],
            "shed_by_reason": fleet.router.shed_counts(),
        }
        if autoscaler is not None:
            auto_audit.update(autoscaler.audit())

    run_arm("static_max", replicas=3)
    run_arm("static_min", replicas=1)
    run_arm("autoscaled", replicas=1, min_r=1, max_r=3)
    exporter.stop()

    # ---- shadow-replica progressive delivery: one promote, one rollback,
    # under live mirrored traffic (no throttle — the mirror needs samples,
    # not backlog) ----
    shadow_cfg = dataclasses.replace(
        base_cfg, replicas=1, shadow_fraction=1.0, shadow_min_samples=8
    )
    sfleet = ServeFleet(model_config, shadow_cfg, v0, shared_engine=engine)
    ctrl = ShadowController(sfleet)
    pump_imgs = make_images(8, (16,), SEED)
    stop_pump = threading.Event()

    def pump():
        i = 0
        while not stop_pump.is_set():
            try:
                sfleet.submit(pump_imgs[i % len(pump_imgs)]).result(timeout=30)
            except Exception:
                pass
            i += 1

    pump_thread = threading.Thread(target=pump, daemon=True)
    pump_thread.start()
    try:
        # A candidate indistinguishable from production (a re-publish):
        # IoU pins at 1.0, PSI at 0 — the promote path.
        promote_rec = ctrl.stage(1, v0, wait_s=15.0)
        # A deliberately degraded candidate: zeroed weights crater the
        # canary IoU and blow out the drift PSI — the rollback path.
        v_bad = jax.tree_util.tree_map(lambda x: x * 0, v0)
        rollback_rec = ctrl.stage(2, v_bad, wait_s=15.0)
    finally:
        stop_pump.set()
        pump_thread.join(timeout=10)
        sfleet.close()
    # Verdict records carry model outputs' floats; round-trip through JSON
    # (numpy scalars -> floats) so the artifact writer never trips.
    promote_rec = json.loads(json.dumps(promote_rec, default=float))
    rollback_rec = json.loads(json.dumps(rollback_rec, default=float))
    shadow = {
        "promote": promote_rec,
        "rollback": rollback_rec,
        "promoted": promote_rec.get("verdict") == "promote"
        and bool(promote_rec.get("installed")),
        "rolled_back": rollback_rec.get("verdict") == "rollback"
        and not rollback_rec.get("installed"),
    }

    auto = arms["autoscaled"]
    return {
        "profile": "diurnal",
        "rate_rps": ELASTIC_RATE,
        "requests": ELASTIC_REQUESTS,
        "slo_p95_ms": slo_ms,
        "queue_bound": base_cfg.queue_bound,
        "arms": arms,
        "autoscaler": auto_audit,
        "autoscaled_cheaper_than_static_max": (
            auto["replica_seconds"] < arms["static_max"]["replica_seconds"]
        ),
        "autoscaled_held_slo": (
            auto["shed"] == 0
            and auto["dropped"] == 0
            and auto["p95_ms"] is not None
            and auto["p95_ms"] <= slo_ms
        ),
        "static_min_shed": arms["static_min"]["shed"] > 0,
        "shadow": shadow,
        "note": (
            "dispatches chaos-throttled to 80 ms so capacity is replica-"
            "bound: the section certifies the control loop (scale before "
            "shed, drain without drops, fewer replica-seconds than "
            "static-max) on a CPU smoke; absolute rps is not a claim"
        ),
    }


def _bench_update_compression(rounds: int = COMPRESSION_ROUNDS) -> dict:
    """Compressed update transport A/B (round 12, fedcrack_tpu/compress).

    Two halves, both cheap enough for a CPU smoke run:

    - **wire** — one REFERENCE-SCALE round delta (the real ModelConfig, a
      synthetic per-leaf-scaled N(0, 1e-3·std) perturbation standing in for
      an Adam round delta) pushed through every codec on the host: measured
      frame bytes on the wire, bytes ratio vs the dense msgpack blob,
      median encode/decode wall. NullCodec is asserted BYTE-IDENTICAL to
      the dense path (null_identical) — the escape-hatch contract.
    - **trajectory** — the mesh plane's on-device encode∘decode twins
      (build_federated_round(update_codec=...)) over ``rounds`` rounds of a
      small 2-client federation: per-round crack-IoU for each codec, max
      absolute IoU delta vs the NullCodec oracle, and the driver's
      RoundRecord.bytes_per_round counter per codec. The null twin is
      additionally pinned bit-identical to a no-codec build (the tier-1
      test re-pins this; here it is recorded in the artifact).
    """
    from fedcrack_tpu.compress import decode_update, get_codec
    from fedcrack_tpu.compress.codecs import DEFAULT_TOPK_FRACTION
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    # ---- wire half: real bytes at reference scale ----
    ref = ModelConfig()
    ref_vars = jax.device_get(create_train_state(jax.random.key(SEED), ref).variables)
    base_tree = {"params": ref_vars["params"], "batch_stats": ref_vars["batch_stats"]}
    base_blob = tree_to_bytes(base_tree)
    rng = np.random.default_rng(SEED)
    upd_tree = jax.tree_util.tree_map(
        lambda x: (
            np.asarray(x, np.float32)
            + (
                1e-3
                * max(1e-6, float(np.std(np.asarray(x, np.float32))))
                * rng.standard_normal(np.shape(x))
            ).astype(np.float32)
        ),
        base_tree,
    )
    upd_blob = tree_to_bytes(upd_tree)
    wire: dict = {}
    reps = max(1, min(REPS, 3))
    for name in ("null", "int8", "topk_delta"):
        codec = get_codec(name)
        enc_times, frame = [], b""
        for _ in range(reps):
            codec.reset()
            t0 = time.perf_counter()
            frame = codec.encode_update(upd_blob, base_blob, round=1, base_version=0)
            enc_times.append(time.perf_counter() - t0)
        dec_times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            if name == "null":
                tree_from_bytes(frame, template=base_tree)
            else:
                decode_update(
                    frame, template=base_tree, base=base_tree, expected_base_version=0
                )
            dec_times.append(time.perf_counter() - t0)
        wire[name] = {
            "bytes_per_round": len(frame),
            "ratio_vs_null": (
                None if name == "null" else round(len(upd_blob) / len(frame), 2)
            ),
            "encode_ms": round(1e3 * float(np.median(enc_times)), 3),
            "decode_ms": round(1e3 * float(np.median(dec_times)), 3),
        }
        if name == "null":
            wire[name]["null_identical"] = frame == upd_blob

    # ---- trajectory half: mesh twins vs the NullCodec oracle ----
    n_clients = 2 if len(jax.devices()) >= 2 else 1
    mesh = make_mesh(n_clients, 1)
    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch = 2, 4
    per_client = [
        synth_crack_batch(steps * batch, img_size=16, seed=i)
        for i in range(n_clients)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    active = np.ones(n_clients, np.float32)
    ns = np.full(n_clients, float(steps * batch), np.float32)
    state0 = create_train_state(jax.random.key(SEED), tiny)
    data_fn = lambda r: (images, masks, active, ns) if r == 0 else None

    trajectory: dict = {}
    null_iou: list[float] | None = None
    for name in ("null", "int8", "topk_delta"):
        rf = build_federated_round(
            mesh,
            tiny,
            learning_rate=1e-3,
            local_epochs=1,
            update_codec=name,
            topk_fraction=DEFAULT_TOPK_FRACTION,
        )
        _, recs = run_mesh_federation(rf, state0.variables, data_fn, rounds, mesh)
        iou = [round(float(np.mean(r.metrics["iou"])), 6) for r in recs]
        if name == "null":
            null_iou = iou
        trajectory[name] = {
            "iou": iou,
            "bytes_per_round": int(recs[-1].bytes_per_round),
            "max_abs_iou_delta_vs_null": (
                None
                if null_iou is None or name == "null"
                else round(max(abs(a - b) for a, b in zip(iou, null_iou)), 6)
            ),
        }

    return {
        "dense_update_bytes": len(upd_blob),
        "rounds": rounds,
        "wire": wire,
        "trajectory": trajectory,
        "ref_model_leaves": len(jax.tree_util.tree_leaves(base_tree)),
        "ref_model_params": int(
            sum(np.asarray(l).size for l in jax.tree_util.tree_leaves(base_tree))
        ),
        "topk_fraction": DEFAULT_TOPK_FRACTION,
        "note": (
            "wire half is REAL bytes at reference scale (synthetic "
            "1e-3-relative round delta; measured frames, zlib'd) — the "
            ">=10x claim; trajectory half is the mesh twins' IoU vs the "
            "NullCodec oracle on a small federation (tolerance pinned at "
            "0.15 absolute by tests/test_compress.py)"
        ),
    }


def _bench_cohort_scale() -> dict:
    """Cohort-scale A/B (round 13). Three pieces, all CPU-smoke cheap:

    - **groups** — one 8-client cohort round executed time-multiplexed as
      groups ∈ {1, 2, 4} over progressively narrower meshes (tiny model):
      per-round wall vs group-dispatch count (the ~linear-in-ceil(C/G)
      scaling claim) and the final-weights sha256 per split — all splits
      must agree BITWISE (the ordered-fold contract, also test-pinned).
    - **tree** — a ``COHORT_TREE_CLIENTS``-simulated-client round through
      the 2-level aggregation tree (tiny 4x4 weight blobs — the protocol
      and memory shape are what is under test, not the model): root/edge
      peak resident update blobs, wire bytes at the root vs the flat
      equivalent, wall, and a double-run bit-reproducibility check from
      the cohort seed.
    - **flat** — the same cohort through a flat root (every leaf enrolls
      directly): peak resident blobs == cohort size, the O(N) shape the
      tree removes.
    """
    import hashlib

    from fedcrack_tpu.configs import FedConfig, ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed import rounds as R
    from fedcrack_tpu.fed.algorithms import sample_cohort
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
    from fedcrack_tpu.fed.tree import run_tree_federation
    from fedcrack_tpu.parallel import (
        build_federated_cohort_round,
        make_mesh,
        run_cohort_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    out: dict = {}

    # ---- group-count sweep: time-multiplexed mesh execution ----
    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch, cohort_c = 2, 4, min(8, max(2, jax.device_count()))
    per_client = [
        synth_crack_batch(steps * batch, img_size=16, seed=i)
        for i in range(cohort_c)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    active = np.ones(cohort_c, np.float32)
    ns = np.full(cohort_c, float(steps * batch), np.float32)
    variables = create_train_state(jax.random.key(SEED), tiny).variables
    groups_out: dict = {}
    shas = set()
    for n_groups in (1, 2, 4):
        if cohort_c % n_groups:
            continue
        g = cohort_c // n_groups
        mesh = make_mesh(g, 1)
        cr = build_federated_cohort_round(
            mesh, tiny, learning_rate=1e-3, local_epochs=1, segments=1
        )
        data_fn = lambda r: (images, masks, active, ns)
        # One compile round, one measured round.
        out_vars, recs = run_cohort_federation(cr, variables, data_fn, 2, mesh)
        sha = hashlib.sha256(
            tree_to_bytes(jax.device_get(out_vars))
        ).hexdigest()
        shas.add(sha)
        groups_out[str(n_groups)] = {
            "group_size": g,
            "group_dispatches": n_groups,
            "round_wall_s": round(recs[-1].wall_clock_s, 4),
            "compile_round_wall_s": round(recs[0].wall_clock_s, 4),
            "staged_bytes": recs[-1].staged_bytes,
            "max_live_staged_bytes": recs[-1].max_live_staged_bytes,
            "weights_sha256": sha,
        }
    out["groups"] = groups_out
    out["groups_bitwise_equal"] = len(shas) == 1
    out["cohort_size_mesh"] = cohort_c

    # ---- 1,024-simulated-client tree round + flat A/B ----
    def _vars(v):
        return {"params": {"w": np.full((4, 4), v, np.float32)}}

    def make_update(idx, r, base_blob, base_version):
        rng = np.random.default_rng([7, idx, r])
        base = tree_from_bytes(base_blob)
        tree = {
            "params": {
                "w": np.asarray(base["params"]["w"], np.float32)
                + rng.standard_normal((4, 4)).astype(np.float32) * 0.01
            }
        }
        return tree_to_bytes(tree), int(rng.integers(1, 50))

    n_tree = COHORT_TREE_CLIENTS
    fan_out = COHORT_TREE_FANOUT
    t0 = time.perf_counter()
    res = run_tree_federation(
        _vars(0.0),
        make_update,
        n_clients=4 * n_tree,
        cohort_size=n_tree,
        n_rounds=2,
        n_edges=fan_out,
        cohort_seed=SEED,
    )
    tree_wall = time.perf_counter() - t0
    res2 = run_tree_federation(
        _vars(0.0),
        make_update,
        n_clients=4 * n_tree,
        cohort_size=n_tree,
        n_rounds=2,
        n_edges=fan_out,
        cohort_seed=SEED,
    )
    out["tree"] = {
        "n_clients": res.n_clients,
        "cohort_size": res.cohort_size,
        "fan_out": res.n_edges,
        "rounds": res.rounds,
        "root_peak_blobs": res.root_peak_blobs,
        "edge_peak_blobs": res.edge_peak_blobs,
        "max_leaf_fan_in": res.max_leaf_fan_in,
        "root_peak_within_fan_in": res.root_peak_blobs <= res.n_edges,
        "bytes_at_root": res.bytes_at_root,
        "bytes_flat_equiv": res.bytes_flat_equiv,
        "leaf_updates": res.leaf_updates,
        "wall_s": round(tree_wall, 3),
        "bit_reproducible": res.global_sha256 == res2.global_sha256,
        "global_sha256": res.global_sha256,
    }

    cfg = FedConfig(
        max_rounds=1,
        cohort_size=n_tree,
        registration_window_s=3600.0,
        sanitize_updates=True,
    )
    state = R.initial_state(cfg, _vars(0.0))
    cohort = sample_cohort(4 * n_tree, n_tree, 0, SEED)
    now = 0.0
    t0 = time.perf_counter()
    for i in cohort:
        now += 1e-4
        state, _ = R.transition(state, R.Ready(cname=f"client-{int(i)}", now=now))
    base_blob = state.broadcast_blob
    flat_peak = 0
    flat_bytes = 0
    for i in cohort:
        blob, n = make_update(int(i), 0, base_blob, state.model_version)
        flat_bytes += len(blob)
        now += 1e-4
        state, rep = R.transition(
            state,
            R.TrainDone(
                cname=f"client-{int(i)}", round=1, blob=blob, num_samples=n, now=now
            ),
        )
        flat_peak = max(
            flat_peak,
            len(state.received) if rep.status != R.RESP_ARY and rep.status != R.FIN
            else n_tree,
        )
    out["flat"] = {
        "n_clients": n_tree,
        "root_peak_blobs": flat_peak,
        "bytes_at_root": flat_bytes,
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    out["note"] = (
        "groups: wall ~linear in group dispatches with BITWISE-equal "
        "weights across splits (ordered-fold contract); tree: root peak "
        "resident update blobs <= fan-out where the flat root holds the "
        "whole cohort — the O(fan-in) memory claim; CPU smoke (protocol + "
        "memory shape), the v5e-8 round-wall point is ROADMAP measurement "
        "item 6"
    )
    return out


def _async_sync_equivalence() -> dict:
    """The buffered mode's escape hatch, pinned in the artifact: with
    ``buffer_k = cohort_size`` and ``staleness_alpha = 0`` the buffered
    flush IS sync FedAvg — sha-identical global bytes over the same
    updates — and a permuted arrival order flushes to the same bytes (the
    sorted-fold discipline). Transition-driven, host-only, milliseconds."""
    import hashlib

    from fedcrack_tpu.configs import FedConfig
    from fedcrack_tpu.fed import rounds as R
    from fedcrack_tpu.fed.serialization import tree_to_bytes

    def _vars(v):
        return {"params": {"w": np.full((8, 8), v, np.float32)}}

    values = {"a": 1.0, "b": 3.0, "c": 6.0}
    samples = {"a": 10, "b": 30, "c": 20}

    def drive(mode: str, order: tuple) -> tuple[str, int]:
        kw = (
            dict(mode="buffered", buffer_k=3, staleness_alpha=0.0, max_staleness=4)
            if mode == "buffered"
            else {}
        )
        cfg = FedConfig(
            max_rounds=3, cohort_size=3, registration_window_s=3600.0, **kw
        )
        st = R.initial_state(cfg, _vars(0.0))
        now = 0.0
        for c in ("a", "b", "c"):
            now += 1e-3
            st, _ = R.transition(st, R.Ready(cname=c, now=now))
        for rnd in range(1, 4):
            for c in order:
                now += 1e-3
                st, _ = R.transition(st, R.PullWeights(cname=c, now=now))
            for c in order:
                now += 1e-3
                st, _ = R.transition(
                    st,
                    R.TrainDone(
                        cname=c,
                        round=rnd,
                        blob=tree_to_bytes(_vars(values[c] + rnd)),
                        num_samples=samples[c],
                        now=now,
                    ),
                )
        return hashlib.sha256(st.global_blob).hexdigest(), int(st.model_version)

    sync_sha, _ = drive("sync", ("a", "b", "c"))
    buf_sha, buf_v = drive("buffered", ("a", "b", "c"))
    perm_sha, _ = drive("buffered", ("c", "a", "b"))
    return {
        "sync_sha": sync_sha,
        "buffered_sha": buf_sha,
        "bit_identical": sync_sha == buf_sha,
        "arrival_order_independent": buf_sha == perm_sha,
        "global_versions": buf_v,
    }


def _async_trajectory_sim(
    seed: int = ASYNC_SEED,
    n_clients: int = 8,
    buffer_k: int = 2,
    alpha: float = 0.5,
    rounds: int = 6,
    lr: float = 0.1,
) -> dict:
    """Equal-wall trajectory quality, sync vs buffered, under the SAME
    seeded storm schedule — a deterministic event-clock simulation (no
    sleeps) of a toy quadratic (each client pulls the global toward its
    own target; the optimum is the target mean). The sync arm runs
    ``rounds`` barrier rounds (wall = sum of per-round max delays); the
    buffered arm replays the same per-(client, iteration) delays up to
    that wall. This is the CPU PROXY for 'trajectory quality at equal
    wall' — the real-model crack-IoU point is TPU measurement item 7."""
    import heapq
    import random as _random

    from fedcrack_tpu.chaos.plan import STRAGGLER_DELAY, FaultPlan
    from fedcrack_tpu.fed.buffered import staleness_weight

    names = [f"c{i}" for i in range(n_clients)]
    n_iter = rounds * 8
    plan = FaultPlan.storm(
        seed,
        clients=names,
        n_iterations=n_iter,
        tail_alpha=1.1,
        scale_s=0.03,
        cap_s=0.8,
    )
    delays = {
        (f.client, f.round): f.delay_s
        for f in plan.pending
        if f.kind == STRAGGLER_DELAY
    }
    rng = _random.Random(seed)
    targets = {n: rng.uniform(0.5, 1.5) for n in names}
    opt = sum(targets[n] for n in names) / n_clients

    def local(w: float, n: str) -> float:
        return w + lr * (targets[n] - w)

    # Sync arm: each round's wall is the cohort MAX delay.
    w, t = 0.0, 0.0
    for r in range(1, rounds + 1):
        t += max(delays[(n, r)] for n in names)
        w = sum(local(w, n) for n in names) / n_clients
    sync_wall, sync_loss = t, (w - opt) ** 2

    # Buffered arm to the same wall: clients loop, the server flushes the
    # staleness-weighted buffer at K (the fed/buffered.py semantics, on
    # the toy model).
    w, version = 0.0, 0
    buf: list = []
    heap: list = []
    for n in names:
        heapq.heappush(heap, (delays[(n, 1)], n, 1, w, version))
    while heap and heap[0][0] <= sync_wall:
        t_fin, n, it, base_w, base_v = heapq.heappop(heap)
        u = local(base_w, n)
        wt = staleness_weight(version - base_v, alpha)
        buf.append((u, wt))
        if len(buf) >= buffer_k:
            # The fed/buffered.py flush: weighted buffer mean, anchored on
            # the current global by the mean staleness weight.
            tot = sum(x for _, x in buf)
            mean = sum(u * x for u, x in buf) / tot
            mix = tot / len(buf)
            w = (1.0 - mix) * w + mix * mean
            version += 1
            buf = []
        nxt = it + 1
        d = delays[(n, (nxt - 1) % n_iter + 1)]
        heapq.heappush(heap, (t_fin + d, n, nxt, w, version))
    buffered_loss = (w - opt) ** 2
    return {
        "equal_wall_s": round(sync_wall, 4),
        "sync_final_loss": round(sync_loss, 8),
        "buffered_final_loss": round(buffered_loss, 8),
        "sync_versions": rounds,
        "buffered_versions": int(version),
        "buffered_at_least_as_close": buffered_loss <= sync_loss,
    }


def _bench_async_federation() -> dict:
    """detail.async_federation (round 14): storm A/B + sync-degeneration
    pin + mid-buffer recovery + equal-wall trajectory sim."""
    from fedcrack_tpu.tools.chaos_drill import (
        run_buffered_kill_drill,
        run_straggler_storm_drill,
    )

    return {
        "storm": run_straggler_storm_drill(seed=ASYNC_SEED),
        "sync_equivalence": _async_sync_equivalence(),
        "recovery": run_buffered_kill_drill(),
        "trajectory": _async_trajectory_sim(),
    }


def _bench_observability() -> dict:
    """detail.observability (round 15): the concurrent mini-soak + its
    end-of-soak invariant audit, self-scraped over a real /metrics HTTP
    endpoint."""
    from fedcrack_tpu.tools.soak import run_soak

    return run_soak(duration_s=SOAK_S, seed=0)


def _bench_federation_health() -> dict:
    """detail.federation_health (round 18): the SCALED_UPDATE end-to-end
    drill — sanitation accepts, ledger flags, canary IoU regresses,
    watchdog breaches with a flight dump."""
    from fedcrack_tpu.tools.chaos_drill import run_scaled_update_drill

    return run_scaled_update_drill()


def _bench_robust_aggregation() -> dict:
    """detail.robust_aggregation (round 21): the 4-arm robust-combine A/B
    over real gRPC — FedAvg drags and cliffs the canary; trimmed-mean,
    Krum, and the ledger-coupled quarantine hold it — plus the
    colluding-minority variant and the health-report exclusion join."""
    from fedcrack_tpu.tools.chaos_drill import run_robust_aggregation_drill

    return run_robust_aggregation_drill()


def _bench_privacy() -> dict:
    """detail.privacy (round 23): what the privacy plane COSTS.

    1. DP utility A/B: the mesh DP-SGD twin at the off arm plus each
       ``PRIVACY_SIGMAS`` noise multiplier — identical tiny model, data
       and seeds, the noise multiplier the only delta — reporting val
       IoU/loss, the final-weight drift off the noiseless trajectory, and
       the accountant's closed-form eps(delta) per arm.
    2. Secagg overhead: host-math masking microbench on a real-sized
       update tree — fixed-point encode + pairwise pads per client timed
       against the plaintext serialize, wire-size ratio, and the unmasked
       weighted mean pinned EXACT against the plaintext fixed-point sum.
    3. The real-gRPC dropped-masker drill (tools/chaos_drill): quorum
       close, seed recovery, bit-for-bit survivor average, zero torn
       rounds.
    """
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.parallel import make_mesh, run_mesh_federation
    from fedcrack_tpu.parallel.fedavg_mesh import (
        build_federated_round,
        stack_client_data,
    )
    from fedcrack_tpu.privacy import secagg as S
    from fedcrack_tpu.privacy.accountant import compute_epsilon
    from fedcrack_tpu.tools.chaos_drill import run_secagg_dropout_drill
    from fedcrack_tpu.train.local import create_train_state, evaluate

    t0 = time.monotonic()
    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4),
    )
    steps, batch = 2, 2
    mesh1 = make_mesh(1, 1)
    state0 = create_train_state(jax.random.key(0), tiny)
    init = state0.variables

    def data_fn(r: int):
        images, masks = stack_client_data(
            [synth_crack_batch(steps * batch, img_size=16, seed=r)],
            steps,
            batch,
        )
        return (
            images,
            masks,
            np.ones(1, np.float32),
            np.full(1, float(steps * batch), np.float32),
        )

    val_images, val_masks = synth_crack_batch(8, img_size=16, seed=977)

    def run_arm(sigma: float):
        rf = build_federated_round(
            mesh1, tiny, learning_rate=1e-3, local_epochs=1,
            dp_clip_norm=1.0 if sigma > 0.0 else 0.0,
            dp_noise_multiplier=sigma, dp_seed=42,
        )
        v, _ = run_mesh_federation(rf, init, data_fn, PRIVACY_ROUNDS, mesh1)
        metrics = evaluate(
            state0.replace_variables(v), [(val_images, val_masks)]
        )
        return v, metrics

    v_off, m_off = run_arm(0.0)
    leaves_off = [np.asarray(x) for x in jax.tree_util.tree_leaves(v_off)]

    def drift(v) -> float:
        return float(
            np.sqrt(
                sum(
                    float(np.sum((np.asarray(a) - b) ** 2))
                    for a, b in zip(jax.tree_util.tree_leaves(v), leaves_off)
                )
            )
        )

    # One noise step per mesh round (local_epochs=1): eps after the run is
    # the accountant's closed form at steps=PRIVACY_ROUNDS, the default
    # FedConfig q/delta (0.01 / 1e-5) — the same numbers the server's
    # history entries carry for this schedule.
    dp_utility: dict = {
        "off": {
            "noise_multiplier": 0.0,
            "clip_norm": 0.0,
            "epsilon": None,
            "val_iou": round(float(m_off["iou"]), 6),
            "val_loss": round(float(m_off["loss"]), 6),
            "weight_drift_vs_off": 0.0,
        }
    }
    for sigma in PRIVACY_SIGMAS:
        v_arm, m_arm = run_arm(float(sigma))
        dp_utility[f"sigma_{sigma:g}"] = {
            "noise_multiplier": float(sigma),
            "clip_norm": 1.0,
            "epsilon": round(
                compute_epsilon(0.01, float(sigma), PRIVACY_ROUNDS, 1e-5), 6
            ),
            "val_iou": round(float(m_arm["iou"]), 6),
            "val_loss": round(float(m_arm["loss"]), 6),
            "weight_drift_vs_off": round(drift(v_arm), 6),
        }

    # ---- secagg masking overhead, host math on a real-sized tree ----
    bits = S.DEFAULT_BITS
    rng = np.random.Generator(np.random.Philox(key=7))
    big_tree = {
        "params": {
            f"layer_{i}": rng.standard_normal(16384).astype(np.float32)
            for i in range(4)
        }
    }
    cohort = {name: S.client_seed(name) for name in ("a", "b", "c")}
    roster = S.round_roster(cohort, 1)
    plaintext_bytes = len(tree_to_bytes(big_tree))
    t_mask = time.perf_counter()
    masked = {
        name: S.mask_update(
            big_tree, cname=name, n_samples=10, roster=roster, bits=bits
        )
        for name in cohort
    }
    mask_ms = (time.perf_counter() - t_mask) / len(cohort) * 1e3
    masked_bytes = max(len(b) for b in masked.values())
    t_unmask = time.perf_counter()
    uploads = {name: S.decode_masked(masked[name]) for name in masked}
    total, total_samples, _dropped = S.unmask_sum(uploads, roster, bits)
    mean = S.unmasked_mean(total, total_samples, big_tree, bits)
    unmask_ms = (time.perf_counter() - t_unmask) * 1e3
    expected = S.fixed_point_decode(
        S.weighted_fixed_sum([big_tree] * 3, [10, 10, 10], bits),
        30, bits, big_tree,
    )
    exact = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(mean),
            jax.tree_util.tree_leaves(expected),
        )
    )
    overhead = {
        "n_params": int(
            sum(np.asarray(x).size for x in jax.tree_util.tree_leaves(big_tree))
        ),
        "cohort": len(cohort),
        "bits": int(bits),
        "plaintext_bytes": plaintext_bytes,
        "masked_bytes": int(masked_bytes),
        "wire_ratio": round(masked_bytes / plaintext_bytes, 4),
        "mask_ms": round(mask_ms, 3),
        "unmask_ms": round(unmask_ms, 3),
        "exact_vs_plaintext": bool(exact),
    }

    return {
        "rounds": PRIVACY_ROUNDS,
        "dp_utility": dp_utility,
        "secagg_overhead": overhead,
        "secagg_drill": run_secagg_dropout_drill(),
        "bench_s": round(time.monotonic() - t0, 2),
    }


def main() -> None:
    # Smoke-test hook: this image pre-imports jax at interpreter startup with
    # the axon (real TPU tunnel) platform, so a JAX_PLATFORMS=cpu env override
    # is swallowed; the runtime config API still works before first backend use.
    if os.environ.get("FEDCRACK_BENCH_FORCE_CPU"):
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialized; run where we are
    # Persistent XLA compilation cache: the sweep + ref-scale programs are
    # O(10) distinct compilations; on a warm cache (any prior run on this
    # host) they cost ~0 instead of minutes of the budget.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    from fedcrack_tpu.obs.flops import device_peak_flops
    from fedcrack_tpu.parallel import make_mesh

    n_clients = max(1, jax.device_count())
    device = jax.devices()[0]
    peak = device_peak_flops(device)
    mesh = make_mesh(n_clients, 1)
    # The reference-scale sections are single-client by definition (the
    # reference's workload is one client's round): they need a 1-device mesh
    # regardless of how many chips the sweep uses.
    ref_mesh = make_mesh(1, 1)
    skips: list = []
    section_s: dict = {}
    # Whatever happens past this point — a later section raising, not just a
    # signal — the sections that DID finish go out as the one JSON line.
    try:
        _run_sections(
            mesh, ref_mesh, n_clients, device, peak, skips, section_s
        )
    finally:
        _emit()


def _run_sections(mesh, ref_mesh, n_clients, device, peak, skips, section_s) -> None:

    def _budget_detail():
        return {
            "budget_s": BUDGET_S,
            "elapsed_s": round(_elapsed(), 1),
            "sections_s": {k: round(v, 1) for k, v in section_s.items()},
        }

    # ---- mandatory: sweep at the flagship size (every ratio needs it) ----
    t0 = time.monotonic()
    sweep: dict = {}

    # Bootstrap + per-point payloads: a TERM landing mid-sweep (even one
    # deferred through a native XLA compile until the call returns) still
    # ships every point that finished, instead of round 3's empty artifact.
    def _sweep_checkpoint():
        done = [p for p in sweep.values() if p.get("round_ms")]
        _set_payload(
            f"INCOMPLETE sweep ({len(done)} point(s) finished before "
            f"interruption): one-program FedAvg round wall-clock, "
            f"{n_clients} client(s), b{BATCH}, {STEPS} steps",
            done[-1]["round_ms"] if done else None,
            None,
            {"sweep": sweep, "skipped": skips, "budget": _budget_detail()},
        )

    _sweep_checkpoint()
    flagship_per_client, f32_state0, (flag_si, flag_sm) = _sweep_size(
        SIZES[0], mesh, n_clients, device, peak, sweep, checkpoint=_sweep_checkpoint
    )
    section_s[f"sweep_{SIZES[0]}"] = time.monotonic() - t0

    f32_key = f"float32_{SIZES[0]}"
    bf16_key = f"bfloat16_{SIZES[0]}"
    mesh_f32_s = sweep[f32_key]["round_s_raw"]
    mesh_bf16_s = sweep[bf16_key]["round_s_raw"]
    mesh_f32_compute_s = STEPS * _step_s(sweep[f32_key])
    mesh_bf16_compute_s = STEPS * _step_s(sweep[bf16_key])

    detail = {
        "sweep": sweep,
        "bf16_speedup_over_f32": (
            round(mesh_f32_compute_s / mesh_bf16_compute_s, 3)
            if sweep[f32_key]["per_step_ms"] is not None
            and sweep[bf16_key]["per_step_ms"] is not None
            else None
        ),
        "device_kind": getattr(device, "device_kind", "unknown"),
        "peak_tflops_bf16": None if peak is None else peak / 1e12,
        "n_clients": n_clients,
        "steps": STEPS,
        "batch": BATCH,
        "skipped": skips,
        "budget": _budget_detail(),
    }
    metric_sweep = (
        f"flagship one-program FedAvg round wall-clock "
        f"({n_clients} client(s), {SIZES[0]}x{SIZES[0]}, bf16 compute, "
        f"b{BATCH}, {STEPS} steps); vs_baseline = host/gRPC-style plane "
        f"over mesh plane at equal float32 dtype, tunnel-inclusive "
        f"(see detail for compute-only ratio, MFU sweep, decomposition)"
    )
    # Safety-net payload before the host plane exists (vs_baseline unknowable).
    _set_payload(metric_sweep, sweep[bf16_key]["round_ms"], None, detail)

    # ---- reference-scale points, budget-gated — the HEADLINE, so they run
    # immediately after the flagship sweep (round-4 weak #1: the host plane
    # used to run first and a congested tunnel starved these out of the
    # driver's budget two rounds running) ----
    run_ref = REF_SCALE == "1" or (
        REF_SCALE == "auto" and getattr(device, "platform", "") == "tpu"
    )
    reference_scale: dict = {}
    segmented_pipeline: dict = {}
    resident_pool: dict = {}
    reuse = None
    total_steps = REF_EPOCHS * REF_STEPS
    if run_ref:
        img = SIZES[0]
        data_bytes = REF_STEPS * BATCH * (img * img * 4)  # uint8 imgs+masks
        synth_bytes = min(512, REF_STEPS * BATCH) * img * img * 16  # f32 synth
        reps = max(1, min(REPS, 3))
        round_est = _step_s(sweep[bf16_key]) * total_steps
        stage_est = _est_stage_s(data_bytes)
        # Warm rounds run ~3x the settled round time through the tunnel
        # (residual streaming from the 400 MB initial staging — measured
        # warm walls of 24 s against a settled 8.2 s), hence 2 warms cost
        # ~6 round-equivalents; one fresh program compile on top.
        flag_est = (
            _est_synth_s(synth_bytes)
            + 3 * stage_est
            + (6 + reps) * round_est
            + (reps + 1) * max(stage_est, round_est)
            + COMPILE_EST_S
            + 8.0
        )
        if _fits(flag_est):
            t0 = time.monotonic()
            point, reuse = _bench_reference_scale(
                img, "bfloat16", device, ref_mesh, full=True
            )
            section_s["ref_bf16"] = time.monotonic() - t0
            if point is not None:
                reference_scale[f"bfloat16_{img}"] = point
            else:
                _skip(skips, f"ref_scale_bfloat16_{img}", flag_est, "budget ran out mid-point")
        else:
            _skip(skips, f"ref_scale_bfloat16_{img}", flag_est, "estimate exceeds remaining budget")

        f32_round_est = _step_s(sweep[f32_key]) * total_steps
        f32_est = (6 + reps) * f32_round_est + COMPILE_EST_S + 4.0
        if reuse is not None and _fits(f32_est):
            t0 = time.monotonic()
            point, reuse = _bench_reference_scale(
                img, "float32", device, ref_mesh, full=False, reuse=reuse
            )
            section_s["ref_f32"] = time.monotonic() - t0
            if point is not None:
                reference_scale[f"float32_{img}"] = point
            else:
                _skip(skips, f"ref_scale_float32_{img}", f32_est, "budget ran out mid-point")
        else:
            _skip(
                skips,
                f"ref_scale_float32_{img}",
                f32_est,
                "estimate exceeds remaining budget"
                if reuse is not None
                else "flagship point skipped, no staged data to reuse",
            )
        # ---- segmented-pipeline A/B (round 7): the SAME reference-scale
        # round as K epoch-segment programs with chunk-grain streamed
        # restaging, vs the monolithic points above — reuses their staged
        # buffers, so it must run before the epoch is dropped ----
        for sp_dtype, with_ov in (("bfloat16", True), ("float32", False)):
            mono_point = reference_scale.get(f"{sp_dtype}_{img}")
            if mono_point is None or reuse is None:
                _skip(
                    skips,
                    f"segmented_pipeline_{sp_dtype}_{img}",
                    0.0,
                    "monolithic reference-scale point missing; no baseline",
                )
                continue
            mono_round_s = mono_point["round_s_raw"]
            stage_est = reuse.get("stage_s") or _est_stage_s(data_bytes)
            sp_est = (
                (2 + reps) * mono_round_s
                + (reps + 1) * max(stage_est, mono_round_s) * (1 if with_ov else 0)
                + COMPILE_EST_S
                + 4.0
            )
            if not _fits(sp_est):
                _skip(
                    skips,
                    f"segmented_pipeline_{sp_dtype}_{img}",
                    sp_est,
                    "estimate exceeds remaining budget",
                )
                continue
            t0 = time.monotonic()
            sp_point = _bench_segmented_pipeline(
                img, sp_dtype, device, ref_mesh, reuse, mono_point,
                with_overlap=with_ov,
            )
            section_s[f"segmented_pipeline_{sp_dtype}"] = time.monotonic() - t0
            if sp_point is not None:
                segmented_pipeline[f"{sp_dtype}_{img}"] = sp_point
            else:
                _skip(
                    skips,
                    f"segmented_pipeline_{sp_dtype}_{img}",
                    sp_est,
                    "budget ran out mid-point",
                )

        # ---- resident-pool A/B (round 9): streamed vs device-resident
        # data plane at reference scale — the roofline-collapse deliverable.
        # Reuses the monolithic point's host arrays + dedup pool, so it must
        # run before the epoch is dropped ----
        mono_bf16 = reference_scale.get(f"bfloat16_{img}")
        if mono_bf16 is None or reuse is None:
            _skip(
                skips,
                f"resident_pool_bfloat16_{img}",
                0.0,
                "monolithic reference-scale point missing; no baseline",
            )
        else:
            mono_round_s = mono_bf16["round_s_raw"]
            rp_est = (2 + reps) * mono_round_s + (reps + 1) * mono_round_s + COMPILE_EST_S + 8.0
            if not _fits(rp_est):
                _skip(
                    skips,
                    f"resident_pool_bfloat16_{img}",
                    rp_est,
                    "estimate exceeds remaining budget",
                )
            else:
                t0 = time.monotonic()
                rp_point = _bench_resident_pool(
                    img, "bfloat16", device, ref_mesh, reuse, mono_bf16
                )
                section_s["resident_pool_bfloat16"] = time.monotonic() - t0
                if rp_point is not None:
                    resident_pool[f"bfloat16_{img}"] = rp_point
                else:
                    _skip(
                        skips,
                        f"resident_pool_bfloat16_{img}",
                        rp_est,
                        "budget ran out mid-point",
                    )

        # The ref-128 epoch (~400 MB host + device) is dead weight for the
        # remaining sections — drop it before the 256px staging below.
        reuse = None

    ref_bf16 = reference_scale.get(f"bfloat16_{SIZES[0]}")
    ref_f32 = reference_scale.get(f"float32_{SIZES[0]}")
    metric_headline = metric_sweep
    value = sweep[bf16_key]["round_ms"]
    vs_baseline = None
    mesh_ref_f32_s = None
    if reference_scale:
        detail["reference_scale"] = reference_scale
        if segmented_pipeline:
            detail["segmented_pipeline"] = segmented_pipeline
        if resident_pool:
            detail["resident_pool"] = resident_pool
        # Ratio denominator: the measured f32 ref round when it ran; else the
        # slope-reconstructed f32 round (conservative — slope excludes the
        # one-dispatch cost the measured round would include).
        denom_note = "measured f32 reference-scale round"
        if ref_f32 is not None:
            mesh_ref_f32_s = ref_f32["round_s_raw"]
        else:
            mesh_ref_f32_s = _step_s(sweep[f32_key]) * total_steps
            denom_note = "slope-reconstructed f32 round (f32 ref point skipped)"
        if ref_bf16 is not None:
            # The metric/value pair switches to reference scale ONLY when the
            # bf16 reference-scale point actually landed (round-4 advisor
            # finding: an aborted bf16 point must not leave a reference-scale
            # metric string over a sweep-scale value).
            value = ref_bf16["round_ms"]
            metric_headline = (
                f"reference-scale one-program FedAvg round wall-clock "
                f"(1 client, {SIZES[0]}x{SIZES[0]}, bf16 compute, b{BATCH}, "
                f"{REF_EPOCHS} epochs x {REF_STEPS} steps = {total_steps} steps, "
                f"uint8 staging); vs_baseline = reconstructed host/gRPC-style "
                f"plane over {denom_note} at equal float32 dtype, "
                f"tunnel-inclusive (detail.vs_baseline_ref_compute_only is the "
                f"dispatch-free floor; detail.reference_scale has the "
                f"staging/compute/overlap decomposition)"
            )
        else:
            metric_headline = metric_sweep + (
                " [bf16 reference-scale point missing: value stays "
                "sweep-scale; vs_baseline is the reference-scale f32 ratio "
                "when reference_scale is non-empty; detail.reference_scale "
                "holds what landed]"
            )
        detail["budget"] = _budget_detail()
        _set_payload(metric_headline, value, vs_baseline, detail)

    # ---- serving plane (round 10): the full serve stack (compiled buckets,
    # micro-batcher, hot-swap manager, gRPC front door) under closed-loop
    # load with one live hot-swap — THIS round's deliverable, so it runs
    # right after the reference-scale headline ----
    if SERVING:
        serve_est = (
            2 * COMPILE_EST_S
            + SERVE_REQUESTS * 0.3
            + _est_synth_s(
                sum(
                    s * s * 16 * (SERVE_REQUESTS // max(1, len(SERVE_SIZES)) + 1)
                    for s in SERVE_SIZES
                )
            )
            + 15.0
        )
        if _fits(serve_est):
            t0 = time.monotonic()
            try:
                detail["serving"] = _bench_serving(device)
            except Exception as e:  # the serving extra must never kill the artifact
                detail["serving"] = {"error": repr(e)}
            section_s["serving"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(skips, "serving", serve_est, "estimate exceeds remaining budget")

    # ---- serve fleet (round 17): replicas x quant grid through the
    # in-process router, the fleet-wide two-phase swap, and the ramp-profile
    # shed run — this round's deliverable, right after the r10 serving
    # section (they share warm programs when both run) ----
    if SERVE_FLEET:
        fleet_est = (
            3 * COMPILE_EST_S  # ref + int8 + (cache-warm) swap/shed builds
            + len(FLEET_REPLICAS) * 2 * FLEET_REQUESTS * 0.15
            + 30.0
        )
        if _fits(fleet_est):
            t0 = time.monotonic()
            try:
                detail["serve_fleet"] = _bench_serve_fleet(device)
            except Exception as e:  # never kills the artifact
                detail["serve_fleet"] = {"error": repr(e)}
            section_s["serve_fleet"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips, "serve_fleet", fleet_est, "estimate exceeds remaining budget"
            )

    # ---- elastic fleet (round 22): the 3-arm diurnal A/B (static-max vs
    # static-min vs autoscaled) through the gRPC front door plus the
    # shadow-replica promote/rollback pins. The model is tiny (host-scale
    # compile); wall is dominated by the seeded diurnal schedule itself —
    # ~2*requests/rate per arm — plus the two shadow stagings ----
    if ELASTIC:
        elastic_est = (
            COMPILE_EST_S
            + 3 * 2.2 * ELASTIC_REQUESTS / max(1.0, ELASTIC_RATE)
            + 40.0
        )
        if _fits(elastic_est):
            t0 = time.monotonic()
            try:
                detail["elastic_fleet"] = _bench_elastic_fleet(device)
            except Exception as e:  # never kills the artifact
                detail["elastic_fleet"] = {"error": repr(e)}
            section_s["elastic_fleet"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips, "elastic_fleet", elastic_est,
                "estimate exceeds remaining budget",
            )

    # ---- video serving (round 19): the frame-coherent session plane —
    # stateless-vs-cached-session A/B over one seeded >=90%-overlap
    # sequence, per-frame byte-identity across a live mid-sequence hot
    # swap, serve_stream_* exposition, and the StreamPredict gRPC smoke.
    # Tiny weights + two small bucket programs: host-scale seconds ----
    if VIDEO:
        video_est = 2 * COMPILE_EST_S + VIDEO_FRAMES * 0.5 + 20.0
        if _fits(video_est):
            t0 = time.monotonic()
            try:
                detail["video_serving"] = _bench_video_serving(device)
            except Exception as e:  # never kills the artifact
                detail["video_serving"] = {"error": repr(e)}
            section_s["video_serving"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips, "video_serving", video_est, "estimate exceeds remaining budget"
            )

    # ---- low-precision kernels (round 20): the kernel-plane A/B —
    # reference vs fused-int8 (interpreter off-TPU) vs fp8-where-supported
    # quantized predict on the r5 interleaved template, with per-plane
    # parity + install-gate verdicts. Tiny engine: host-scale seconds
    # off-TPU; the function budget-gates its variants individually ----
    if LOWP:
        t0 = time.monotonic()
        try:
            lowp_point = _bench_lowp_kernels(device, skips)
            if lowp_point is not None:
                detail["lowp_kernels"] = lowp_point
        except Exception as e:  # never kills the artifact
            detail["lowp_kernels"] = {"error": repr(e)}
        section_s["lowp_kernels"] = time.monotonic() - t0
        detail["budget"] = _budget_detail()
        _set_payload(metric_headline, value, vs_baseline, detail)

    # ---- layout A/B (round 6): the VERDICT r5 top ask — space-to-depth /
    # channel-packing graph transforms vs the reference layout, interleaved,
    # at the flagship size in the headline dtypes. Runs right after the
    # reference-scale headline (it is this round's deliverable) and before
    # the host plane; per-variant budget gating degrades it gracefully ----
    layout_ab: dict = {}

    def _layout_checkpoint():
        detail["layout_ab"] = layout_ab
        detail["budget"] = _budget_detail()
        _set_payload(metric_headline, value, vs_baseline, detail)

    t0 = time.monotonic()
    for ab_dtype in ("bfloat16", "float32"):
        _layout_ab(
            SIZES[0],
            mesh,
            n_clients,
            device,
            peak,
            flag_si,
            flag_sm,
            layout_ab,
            dtype=ab_dtype,
            round_s_hint=sweep[f"{ab_dtype}_{SIZES[0]}"]["round_s_raw"],
            skips=skips,
            checkpoint=_layout_checkpoint,
        )
    if layout_ab:
        section_s[f"layout_ab_{SIZES[0]}"] = time.monotonic() - t0
        _layout_checkpoint()

    # ---- host plane (reference architecture) — AFTER the headline sections
    # (round-4 weak #1: it cost 240 s under a congested tunnel and starved
    # them); degrades to a 1-rep median, then to a recorded skip ----
    host_parts = None
    host_total_s = None
    host_round_est = n_clients * STEPS * (_step_s(sweep[f32_key]) + 0.12) + 2.0
    for host_reps in (REPS, 1):
        host_est = COMPILE_EST_S + (1 + host_reps) * host_round_est + 5.0
        if _fits(host_est):
            t0 = time.monotonic()
            host_total_s, host_parts = _measure_host_plane(
                n_clients,
                f32_state0.variables,
                flagship_per_client,
                f32_state0,
                reps=host_reps,
            )
            section_s["host_plane"] = time.monotonic() - t0
            break
    else:
        _skip(skips, "host_plane", host_est, "estimate exceeds remaining budget")
        if (
            "reconstructed host/gRPC-style" in metric_headline
            or "reference-scale f32 ratio" in metric_headline
        ):
            # The metric text promises a host-plane ratio that now cannot be
            # computed — annotate rather than mislabel (the same
            # labeling-honesty class as the round-4 metric/value fix). BOTH
            # promising variants are matched (ADVICE r5 #2): the full
            # ref-scale string and the bf16-point-missing string, whose
            # "vs_baseline is the reference-scale f32 ratio" clause would
            # otherwise keep promising a ratio that stays None.
            metric_headline += (
                " [host plane budget-skipped: vs_baseline unavailable this run]"
            )
            _set_payload(metric_headline, value, vs_baseline, detail)

    host_ref_s = None
    host_ref_compute_s = None
    if host_parts is not None:
        # Compute-only reconstruction of a host round: the same SGD step costs
        # what the mesh plane's scan charges per step (identical XLA program);
        # everything above that is the host architecture's own overhead.
        compute_s = n_clients * STEPS * _step_s(sweep[f32_key])
        ser_s = host_parts["serialization_ms"] / 1e3
        agg_s = host_parts["host_fedavg_ms"] / 1e3
        dispatch_s = max(0.0, host_total_s - compute_s - ser_s - agg_s)
        compute_only_s = compute_s + ser_s + agg_s

        detail["host_plane"] = {
            "dtype": "float32",
            "img_size": SIZES[0],
            "round_ms": round(host_total_s * 1e3, 2),
            "reps": host_reps,
            "per_step_compute_ms": round(_step_s(sweep[f32_key]) * 1e3, 3),
            "serialization_ms": round(host_parts["serialization_ms"], 2),
            "host_fedavg_ms": round(host_parts["host_fedavg_ms"], 2),
            "dispatch_overhead_ms": round(dispatch_s * 1e3, 2),
            "note": (
                "dispatch_overhead is per-step Python dispatch + host<->device "
                "transfer round-trips; through a remote-device tunnel it is "
                "dominated by tunnel latency and is NOT a compute advantage"
            ),
        }
        # Same-architecture-work ratio, dispatch excluded on BOTH sides: host
        # round rebuilt from its compute + serialization + aggregation parts,
        # over the mesh round's slope-based (dispatch-free) time.
        detail["vs_baseline_compute_only"] = round(
            compute_only_s / mesh_f32_compute_s, 3
        )
        # Measured end-to-end ratio against the bf16 flagship.
        detail["vs_baseline_vs_flagship"] = round(host_total_s / mesh_bf16_s, 3)

        if reference_scale:
            # Host plane restated AT THE REFERENCE'S SCALE: reconstructed from
            # measured components — per-step compute slope, per-step dispatch
            # overhead from the measured STEPS-step host round, serialization,
            # host FedAvg — because driving 3,880 Python-dispatched steps
            # through the tunnel per rep is minutes per measurement for no
            # added information.
            per_step_overhead_s = dispatch_s / max(1, n_clients * STEPS)
            # 1-client serialization shape: 1 broadcast + 1 upload serialized,
            # 1 client parse + 1 server parse (NOT this run's n_clients total).
            ser_ref_s = (
                2 * host_parts["to_bytes_s_raw"]
                + 2 * host_parts["from_bytes_s_raw"]
            )
            agg_ref_s = host_parts["fedavg_s_raw"]
            host_ref_s = (
                total_steps * (_step_s(sweep[f32_key]) + per_step_overhead_s)
                + ser_ref_s
                + agg_ref_s
            )
            host_ref_compute_s = (
                total_steps * _step_s(sweep[f32_key]) + ser_ref_s + agg_ref_s
            )
            detail["host_ref_reconstructed_s"] = round(host_ref_s, 3)
            detail["vs_baseline_ref_compute_only"] = round(
                host_ref_compute_s / mesh_ref_f32_s, 3
            )
            vs_baseline = round(host_ref_s / mesh_ref_f32_s, 3)
        else:
            vs_baseline = round(host_total_s / mesh_f32_s, 3)
        detail["budget"] = _budget_detail()
        _set_payload(metric_headline, value, vs_baseline, detail)

    # ---- input pipeline: the reference's synchronous per-step decode cost
    # (host-CPU-only, cheap — no tunnel traffic) — closes the
    # decode-exclusive-reconstruction caveat (round-4 weak #4) ----
    input_pipeline = None
    if _fits(20.0):
        t0 = time.monotonic()
        try:
            input_pipeline = _measure_input_pipeline(SIZES[0])
        except Exception as e:  # a host-only extra must never kill the artifact
            input_pipeline = {"error": repr(e)}
        section_s["input_pipeline"] = time.monotonic() - t0
    else:
        _skip(skips, "input_pipeline", 20.0, "estimate exceeds remaining budget")
    if input_pipeline is not None:
        detail["input_pipeline"] = input_pipeline
        dec = input_pipeline.get("charged_per_step_s_raw")
        if dec is not None and host_ref_s is not None:
            # Decode-inclusive reconstruction: the reference pays BATCH
            # synchronous image+mask decodes before every step (inside fit);
            # the mesh plane's input cost is already inside its measured
            # round (uint8 pool staged + overlapped by parallel.driver).
            detail["host_ref_with_input_s"] = round(
                host_ref_s + total_steps * dec, 3
            )
            detail["vs_baseline_ref_with_input"] = round(
                (host_ref_s + total_steps * dec) / mesh_ref_f32_s, 3
            )
            detail["vs_baseline_ref_compute_plus_input"] = round(
                (host_ref_compute_s + total_steps * dec) / mesh_ref_f32_s, 3
            )
        detail["budget"] = _budget_detail()
        _set_payload(metric_headline, value, vs_baseline, detail)

    # ---- chaos recovery: the mid-round server kill→restart drill (host-only
    # control plane, tiny weights, seconds — times the round-8 durable-
    # statefile crash-recovery path; semantics are pinned by the tier-1
    # chaos suite, this section contributes the TIMING artifact) ----
    if CHAOS:
        if _fits(15.0):
            t0 = time.monotonic()
            try:
                from fedcrack_tpu.tools.chaos_drill import run_kill_restart_drill

                detail["chaos_recovery"] = run_kill_restart_drill()
            except Exception as e:  # a host-only extra must never kill the artifact
                detail["chaos_recovery"] = {"error": repr(e)}
            section_s["chaos_recovery"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(skips, "chaos_recovery", 15.0, "estimate exceeds remaining budget")

    # ---- compressed update transport A/B (round 12): wire bytes + codec
    # timings at reference scale (host, seconds) and the mesh twins'
    # IoU-trajectory delta vs the NullCodec oracle (three tiny-model round
    # programs; COMPILE-dominated, so the estimate assumes cold) ----
    if COMPRESSION:
        comp_est = 3 * 20.0 + 10.0
        if _fits(comp_est):
            t0 = time.monotonic()
            try:
                detail["update_compression"] = _bench_update_compression()
            except Exception as e:  # a host-side extra must never kill the artifact
                detail["update_compression"] = {"error": repr(e)}
            section_s["update_compression"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips,
                "update_compression",
                comp_est,
                "estimate exceeds remaining budget",
            )

    # ---- cohort scale (round 13): the group-count sweep over the time-
    # multiplexed cohort round (three grouped builds of the tiny model —
    # compile-dominated, assume cold) plus the 1,024-simulated-client
    # tree round and its flat A/B (host-only, tiny blobs, seconds) ----
    if COHORT:
        cohort_est = 3 * 30.0 + 20.0
        if _fits(cohort_est):
            t0 = time.monotonic()
            try:
                detail["cohort_scale"] = _bench_cohort_scale()
            except Exception as e:  # a host-side extra must never kill the artifact
                detail["cohort_scale"] = {"error": repr(e)}
            section_s["cohort_scale"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips,
                "cohort_scale",
                cohort_est,
                "estimate exceeds remaining budget",
            )

    # ---- async federation (round 14): the straggler-storm sync-vs-
    # buffered A/B over a real gRPC control plane (seeded delays, equal
    # wall — seconds of real sleeps), the bit-exact sync-degeneration pin,
    # the mid-buffer kill→restart drill, and the equal-wall trajectory
    # simulation (host-only, deterministic) ----
    if ASYNC:
        if _fits(20.0):
            t0 = time.monotonic()
            try:
                detail["async_federation"] = _bench_async_federation()
            except Exception as e:  # a host-only extra must never kill the artifact
                detail["async_federation"] = {"error": repr(e)}
            section_s["async_federation"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips, "async_federation", 20.0, "estimate exceeds remaining budget"
            )

    # ---- observability (round 15): the concurrent mini-soak — buffered
    # federation + edge shard + live hot-swapping serve plane + driver leg
    # under a rolling chaos schedule (storm delays, corrupt frames, a
    # mid-soak server kill→restart), watched through its own /metrics
    # endpoint and closed with the invariant audit ----
    if OBSERVABILITY:
        obsy_est = SOAK_S + 25.0  # + tiny-engine compile & teardown
        if _fits(obsy_est):
            t0 = time.monotonic()
            try:
                detail["observability"] = _bench_observability()
            except Exception as e:  # an in-process extra must never kill the artifact
                detail["observability"] = {"error": repr(e)}
            section_s["observability"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips, "observability", obsy_est, "estimate exceeds remaining budget"
            )

    # ---- federation health (round 18): the SCALED_UPDATE drill — the
    # sanitation gate accepts a scaled-but-finite update, the per-client
    # ledger's robust-z score flags it, the canary IoU cliffs on the
    # poisoned install, and the health watchdog turns the pair of signals
    # into a breach + flight dump + exit-3 verdict ----
    if HEALTH:
        health_est = 30.0  # one 1-round federation + tiny-engine compile
        if _fits(health_est):
            t0 = time.monotonic()
            try:
                detail["federation_health"] = _bench_federation_health()
            except Exception as e:  # a host-only extra must never kill the artifact
                detail["federation_health"] = {"error": repr(e)}
            section_s["federation_health"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips,
                "federation_health",
                health_est,
                "estimate exceeds remaining budget",
            )

    # ---- robust aggregation (round 21): the same SCALED_UPDATE poison as
    # a 4-arm A/B — FedAvg drags the global ~x300 and cliffs the canary;
    # trimmed-mean / Krum / the ledger-coupled quarantine hold IoU and cut
    # the drag by >= 10x; the colluding-minority variant and the
    # health-report join ride along ----
    if ROBUST:
        robust_est = 20.0  # nine tiny 1-round federations + one engine
        if _fits(robust_est):
            t0 = time.monotonic()
            try:
                detail["robust_aggregation"] = _bench_robust_aggregation()
            except Exception as e:  # a host-only extra must never kill the artifact
                detail["robust_aggregation"] = {"error": repr(e)}
            section_s["robust_aggregation"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips,
                "robust_aggregation",
                robust_est,
                "estimate exceeds remaining budget",
            )

    # ---- privacy (round 23): the DP utility/epsilon A/B on the mesh
    # twin (one compile per noise arm — that IS the wall), the secagg
    # masking-overhead microbench with its exact unmask pin, and the
    # real-gRPC dropped-masker drill ----
    if PRIVACY:
        privacy_est = (1 + len(PRIVACY_SIGMAS)) * COMPILE_EST_S + 15.0
        if _fits(privacy_est):
            t0 = time.monotonic()
            try:
                detail["privacy"] = _bench_privacy()
            except Exception as e:  # a host-only extra must never kill the artifact
                detail["privacy"] = {"error": repr(e)}
            section_s["privacy"] = time.monotonic() - t0
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)
        else:
            _skip(
                skips,
                "privacy",
                privacy_est,
                "estimate exceeds remaining budget",
            )

    # ---- batch-scaling curve (bf16 flagship at batch 32/64; non-parity
    # appendix substantiating the width-bound-ceiling claim) ----
    curve: dict = {}
    bf16_round_s = sweep[bf16_key]["round_s_raw"]
    curve_est = (
        2 * (2 + REPS) * (1 + FIT_FACTOR) * bf16_round_s + 4 * COMPILE_EST_S + 5.0
    )
    if _fits(curve_est):

        def _curve_checkpoint():
            detail["batch_curve"] = curve
            detail["budget"] = _budget_detail()
            _set_payload(metric_headline, value, vs_baseline, detail)

        t0 = time.monotonic()
        _batch_curve(
            SIZES[0],
            mesh,
            n_clients,
            device,
            peak,
            flag_si,
            flag_sm,
            curve,
            checkpoint=_curve_checkpoint,
        )
        section_s["batch_curve"] = time.monotonic() - t0
        _curve_checkpoint()
    else:
        _skip(skips, "batch_curve", curve_est, "estimate exceeds remaining budget")
    # The staged flagship arrays are dead weight for the remaining sections.
    del flag_si, flag_sm

    # ---- secondary sweep sizes (MFU completeness; least load-bearing) ----
    for img in SIZES[1:]:
        sz_bytes = STEPS * BATCH * n_clients * img * img * 16
        # Per dtype: (2 warm + REPS) rounds at BOTH scan lengths (short +
        # FIT_FACTOR x long); per-step cost scales ~quadratically with crop.
        # 4 fresh programs (2 dtypes x 2 scan lengths) assumed UNCACHED.
        step_scaled = _step_s(sweep[f32_key]) * (img / SIZES[0]) ** 2
        est = (
            _est_synth_s(sz_bytes)
            + _est_stage_s(sz_bytes)
            + 2 * (2 + REPS) * (1 + FIT_FACTOR) * STEPS * step_scaled
            + 4 * COMPILE_EST_S
            + 5.0
        )
        if not _fits(est):
            _skip(skips, f"sweep_{img}", est, "estimate exceeds remaining budget")
            continue
        t0 = time.monotonic()
        _, _, (sz_si, sz_sm) = _sweep_size(img, mesh, n_clients, device, peak, sweep)
        section_s[f"sweep_{img}"] = time.monotonic() - t0
        detail["budget"] = _budget_detail()
        _set_payload(metric_headline, value, vs_baseline, detail)
        # Layout A/B at the secondary size (the 256 px point of the round-6
        # deliverable), reusing this sweep's staged arrays — bf16 only (the
        # MFU headline dtype); per-variant gating trims it under pressure.
        t0 = time.monotonic()
        _layout_ab(
            img,
            mesh,
            n_clients,
            device,
            peak,
            sz_si,
            sz_sm,
            layout_ab,
            dtype="bfloat16",
            round_s_hint=sweep[f"bfloat16_{img}"]["round_s_raw"],
            skips=skips,
            checkpoint=_layout_checkpoint,
        )
        if f"bfloat16_{img}" in layout_ab:
            section_s[f"layout_ab_{img}"] = time.monotonic() - t0
            _layout_checkpoint()
        del sz_si, sz_sm

    # ---- opt-in: the ~10 min bf16/256 reference-scale point ----
    if run_ref and REF_256 and len(SIZES) > 1:
        img = SIZES[1]
        data_bytes = REF_STEPS * BATCH * (img * img * 4)
        round_256_est = _step_s(sweep[bf16_key]) * total_steps * (img / SIZES[0]) ** 2
        est = (
            _est_synth_s(min(512, REF_STEPS * BATCH) * img * img * 16)
            + 3 * _est_stage_s(data_bytes)
            + (6 + REPS) * round_256_est
            + (REPS + 1) * max(_est_stage_s(data_bytes), round_256_est)
            + COMPILE_EST_S
            + 8.0
        )
        if _fits(est):
            t0 = time.monotonic()
            try:
                # Round 7: measured via epoch-chunked execution — K programs
                # of REF_STEPS steps each, staged as K chunk transfers. The
                # monolithic form is exactly what this tunnel's remote
                # compile helper 500s on (round 5: the 3,880-step program /
                # 1.6 GB single transfer — bench_runs/ isolation logs);
                # each 388-step segment is the same size class as the
                # 128 px programs that compile fine.
                point, _ = _bench_reference_scale(
                    img, "bfloat16", device, ref_mesh, full=True,
                    segments=(
                        SEGMENTS
                        if SEGMENTS > 0 and REF_EPOCHS % SEGMENTS == 0
                        else REF_EPOCHS
                    ),
                )
            except Exception as e:
                # Even the chunked form can die on an exotic tunnel; record
                # the failure as a skip — every earlier section's data is
                # already in the payload.
                point = None
                _skip(skips, f"ref_scale_bfloat16_{img}", est, f"failed: {e!r:.180}")
            section_s[f"ref_bf16_{img}"] = time.monotonic() - t0
            if point is not None:
                detail.setdefault("reference_scale", {})[f"bfloat16_{img}"] = point
            elif not any(s["section"] == f"ref_scale_bfloat16_{img}" for s in skips):
                _skip(skips, f"ref_scale_bfloat16_{img}", est, "budget ran out mid-point")
        else:
            _skip(skips, f"ref_scale_bfloat16_{img}", est, "estimate exceeds remaining budget")

    detail["budget"] = _budget_detail()


if __name__ == "__main__":
    main()
