"""Pytest root conftest: run the suite on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; JAX's host-platform device
emulation gives the suite 8 virtual CPU devices so mesh/psum sharding code
runs for real. Must be set before the first ``import jax``.
"""

import os
import sys

# Hard override: the container profile exports JAX_PLATFORMS=axon (the real
# TPU tunnel); the suite must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep TF (used only by h5-importer parity tests) off any accelerator and quiet.
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
# Persistent XLA compilation cache: the U-Net programs take O(10s) each to
# compile on CPU; cache them across test runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
