"""Pytest root conftest: run the suite on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; JAX's host-platform device
emulation gives the suite 8 virtual CPU devices so mesh/psum sharding code
runs for real (SURVEY.md §4 "distributed-without-a-cluster").

NOTE: in this image JAX is pre-imported at interpreter startup (a site hook),
so ``JAX_PLATFORMS``/``XLA_FLAGS`` environment overrides are captured before
any conftest runs. The runtime ``jax.config.update`` API is the reliable
override — it works any time before first backend use.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Hard override: the container profile exports JAX_PLATFORMS=axon (the real
# TPU tunnel); the suite must run on the virtual CPU mesh regardless. The
# helper handles every JAX version (jax_num_cpu_devices where it exists,
# the XLA_FLAGS host-device flag — read at first backend use, still in the
# future here — where it doesn't).
from fedcrack_tpu.jaxcompat import ensure_cpu_devices

ensure_cpu_devices(8)

# Keep TF (used only by h5-importer parity tests) off any accelerator and quiet.
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
# Persistent XLA compilation cache: the U-Net programs take O(10s) each to
# compile on CPU; cache them across test runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
