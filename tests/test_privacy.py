"""Privacy plane (round 23): DP-SGD + RDP accountant + pairwise-mask secagg.

Four layers, each pinned where it can silently rot:

- the RDP accountant's eps(delta) against regression pins and the q=1
  closed form (min over orders of T*a/(2 sigma^2) + log(1/delta)/(a-1));
- the DP-SGD host update's clip closed form and seeded-noise determinism
  (same (seed, client, round) -> bit-identical noise);
- the secagg residue ring: fixed-point round trips, pairwise masks
  canceling EXACTLY (not approximately) across cohort sizes and upload
  orders, and dropout recovery reconstructing the missing pads bit-for-bit;
- the server state machine end to end in-process: roster freeze at the
  RUNNING transition, the TrainingNotice roster reply, masked rounds
  closing to the plaintext weighted fixed-point mean bit-for-bit (with and
  without a dropped masker), epsilon charged into history + statefile and
  surviving a serialize/restore cycle, and the budget finishing the
  federation loudly.

The real-gRPC secagg drill (dropped masker over the wire) lives in
tests/test_chaos.py next to the other transport drills.
"""

from __future__ import annotations

import dataclasses
import json
import math

import numpy as np
import pytest

pytestmark = pytest.mark.privacy

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.privacy import secagg as S
from fedcrack_tpu.privacy.accountant import (
    DEFAULT_ORDERS,
    PrivacyAccountant,
    compute_epsilon,
    rdp_subsampled_gaussian,
    rdp_to_epsilon,
)
from fedcrack_tpu.privacy.dpsgd import dp_update_host


# ---- accountant ----


def test_accountant_epsilon_regression_pin():
    """The Abadi-regime pin: sigma=1.1, q=0.01, T=1000, delta=1e-5. The
    value is this implementation's output, pinned so a refactor cannot
    silently change what the server REPORTS as spent privacy."""
    eps = compute_epsilon(0.01, 1.1, 1000, 1e-5)
    assert eps == pytest.approx(2.0867961135743176, rel=1e-9)


def test_accountant_full_batch_closed_form():
    """At q=1 subsampling amplifies nothing: per-step RDP of the Gaussian
    mechanism is exactly a/(2 sigma^2), so eps(delta) is the direct
    minimization over orders — computable in four lines here and required
    to match the production path bit-for-bit."""
    sigma, steps, delta = 1.1, 1000, 1e-5
    expected = min(
        steps * a / (2.0 * sigma * sigma) + math.log(1.0 / delta) / (a - 1.0)
        for a in DEFAULT_ORDERS
        if a > 1
    )
    assert compute_epsilon(1.0, sigma, steps, delta) == pytest.approx(
        expected, rel=1e-12
    )
    assert compute_epsilon(1.0, sigma, steps, delta) == pytest.approx(
        837.9592064567056, rel=1e-9
    )


def test_accountant_monotone_and_zero():
    eps = [compute_epsilon(0.01, 1.1, t, 1e-5) for t in (0, 1, 10, 100, 1000)]
    assert eps[0] == 0.0
    assert all(a < b for a, b in zip(eps, eps[1:]))
    # More noise -> less epsilon at equal steps.
    assert compute_epsilon(0.01, 2.0, 100, 1e-5) < compute_epsilon(
        0.01, 1.1, 100, 1e-5
    )


def test_accountant_class_tracks_per_client_and_round_trips_wire():
    acct = PrivacyAccountant(
        noise_multiplier=1.1, sample_rate=0.01, delta=1e-5
    )
    acct.record(["a", "b"], steps=3)
    acct.record(["a"], steps=2)
    assert acct.epsilon_of("a") > acct.epsilon_of("b") > 0.0
    assert acct.epsilon_of("a") == pytest.approx(
        compute_epsilon(0.01, 1.1, 5, 1e-5), rel=1e-9
    )
    assert acct.max_epsilon() == acct.epsilon_of("a")
    twin = PrivacyAccountant(
        noise_multiplier=1.1, sample_rate=0.01, delta=1e-5
    )
    twin.load_wire(acct.to_wire())
    assert twin.epsilons() == acct.epsilons()


# ---- DP-SGD host update ----


def _vec_tree(value, n=8):
    return {"params": {"w": np.full(n, value, np.float32)}}


def test_dp_clip_closed_form():
    """Delta norm 10 clipped to 1.0: the private update is base +
    delta/10, exactly (noise off)."""
    base = _vec_tree(0.0, 4)
    trained = {"params": {"w": np.float32([10.0, 0.0, 0.0, 0.0])}}
    out = dp_update_host(
        trained, base, clip_norm=1.0, noise_multiplier=0.0,
        dp_seed=7, cname="a", round_idx=1,
    )
    np.testing.assert_array_equal(
        out["params"]["w"], np.float32([1.0, 0.0, 0.0, 0.0])
    )
    # Inside the ball the update passes through untouched.
    small = {"params": {"w": np.float32([0.3, 0.0, 0.0, 0.0])}}
    out2 = dp_update_host(
        small, base, clip_norm=1.0, noise_multiplier=0.0,
        dp_seed=7, cname="a", round_idx=1,
    )
    np.testing.assert_array_equal(out2["params"]["w"], small["params"]["w"])


def test_dp_noise_is_seeded_per_client_and_round():
    base, trained = _vec_tree(0.0), _vec_tree(0.5)
    kw = dict(clip_norm=1.0, noise_multiplier=1.1, dp_seed=42)
    a1 = dp_update_host(trained, base, cname="a", round_idx=3, **kw)
    a2 = dp_update_host(trained, base, cname="a", round_idx=3, **kw)
    b = dp_update_host(trained, base, cname="b", round_idx=3, **kw)
    a_next = dp_update_host(trained, base, cname="a", round_idx=4, **kw)
    np.testing.assert_array_equal(a1["params"]["w"], a2["params"]["w"])
    assert not np.array_equal(a1["params"]["w"], b["params"]["w"])
    assert not np.array_equal(a1["params"]["w"], a_next["params"]["w"])
    assert np.all(np.isfinite(a1["params"]["w"]))


# ---- secagg residue ring ----


def test_fixed_point_round_trip_exact_at_bits_precision():
    rng = np.random.Generator(np.random.Philox(key=3))
    tree = {"w": rng.standard_normal(64).astype(np.float32)}
    bits = 24
    enc = S.fixed_point_encode(tree, bits)
    dec = S.fixed_point_decode(enc, 1, bits, tree)
    # Quantization error is bounded by half an LSB of the fixed point.
    assert np.max(np.abs(dec["w"] - tree["w"])) <= 0.5 / (1 << bits)
    # And a round-tripped quantized tree is a fixed point of the codec.
    enc2 = S.fixed_point_encode(dec, bits)
    for a, b in zip(enc, enc2):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("n_clients", [2, 3, 5])
def test_mask_cancellation_exact(n_clients):
    """The tentpole identity: summing every client's MASKED residues in
    any order equals the plaintext weighted fixed-point sum bit-for-bit —
    uint64 wraparound addition is associative-exact, and the pairwise
    pads telescope to zero."""
    rng = np.random.Generator(np.random.Philox(key=11))
    names = [f"c{i}" for i in range(n_clients)]
    cohort = {n: S.client_seed(n) for n in names}
    roster = S.round_roster(cohort, 2)
    trees = [
        {"w": rng.standard_normal(33).astype(np.float32)} for _ in names
    ]
    samples = [7 * (i + 1) for i in range(n_clients)]
    expected = S.weighted_fixed_sum(trees, samples, 24)
    for perm in ([*range(n_clients)], [*reversed(range(n_clients))]):
        total = None
        for i in perm:
            blob = S.mask_update(
                trees[i], cname=names[i], n_samples=samples[i],
                roster=roster, bits=24,
            )
            leaves = [
                np.asarray(x, np.uint64)
                for x in S.decode_masked(blob)["leaves"]
            ]
            total = (
                leaves
                if total is None
                else [a + b for a, b in zip(total, leaves)]
            )
        for a, b in zip(total, expected):
            np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dropped", [("c0",), ("c2",), ("c0", "c3")])
def test_dropout_recovery_exact(dropped):
    """Survivors' masked sum still carries pads toward the dropped; the
    server reconstructs each (survivor, dropped) pad from roster seeds and
    subtracts — the unmasked sum equals the SURVIVORS' plaintext weighted
    sum bit-for-bit (ragged dropout sweep)."""
    rng = np.random.Generator(np.random.Philox(key=13))
    names = [f"c{i}" for i in range(4)]
    cohort = {n: S.client_seed(n) for n in names}
    roster = S.round_roster(cohort, 5)
    trees = {n: {"w": rng.standard_normal(17).astype(np.float32)} for n in names}
    samples = {n: 10 + i for i, n in enumerate(names)}
    survivors = [n for n in names if n not in dropped]
    uploads = {
        n: S.decode_masked(
            S.mask_update(
                trees[n], cname=n, n_samples=samples[n],
                roster=roster, bits=24,
            )
        )
        for n in survivors
    }
    total, total_samples, recovered = S.unmask_sum(uploads, roster, 24)
    assert recovered == sorted(dropped)
    assert total_samples == sum(samples[n] for n in survivors)
    expected = S.weighted_fixed_sum(
        [trees[n] for n in survivors], [samples[n] for n in survivors], 24
    )
    for a, b in zip(total, expected):
        np.testing.assert_array_equal(a, b)
    mean = S.unmasked_mean(total, total_samples, trees[names[0]], 24)
    ref = S.fixed_point_decode(
        expected, total_samples, 24, trees[names[0]]
    )
    np.testing.assert_array_equal(mean["w"], ref["w"])


def test_round_roster_never_repeats_pads():
    cohort = {"a": S.client_seed("a"), "b": S.client_seed("b")}
    r1, r2 = S.round_roster(cohort, 1), S.round_roster(cohort, 2)
    assert set(r1) == set(r2) == {"a", "b"}
    assert r1 != r2  # a fresh pad basis every round
    assert S.round_roster(cohort, 1) == r1  # but deterministic per round
    m1 = S.pair_mask(S.pair_seed("a", r1["a"], "b", r1["b"]), [(5,)])
    m2 = S.pair_mask(S.pair_seed("a", r2["a"], "b", r2["b"]), [(5,)])
    assert not np.array_equal(m1[0], m2[0])


def test_client_seed_fits_signed_int64():
    """The enroll seed travels in the proto Scalar's SIGNED as_int: 63
    bits, deterministic, distinct per client."""
    for name in ("a", "b", "worker-17", "edge/0"):
        seed = S.client_seed(name)
        assert 0 <= seed < 2**63
        assert seed == S.client_seed(name)
    assert S.client_seed("a") != S.client_seed("b")


def test_validate_masked_gate():
    tree = {"w": np.zeros(4, np.float32)}
    cohort = {"a": 1, "b": 2}
    roster = S.round_roster(cohort, 1)
    blob = S.mask_update(tree, cname="a", n_samples=3, roster=roster, bits=24)
    assert S.validate_masked(blob, tree, bits=24, cohort=roster) is None
    # Wrong precision, stale cohort, and plaintext all REJECT loudly.
    assert S.validate_masked(blob, tree, bits=16, cohort=roster) is not None
    assert (
        S.validate_masked(blob, tree, bits=24, cohort={"a": 1, "c": 9})
        is not None
    )
    assert S.validate_masked(tree_to_bytes(tree), tree, bits=24, cohort=roster)
    assert not S.is_masked_blob(tree_to_bytes(tree))
    assert S.is_masked_blob(blob)


# ---- config validation ----


def test_config_validation_refuses_bad_privacy_combos():
    ok = dict(
        secagg=True, aggregation="fedavg", quarantine_z=0.0,
        update_codec="null", mode="sync",
    )
    FedConfig(**ok)  # the valid combination loads
    with pytest.raises(ValueError, match="privacy/robustness"):
        FedConfig(**{**ok, "aggregation": "trimmed_mean"})
    with pytest.raises(ValueError, match="quarantine_z=0"):
        FedConfig(**{**ok, "quarantine_z": 3.5})
    with pytest.raises(ValueError, match="update_codec='null'"):
        FedConfig(**{**ok, "update_codec": "int8"})
    with pytest.raises(ValueError, match="mode='sync'"):
        FedConfig(**{**ok, "mode": "buffered"})
    with pytest.raises(ValueError, match="secagg_bits"):
        FedConfig(secagg_bits=60)
    with pytest.raises(ValueError, match="dp_clip_norm > 0"):
        FedConfig(dp_noise_multiplier=1.0)
    with pytest.raises(ValueError, match="dp_sample_rate"):
        FedConfig(dp_sample_rate=0.0)
    with pytest.raises(ValueError, match="dp_delta"):
        FedConfig(dp_delta=1.0)


# ---- the server state machine, in-process ----

_TMPL = {"w": np.zeros(6, np.float32)}


def _enroll(cfg, names, with_seeds=True):
    state = R.initial_state(cfg, _TMPL)
    for n in names:
        seed = S.client_seed(n) if with_seeds else None
        state, rep = R.transition(state, R.Ready(cname=n, now=0.0, secagg_seed=seed))
    return R._advance_time(state, cfg.registration_window_s + 1.0)


def _secagg_cfg(**kw):
    base = dict(
        cohort_size=3, max_rounds=1, registration_window_s=1.0,
        secagg=True, quarantine_z=0.0, update_codec="null",
        aggregation="fedavg", mode="sync",
    )
    base.update(kw)
    return FedConfig(**base)


def test_secagg_roster_freezes_at_running_and_notice_distributes_it():
    cfg = _secagg_cfg()
    state = _enroll(cfg, ["a", "b", "c"])
    assert state.phase == R.PHASE_RUNNING
    assert set(state.secagg_roster) == {"a", "b", "c"}
    # Enroll-time seeds land verbatim; the notice reply hands the frozen
    # roster + round index to every masker.
    assert state.secagg_roster["a"] == S.client_seed("a")
    state, rep = R.transition(state, R.TrainingNotice(cname="a", now=2.0))
    roster_doc = json.loads(rep.config["__secagg_roster"])
    assert {n: int(s) for n, s in roster_doc.items()} == dict(
        state.secagg_roster
    )
    assert int(rep.config["current_round"]) == state.current_round
    # A client that never shipped a seed still lands on the same roster
    # entry via the deterministic fallback.
    state2 = _enroll(cfg, ["a", "b", "c"], with_seeds=False)
    assert dict(state2.secagg_roster) == dict(state.secagg_roster)


def _masked_blob(state, name, tree, ns):
    roster = S.round_roster(state.secagg_roster, state.current_round)
    return S.mask_update(
        tree, cname=name, n_samples=ns, roster=roster,
        bits=state.config.secagg_bits,
    )


def test_secagg_round_closes_to_exact_fixed_point_mean():
    cfg = _secagg_cfg()
    state = _enroll(cfg, ["a", "b", "c"])
    trees = {
        "a": {"w": np.full(6, 1.0, np.float32)},
        "b": {"w": np.full(6, 3.0, np.float32)},
        "c": {"w": np.full(6, 5.0, np.float32)},
    }
    ns = {"a": 10, "b": 30, "c": 20}
    rnd = state.current_round
    for name in ("a", "b", "c"):
        state, rep = R.transition(
            state,
            R.TrainDone(
                cname=name, blob=_masked_blob(state, name, trees[name], ns[name]),
                num_samples=ns[name], round=rnd, now=2.0,
            ),
        )
    assert state.phase == R.PHASE_FINISHED
    entry = state.history[-1]
    assert entry["secagg"]["maskers"] == ["a", "b", "c"]
    assert entry["secagg"]["recovered"] == []
    got = tree_from_bytes(state.global_blob, template=_TMPL)
    expected = S.fixed_point_decode(
        S.weighted_fixed_sum(
            [trees[n] for n in ("a", "b", "c")], [10, 30, 20],
            cfg.secagg_bits,
        ),
        60, cfg.secagg_bits, _TMPL,
    )
    np.testing.assert_array_equal(got["w"], expected["w"])


def test_secagg_dropout_round_recovers_and_matches_survivor_mean():
    cfg = _secagg_cfg(quorum_fraction=0.67, round_deadline_s=5.0)
    state = _enroll(cfg, ["a", "b", "c"])
    trees = {
        "a": {"w": np.full(6, 1.0, np.float32)},
        "b": {"w": np.full(6, 3.0, np.float32)},
    }
    rnd = state.current_round
    for name in ("a", "b"):
        state, rep = R.transition(
            state,
            R.TrainDone(
                cname=name, blob=_masked_blob(state, name, trees[name], 10),
                num_samples=10, round=rnd, now=2.0,
            ),
        )
    assert state.phase == R.PHASE_RUNNING  # quorum met, deadline not yet
    state = R._advance_time(state, 100.0)
    assert state.phase == R.PHASE_FINISHED
    entry = state.history[-1]
    assert entry["secagg"]["maskers"] == ["a", "b"]
    assert entry["secagg"]["recovered"] == ["c"]
    got = tree_from_bytes(state.global_blob, template=_TMPL)
    expected = S.fixed_point_decode(
        S.weighted_fixed_sum(
            [trees["a"], trees["b"]], [10, 10], cfg.secagg_bits
        ),
        20, cfg.secagg_bits, _TMPL,
    )
    np.testing.assert_array_equal(got["w"], expected["w"])


def test_secagg_rejects_plaintext_and_wrong_roster_uploads():
    cfg = _secagg_cfg()
    state = _enroll(cfg, ["a", "b", "c"])
    rnd = state.current_round
    tree = {"w": np.ones(6, np.float32)}
    state, rep = R.transition(
        state,
        R.TrainDone(
            cname="a", blob=tree_to_bytes(tree), num_samples=10,
            round=rnd, now=2.0,
        ),
    )
    assert rep.status == R.REJECTED
    # Wrong fixed-point precision fails the structural gate.
    narrow = S.mask_update(
        tree, cname="b", n_samples=10,
        roster=S.round_roster(state.secagg_roster, rnd), bits=16,
    )
    state, rep = R.transition(
        state,
        R.TrainDone(cname="b", blob=narrow, num_samples=10, round=rnd, now=2.0),
    )
    assert rep.status == R.REJECTED
    # The sample count inside the masked frame must agree with the event.
    lying = S.mask_update(
        tree, cname="c", n_samples=10,
        roster=S.round_roster(state.secagg_roster, rnd),
        bits=cfg.secagg_bits,
    )
    state, rep = R.transition(
        state,
        R.TrainDone(cname="c", blob=lying, num_samples=25, round=rnd, now=2.0),
    )
    assert rep.status == R.REJECTED


def _dp_cfg(**kw):
    base = dict(
        cohort_size=2, max_rounds=3, registration_window_s=1.0,
        dp_clip_norm=1.0, dp_noise_multiplier=1.1, dp_sample_rate=0.01,
        dp_steps_per_round=4, dp_delta=1e-5,
    )
    base.update(kw)
    return FedConfig(**base)


def _run_dp_round(state, now):
    rnd = state.current_round
    blob = tree_to_bytes({"w": np.full(6, 0.5, np.float32)})
    for n in sorted(state.cohort):
        state, _ = R.transition(
            state,
            R.TrainDone(cname=n, blob=blob, num_samples=10, round=rnd, now=now),
        )
    return state


def test_dp_epsilon_charged_into_history_and_summary():
    cfg = _dp_cfg()
    state = _enroll(cfg, ["a", "b"])
    state = _run_dp_round(state, 2.0)
    entry = state.history[-1]
    assert dict(state.privacy_steps) == {"a": 4, "b": 4}
    assert entry["epsilon"]["a"] == pytest.approx(
        compute_epsilon(0.01, 1.1, 4, 1e-5), abs=1e-6
    )
    assert "epsilon_budget_exhausted" not in entry
    summary = R.privacy_summary(state)
    assert summary["dp"]["enabled"] is True
    assert summary["dp"]["clients"]["a"]["steps"] == 4
    assert summary["dp"]["max_epsilon"] == pytest.approx(
        entry["epsilon"]["a"], abs=1e-9
    )
    assert summary["secagg"]["enabled"] is False


def test_dp_budget_exhaustion_finishes_loudly():
    # Budget sits strictly between the 1-round and 2-round spends (eps
    # grows sublinearly at small step counts — a multiplier would miss).
    eps_r1 = compute_epsilon(0.01, 1.1, 4, 1e-5)
    eps_r2 = compute_epsilon(0.01, 1.1, 8, 1e-5)
    cfg = _dp_cfg(dp_epsilon_budget=(eps_r1 + eps_r2) / 2.0)
    state = _enroll(cfg, ["a", "b"])
    state = _run_dp_round(state, 2.0)
    assert state.phase == R.PHASE_RUNNING  # one round spent, budget not hit
    state = _run_dp_round(state, 3.0)
    assert state.phase == R.PHASE_FINISHED  # budget breached before max_rounds
    assert state.history[-1]["epsilon_budget_exhausted"] is True
    assert state.current_round <= cfg.max_rounds


def test_privacy_maps_survive_statefile_round_trip():
    """Mid-round kill-restart: seeds, roster and the accountant's step
    counts are statefile-persisted; epsilon is RECOMPUTED from the
    restored steps, never stored — so a restart cannot fork the spend."""
    from fedcrack_tpu.ckpt.statefile import (
        server_state_from_bytes,
        server_state_to_bytes,
    )

    cfg = _dp_cfg()
    state = _enroll(cfg, ["a", "b"])
    state = _run_dp_round(state, 2.0)
    state = state._replace(
        secagg_seeds={"a": 123, "b": 456},
        secagg_roster={"a": 123, "b": 456},
    )
    blob = server_state_to_bytes(state)
    restored = server_state_from_bytes(blob, cfg)
    assert dict(restored.privacy_steps) == {"a": 4, "b": 4}
    assert dict(restored.secagg_seeds) == {"a": 123, "b": 456}
    assert dict(restored.secagg_roster) == {"a": 123, "b": 456}
    assert R._epsilons_for(cfg, restored.privacy_steps) == R._epsilons_for(
        cfg, state.privacy_steps
    )
    # Byte-stable: re-serializing the restored state is identical.
    assert server_state_to_bytes(restored) == blob


def test_buffered_flush_charges_epsilon_and_respects_budget():
    cfg = FedConfig(
        mode="buffered", buffer_k=2, cohort_size=2, max_rounds=5,
        registration_window_s=1.0, dp_clip_norm=1.0,
        dp_noise_multiplier=1.1, dp_sample_rate=0.01,
        dp_steps_per_round=3, dp_delta=1e-5,
    )

    def run(cfg):
        state = _enroll(cfg, ["a", "b"], with_seeds=False)
        for n in ("a", "b"):
            state, _ = R.transition(state, R.PullWeights(cname=n, now=1.5))
        blob = tree_to_bytes({"w": np.full(6, 0.5, np.float32)})
        rnd = state.current_round
        for n in ("a", "b"):
            state, _ = R.transition(
                state,
                R.TrainDone(cname=n, blob=blob, num_samples=10, round=rnd, now=2.0),
            )
        return state

    state = run(cfg)
    entry = state.history[-1]
    assert dict(state.privacy_steps) == {"a": 3, "b": 3}
    assert entry["epsilon"]["a"] == pytest.approx(
        compute_epsilon(0.01, 1.1, 3, 1e-5), abs=1e-6
    )
    tight = dataclasses.replace(
        cfg, dp_epsilon_budget=entry["epsilon"]["a"] * 0.5
    )
    state2 = run(tight)
    assert state2.phase == R.PHASE_FINISHED
    assert state2.history[-1]["epsilon_budget_exhausted"] is True


# ---- the mesh twin's null-build pin ----


def test_mesh_dp_off_build_is_the_null_twin():
    """dp_clip_norm=0 must be byte-identical to a build that never heard
    of DP — the r12 codec-twin discipline: the off program IS the old
    program, pinned by running both over the same data."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import make_mesh, run_mesh_federation
    from fedcrack_tpu.parallel.fedavg_mesh import (
        build_federated_round,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4),
    )
    steps, batch = 1, 2
    mesh = make_mesh(1, 1)
    init = create_train_state(jax.random.key(0), tiny).variables

    def data_fn(r):
        images, masks = stack_client_data(
            [synth_crack_batch(steps * batch, img_size=16, seed=r)],
            steps, batch,
        )
        return (
            images, masks, np.ones(1, np.float32),
            np.full(1, float(steps * batch), np.float32),
        )

    legacy = build_federated_round(mesh, tiny, learning_rate=1e-3, local_epochs=1)
    dp_off = build_federated_round(
        mesh, tiny, learning_rate=1e-3, local_epochs=1,
        dp_clip_norm=0.0, dp_noise_multiplier=0.0, dp_seed=99,
    )
    assert legacy.dp == "null" and dp_off.dp == "null"
    v_legacy, _ = run_mesh_federation(legacy, init, data_fn, 1, mesh)
    v_off, _ = run_mesh_federation(dp_off, init, data_fn, 1, mesh)
    for a, b in zip(
        jax.tree_util.tree_leaves(v_legacy), jax.tree_util.tree_leaves(v_off)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The on build is its own program, tagged so the driver knows.
    dp_on = build_federated_round(
        mesh, tiny, learning_rate=1e-3, local_epochs=1,
        dp_clip_norm=1.0, dp_noise_multiplier=1.1, dp_seed=42,
    )
    assert dp_on.dp == "dpsgd"


# ---- the real-gRPC drills (tools/chaos_drill) ----


def test_secagg_dropout_drill_over_real_grpc():
    """The acceptance drill: three maskers over a real gRPC server, one
    killed by a chaos-plan SECAGG_DROPOUT after its masks are committed to
    the roster; the round still closes, the dropped pad is recovered from
    the enroll seeds, and the unmasked global equals the SURVIVORS'
    plaintext fixed-point mean bit-for-bit with zero torn rounds."""
    from fedcrack_tpu.tools.chaos_drill import run_secagg_dropout_drill

    out = run_secagg_dropout_drill()
    assert out["fault_fired"] is True
    assert out["dropper_crashed"] is True
    assert out["survivors_completed"] is True
    assert out["round_closed"] is True
    assert out["maskers"] == ["a", "b"]
    assert out["recovered"] == ["c"]
    assert out["dropout_recovered"] is True
    assert out["exact_average_bit_for_bit"] is True
    assert out["torn_rounds"] == 0


@pytest.mark.slow
def test_dp_replay_drill_bit_identical():
    """Chaos-retried DP rounds never double-draw noise: the injected
    device failure forces a retry whose trajectory is bit-identical to an
    uninterrupted run (the noise key chain restores with codec_state)."""
    from fedcrack_tpu.tools.chaos_drill import run_dp_replay_drill

    out = run_dp_replay_drill()
    assert out["fault_fired"] is True
    assert out["retries_round_0"] >= 1
    assert out["replay_bit_identical"] is True
