"""Pallas fused BCE+stats kernel: numerics parity vs the XLA reference.

Runs the kernel under the Pallas interpreter (the suite is on the virtual
CPU mesh; the compiled path exercises the identical kernel body on real TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.ops.losses import segmentation_metrics
from fedcrack_tpu.ops.pallas_bce import (
    bce_sums,
    default_impl,
    fused_segmentation_metrics,
)


def _data(n_elems: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.normal(0, 2, (n_elems,)).astype(np.float32))
    masks = jnp.asarray((rng.uniform(size=(n_elems,)) > 0.7).astype(np.float32))
    return logits, masks


@pytest.mark.parametrize(
    "n", [1, 100, 128, 32768, 32769, 100_000]
)  # below/at/above one 256x128 block, plus ragged tails
def test_sums_parity_interpret_vs_jnp(n):
    logits, masks = _data(n, seed=n % 97)
    ref = bce_sums(logits, masks, "jnp")
    ker = bce_sums(logits, masks, "interpret")
    np.testing.assert_allclose(np.asarray(ker), np.asarray(ref), rtol=1e-5, atol=1e-3)


def test_fused_metrics_match_reference_metrics():
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.normal(0, 2, (2, 32, 32, 1)).astype(np.float32))
    masks = jnp.asarray((rng.uniform(size=(2, 32, 32, 1)) > 0.8).astype(np.float32))
    for pw in (None, 4.0):
        ref = segmentation_metrics(logits, masks, pos_weight=pw)
        fused = fused_segmentation_metrics(
            logits, masks, impl="interpret", pos_weight=pw
        )
        for key in ref:
            np.testing.assert_allclose(
                float(fused[key]), float(ref[key]), rtol=1e-5, atol=1e-5,
                err_msg=f"{key} pw={pw}",
            )


def test_gradient_matches_reference():
    logits, masks = _data(4096, seed=11)

    def loss_fused(x):
        return bce_sums(x, masks, "interpret")[0] / x.size

    def loss_ref(x):
        import optax

        return jnp.mean(optax.sigmoid_binary_cross_entropy(x, masks))

    g_fused = jax.grad(loss_fused)(logits)
    g_ref = jax.grad(loss_ref)(logits)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_ref), rtol=1e-5, atol=1e-6
    )


def test_label_gradient_is_correct():
    logits, masks = _data(512, seed=5)

    def loss_fused(y):
        return bce_sums(logits, y, "interpret")[0]

    def loss_ref(y):
        import optax

        return jnp.sum(optax.sigmoid_binary_cross_entropy(logits, y))

    g_fused = jax.grad(loss_fused)(masks)
    g_ref = jax.grad(loss_ref)(masks)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_ref), rtol=1e-5, atol=1e-4
    )


def test_pos_weight_loss_matches_weighted_bce():
    """pos_weight composes from the kernel's pos_bce_sum lane: the loss must
    equal mean((1 + (pw-1)*y) * bce) exactly, and pw=1 must be plain BCE."""
    import optax

    logits, masks = _data(4096, seed=23)
    pw = 3.0
    w = 1.0 + (pw - 1.0) * masks
    ref_loss = jnp.mean(w * optax.sigmoid_binary_cross_entropy(logits, masks))
    for impl in ("interpret", "jnp"):
        fused = fused_segmentation_metrics(logits, masks, impl=impl, pos_weight=pw)
        np.testing.assert_allclose(float(fused["loss"]), float(ref_loss), rtol=1e-5)
        one = fused_segmentation_metrics(logits, masks, impl=impl, pos_weight=1.0)
        plain = fused_segmentation_metrics(logits, masks, impl=impl)
        np.testing.assert_allclose(float(one["loss"]), float(plain["loss"]), rtol=1e-6)
        # counts are weight-independent
        assert float(fused["iou_inter"]) == float(plain["iou_inter"])


def test_pos_weight_gradient_matches_reference():
    import optax

    logits, masks = _data(2048, seed=29)
    pw = jnp.float32(5.0)
    w = 1.0 + (pw - 1.0) * masks

    def loss_fused(x):
        return fused_segmentation_metrics(
            x, masks, impl="interpret", pos_weight=pw
        )["loss"]

    def loss_ref(x):
        return jnp.mean(w * optax.sigmoid_binary_cross_entropy(x, masks))

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_fused)(logits)),
        np.asarray(jax.grad(loss_ref)(logits)),
        rtol=1e-5,
        atol=1e-6,
    )


def test_bfloat16_inputs_accumulate_in_f32():
    logits, masks = _data(8192, seed=7)
    ker = bce_sums(logits.astype(jnp.bfloat16), masks.astype(jnp.bfloat16), "interpret")
    ref = bce_sums(logits, masks, "jnp")
    assert ker.dtype == jnp.float32
    # bf16 quantization of inputs dominates the error budget
    np.testing.assert_allclose(np.asarray(ker), np.asarray(ref), rtol=2e-2, atol=30.0)


def test_default_impl_on_cpu_is_jnp():
    assert default_impl() == "jnp"  # suite runs on the CPU mesh


def test_under_shard_map():
    """The kernel runs inside the mesh round's shard_map (fedavg_mesh.py).

    The Pallas *interpreter* does not propagate vma onto kernel-internal
    constants (iota/literals), so check_vma is disabled here — the compiled
    TPU path propagates vma via the out_shape annotation (pallas_bce.py) and
    runs under the mesh round's default-checked shard_map in bench.py."""
    from jax.sharding import Mesh, PartitionSpec as P
    from functools import partial

    from fedcrack_tpu.jaxcompat import shard_map as _shard_map

    shard_map = partial(_shard_map, check_vma=False)

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("clients",))
    logits, masks = _data(4 * 1024, seed=17)
    logits = logits.reshape(4, 1024)
    masks = masks.reshape(4, 1024)

    def per_client(x, y):
        return bce_sums(x[0], y[0], "interpret")[None]

    fn = jax.jit(
        shard_map(
            per_client,
            mesh=mesh,
            in_specs=(P("clients"), P("clients")),
            out_specs=P("clients"),
        )
    )
    out = np.asarray(fn(logits, masks))
    for c in range(4):
        ref = np.asarray(bce_sums(logits[c], masks[c], "jnp"))
        np.testing.assert_allclose(out[c], ref, rtol=1e-5, atol=1e-3)


def test_jit_and_under_vmap():
    logits, masks = _data(2048, seed=13)
    jitted = jax.jit(lambda x, y: bce_sums(x, y, "interpret"))
    np.testing.assert_allclose(
        np.asarray(jitted(logits, masks)),
        np.asarray(bce_sums(logits, masks, "jnp")),
        rtol=1e-5,
        atol=1e-3,
    )
