"""Elastic serve fleet (round 22): SLO-driven autoscaler + shadow-replica
progressive delivery.

The load-bearing claims, each pinned here:

- the ``ServeConfig`` band validates as a unit: ``max_replicas`` without
  ``min_replicas`` is a DISARMED ceiling and refuses to construct, and the
  static ``replicas`` must sit inside an armed band;
- the autoscaler consumes the registry's OWN Prometheus exposition (the
  r15 parser over the r16 watchdog idiom) and takes at most one action per
  evaluation: queue pressure scales up, cooldown blocks immediately after,
  and only ``scale_down_idle_evals`` consecutive calm evaluations drain a
  replica (hysteresis — a gust cannot flap the fleet);
- ``ServeFleet.add_replica`` grows the fleet OFF the serving path: the new
  replica's weights slot is committed before the router sees it, and a
  fleet-wide install after a grow is still torn-version-free;
- scale-down drains through the r17 ``kill_replica`` reroute: queued
  requests on the drained replica complete on survivors with their
  ORIGINAL futures — zero accepted requests drop;
- shedding stays the loud backstop, not the steady state: a static fleet
  against a tight queue bound sheds a paced burst, the SAME burst against
  the SAME bound with the autoscaler armed completes shed-free because
  capacity arrives first;
- the shadow lane has NO wire path to clients: while a candidate with
  different weights is staged under live traffic, every production answer
  still carries the production version, and the router's replica set never
  contains the shadow;
- promote is the r17 two-phase commit (candidate == production → IoU 1.0,
  PSI 0 → installed fleet-wide); a degraded candidate rolls back (IoU
  cliff + PSI blowout, never installed, remembered so the poll loop will
  not re-stage it);
- the new chaos fault kinds are registered, and load_gen's --metrics-url
  sampler reports a replica gauge that actually varied.
"""

import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serve

TINY_KW = dict(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
BUCKETS = (16,)


def _serve_config(**over):
    from fedcrack_tpu.configs import ServeConfig

    kw = dict(
        bucket_sizes=BUCKETS, max_batch=4, max_delay_ms=10.0, tile_overlap=4
    )
    kw.update(over)
    return ServeConfig(**kw)


@pytest.fixture(scope="module")
def stack():
    """One shared compiled engine and two weight versions — the bucket
    compile dominates; every test takes fresh fleets over the same engine."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import InferenceEngine

    model_config = ModelConfig(**TINY_KW)
    engine = InferenceEngine(model_config, _serve_config())
    var0 = init_variables(jax.random.key(0), model_config)
    var1 = init_variables(jax.random.key(1), model_config)
    return model_config, engine, var0, var1


def _fleet(stack, *, chaos=None, **cfg_over):
    from fedcrack_tpu.serve import ServeFleet

    model_config, engine, var0, _ = stack
    cfg = _serve_config(**cfg_over)
    return ServeFleet(
        model_config, cfg, var0, shared_engine=engine, chaos=chaos, warmup=False
    )


def _img(size=16, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (size, size, 3), dtype=np.uint8)


class _SlowBatches:
    """Stretch every dispatch so backlogs provably exist when a drain or a
    shed race needs one (the r17 drill idiom)."""

    def __init__(self, delay_s=0.05):
        self.delay_s = delay_s

    def on_batch(self, bucket, batch_index, attempt):
        time.sleep(self.delay_s)


def _parsed(live, p95_s, queued_by_bucket):
    """A synthetic parse_prometheus_text result — the autoscaler's unit
    harness (the production path parses the registry's own exposition)."""
    return {
        "serve_fleet_replicas": {
            "type": "gauge", "help": "", "samples": {(): float(live)}
        },
        "serve_rolling_p95_seconds": {
            "type": "gauge", "help": "", "samples": {(): float(p95_s)}
        },
        "serve_router_queue_depth_total": {
            "type": "gauge",
            "help": "",
            "samples": {
                (("bucket", str(b)),): float(n)
                for b, n in queued_by_bucket.items()
            },
        },
    }


# ---- config band validation ----


def test_serve_config_elastic_validation():
    from fedcrack_tpu.configs import ServeConfig

    _serve_config(replicas=2, min_replicas=1, max_replicas=4)
    # max without min is a disarmed ceiling: loudly refused, never ignored.
    with pytest.raises(ValueError):
        _serve_config(max_replicas=4)
    with pytest.raises(ValueError):
        _serve_config(min_replicas=3, max_replicas=2, replicas=3)
    # The static size must sit inside an armed band.
    with pytest.raises(ValueError):
        _serve_config(replicas=5, min_replicas=1, max_replicas=4)
    with pytest.raises(ValueError):
        _serve_config(min_replicas=-1)
    for bad in (
        dict(scale_interval_s=0.0),
        dict(scale_cooldown_s=-1.0),
        dict(scale_up_queue_depth=0),
        dict(scale_up_p95_frac=0.0),
        dict(scale_up_p95_frac=1.5),
        dict(scale_down_idle_evals=0),
        dict(shadow_fraction=-0.1),
        dict(shadow_fraction=1.5),
        dict(shadow_min_samples=0),
        dict(shadow_iou_floor=0.0),
        dict(shadow_iou_floor=1.5),
        dict(shadow_psi_ceiling=0.0),
        dict(shadow_latency_factor=0.5),
    ):
        with pytest.raises(ValueError):
            _serve_config(**bad)
    # Defaults stay disarmed: a pre-r22 config constructs unchanged.
    assert ServeConfig().min_replicas == 0 and ServeConfig().shadow_fraction == 0.0


def test_c18_preset_round_trips():
    from fedcrack_tpu.configs import FedConfig

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "configs", "c18_elastic_fleet.json")) as f:
        fed = FedConfig.from_json(f.read())
    assert fed.serve.min_replicas == 1 and fed.serve.max_replicas == 6
    assert fed.serve.min_replicas <= fed.serve.replicas <= fed.serve.max_replicas
    assert 0.0 < fed.serve.shadow_fraction <= 1.0
    assert FedConfig.from_json(fed.to_json()) == fed


# ---- autoscaler control law ----


def test_autoscaler_requires_armed_band(stack):
    from fedcrack_tpu.serve import FleetAutoscaler

    fleet = _fleet(stack, replicas=1)
    try:
        with pytest.raises(ValueError):
            FleetAutoscaler(fleet)
    finally:
        fleet.close()


def test_autoscaler_scale_up_cooldown_and_calm_scale_down(stack):
    from fedcrack_tpu.serve import FleetAutoscaler
    from fedcrack_tpu.serve.autoscaler import SCALE_DOWN, SCALE_UP

    fleet = _fleet(
        stack,
        replicas=1,
        min_replicas=1,
        max_replicas=3,
        scale_cooldown_s=10.0,
        scale_up_queue_depth=4,
        scale_down_idle_evals=3,
        slo_p95_ms=200.0,
    )
    now = [1000.0]
    auto = FleetAutoscaler(fleet, clock=lambda: now[0])
    try:
        pressure = _parsed(1, 0.0, {16: 5})
        calm = _parsed(2, 0.0, {16: 0})

        d = auto.evaluate(pressure)
        assert d["action"] == SCALE_UP and d["replica"] == 1
        assert len([r for r in fleet.router.replicas if r.alive]) == 2
        # Cooldown: the identical pressure signal takes NO action.
        now[0] += 1.0
        assert auto.evaluate(pressure)["reason"] == "cooldown"
        assert len([r for r in fleet.router.replicas if r.alive]) == 2
        # p95 trigger (scale_up_p95_frac x SLO) fires without queue depth.
        now[0] += 10.0
        d = auto.evaluate(_parsed(2, 0.190, {16: 0}))
        assert d["action"] == SCALE_UP and "p95" in d["reason"]
        # Calm must hold for scale_down_idle_evals consecutive evaluations;
        # one gust in between resets the counter (hysteresis).
        now[0] += 10.0
        live3 = _parsed(3, 0.0, {16: 0})
        assert auto.evaluate(live3)["action"] is None
        assert auto.evaluate(_parsed(3, 0.0, {16: 2}))["action"] is None  # gust
        assert auto.evaluate(live3)["action"] is None
        assert auto.evaluate(live3)["action"] is None
        d = auto.evaluate(live3)
        assert d["action"] == SCALE_DOWN
        # The newest replica drains first; replica 0 never does.
        assert d["replica"] == 2 and fleet.router.replicas[0].alive
        audit = auto.audit()
        assert audit["scale_ups"] == 2 and audit["scale_downs"] == 1
        assert audit["replica_seconds"] > 0
    finally:
        auto.stop()
        fleet.close()


def test_autoscaler_at_max_never_grows(stack):
    from fedcrack_tpu.serve import FleetAutoscaler

    fleet = _fleet(stack, replicas=2, min_replicas=1, max_replicas=2)
    auto = FleetAutoscaler(fleet, clock=lambda: 0.0)
    try:
        d = auto.evaluate(_parsed(2, 0.0, {16: 99}))
        assert d["action"] is None and "at max_replicas" in d["reason"]
        assert len(fleet.router.replicas) == 2
    finally:
        fleet.close()


def test_autoscaler_reads_own_exposition(stack):
    """The production signal path: refresh_gauges -> registry exposition ->
    r15 parser -> the exact live/p95/queued triple."""
    from fedcrack_tpu.serve import FleetAutoscaler

    fleet = _fleet(stack, replicas=2, min_replicas=1, max_replicas=2)
    auto = FleetAutoscaler(fleet)
    try:
        sig = auto.read_signals()
        assert sig["live"] == 2 and sig["queued"] == 0
        assert sig["p95_ms"] >= 0.0
    finally:
        fleet.close()


# ---- fleet growth + drain ----


def test_add_replica_commits_slot_before_router_and_swap_stays_zero_torn(stack):
    _, _, _, var1 = stack
    fleet = _fleet(stack, replicas=1)
    try:
        replica = fleet.add_replica(warm=False)
        assert replica.index == 1 and len(fleet.router.replicas) == 2
        # The weights slot committed with the grow: version matches prod.
        v, payload = fleet.manager.snapshot_for(1)
        assert v == 0 and payload is not None
        results = [fleet.submit(_img()).result(timeout=60) for _ in range(8)]
        assert {r.model_version for r in results} == {0}
        # A fleet-wide install AFTER the grow covers the new replica too.
        fleet.install(1, var1)
        results = [fleet.submit(_img()).result(timeout=60) for _ in range(8)]
        assert {r.model_version for r in results} == {1}
    finally:
        fleet.close()


def test_scale_down_zero_accepted_drops(stack):
    """The drain pin: a backlogged replica leaves through the kill_replica
    reroute — every accepted future completes on a survivor."""
    fleet = _fleet(stack, replicas=2, chaos=_SlowBatches(0.05))
    try:
        futures = [fleet.submit(_img(seed=i)) for i in range(16)]
        reroute = fleet.remove_replica(1)
        results = [f.result(timeout=60) for f in futures]
        assert len(results) == 16  # zero drops, zero exceptions
        assert sum(1 for r in fleet.router.replicas if r.alive) == 1
        assert reroute["rerouted"] >= 0
    finally:
        fleet.close()


def test_shed_is_backstop_static_sheds_autoscaled_does_not(stack):
    """The diurnal pin, compressed: the same paced burst against the same
    tight queue bound — the static single-replica fleet sheds loudly, the
    autoscaled fleet grows first and completes everything."""
    from fedcrack_tpu.serve import FleetAutoscaler
    from fedcrack_tpu.serve.router import LoadShedError

    def paced_burst(fleet, n=40, gap_s=0.01):
        sheds, futures = 0, []
        for i in range(n):
            try:
                futures.append(fleet.submit(_img(seed=i)))
            except LoadShedError:
                sheds += 1
            time.sleep(gap_s)
        results = [f.result(timeout=60) for f in futures]
        return sheds, len(results)

    static = _fleet(stack, replicas=1, queue_bound=6, chaos=_SlowBatches(0.06))
    try:
        static_sheds, static_done = paced_burst(static)
    finally:
        static.close()
    assert static_sheds > 0  # the backstop fired, loudly
    assert static_done == 40 - static_sheds  # and dropped nothing accepted

    elastic = _fleet(
        stack,
        replicas=1,
        min_replicas=1,
        max_replicas=3,
        queue_bound=6,
        scale_interval_s=0.01,
        scale_cooldown_s=0.05,
        scale_up_queue_depth=2,
        chaos=_SlowBatches(0.06),
    )
    auto = FleetAutoscaler(elastic)
    auto.start()
    try:
        elastic_sheds, elastic_done = paced_burst(elastic)
    finally:
        auto.stop()
        elastic.close()
    assert elastic_sheds == 0 and elastic_done == 40
    assert auto.audit()["scale_ups"] >= 1  # capacity arrived before the bound


# ---- shadow delivery ----


def test_shadow_isolation_no_wire_path_to_clients(stack):
    """While a DIFFERENT-weights candidate is staged under live traffic,
    every production answer still carries the production version, and the
    shadow lane never appears in the router's replica set."""
    from fedcrack_tpu.serve import ShadowController

    _, _, _, var1 = stack
    fleet = _fleet(stack, replicas=1, shadow_fraction=1.0, shadow_min_samples=2)
    ctrl = ShadowController(fleet)
    versions, errors = [], []
    stop = threading.Event()

    def pump():
        i = 0
        while not stop.is_set():
            try:
                versions.append(fleet.submit(_img(seed=i)).result(timeout=30).model_version)
            except Exception as e:  # pragma: no cover - failure is the assert
                errors.append(e)
            i += 1

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        record = ctrl.stage(7, var1, wait_s=10.0)
    finally:
        stop.set()
        t.join(timeout=10)
        fleet.close()
    assert not errors
    assert versions and set(versions) == {0}  # candidate never reached a client
    assert len(fleet.router.replicas) == 1  # shadow is not a replica
    assert fleet.router._shadow is None  # lane torn down with the verdict
    assert record["completed"] >= 1  # mirrored traffic DID reach the shadow


def test_shadow_promote_installs_fleet_wide(stack):
    from fedcrack_tpu.serve import ShadowController

    _, _, var0, _ = stack
    fleet = _fleet(stack, replicas=2, shadow_fraction=1.0, shadow_min_samples=2)
    ctrl = ShadowController(fleet)
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            fleet.submit(_img()).result(timeout=30)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        # A re-publish of the production weights: indistinguishable by
        # construction — IoU 1.0, PSI 0 — the promote path.
        record = ctrl.stage(1, var0, wait_s=10.0)
    finally:
        stop.set()
        t.join(timeout=10)
    try:
        assert record["verdict"] == "promote" and record["installed"]
        assert record["reasons"] == [] and record["iou"] == 1.0
        assert fleet.manager.version == 1
        res = fleet.submit(_img()).result(timeout=30)
        assert res.model_version == 1
        assert ctrl.audit()["promoted"] == 1
    finally:
        fleet.close()


def test_shadow_rollback_never_installs_and_is_remembered(stack):
    import jax

    from fedcrack_tpu.serve import ShadowController

    _, _, var0, _ = stack
    fleet = _fleet(stack, replicas=1, shadow_fraction=1.0, shadow_min_samples=2)
    ctrl = ShadowController(fleet)
    v_bad = jax.tree_util.tree_map(lambda x: x * 0, var0)
    record = ctrl.stage(5, v_bad, wait_s=0.2)
    try:
        assert record["verdict"] == "rollback" and not record["installed"]
        # The deciding deltas are IN the record: IoU cliff + PSI blowout.
        assert record["iou"] < ctrl.cfg.shadow_iou_floor
        assert record["psi_max"] > ctrl.cfg.shadow_psi_ceiling
        assert any("iou" in r for r in record["reasons"])
        assert fleet.manager.version == 0  # production untouched
        assert 5 in ctrl._rejected  # the poll loop will never re-stage it
        assert ctrl.audit()["rolled_back"] == 1
    finally:
        fleet.close()


def test_shadow_mirror_sampling_stride_and_failure_containment(stack):
    from fedcrack_tpu.serve.batcher import MicroBatcher, StaticWeights
    from fedcrack_tpu.serve.shadow import ShadowMirror

    _, engine, var0, _ = stack
    payload = engine.prepare(var0)
    batcher = MicroBatcher(engine, StaticWeights(payload, 3))
    mirror = ShadowMirror(batcher, 0.25)
    assert mirror.stride == 4
    try:
        for i in range(8):
            mirror.observe(_img(seed=i))
        snap = mirror.snapshot()
        assert snap["seen"] == 8 and snap["mirrored"] == 2
    finally:
        batcher.close()
    # A dead shadow lane: observe swallows, failures counted, nothing raises.
    dead = ShadowMirror(batcher, 1.0)
    dead.observe(_img())
    dead.observe(_img())
    assert dead.snapshot()["failures"] == 2


# ---- chaos kinds + satellites ----


def test_elastic_chaos_kinds_registered():
    from fedcrack_tpu.chaos.plan import (
        ALL_KINDS,
        FLEET_KINDS,
        REPLICA_CRASH_DURING_SCALE,
        SERVE_REPLICA_CRASH,
        SHADOW_REPLICA_CRASH,
        Fault,
        FaultPlan,
    )

    assert {
        SERVE_REPLICA_CRASH, REPLICA_CRASH_DURING_SCALE, SHADOW_REPLICA_CRASH
    } <= FLEET_KINDS <= ALL_KINDS
    plan = FaultPlan(
        [
            Fault(kind=REPLICA_CRASH_DURING_SCALE, round=1),
            Fault(kind=SHADOW_REPLICA_CRASH, round=0),
        ]
    )
    assert plan.take(REPLICA_CRASH_DURING_SCALE, round=1) is not None
    assert plan.take(REPLICA_CRASH_DURING_SCALE, round=1) is None  # one-shot
    with pytest.raises(ValueError):
        Fault(kind="replica_crash_during_scalee", round=0)


def test_metrics_sampler_reports_replica_variation():
    from fedcrack_tpu.obs.promexp import MetricsExporter
    from fedcrack_tpu.obs.registry import REGISTRY
    from fedcrack_tpu.tools.load_gen import _MetricsSampler

    gauge = REGISTRY.gauge("serve_fleet_replicas", "")
    exporter = MetricsExporter(REGISTRY)
    url = f"http://127.0.0.1:{exporter.start()}/metrics"
    try:
        sampler = _MetricsSampler(url, interval_s=0.05)
        gauge.set(1)
        sampler.sample_once()
        gauge.set(3)
        sampler.sample_once()
        summary = sampler.summary()
        assert summary["replicas_min"] == 1 and summary["replicas_max"] == 3
        assert summary["replicas_varied"] and summary["scrape_errors"] == 0
    finally:
        exporter.stop()
    with pytest.raises(ValueError):
        _MetricsSampler(url, interval_s=0.0)


def test_router_gauges_refresh_for_the_scraper(stack):
    from fedcrack_tpu.obs.promexp import parse_prometheus_text
    from fedcrack_tpu.obs.registry import REGISTRY

    fleet = _fleet(stack, replicas=2)
    try:
        out = fleet.router.refresh_gauges()
        assert out["p95_s"] >= 0.0 and out["queue_depth"].get(16) == 0
        parsed = parse_prometheus_text(REGISTRY.exposition())
        fam = parsed["serve_router_queue_depth_total"]
        assert (("bucket", "16"),) in fam["samples"]
    finally:
        fleet.close()
