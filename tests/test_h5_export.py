"""Flax -> Keras h5 exporter: the two-way door must actually open.

Two oracles: (1) export -> h5_import round-trips bit-exactly through our own
reader; (2) REAL Keras loads the exported file via ``load_weights`` and its
forward pass matches the Flax model — the workflow a reference user runs
(test/Segmentation2.py:94 loads a checkpoint for inference)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models import ResUNet
from fedcrack_tpu.models.resunet import init_variables
from fedcrack_tpu.tools.h5_export import export_resunet_h5
from fedcrack_tpu.tools.h5_import import import_resunet_h5

TINY = ModelConfig(
    img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)


def _random_variables(seed: int = 0) -> dict:
    """Random params AND batch_stats so the export exercises both trees."""
    variables = init_variables(jax.random.key(seed), TINY)
    rng = np.random.RandomState(seed)

    def perturb(x):
        arr = np.asarray(x, np.float32)
        return rng.normal(0.1, 0.4, arr.shape).astype(np.float32)

    out = jax.tree_util.tree_map(perturb, variables)
    # moving variance must stay positive
    out["batch_stats"] = jax.tree_util.tree_map(
        lambda x: np.abs(x) + 0.25, out["batch_stats"]
    )
    return out


def test_export_import_round_trip_exact(tmp_path):
    variables = _random_variables()
    path = str(tmp_path / "export.h5")
    export_resunet_h5(variables, path, TINY)
    back = import_resunet_h5(path, TINY)
    want = jax.tree_util.tree_leaves_with_path(variables)
    got = dict(jax.tree_util.tree_leaves_with_path(back))
    assert len(want) == len(got)
    for key, w in want:
        np.testing.assert_array_equal(
            np.asarray(got[key]), np.asarray(w), err_msg=jax.tree_util.keystr(key)
        )


def test_real_keras_loads_export_with_forward_parity(tmp_path):
    tf = pytest.importorskip("tensorflow")
    from test_h5_import import build_keras_resunet

    variables = _random_variables(3)
    path = str(tmp_path / "export.h5")
    export_resunet_h5(variables, path, TINY)

    model = build_keras_resunet(TINY)
    model.load_weights(path)

    rng = np.random.RandomState(11)
    images = rng.uniform(0, 1, (2, *TINY.input_shape)).astype(np.float32)
    y_keras = model.predict(images, verbose=0)
    logits = ResUNet(config=TINY).apply(variables, jnp.asarray(images), train=False)
    y_flax = np.asarray(jax.nn.sigmoid(logits))
    np.testing.assert_allclose(y_flax, y_keras, atol=2e-5, rtol=1e-4)


def test_export_rejects_config_model_mismatch(tmp_path):
    """A config declaring fewer blocks than the weights hold must raise, not
    write a well-formed h5 with blocks silently missing."""
    variables = _random_variables()
    smaller = ModelConfig(
        img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8,)
    )
    with pytest.raises(ValueError, match="unconsumed"):
        export_resunet_h5(variables, str(tmp_path / "x.h5"), smaller)


def test_cli_round_trip(tmp_path):
    """msgpack -> h5 via the CLI entry point, then back through the importer."""
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.tools.h5_export import main

    variables = _random_variables(5)
    mp = tmp_path / "model.msgpack"
    mp.write_bytes(tree_to_bytes(variables))
    out = tmp_path / "model.h5"
    # TINY is not the default 128px config: exercise --config plumbing via a
    # FedConfig file carrying the model section.
    from fedcrack_tpu.configs import DataConfig, FedConfig

    cfg = FedConfig(model=TINY, data=DataConfig(img_size=TINY.img_size))
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(cfg.to_json())
    assert main([str(mp), str(out), "--config", str(cfg_path)]) == 0
    back = import_resunet_h5(str(out), TINY)
    leaf = jax.tree_util.tree_leaves(back["params"])[0]
    want = jax.tree_util.tree_leaves(variables["params"])[0]
    np.testing.assert_array_equal(np.asarray(leaf), np.asarray(want))
