"""Epoch-segmented round execution (round 7).

The non-negotiable gate: the segmented round — K device-resident-carry
segment programs threaded by a host loop — must be BYTE-identical to the
monolithic one-program round on the same inputs (same carry, same op
order), for any K dividing local_epochs and any step-axis chunking of the
staged data. Everything else (streamed staging, donation, the 2-epoch-slab
HBM bound, checkpoint resume) is pinned on top of that.
"""

import jax
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.pipeline import split_epoch_slab
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.parallel import (
    SegmentedRound,
    build_federated_round,
    build_federated_round_segments,
    make_mesh,
    run_mesh_federation,
    stack_client_data,
)
from fedcrack_tpu.train.local import create_train_state

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
STEPS, BATCH, N_CLIENTS = 2, 4, 2
EPOCHS = 10  # the reference's local fit depth — K in {1, 2, 10} divides it


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N_CLIENTS, 1)


@pytest.fixture(scope="module")
def data():
    per_client = [
        synth_crack_batch(STEPS * BATCH, img_size=TINY.img_size, seed=i)
        for i in range(N_CLIENTS)
    ]
    images, masks = stack_client_data(per_client, STEPS, BATCH)
    active = np.ones(N_CLIENTS, np.float32)
    n_samples = np.full(N_CLIENTS, float(STEPS * BATCH), np.float32)
    return images, masks, active, n_samples


@pytest.fixture(scope="module")
def variables():
    return create_train_state(jax.random.key(0), TINY).variables


@pytest.fixture(scope="module")
def monolithic_result(mesh, data, variables):
    round_fn = build_federated_round(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS
    )
    new_vars, metrics = round_fn(variables, *data)
    return (
        jax.tree_util.tree_map(np.asarray, new_vars),
        jax.tree_util.tree_map(np.asarray, metrics),
    )


def _assert_trees_bytes_equal(got, want):
    gl = jax.tree_util.tree_leaves_with_path(got)
    wl = jax.tree_util.tree_leaves(want)
    assert len(gl) == len(wl)
    for (path, g), w in zip(gl, wl):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=jax.tree_util.keystr(path)
        )


# K=10 (the flagship one-segment-per-epoch configuration) stays tier-1;
# K=1 (isolates the program-boundary carry round-trip) and K=2 are
# slow-marked — each K is a fresh set of XLA compiles, and on this 2-core
# host with 8 spin-waiting virtual devices the tier-1 wall-clock budget is
# the binding constraint (ROADMAP tier-1 command's 870 s timeout).
@pytest.mark.parametrize(
    "segments",
    [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        10,
    ],
)
def test_segmented_round_byte_identical(
    mesh, data, variables, monolithic_result, segments
):
    """Post-Adam global weights AND metrics from the segmented round match
    the monolithic round byte for byte, on the 8-device CPU mesh, for K in
    {1, 2, 10}. K=1 isolates the program-boundary carry round-trip; K=10
    is the flagship one-segment-per-epoch configuration."""
    seg = build_federated_round_segments(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=segments
    )
    assert isinstance(seg, SegmentedRound)
    assert seg.n_segments == segments
    assert seg.segment_epochs == EPOCHS // segments
    new_vars, metrics = seg(variables, *data)
    want_vars, want_metrics = monolithic_result
    _assert_trees_bytes_equal(new_vars, want_vars)
    _assert_trees_bytes_equal(metrics, want_metrics)


@pytest.mark.slow
def test_segmented_round_chunked_data_byte_identical(
    mesh, data, variables, monolithic_result
):
    """Step-axis chunked staging (what the streaming driver feeds the
    round) changes nothing: consecutive scans with the carry threaded are
    the same step sequence as one scan over the concatenation. Slow-marked
    (the 2-chunk signature is a fresh compile); the chunked path is still
    pinned tier-1 END TO END by the streaming driver test below, whose
    run_mesh_federation stages 2 chunks per round."""
    images, masks, active, n_samples = data
    ic, mc = split_epoch_slab(images, masks, 2)
    assert len(ic) == 2 and sum(c.shape[1] for c in ic) == STEPS
    np.testing.assert_array_equal(np.concatenate(ic, axis=1), images)
    seg = build_federated_round_segments(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=2
    )
    new_vars, metrics = seg(variables, ic, mc, active, n_samples)
    _assert_trees_bytes_equal(new_vars, monolithic_result[0])
    _assert_trees_bytes_equal(metrics, monolithic_result[1])


def test_segments_must_divide_epochs(mesh):
    with pytest.raises(ValueError, match="divis"):
        build_federated_round_segments(mesh, TINY, local_epochs=10, segments=3)


# Tier-1 budget re-balance (round 14): donation is the MECHANISM; its
# user-visible bound — peak staged HBM ≤ 2 slabs — stays tier-1 via the
# max_live_staged_bytes pins in the streaming test below.
@pytest.mark.slow
def test_segment_carry_is_donated(mesh, data, variables, seg_round):
    """The carry buffers of segment k back segment k+1's: the split costs
    zero steady-state HBM over the monolithic scan. jax marks donated
    inputs deleted; this CPU backend (and TPU) honor the donation."""
    images, masks, active, n_samples = data
    seg = seg_round
    carry = seg.init(variables)
    old_leaves = jax.tree_util.tree_leaves(carry)
    carry2, _ = seg.segment(carry, variables, images, masks)
    jax.block_until_ready(jax.tree_util.tree_leaves(carry2)[0])
    deleted = [leaf.is_deleted() for leaf in old_leaves]
    assert all(deleted), (
        f"{deleted.count(False)}/{len(deleted)} carry buffers survived "
        "donation — the segmented path would hold two carries live"
    )


def _fresh_data_fn(seed0=100):
    def data_fn(r):
        per_client = [
            synth_crack_batch(
                STEPS * BATCH, img_size=TINY.img_size, seed=seed0 + 10 * r + i
            )
            for i in range(N_CLIENTS)
        ]
        images, masks = stack_client_data(per_client, STEPS, BATCH)
        active = np.ones(N_CLIENTS, np.float32)
        n_samples = np.full(N_CLIENTS, float(STEPS * BATCH), np.float32)
        return images, masks, active, n_samples

    return data_fn


@pytest.fixture(scope="module")
def seg_round(mesh):
    return build_federated_round_segments(
        mesh, TINY, learning_rate=1e-3, local_epochs=2, segments=2
    )


def test_driver_segmented_streaming_matches_monolithic(mesh, variables, seg_round):
    """run_mesh_federation over a SegmentedRound — chunk-grain streamed
    staging, donated carries, explicit buffer release — returns the same
    weights as the monolithic driver path, records the per-segment host
    timeline, and never holds more than 2 epoch slabs of staged data
    (the previous round's chunks are released at the round barrier while
    the next round's stream in — the double buffer, never a third slab)."""
    mono = build_federated_round(mesh, TINY, learning_rate=1e-3, local_epochs=2)
    v_mono, _ = run_mesh_federation(mono, variables, _fresh_data_fn(), 3, mesh)
    v_stream, rec_stream = run_mesh_federation(
        seg_round, variables, _fresh_data_fn(), 3, mesh
    )
    _assert_trees_bytes_equal(v_stream, v_mono)
    # The per-segment host timeline is recorded, and overlapped rounds
    # carry the next round's chunk transfers inside it.
    for rec in rec_stream:
        assert len(rec.segments) >= 2
        assert all("dispatch_s" in e for e in rec.segments if e["segment"] != "drain")
    staged_in_timeline = sum(
        e.get("staged_bytes", 0) for e in rec_stream[0].segments
    )
    assert staged_in_timeline == rec_stream[1].staged_bytes > 0
    # 2-epoch-slab peak, and the bound is TIGHT on overlapped rounds (two
    # slabs really were live — not trivially satisfied by serial staging).
    slab = rec_stream[0].staged_bytes
    assert slab > 0
    for rec in rec_stream:
        assert 0 < rec.max_live_staged_bytes <= 2 * slab
    assert rec_stream[0].max_live_staged_bytes == 2 * slab


def test_driver_round_overlap_bit_identical(mesh, variables, seg_round):
    """Round-overlap (round 14): pipelining round N+1's first segment
    under round N's aggregation tail is pure host scheduling — weights
    AND metrics byte-identical to the unpipelined schedule, including
    across a data_fn(r)->None buffer-reuse round, with the pipelined
    segment visible in the consuming round's timeline."""

    def reuse_data_fn():
        fresh = _fresh_data_fn()

        def data_fn(r):
            return None if r == 2 else fresh(r)

        return data_fn

    v_plain, rec_plain = run_mesh_federation(
        seg_round, variables, reuse_data_fn(), 3, mesh
    )
    v_pipe, rec_pipe = run_mesh_federation(
        seg_round, variables, reuse_data_fn(), 3, mesh, round_overlap=True
    )
    _assert_trees_bytes_equal(v_pipe, v_plain)
    for rp, rq in zip(rec_plain, rec_pipe):
        _assert_trees_bytes_equal(rq.metrics, rp.metrics)
    # Rounds 1 and 2 consumed a pre-dispatched segment 0.
    assert [e["segment"] for e in rec_pipe[1].segments if e.get("pipelined")] == [0]
    assert [e["segment"] for e in rec_pipe[2].segments if e.get("pipelined")] == [0]
    assert not any(e.get("pipelined") for e in rec_pipe[0].segments)


def test_round_overlap_contract_errors(mesh, variables, seg_round):
    mono = build_federated_round(mesh, TINY, learning_rate=1e-3, local_epochs=2)
    with pytest.raises(ValueError, match="SegmentedRound"):
        run_mesh_federation(
            mono, variables, _fresh_data_fn(), 2, mesh, round_overlap=True
        )
    with pytest.raises(ValueError, match="overlap_staging"):
        run_mesh_federation(
            seg_round, variables, _fresh_data_fn(), 2, mesh,
            round_overlap=True, overlap_staging=False,
        )
    with pytest.raises(ValueError, match="max_round_retries"):
        run_mesh_federation(
            seg_round, variables, _fresh_data_fn(), 2, mesh,
            round_overlap=True, max_round_retries=1,
        )


@pytest.mark.slow
def test_driver_segmented_sequential_and_round_grain_modes(
    mesh, variables, seg_round
):
    """The two non-default staging modes — sequential (overlap_staging
    False) and round-grain (segment_overlap=False) — also reproduce the
    monolithic weights byte for byte. Slow-marked belt-and-suspenders:
    the round-level byte-identity (K in {1,2,10}, chunked data) and the
    default streaming mode are pinned tier-1 above."""
    mono = build_federated_round(mesh, TINY, learning_rate=1e-3, local_epochs=2)
    v_mono, _ = run_mesh_federation(mono, variables, _fresh_data_fn(), 3, mesh)
    v_seq, rec_seq = run_mesh_federation(
        seg_round, variables, _fresh_data_fn(), 3, mesh, overlap_staging=False
    )
    v_coarse, _ = run_mesh_federation(
        seg_round, variables, _fresh_data_fn(), 3, mesh, segment_overlap=False
    )
    _assert_trees_bytes_equal(v_seq, v_mono)
    _assert_trees_bytes_equal(v_coarse, v_mono)
    # Sequential mode charges every round its own staging (boundary fix).
    assert all(r.staging_s > 0.0 for r in rec_seq)


def test_driver_checkpoint_kill_and_resume(tmp_path, mesh, variables, seg_round):
    """VERDICT r5 #7: a federation killed after round r resumes at round
    r+1 with an IDENTICAL trajectory — weights byte-equal to the
    uninterrupted run — via the FedCheckpointer threaded through
    run_mesh_federation (deterministic data_fn, absolute round indices)."""
    orbax = pytest.importorskip("orbax.checkpoint")  # noqa: F841
    from fedcrack_tpu.ckpt.manager import FedCheckpointer

    v_straight, rec_straight = run_mesh_federation(
        seg_round, variables, _fresh_data_fn(), 3, mesh
    )

    # "Kill" after round 2 of 3: run only rounds 0-1 with a checkpointer...
    with FedCheckpointer(tmp_path / "ck") as ck:
        run_mesh_federation(
            seg_round, variables, _fresh_data_fn(), 2, mesh, checkpointer=ck
        )
    # ...then a fresh "process" restores and continues rounds 2..3.
    with FedCheckpointer(tmp_path / "ck") as ck:
        ckpt = ck.restore()
        assert ckpt is not None and ckpt.current_round == 2
        assert len(ckpt.history) == 2
        v_resumed, rec_resumed = run_mesh_federation(
            seg_round,
            ckpt.variables,
            _fresh_data_fn(),
            3,
            mesh,
            checkpointer=ck,
            start_round=ckpt.current_round,
            history=ckpt.history,
        )
        final = ck.restore()
    _assert_trees_bytes_equal(v_resumed, v_straight)
    assert [r.round_idx for r in rec_resumed] == [2]
    for k in rec_straight[2].metrics:
        np.testing.assert_array_equal(
            rec_resumed[0].metrics[k], rec_straight[2].metrics[k]
        )
    # The resumed session's checkpoint carries the FULL 3-round history.
    assert final.current_round == 3
    assert [h["round"] for h in final.history] == [1, 2, 3]


def test_split_epoch_slab_contract():
    images = np.arange(2 * 7 * 3 * 2, dtype=np.uint8).reshape(2, 7, 3, 2)
    masks = np.arange(2 * 7 * 3 * 1, dtype=np.uint8).reshape(2, 7, 3, 1)
    ic, mc = split_epoch_slab(images, masks, 3)
    assert [c.shape[1] for c in ic] == [3, 2, 2]
    np.testing.assert_array_equal(np.concatenate(ic, axis=1), images)
    np.testing.assert_array_equal(np.concatenate(mc, axis=1), masks)
    # n_chunks beyond steps clamps (no empty chunks); views, not copies.
    ic2, _ = split_epoch_slab(images, masks, 99)
    assert len(ic2) == 7
    assert ic[0].base is not None  # view of the slab, not a copy
    with pytest.raises(ValueError, match="n_chunks"):
        split_epoch_slab(images, masks, 0)
    with pytest.raises(ValueError, match="disagree"):
        split_epoch_slab(images, masks[:, :3], 2)


def test_fedconfig_segment_knobs():
    from fedcrack_tpu.configs import FedConfig

    cfg = FedConfig(segments=5, local_epochs=10)
    assert cfg.segments == 5 and cfg.segment_overlap is True
    rt = FedConfig.from_json(cfg.to_json())
    assert rt.segments == 5 and rt.segment_overlap is True
    with pytest.raises(ValueError, match="divide"):
        FedConfig(segments=3, local_epochs=10)
    with pytest.raises(ValueError, match=">= 0"):
        FedConfig(segments=-1)


def test_c7_preset_parses():
    import json
    import os

    from fedcrack_tpu.configs import FedConfig

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "configs",
        "c7_segmented_pipeline.json",
    )
    with open(path) as f:
        cfg = FedConfig.from_dict(json.load(f))
    assert cfg.segments == cfg.local_epochs == 10
    assert cfg.segment_overlap is True
