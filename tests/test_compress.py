"""Compressed update transport (round 12, fedcrack_tpu/compress).

Three layers under test:

- **codec properties** (seeded sweeps): NullCodec identity bytes, Int8Codec
  bounded per-leaf error (<= scale/2), TopKDelta error-feedback mass
  draining to zero on a fixed sequence, frame CRC catching every single-bit
  flip it is shown.
- **protocol integration**: the server decodes framed uploads through the
  SAME validate_update sanitation gate as raw bytes; corrupt / stale-base
  frames are REJECTED and history-logged; a quorum round survives a
  poisoned frame; wire-vs-decoded byte accounting lands in history; the
  codec is negotiated in-band end to end over real gRPC.
- **mesh twin**: build_federated_round(update_codec=...) — null is
  bit-identical to a pre-codec build, int8/topk complete N>=3 rounds with
  finite weights and a bounded IoU trajectory delta vs the null oracle,
  and the driver's bytes_per_round counter prices the codecs in order.
"""

import dataclasses

import numpy as np
import pytest

from fedcrack_tpu.compress import (
    Frame,
    decode_frame,
    decode_update,
    encode_frame,
    encoded_bytes_model,
    get_codec,
    is_frame,
)
from fedcrack_tpu.compress.codecs import (
    int8_dequantize,
    int8_quantize,
    leaf_k,
    qsgd_scales,
    topk_select,
)
from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import (
    tree_from_bytes,
    tree_to_bytes,
    validate_update,
)

pytestmark = [pytest.mark.compress]


def _tree(rng, scale=1.0):
    return {
        "params": {
            "w": (scale * rng.normal(size=(32, 16))).astype(np.float32),
            "b": (scale * rng.normal(size=(5,))).astype(np.float32),
        },
        "batch_stats": {"m": (scale * rng.normal(size=(7,))).astype(np.float32)},
    }


def _shifted(tree, rng, mag):
    import jax

    return jax.tree_util.tree_map(
        lambda x: x + (mag * rng.standard_t(3, size=x.shape)).astype(np.float32),
        tree,
    )


# ---------- codec properties ----------


def test_null_codec_identity_bytes():
    rng = np.random.default_rng(0)
    blob = tree_to_bytes(_tree(rng))
    base = tree_to_bytes(_tree(rng))
    assert get_codec("null").encode_update(blob, base) == blob
    # and a null upload is NOT a frame — it is literally today's bytes
    assert not is_frame(blob)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_int8_bounded_per_leaf_error(seed):
    """QSGD property: every entry's reconstruction error is bounded by its
    bucket's scale (stochastic floor rounding moves a value at most one
    quantization step), at every magnitude in the sweep."""
    import jax

    rng = np.random.default_rng(seed)
    base = _tree(rng)
    upd = _shifted(base, rng, mag=10.0 ** rng.uniform(-4, 0))
    frame_blob = get_codec("int8").encode_update(
        tree_to_bytes(upd), tree_to_bytes(base), base_version=3
    )
    got, frame = decode_update(
        frame_blob, template=base, base=base, expected_base_version=3
    )
    for g, u, b in zip(
        jax.tree_util.tree_leaves(got),
        jax.tree_util.tree_leaves(upd),
        jax.tree_util.tree_leaves(base),
    ):
        delta = (u - b).ravel()
        scales = qsgd_scales(delta)
        per_entry = np.repeat(scales, 16384)[: delta.size]
        err = np.abs(np.asarray(g).ravel() - u.ravel())
        assert np.all(err <= per_entry + 1e-6), float(np.max(err / per_entry))


def test_int8_stochastic_rounding_is_unbiased_and_seeded():
    rng = np.random.default_rng(5)
    x = (0.01 * rng.standard_t(3, size=4096)).astype(np.float32)
    # deterministic per seed
    q1, s1 = int8_quantize(x, bucket=512, seed=(7, 0, 0))
    q2, s2 = int8_quantize(x, bucket=512, seed=(7, 0, 0))
    np.testing.assert_array_equal(q1, q2)
    np.testing.assert_array_equal(s1, s2)
    assert not np.array_equal(q1, int8_quantize(x, bucket=512, seed=(8, 0, 0))[0])
    # unbiased: the seed-averaged dequantization converges on x
    acc = np.zeros_like(x)
    n_seeds = 300
    for s in range(n_seeds):
        q, sc = int8_quantize(x, bucket=512, seed=(s, 1, 2))
        acc += int8_dequantize(q, sc, bucket=512)
    scale_cap = float(np.max(np.repeat(qsgd_scales(x, 512), 512)[: x.size]))
    # mean error shrinks ~1/sqrt(N) of one quantization step
    assert np.max(np.abs(acc / n_seeds - x)) < 5.0 * scale_cap / np.sqrt(n_seeds)


def test_int8_quantize_zero_leaf_is_exact():
    q, scales = int8_quantize(np.zeros(16, np.float32), bucket=8, seed=(0,))
    assert scales.tolist() == [1.0, 1.0] and not q.any()


@pytest.mark.parametrize("fraction", [0.05, 0.25])
def test_topk_error_feedback_mass_drains_to_zero(fraction):
    """Fixed sequence: one real delta, then identical-to-base rounds. Each
    later round transmits the top-k of the residual, so the accumulated
    mass must be strictly decreasing and reach (near) zero — Lin et al.'s
    'dropped mass is delayed, never lost'."""
    rng = np.random.default_rng(42)
    base = _tree(rng)
    base_blob = tree_to_bytes(base)
    upd_blob = tree_to_bytes(_shifted(base, rng, 0.1))
    codec = get_codec("topk_delta", topk_fraction=fraction)
    codec.encode_update(upd_blob, base_blob)
    masses = [codec.residual_mass()]
    for _ in range(200):
        if codec.residual_mass() == 0.0:
            break
        codec.encode_update(base_blob, base_blob)  # zero new delta
        masses.append(codec.residual_mass())
    assert all(b < a for a, b in zip(masses, masses[1:])), "mass must drain"
    assert masses[-1] <= 1e-6 * max(1.0, masses[0])


def test_topk_nothing_lost_only_delayed():
    """Sum of everything transmitted over the drain equals the original
    delta: reconstruct every frame against a zero base and accumulate."""
    import jax

    rng = np.random.default_rng(7)
    base = _tree(rng)
    base_blob = tree_to_bytes(base)
    upd = _shifted(base, rng, 0.05)
    codec = get_codec("topk_delta", topk_fraction=0.2)
    zeros = jax.tree_util.tree_map(lambda x: np.zeros_like(x), base)
    acc = jax.tree_util.tree_map(lambda x: np.zeros_like(x), base)
    blob = tree_to_bytes(upd)
    for i in range(60):
        frame_blob = codec.encode_update(
            blob if i == 0 else base_blob, base_blob
        )
        got, _ = decode_update(frame_blob, template=base, base=zeros)
        acc = jax.tree_util.tree_map(lambda a, g: a + np.asarray(g), acc, got)
        if codec.residual_mass() == 0.0:
            break
    want = jax.tree_util.tree_map(lambda u, b: u - b, upd, base)
    for a, w in zip(jax.tree_util.tree_leaves(acc), jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(a, w, atol=1e-5)


def test_topk_rollback_restores_unaggregated_mass():
    """Straggler path (r12 review fix): encode_update drops the top-k mass
    from the accumulator at encode time, but a NOT_WAIT resync means the
    server never averaged that upload — rollback_last must restore the
    full pre-drop effective delta so 'nothing lost, only delayed' holds
    across the PROTOCOL, not just across accepted uploads."""
    import jax

    rng = np.random.default_rng(13)
    base = _tree(rng)
    upd = _shifted(base, rng, 0.1)
    full_mass = sum(
        float(np.sum(np.abs(np.asarray(u, np.float32) - np.asarray(b, np.float32))))
        for u, b in zip(
            jax.tree_util.tree_leaves(upd), jax.tree_util.tree_leaves(base)
        )
    )
    codec = get_codec("topk_delta", topk_fraction=0.05)
    codec.encode_update(tree_to_bytes(upd), tree_to_bytes(base))
    assert codec.residual_mass() < full_mass * 0.999  # mass left with the upload
    codec.rollback_last()
    np.testing.assert_allclose(codec.residual_mass(), full_mass, rtol=1e-5)
    codec.rollback_last()  # a second rollback is a no-op
    np.testing.assert_allclose(codec.residual_mass(), full_mass, rtol=1e-5)
    # stateless codecs: no-op, no error
    get_codec("null").rollback_last()
    get_codec("int8").rollback_last()


def test_topk_select_deterministic_under_ties():
    x = np.array([1.0, -1.0, 1.0, 0.5], np.float32)
    assert topk_select(x, 2).tolist() == [0, 1]
    assert leaf_k(1000, 0.01) == 10 and leaf_k(3, 0.01) == 1


def test_codec_registry_and_validation():
    with pytest.raises(ValueError):
        get_codec("gzip9")
    with pytest.raises(ValueError):
        get_codec("topk_delta", topk_fraction=0.0)
    with pytest.raises(ValueError):
        FedConfig(update_codec="lz4")
    with pytest.raises(ValueError):
        FedConfig(topk_fraction=1.5)
    with pytest.raises(ValueError):
        FedConfig(max_message_mb=0)
    cfg = FedConfig(update_codec="topk_delta", topk_fraction=0.02)
    assert FedConfig.from_json(cfg.to_json()) == cfg


# ---------- frames ----------


def test_frame_roundtrip_and_fields():
    payload = bytes(range(256)) * 4
    blob = encode_frame("int8", 3, 7, [{"shape": [4], "enc": "int8"}], payload)
    assert is_frame(blob)
    frame = decode_frame(blob)
    assert frame == Frame(
        codec="int8",
        round=3,
        base_version=7,
        leaves=({"shape": [4], "enc": "int8"},),
        payload=payload,
    )


@pytest.mark.parametrize("seed", [0, 1])
def test_frame_crc_catches_every_single_bit_flip_tried(seed):
    rng = np.random.default_rng(seed)
    blob = encode_frame(
        "topk_delta", 1, 0, [{"shape": [64], "enc": "topk", "k": 4}],
        rng.bytes(128),
    )
    for _ in range(32):
        pos = int(rng.integers(4, len(blob)))  # past the magic
        bit = 1 << int(rng.integers(8))
        flipped = blob[:pos] + bytes([blob[pos] ^ bit]) + blob[pos + 1 :]
        with pytest.raises(ValueError):
            decode_frame(flipped)


def test_decode_update_rejects_stale_base_and_lying_manifest():
    rng = np.random.default_rng(0)
    base = _tree(rng)
    upd_blob = tree_to_bytes(_shifted(base, rng, 0.1))
    frame_blob = get_codec("int8").encode_update(
        upd_blob, tree_to_bytes(base), base_version=4
    )
    with pytest.raises(ValueError, match="stale round base"):
        decode_update(frame_blob, template=base, base=base, expected_base_version=5)
    # manifest lying about k / shapes / payload length must be a ValueError
    short = encode_frame(
        "topk_delta", 1, 0, [{"shape": [100], "enc": "topk", "k": 50}], b"\x00" * 8
    )
    with pytest.raises(ValueError, match="truncated"):
        decode_update(short, template={"w": np.zeros(100, np.float32)},
                      base={"w": np.zeros(100, np.float32)})
    bad_idx = encode_frame(
        "topk_delta", 1, 0, [{"shape": [4], "enc": "topk", "k": 1}],
        np.array([9], np.int32).tobytes() + np.array([1.0], np.float32).tobytes(),
    )
    with pytest.raises(ValueError, match="out of range"):
        decode_update(bad_idx, template={"w": np.zeros(4, np.float32)},
                      base={"w": np.zeros(4, np.float32)})


def test_topk_refuses_nonfinite_delta():
    """Same contract as Int8Codec (r12 review fix): NaNs sort to the END of
    the magnitude order, so a poisoned delta would otherwise transmit an
    all-finite, sanitation-passing top-k while the residual keeps the NaNs
    forever — laundered poison plus a permanently corrupted accumulator."""
    rng = np.random.default_rng(0)
    base = _tree(rng)
    nan_upd = _shifted(base, rng, 0.1)
    nan_upd["params"]["w"][0, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        get_codec("topk_delta").encode_update(
            tree_to_bytes(nan_upd), tree_to_bytes(base)
        )


def test_lying_giant_shape_manifest_is_valueerror_not_allocation():
    """A CRC-valid frame declaring shape [10**12] with k=0 dodges every
    payload-size bound; decode_update must refuse it against the template
    BEFORE reconstruction allocates anything (r12 review fix) — a
    MemoryError would escape the server's ValueError rejection handling."""
    huge = encode_frame(
        "topk_delta", 1, 0,
        [{"shape": [10**12], "enc": "topk", "k": 0}], b"",
    )
    with pytest.raises(ValueError, match="shape mismatch"):
        decode_update(huge, template={"w": np.zeros(4, np.float32)},
                      base={"w": np.zeros(4, np.float32)})
    # leaf-count lies are refused before reconstruction too
    extra = encode_frame(
        "topk_delta", 1, 0,
        [{"shape": [4], "enc": "topk", "k": 1}] * 2,
        (np.zeros(1, np.int32).tobytes() + np.zeros(1, np.float32).tobytes()) * 2,
    )
    with pytest.raises(ValueError, match="leaves"):
        decode_update(extra, template={"w": np.zeros(4, np.float32)},
                      base={"w": np.zeros(4, np.float32)})


def test_zlib_bomb_rejected_before_inflation():
    """A CRC-valid frame whose zlib payload inflates far past what its own
    manifest implies must be a ValueError BEFORE the full inflate (r12
    review fix) — a decompression bomb would otherwise allocate hundreds
    of MB inside the single-writer transition and escape the ValueError
    rejection path as a MemoryError."""
    bomb = encode_frame(
        "int8", 1, 0,
        [{"shape": [4], "enc": "int8", "scales": b"\x00" * 4, "bucket": 4}],
        bytes(32 * 1024 * 1024),  # 32 MB of zeros -> ~32 KB on the wire
    )
    assert len(bomb) < 1024 * 1024
    with pytest.raises(ValueError, match="inflates past"):
        decode_update(bomb, template={"w": np.zeros(4, np.float32)},
                      base={"w": np.zeros(4, np.float32)})
    # and a manifest CLAIMING more than the template could ever need is
    # refused before a single byte inflates
    big_claim = encode_frame(
        "topk_delta", 1, 0,
        [{"shape": [4], "enc": "topk", "k": 10**9}], b"",
    )
    with pytest.raises(ValueError, match="caller bound"):
        decode_update(big_claim, template={"w": np.zeros(4, np.float32)},
                      base={"w": np.zeros(4, np.float32)})


def test_absurd_bucket_cannot_force_giant_allocation():
    """expand_scales is an O(n) index gather: an int8 manifest declaring a
    bucket of 10**12 with one scale decodes (one bucket covers the whole
    leaf) instead of materializing a bucket-sized np.repeat (r12 review
    fix)."""
    q = np.array([1, -2, 3, 0], np.int8)
    frame_blob = encode_frame(
        "int8", 1, 0,
        [{
            "shape": [4], "enc": "int8",
            "scales": np.array([0.5], np.float32).tobytes(),
            "bucket": 10**12,
        }],
        q.tobytes(),
    )
    got, _ = decode_update(
        frame_blob,
        template={"w": np.zeros(4, np.float32)},
        base={"w": np.zeros(4, np.float32)},
    )
    np.testing.assert_allclose(got["w"], [0.5, -1.0, 1.5, 0.0])


def test_validate_update_accepts_trees_and_bytes():
    """The gate's two entry forms agree: the framed path validates the
    materialized tree directly (no redundant encode∘decode per upload)."""
    template = {"w": np.zeros((3, 3), np.float32)}
    good = {"w": np.ones((3, 3), np.float32)}
    assert validate_update(good, template) is None
    assert validate_update(tree_to_bytes(good), template) is None
    bad = {"w": np.full((3, 3), np.nan, np.float32)}
    assert "non-finite" in validate_update(bad, template)
    assert "non-finite" in validate_update(tree_to_bytes(bad), template)
    assert "shape mismatch" in validate_update(
        {"w": np.ones((9,), np.float32)}, template
    )


def test_nan_update_fault_composes_with_framed_cohort():
    """chaos NAN_UPDATE on a compressed cohort must deliver what the fault
    kind promises — a CRC-VALID frame whose reconstruction is non-finite —
    so the validate_update gate, not the CRC, refuses it (r12 review fix:
    it previously crashed trying to msgpack-decode the frame)."""
    from fedcrack_tpu.chaos.inject import _poison_weights
    from fedcrack_tpu.chaos.plan import NAN_UPDATE

    for codec_name in ("int8", "topk_delta"):
        state, _ = _enrolled_state(
            _cfg(update_codec=codec_name, quorum_fraction=0.5)
        )
        ev = _framed_done(state, "a", 1.0, 10,
                          poison=lambda b: _poison_weights(b, NAN_UPDATE))
        assert is_frame(ev.blob)
        decode_frame(ev.blob)  # CRC-valid: the frame layer must NOT catch it
        state, rep = R.transition(state, ev)
        assert rep.status == R.REJECTED
        assert "non-finite" in state.rejected["a"]
        # the round continues: the clean peer still aggregates
        state, rep = R.transition(state, _framed_done(state, "b", 3.0, 30))
        assert rep.status in (R.RESP_ARY, R.FIN)


def test_crc_valid_frame_with_junk_typed_fields_is_valueerror():
    """A CRC-valid body carrying junk-typed fields (round=None, non-dict
    manifest entries) must decode-fail as ValueError — the only family the
    server's rejection path catches — never TypeError aborting the RPC
    stream (r12 review fix)."""
    import msgpack as _msgpack
    import struct as _struct

    from fedcrack_tpu.native import crc32c

    for body_map in (
        {"v": 1, "codec": "int8", "round": None, "base_version": 0,
         "leaves": [], "zlib": False, "payload": b""},
        {"v": 1, "codec": "int8", "round": 1, "base_version": 0,
         "leaves": [1, 2], "zlib": False, "payload": b""},
    ):
        body = _msgpack.packb(body_map, use_bin_type=True)
        blob = b"FCWF" + _struct.pack("<I", crc32c(body)) + body
        with pytest.raises(ValueError):
            decode_frame(blob)


def test_startup_budget_covers_many_small_leaf_models():
    """The startup cap assertion must price topk's per-leaf floors
    (k >= 1, manifest entries): a model of many tiny leaves costs far more
    than fraction*dense on the wire, and a cap that fits the naive bound
    but not the real frame must be refused at construction, not die
    RESOURCE_EXHAUSTED mid-round (r12 review fix)."""
    from fedcrack_tpu.compress.codecs import DEFAULT_TOPK_FRACTION

    sizes = [4] * 5000  # 5000 BN-scalar-ish leaves, 80 KB dense payload
    model = encoded_bytes_model(sizes, "topk_delta",
                                topk_fraction=DEFAULT_TOPK_FRACTION)
    naive_fraction_bound = int(
        4 * sum(sizes) * 2 * DEFAULT_TOPK_FRACTION
    )  # what a dense-length·2f model would claim
    assert model > naive_fraction_bound  # per-leaf floors dominate here


def test_encoded_bytes_model_orders_codecs():
    sizes = [1000, 10]
    assert (
        encoded_bytes_model(sizes, "topk_delta", topk_fraction=0.01)
        < encoded_bytes_model(sizes, "int8")
        < encoded_bytes_model(sizes, "null")
    )


# ---------- protocol integration (state machine level) ----------


def _vars(value: float, n: int = 64):
    return {"params": {"w": np.full((n, n), value, np.float32)}}


def _cfg(**kw):
    base = dict(
        max_rounds=2,
        cohort_size=2,
        registration_window_s=100.0,
        update_codec="int8",
    )
    base.update(kw)
    return FedConfig(**base)


def _enrolled_state(cfg, value=0.0):
    state = R.initial_state(cfg, _vars(value))
    state, _ = R.transition(state, R.Ready(cname="a", now=0.0))
    state, rep = R.transition(state, R.Ready(cname="b", now=0.0))
    assert state.phase == R.PHASE_RUNNING
    return state, rep


def _framed_done(state, cname, value, ns, now=1.0, poison=None, base_version=None):
    codec = get_codec(state.config.update_codec, client_tag=cname)
    blob = codec.encode_update(
        tree_to_bytes(_vars(value)),
        state.broadcast_blob,
        round=state.current_round,
        base_version=state.model_version if base_version is None else base_version,
    )
    if poison is not None:
        blob = poison(blob)
    return R.TrainDone(cname=cname, round=state.current_round, blob=blob,
                       num_samples=ns, now=now)


def _decoded_w(state, blob):
    """What the server's decode path reconstructs from an upload — the
    oracle for exact-aggregation assertions (int8 encode is seeded, so the
    frame and its reconstruction are deterministic). The delta base is the
    BROADCAST blob — the bytes the client pulled — which differs from
    global_blob under wire_dtype=bfloat16."""
    if is_frame(blob):
        tree, _ = decode_update(
            blob,
            template=state.template,
            base=tree_from_bytes(state.broadcast_blob, template=state.template),
            expected_base_version=state.model_version,
        )
        return np.asarray(tree["params"]["w"], np.float32)
    return np.asarray(tree_from_bytes(blob)["params"]["w"], np.float32)


def _qsgd_bound(state, values_weights):
    """Weighted per-entry QSGD error bound for constant-leaf client deltas:
    stochastic floor rounding moves each entry at most one bucket scale."""
    total = sum(w for _, w in values_weights)
    base = np.asarray(
        tree_from_bytes(state.global_blob)["params"]["w"], np.float32
    )
    bound = np.zeros_like(base)
    for v, w in values_weights:
        delta = (np.full_like(base, v) - base).ravel()
        scales = qsgd_scales(delta)
        per_entry = np.repeat(scales, 16384)[: delta.size].reshape(base.shape)
        bound += (w / total) * per_entry
    return bound


def test_framed_round_aggregates_and_accounts_wire_bytes():
    state0, _ = _enrolled_state(_cfg())
    state = state0
    ev_a = _framed_done(state, "a", 1.0, 10)
    ev_b = _framed_done(state, "b", 3.0, 30)
    # Exact-aggregation oracle: the round must average EXACTLY what
    # decode_update reconstructs from each frame, weighted by samples.
    want = (10 * _decoded_w(state, ev_a.blob) + 30 * _decoded_w(state, ev_b.blob)) / 40
    state, rep = R.transition(state, ev_a)
    assert rep.status == R.RESP_ACY
    state, rep = R.transition(state, ev_b)
    assert rep.status == R.RESP_ARY
    got = tree_from_bytes(rep.blob)["params"]["w"]
    np.testing.assert_allclose(got, want, atol=1e-5)
    # and the reconstruction respects the quantizer's error bound around
    # the ideal average (10*1 + 30*3)/40 = 2.5
    bound = _qsgd_bound(state0, [(1.0, 10), (3.0, 30)])
    assert np.all(np.abs(np.asarray(got) - 2.5) <= bound + 1e-6)
    entry = state.history[0]
    assert entry["codecs"] == {"a": "int8", "b": "int8"}
    assert entry["bytes_received"] == len(ev_a.blob) + len(ev_b.blob)
    # the whole point: the wire carried less than the decoded trees
    assert entry["bytes_received"] < entry["decoded_bytes_received"]


def test_corrupt_frame_rejected_and_quorum_round_completes():
    from fedcrack_tpu.chaos.inject import _poison_weights
    from fedcrack_tpu.chaos.plan import CORRUPT_COMPRESSED_FRAME

    cfg = _cfg(cohort_size=3, quorum_fraction=2.0 / 3.0, max_rounds=1)
    state = R.initial_state(cfg, _vars(0.0))
    for c in ("a", "b", "c"):
        state, _ = R.transition(state, R.Ready(cname=c, now=0.0))
    flip = lambda b: _poison_weights(b, CORRUPT_COMPRESSED_FRAME)
    state, rej = R.transition(state, _framed_done(state, "c", 9.0, 20, poison=flip))
    assert rej.status == R.REJECTED
    ev_a = _framed_done(state, "a", 1.0, 10)
    ev_b = _framed_done(state, "b", 3.0, 30)
    want = (10 * _decoded_w(state, ev_a.blob) + 30 * _decoded_w(state, ev_b.blob)) / 40
    state, _ = R.transition(state, ev_a)
    state, rep = R.transition(state, ev_b)
    assert rep.status == R.FIN  # quorum 2-of-3 closed the round
    entry = state.history[0]
    assert entry["clients"] == ["a", "b"]
    assert "checksum" in entry["rejected"]["c"]
    got = tree_from_bytes(rep.blob)["params"]["w"]
    # exactly the weighted mean of the two CLEAN reconstructions — the
    # poisoned frame contributed nothing
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_bf16_wire_delta_base_is_the_broadcast_blob():
    """wire_dtype=bfloat16 + int8: the client computes its delta against
    the bf16-cast BROADCAST blob, so the server must apply the delta to
    those same bytes. Decoding against the float32 global would add
    (f32_base - bf16(f32_base)) to every reconstructed weight — finite and
    shape-correct, so it would sail through sanitation silently wrong
    (r12 review fix). Base value 1000.3 makes the bf16 cast error ~0.3, so
    the two bases are unambiguously distinguishable."""
    state, _ = _enrolled_state(_cfg(wire_dtype="bfloat16"), value=1000.3)
    ev_a = _framed_done(state, "a", 1001.0, 10)
    ev_b = _framed_done(state, "b", 1003.0, 30)
    want = (10 * _decoded_w(state, ev_a.blob) + 30 * _decoded_w(state, ev_b.blob)) / 40
    # sanity: the broadcast-based and global-based reconstructions differ
    # materially here — the oracle discriminates the bug it pins.
    wrong_base = tree_from_bytes(state.global_blob, template=state.template)
    wrong, _ = decode_update(
        ev_a.blob, template=state.template, base=wrong_base,
        expected_base_version=state.model_version,
    )
    assert (
        float(np.max(np.abs(np.asarray(wrong["params"]["w"])
                            - _decoded_w(state, ev_a.blob)))) > 0.05
    )
    state, rep = R.transition(state, ev_a)
    assert rep.status == R.RESP_ACY
    state, rep = R.transition(state, ev_b)
    assert rep.status == R.RESP_ARY
    # compare the f32 GLOBAL (the reply blob is the bf16-cast broadcast,
    # whose wire rounding at magnitude ~1000 is ~8x coarser than the claim)
    got = tree_from_bytes(state.global_blob, template=state.template)
    np.testing.assert_allclose(
        np.asarray(got["params"]["w"], np.float32), want, atol=1e-4
    )


def test_int8_client_tag_decorrelates_rounding_noise():
    """Two clients encoding the SAME update in the same round must draw
    INDEPENDENT stochastic-rounding noise (correlated noise would keep the
    cohort-averaged quantization error at per-client magnitude instead of
    shrinking ~1/sqrt(C)); the same client replaying the same round must
    reproduce identical frame bytes (chaos-replay determinism)."""
    rng = np.random.default_rng(11)
    base = _tree(rng)
    base_blob = tree_to_bytes(base)
    upd_blob = tree_to_bytes(_shifted(base, rng, 0.1))
    enc = lambda tag: get_codec("int8", client_tag=tag).encode_update(
        upd_blob, base_blob, round=3, base_version=2
    )
    assert enc("client-a") == enc("client-a")  # pure per client
    assert enc("client-a") != enc("client-b")  # independent across clients


def test_stale_base_frame_rejected_and_history_logged():
    state, _ = _enrolled_state(_cfg())
    ev = _framed_done(state, "a", 1.0, 10, base_version=99)
    state, rep = R.transition(state, ev)
    assert rep.status == R.REJECTED
    assert "stale round base" in state.rejected["a"]


def test_poison_frame_rejected_by_validate_update_gate():
    """A CRC-VALID frame can still reconstruct to non-finite weights (a
    crafted inf scale sidecar): the frame layer proves transport integrity,
    validate_update proves averageability — the exact split fedlint COMP001
    pins statically. The honest client path can't even produce this: the
    Int8Codec refuses to encode a non-finite delta (it would otherwise be
    silently clipped to zero codes — a laundered poison)."""
    state, _ = _enrolled_state(_cfg())
    nan_vars = {"params": {"w": np.full((64, 64), np.nan, np.float32)}}
    with pytest.raises(ValueError, match="non-finite"):
        get_codec("int8").encode_update(
            tree_to_bytes(nan_vars), state.broadcast_blob,
            round=1, base_version=state.model_version,
        )
    # The adversarial path: a hand-crafted frame with an inf scale passes
    # every CRC/shape check and reconstructs to inf weights.
    blob = encode_frame(
        "int8", 1, state.model_version,
        [{
            "shape": [64, 64],
            "enc": "int8",
            "scales": np.array([np.inf], np.float32).tobytes(),
            "bucket": 64 * 64,
        }],
        bytes([1]) * (64 * 64),
    )
    state, rep = R.transition(
        state, R.TrainDone(cname="a", round=1, blob=blob, num_samples=5, now=1.0)
    )
    assert rep.status == R.REJECTED
    assert "non-finite" in state.rejected["a"]
    # sanity: the gate that refused it is the shared sanitation function
    decoded, _ = decode_update(
        blob, template=state.template,
        base=tree_from_bytes(state.global_blob, template=state.template),
        expected_base_version=state.model_version,
    )
    assert validate_update(tree_to_bytes(decoded), state.template) is not None


def test_frames_sanitized_even_with_sanitize_updates_off():
    state, _ = _enrolled_state(_cfg(sanitize_updates=False))
    flip = lambda b: b[:-2] + bytes([b[-2] ^ 1]) + b[-1:]
    state, rep = R.transition(state, _framed_done(state, "a", 1.0, 10, poison=flip))
    assert rep.status == R.REJECTED


def test_raw_blob_still_accepted_in_compressed_cohort():
    """Mixed-codec cohort: a legacy client ignoring the negotiated codec
    uploads raw msgpack; it aggregates with framed peers correctly."""
    state, _ = _enrolled_state(_cfg())
    raw_blob = tree_to_bytes(_vars(1.0))
    ev_b = _framed_done(state, "b", 3.0, 30)
    want = (10 * _decoded_w(state, raw_blob) + 30 * _decoded_w(state, ev_b.blob)) / 40
    state, rep = R.transition(
        state,
        R.TrainDone(cname="a", round=1, blob=raw_blob, num_samples=10, now=1.0),
    )
    assert rep.status == R.RESP_ACY
    state, rep = R.transition(state, ev_b)
    assert rep.status == R.RESP_ARY
    got = tree_from_bytes(rep.blob)["params"]["w"]
    np.testing.assert_allclose(got, want, atol=1e-5)
    assert state.history[0]["codecs"] == {"a": "null", "b": "int8"}


def test_handshake_advertises_codec():
    state, rep = _enrolled_state(_cfg(update_codec="topk_delta"))
    assert rep.config["update_codec"] == "topk_delta"
    assert rep.config["topk_fraction"] == pytest.approx(0.01)


def test_statefile_preserves_wire_accounting():
    from fedcrack_tpu.ckpt.statefile import (
        server_state_from_bytes,
        server_state_to_bytes,
    )

    cfg = _cfg()
    state, _ = _enrolled_state(cfg)
    state, _ = R.transition(state, _framed_done(state, "a", 1.0, 10))
    blob = server_state_to_bytes(state)
    restored = server_state_from_bytes(blob, cfg)
    assert dict(restored.wire_bytes) == dict(state.wire_bytes)
    assert dict(restored.codecs) == {"a": "int8"}


def test_server_startup_asserts_frame_budget_fits_cap():
    from fedcrack_tpu.transport.service import FedServer

    big = {"params": {"w": np.zeros(600_000, np.float32)}}  # ~2.4 MB blob
    with pytest.raises(ValueError, match="max_message_mb"):
        FedServer(_cfg(max_message_mb=1), big)
    FedServer(_cfg(max_message_mb=8), big)  # and a sane cap boots


# ---------- end-to-end over gRPC: in-band negotiation ----------


def test_grpc_session_negotiates_codec_and_shrinks_uploads():
    import threading

    from fedcrack_tpu.transport import FedClient, FedServer
    from fedcrack_tpu.transport.service import ServerThread

    cfg = dataclasses.replace(
        _cfg(),
        max_rounds=2,
        registration_window_s=5.0,
        poll_period_s=0.05,
        port=0,
    )

    def make_train_fn(delta):
        def train_fn(weights_blob, rnd):
            tree = tree_from_bytes(weights_blob)
            import jax

            out = jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32) + delta, tree
            )
            return tree_to_bytes(out), 10, {"loss": 0.0}

        return train_fn

    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        clients = [
            FedClient(cfg, make_train_fn(d), cname=f"c{d}", port=st.port,
                      poll_period_s=0.05)
            for d in (1.0, 3.0)
        ]
        results = [None, None]

        def run(i):
            results[i] = clients[i].run_session()

        threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        state = st.state
    assert all(r is not None and r.rounds_completed == 2 for r in results)
    # negotiated in-band: both clients picked up int8 from the handshake
    assert all(c.codec.name == "int8" for c in clients)
    for entry in state.history:
        assert set(entry["codecs"].values()) == {"int8"}
        assert entry["bytes_received"] < entry["decoded_bytes_received"]
    # each round's average: both clients add their delta to the same base,
    # so the ideal global after round R is R * mean(1, 3) = 2R. The QSGD
    # quantizer moves each entry at most one bucket scale per round
    # (64*v/127 for these constant deltas: 0.504 + 1.512 halved = 1.008/
    # round, 2.016 over two) and is unbiased, so the mean stays close.
    # Exact aggregation of reconstructions is pinned by the state-machine
    # tests above; this e2e run pins negotiation + wire shrinkage.
    final = np.asarray(tree_from_bytes(state.global_blob)["params"]["w"])
    assert float(np.max(np.abs(final - 4.0))) <= 2.05
    assert abs(float(np.mean(final)) - 4.0) < 0.2
    for r in results:
        assert all(h["upload_bytes"] < len(tree_to_bytes(_vars(0.0)))
                   for h in r.history)


def _spy_rollback(monkeypatch):
    """Record every TopKDeltaCodec.rollback_last call (by codec identity)
    while keeping its behavior."""
    from fedcrack_tpu.compress import codecs as codecs_mod

    calls = []
    orig = codecs_mod.TopKDeltaCodec.rollback_last

    def spy(self):
        calls.append(self)
        return orig(self)

    monkeypatch.setattr(codecs_mod.TopKDeltaCodec, "rollback_last", spy)
    return calls


def test_topk_no_rollback_when_accepted_upload_is_aggregated(monkeypatch):
    """r12 review fix: a NOT_WAIT from the post-accept POLL means the round
    closed WITH this client's upload averaged — the client must NOT roll
    back the error-feedback accumulator there (re-banking transmitted mass
    would re-send it next round: applied twice, not 'only delayed').
    A clean 2-client full-barrier session exercises exactly that path for
    the first uploader of every round: zero rollbacks may fire."""
    import threading

    from fedcrack_tpu.transport import FedClient, FedServer
    from fedcrack_tpu.transport.service import ServerThread

    calls = _spy_rollback(monkeypatch)
    cfg = _cfg(
        update_codec="topk_delta", max_rounds=2, registration_window_s=5.0,
        poll_period_s=0.05, port=0,
    )

    def make_train_fn(delta):
        def train_fn(blob, rnd):
            tree = tree_from_bytes(blob)
            return (
                tree_to_bytes({"params": {"w": tree["params"]["w"] + delta}}),
                10,
                {"loss": 0.0},
            )

        return train_fn

    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        clients = [
            FedClient(cfg, make_train_fn(d), cname=f"c{d}", port=st.port,
                      poll_period_s=0.05)
            for d in (1.0, 3.0)
        ]
        results = [None, None]

        def run(i):
            results[i] = clients[i].run_session()

        threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert all(r is not None and r.rounds_completed == 2 for r in results)
    assert all(c.codec.name == "topk_delta" for c in clients)
    assert calls == []  # every upload was averaged; nothing to give back


def test_topk_rollback_fires_on_direct_stale_round_resync(monkeypatch):
    """The true straggler path: a TrainDone whose reply ITSELF is NOT_WAIT
    (stale-round resync — the upload was never averaged) must roll the
    error-feedback accumulator back, and only that one. Choreographed
    deterministically: quorum 1-of-2 lets the fast client close round 1
    alone while the straggler's train_fn WAITS (on live server state, not
    a sleep) for that round to pass, so its round-1 upload is stale by
    construction; the fast client's round-2 train then waits for the
    straggler's session to finish so the federation cannot FIN early."""
    import threading
    import time as time_mod

    from fedcrack_tpu.transport import FedClient, FedServer
    from fedcrack_tpu.transport.service import ServerThread

    calls = _spy_rollback(monkeypatch)
    cfg = _cfg(
        update_codec="topk_delta", max_rounds=2, quorum_fraction=0.5,
        registration_window_s=5.0, poll_period_s=0.05, port=0,
    )
    straggler_done = threading.Event()

    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:

        def fast_train(blob, rnd):
            if rnd >= 2:
                straggler_done.wait(timeout=30)
            tree = tree_from_bytes(blob)
            return (
                tree_to_bytes({"params": {"w": tree["params"]["w"] + 1.0}}),
                10,
                {"loss": 0.0},
            )

        def straggler_train(blob, rnd):
            if rnd == 1:
                deadline = time_mod.monotonic() + 30
                while (st.state.current_round == 1
                       and time_mod.monotonic() < deadline):
                    time_mod.sleep(0.02)
            tree = tree_from_bytes(blob)
            return (
                tree_to_bytes({"params": {"w": tree["params"]["w"] + 3.0}}),
                10,
                {"loss": 0.0},
            )

        fast = FedClient(cfg, fast_train, cname="fast", port=st.port,
                         poll_period_s=0.05)
        strag = FedClient(cfg, straggler_train, cname="strag", port=st.port,
                          poll_period_s=0.05)
        results = {}

        def run(c, key):
            try:
                results[key] = c.run_session()
            except Exception as e:  # noqa: BLE001 — the exception IS the result
                results[key] = e
            if key == "strag":
                straggler_done.set()

        threads = [
            threading.Thread(target=run, args=(c, k))
            for c, k in ((strag, "strag"), (fast, "fast"))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        state = st.state
    assert not isinstance(results["strag"], Exception), results["strag"]
    assert not isinstance(results["fast"], Exception), results["fast"]
    # Round 1 aggregated without the straggler; its stale upload drew the
    # direct NOT_WAIT and rolled back EXACTLY its own codec, once.
    assert state.history[0]["clients"] == ["fast"]
    assert len(calls) == 1 and calls[0] is strag.codec


# ---------- mesh twin ----------


@pytest.mark.parametrize("codec", ["int8", "topk_delta"])
def test_mesh_codec_value_maps_match_host_codecs(codec):
    import jax.numpy as jnp

    from fedcrack_tpu.compress.mesh import (
        int8_roundtrip,
        topk_roundtrip,
        zero_residual_like,
    )

    rng = np.random.default_rng(3)
    x = (0.01 * rng.standard_t(3, size=(257,))).astype(np.float32)
    if codec == "int8":
        # Parity is distributional for int8 (different PRNGs): identical
        # scale rule, error bounded by the bucket scale, zero stays zero.
        import jax

        got = np.asarray(
            int8_roundtrip(
                {"x": jnp.asarray(x)}, jax.random.PRNGKey(0), bucket=64
            )["x"]
        )
        per_entry = np.repeat(qsgd_scales(x, 64), 64)[: x.size]
        assert np.all(np.abs(got - x) <= per_entry + 1e-6)
        zero = np.asarray(
            int8_roundtrip(
                {"x": jnp.zeros(16)}, jax.random.PRNGKey(1), bucket=8
            )["x"]
        )
        assert not zero.any()
    else:
        tree = {"x": jnp.asarray(x)}
        kept, res = topk_roundtrip(tree, zero_residual_like(tree), 0.05)
        k = leaf_k(x.size, 0.05)
        idx = topk_select(x, k)
        want = np.zeros_like(x)
        want[idx] = x[idx]
        np.testing.assert_allclose(np.asarray(kept["x"]), want, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(res["x"]), x - want, atol=1e-7
        )


@pytest.mark.slow
def test_mesh_codec_trajectory_and_bytes_counter():
    """One tiny-model pass over all three twins: null is BIT-identical to a
    pre-codec build (the escape hatch), int8/topk complete N>=3 rounds with
    finite weights and a bounded final-IoU delta vs the null oracle, the
    topk twin carries device-resident EF state with a working reset, and
    RoundRecord.bytes_per_round prices the codecs in strict order.

    Slow-marked (~87 s: four round-program compilations — the round-9
    tier-1-budget precedent): the twins' VALUE MAPS stay tier-1 via
    test_mesh_codec_value_maps_match_host_codecs, and the trajectory runs
    again in every bench artifact (detail.update_compression.trajectory,
    bench_runs/r12_*)."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch, n_rounds = 2, 4, 3
    mesh = make_mesh(2, 1)
    per_client = [
        synth_crack_batch(steps * batch, img_size=16, seed=i) for i in range(2)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    active = np.ones(2, np.float32)
    ns = np.full(2, float(steps * batch), np.float32)
    state0 = create_train_state(jax.random.key(0), tiny)
    data_fn = lambda r: (images, masks, active, ns) if r == 0 else None

    runs = {}
    for codec in (None, "null", "int8", "topk_delta"):
        rf = build_federated_round(
            mesh, tiny, learning_rate=1e-3, local_epochs=1,
            update_codec=codec, topk_fraction=0.05,
        )
        vars_, recs = run_mesh_federation(
            rf, state0.variables, data_fn, n_rounds, mesh
        )
        runs[codec] = (jax.device_get(vars_), recs, rf)

    # escape hatch: null twin == no-codec build, bit for bit
    base_leaves = jax.tree_util.tree_leaves(runs[None][0])
    null_leaves = jax.tree_util.tree_leaves(runs["null"][0])
    for a, b in zip(base_leaves, null_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    null_iou = [float(np.mean(r.metrics["iou"])) for r in runs["null"][1]]
    for codec in ("int8", "topk_delta"):
        vars_, recs, rf = runs[codec]
        assert len(recs) == n_rounds
        assert all(
            np.isfinite(np.asarray(l)).all()
            for l in jax.tree_util.tree_leaves(vars_)
        )
        iou = [float(np.mean(r.metrics["iou"])) for r in recs]
        # documented tolerance (BASELINE.md round 12): compressed-twin IoU
        # stays within 0.15 absolute of the null oracle per round at this
        # scale — compression perturbs the trajectory, it must not break it
        assert max(abs(a - b) for a, b in zip(iou, null_iou)) < 0.15
        assert all(r.bytes_per_round == rf.wire_bytes_per_client * 2 for r in recs)

    wpc = {c: runs[c][2].wire_bytes_per_client for c in ("null", "int8", "topk_delta")}
    # Strict ordering at ANY scale; the >=10x ratio only emerges once real
    # leaf sizes amortize the per-leaf floors (k >= 1, manifest overhead) —
    # test_encoded_bytes_model_orders_codecs covers it on realistic sizes
    # and bench.py detail.update_compression measures it at reference scale.
    assert wpc["topk_delta"] < wpc["int8"] < wpc["null"]

    # topk EF state: device-resident across calls, dropped by reset_ef
    rf_topk = runs["topk_delta"][2]
    rf_topk.reset_ef()


@pytest.mark.slow
def test_topk_twin_ef_frozen_for_inactive_clients():
    """On the wire an inactive client never encodes, so its error-feedback
    residual is untouched; the mesh twin must match (r12 review fix): one
    round with client 1 masked inactive leaves its EF slab exactly zero
    while the active client's accumulates."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch = 2, 4
    mesh = make_mesh(2, 1)
    per_client = [
        synth_crack_batch(steps * batch, img_size=16, seed=i) for i in range(2)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    active = np.array([1.0, 0.0], np.float32)
    ns = np.array([float(steps * batch), 0.0], np.float32)
    state0 = create_train_state(jax.random.key(0), tiny)
    rf = build_federated_round(
        mesh, tiny, learning_rate=1e-3, local_epochs=1,
        update_codec="topk_delta", topk_fraction=0.05,
    )
    rf(state0.variables, images, masks, active, ns)
    ef_leaves = jax.tree_util.tree_leaves(jax.device_get(rf.ef_state()))
    assert all(not np.asarray(l)[1].any() for l in ef_leaves), "inactive EF moved"
    assert any(np.asarray(l)[0].any() for l in ef_leaves), "active EF empty"


def test_driver_retry_restores_codec_twin_state():
    """r12 review fix: the round program commits the topk twin's EF pytree
    (and int8's seed counter) when the async dispatch returns — BEFORE a
    poisoned output can surface at the driver's host-side finiteness
    check — so the replay path must restore round_fn.codec_state()
    alongside its weights snapshot. Without it the retry reruns the round
    against the DISCARDED attempt's residual: its kept mass is lost and
    its dropped mass double-banked. Pinned bit-identically: a
    NaN-poisoned round 0 absorbed by one replay == the unfaulted run,
    final weights AND error-feedback state."""
    import jax

    from fedcrack_tpu.chaos import Fault, FaultPlan, MESH_NONFINITE, MeshChaos
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch = 2, 4
    mesh = make_mesh(2, 1)

    def data_fn(r):
        per_client = [
            synth_crack_batch(steps * batch, img_size=16, seed=10 * r + i)
            for i in range(2)
        ]
        images, masks = stack_client_data(per_client, steps, batch)
        return (
            images, masks,
            np.ones(2, np.float32),
            np.full(2, float(steps * batch), np.float32),
        )

    def build():
        return build_federated_round(
            mesh, tiny, learning_rate=1e-3, local_epochs=1,
            update_codec="topk_delta", topk_fraction=0.05,
        )

    init = create_train_state(jax.random.key(0), tiny).variables
    rf_clean = build()
    v_clean, _ = run_mesh_federation(rf_clean, init, data_fn, 2, mesh)
    ef_clean = jax.device_get(rf_clean.ef_state())

    rf_chaos = build()
    plan = FaultPlan([Fault(MESH_NONFINITE, round=0)])
    v_chaos, records = run_mesh_federation(
        rf_chaos, init, data_fn, 2, mesh,
        max_round_retries=1, fault_injector=MeshChaos(plan),
    )
    ef_chaos = jax.device_get(rf_chaos.ef_state())
    assert records[0].retries == 1 and not plan.pending
    for a, b in zip(
        jax.tree_util.tree_leaves(v_clean), jax.tree_util.tree_leaves(v_chaos)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(ef_clean), jax.tree_util.tree_leaves(ef_chaos)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_segmented_builder_has_no_codec_arg():
    from fedcrack_tpu.parallel import build_federated_round_segments, make_mesh

    with pytest.raises(TypeError):
        build_federated_round_segments(make_mesh(1, 1), update_codec="int8")
