"""Low-precision kernel plane (round 20): fused dequant kernels, the
engine's kernel_plane selection, fp8 degradation, and the training-side
fake-quant twin.

The load-bearing claims, each pinned here:

- the fused dequant-matmul's interpret-mode twin matches the r17 reference
  dequantize path within the per-channel scale PER ENTRY, across dtypes
  and ragged shapes, deterministically (same inputs -> byte-identical
  outputs across runs);
- the fused predict program (kernel_plane="fused_int8") agrees with the
  r17 reference plane's program at mask level and clears the production
  install gate; the fp8 program agrees with ITS own dequantize oracle
  (e4m3 rounding is the model delta, not the kernel's);
- requesting fp8 on a backend without fp8 dtypes degrades to the r17
  reference plane BIT-exactly (same closure, test-pinned), visible via
  ``effective_kernel_plane``;
- a garbage quantized build fails the gate on EVERY fused plane and the
  fleet keeps serving the reference program bit-exactly (the r17 refusal
  contract re-pinned through the new selection path);
- ServeConfig.kernel_plane validates at construction (unknown plane,
  fused plane without int8 quant);
- the serve_kernel_plane_info gauge exports exactly one current series;
- the training-side straight-through fake-quant transform bounds its
  weight error by the per-channel scale, passes gradients, and the
  lowp="null" build is byte-identical to a knob-free build (trajectory
  tolerance vs the null oracle is the slow-marked mesh test, the r12
  precedent).
"""

import dataclasses

import numpy as np
import pytest

pytestmark = pytest.mark.kernels

TINY_KW = dict(
    img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
BUCKET = 32


def _serve_config(**over):
    from fedcrack_tpu.configs import ServeConfig

    kw = dict(
        bucket_sizes=(BUCKET,), max_batch=4, max_delay_ms=10.0, tile_overlap=4
    )
    kw.update(over)
    return ServeConfig(**kw)


@pytest.fixture(scope="module")
def kstack():
    """Shared tiny model + per-plane engines (bucket compiles dominate)."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve.engine import InferenceEngine

    model_config = ModelConfig(**TINY_KW)
    variables = init_variables(jax.random.key(0), model_config)
    engines = {
        plane: InferenceEngine(
            model_config, _serve_config(quant="int8", kernel_plane=plane)
        )
        for plane in ("reference", "fused_int8")
    }
    return model_config, variables, engines


# ---- fused dequant kernel twins ----

# Ragged channel counts and sub-tile rows on purpose: the kernel pads to
# (8,128)/(32,128) tiles internally and must slice back exactly.
SWEEP_SHAPES = [(4, 7, 5), (8, 128, 128), (33, 130, 129), (1, 256, 3), (16, 9, 17)]


@pytest.mark.parametrize("shape", SWEEP_SHAPES, ids=[str(s) for s in SWEEP_SHAPES])
def test_dequant_matmul_interpret_twin_error_bound(shape):
    """Interpret-mode fused matmul vs the r17 reference dequantize order:
    per-entry error <= the per-channel scale (the documented bound — the
    two orders differ only by float reassociation), deterministic."""
    from fedcrack_tpu.kernels.dequant import dequant_matmul
    from fedcrack_tpu.serve.quant import QKEY, SKEY, quantize_leaf

    m, k, n = shape
    rng = np.random.default_rng(hash(shape) % (2**31))
    x = rng.normal(0, 1.0, (m, k)).astype(np.float32)
    w = rng.normal(0, 0.1, (k, n)).astype(np.float32)
    leaf = quantize_leaf(w)
    q, scale = leaf[QKEY], leaf[SKEY]

    ref = np.asarray(dequant_matmul(x, q, scale, impl="reference"))
    # The reference impl IS the r17 order — pin that before trusting it as
    # the oracle.
    np.testing.assert_allclose(
        ref, x @ (q.astype(np.float32) * scale), rtol=1e-5, atol=1e-5
    )
    out = np.asarray(dequant_matmul(x, q, scale, impl="interpret"))
    assert np.all(np.abs(out - ref) <= scale[None, :] + 1e-6), (
        f"per-entry error exceeds the per-channel scale at {shape}"
    )
    out2 = np.asarray(dequant_matmul(x, q, scale, impl="interpret"))
    np.testing.assert_array_equal(out, out2)  # deterministic run-to-run


def test_dequant_matmul_fp8_codes_through_same_kernel():
    from fedcrack_tpu import jaxcompat
    from fedcrack_tpu.kernels.dequant import dequant_matmul
    from fedcrack_tpu.serve.quant import QKEY_FP8, SKEY, quantize_leaf_fp8

    if not jaxcompat.fp8_supported():
        pytest.skip("backend has no fp8 dtypes")
    rng = np.random.default_rng(7)
    x = rng.normal(0, 1.0, (9, 37)).astype(np.float32)
    w = rng.normal(0, 0.1, (37, 11)).astype(np.float32)
    leaf = quantize_leaf_fp8(w)
    q, scale = leaf[QKEY_FP8], leaf[SKEY]
    ref = np.asarray(dequant_matmul(x, q, scale, impl="reference"))
    out = np.asarray(dequant_matmul(x, q, scale, impl="interpret"))
    assert np.all(np.abs(out - ref) <= scale[None, :] + 1e-6)


def test_dequant_codes_twin_matches_reference():
    from fedcrack_tpu.kernels.dequant import dequant_codes
    from fedcrack_tpu.serve.quant import QKEY, SKEY, quantize_leaf

    rng = np.random.default_rng(11)
    w = rng.normal(0, 0.1, (130, 17)).astype(np.float32)
    leaf = quantize_leaf(w)
    ref = np.asarray(dequant_codes(leaf[QKEY], leaf[SKEY], impl="reference"))
    out = np.asarray(dequant_codes(leaf[QKEY], leaf[SKEY], impl="interpret"))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-7)


def test_dequant_matmul_validates_shapes():
    from fedcrack_tpu.kernels.dequant import dequant_matmul

    x = np.zeros((4, 8), np.float32)
    q = np.zeros((8, 3), np.int8)
    with pytest.raises(ValueError):
        dequant_matmul(x, q, np.ones(4, np.float32))  # scale != n
    with pytest.raises(ValueError):
        dequant_matmul(x, np.zeros((7, 3), np.int8), np.ones(3, np.float32))
    with pytest.raises(TypeError):
        dequant_matmul(x, q.astype(np.int32), np.ones(3, np.float32))


# ---- engine plane selection ----


def test_fused_int8_plane_matches_reference_plane_and_gates(kstack):
    """The fused predict program vs the r17 reference plane's program over
    the SAME int8 tree: near-identical probabilities and a green
    production-floor install gate (the gate runs the FUSED program — the
    selection point is inside the engine's quantized closure)."""
    from fedcrack_tpu.serve import quant as quant_mod

    _, variables, engines = kstack
    qv = quant_mod.quantize_variables(variables)
    batch = quant_mod.probe_images(BUCKET, 4, 0)
    outs = {}
    for plane, engine in engines.items():
        assert engine.effective_kernel_plane == plane
        payload = engine.prepare_quantized(qv)
        gate = quant_mod.quant_gate(engine, engine.prepare(variables), payload)
        assert gate.passed, f"{plane} gate refused: {gate.to_json()}"
        outs[plane] = engine.predict_bucket(payload, batch)
    diff = np.max(
        np.abs(
            np.asarray(outs["fused_int8"], np.float64)
            - np.asarray(outs["reference"], np.float64)
        )
    )
    assert diff < 1e-3, f"fused_int8 vs reference plane prob diff {diff}"
    assert quant_mod.mask_iou(outs["fused_int8"], outs["reference"]) >= 0.99


def test_fp8_plane_matches_its_dequantize_oracle(kstack):
    """fp8 numerics are the MODEL's delta (e4m3 rounding); the KERNEL must
    match the plain-XLA forward over the dequantized fp8 weights tightly."""
    from fedcrack_tpu import jaxcompat
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.serve import quant as quant_mod
    from fedcrack_tpu.serve.engine import InferenceEngine

    if not jaxcompat.fp8_supported():
        pytest.skip("backend has no fp8 dtypes")
    model_config, variables, _ = kstack
    engine = InferenceEngine(
        model_config, _serve_config(quant="int8", kernel_plane="fp8")
    )
    assert engine.effective_kernel_plane == "fp8"
    qv = quant_mod.quantize_for_plane(variables, "fp8")
    batch = quant_mod.probe_images(BUCKET, 4, 0)
    got = engine.predict_bucket(engine.prepare_quantized(qv), batch)
    oracle_vars = quant_mod.dequantize_variables(qv)
    want = engine.predict_bucket(engine.prepare(oracle_vars), batch)
    diff = np.max(np.abs(np.asarray(got, np.float64) - np.asarray(want, np.float64)))
    assert diff < 1e-3, f"fp8 kernel vs its dequantize oracle diff {diff}"
    assert quant_mod.mask_iou(got, want) >= 0.99


def test_fp8_unsupported_backend_degrades_to_reference_bit_exactly(
    kstack, monkeypatch
):
    """kernel_plane="fp8" without backend fp8 support = the r17 reference
    closure, BIT-exact (not merely close), and the degradation is visible
    in effective_kernel_plane."""
    from fedcrack_tpu.serve import quant as quant_mod
    from fedcrack_tpu.serve.engine import InferenceEngine

    monkeypatch.setattr("fedcrack_tpu.jaxcompat.fp8_supported", lambda: False)
    model_config, variables, engines = kstack
    engine = InferenceEngine(
        model_config, _serve_config(quant="int8", kernel_plane="fp8")
    )
    assert engine.kernel_plane == "fp8"
    assert engine.effective_kernel_plane == "reference"
    qv = quant_mod.quantize_for_plane(variables, engine.effective_kernel_plane)
    batch = quant_mod.probe_images(BUCKET, 4, 0)
    got = engine.predict_bucket(engine.prepare_quantized(qv), batch)
    want = engines["reference"].predict_bucket(
        engines["reference"].prepare_quantized(quant_mod.quantize_variables(variables)),
        batch,
    )
    np.testing.assert_array_equal(got, want)


def _garbage_for_plane(monkeypatch, quant_mod):
    """Monkeypatch quantize_for_plane to zero every code leaf — the gate
    must refuse the resulting build regardless of plane."""
    real = quant_mod.quantize_for_plane

    def garbage(variables, plane):
        q = real(variables, plane)

        def zero(node):
            if isinstance(node, dict) and quant_mod.SKEY in node:
                key = quant_mod.QKEY if quant_mod.QKEY in node else quant_mod.QKEY_FP8
                if key in node:
                    return {key: np.zeros_like(node[key]), quant_mod.SKEY: node[quant_mod.SKEY]}
            if isinstance(node, dict):
                return {k: zero(v) for k, v in node.items()}
            return node

        return quant_mod.QuantizedVariables(zero(q.tree))

    monkeypatch.setattr("fedcrack_tpu.serve.quant.quantize_for_plane", garbage)


@pytest.mark.parametrize("plane", ["fused_int8", "fp8"])
def test_gate_refusal_keeps_serving_reference_per_plane(kstack, monkeypatch, plane):
    """The r17 refusal contract re-pinned THROUGH the kernel-plane
    selection path: a garbage quantized build on a fused plane fails the
    gate, the fleet serves the un-quantized reference program bit-exactly,
    and the refusal names the plane."""
    from fedcrack_tpu import jaxcompat
    from fedcrack_tpu.serve import quant as quant_mod
    from fedcrack_tpu.serve.fleet import ServeFleet
    from fedcrack_tpu.serve.quant import QuantizedVariables

    if plane == "fp8" and not jaxcompat.fp8_supported():
        pytest.skip("backend has no fp8 dtypes")
    model_config, variables, engines = kstack
    from fedcrack_tpu.serve.engine import InferenceEngine

    engine = (
        engines[plane]
        if plane in engines
        else InferenceEngine(model_config, _serve_config(quant="int8", kernel_plane=plane))
    )
    _garbage_for_plane(monkeypatch, quant_mod)
    cfg = _serve_config(quant="int8", kernel_plane=plane, replicas=2)
    fleet = ServeFleet(
        model_config, cfg, variables, shared_engine=engine, warmup=False
    )
    try:
        gate = fleet.manager.last_quant_gate
        assert gate is not None and gate["passed"] is False
        _, payload = fleet.manager.snapshot_for(0)
        assert not isinstance(payload, QuantizedVariables)
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (BUCKET, BUCKET, 3), dtype=np.uint8)
        got = fleet.submit(img).result(timeout=60)
        want = engine.predict_bucket(engine.prepare(variables), img[None])
        np.testing.assert_array_equal(got.probs, want[0])
    finally:
        fleet.close()


# ---- config validation + gauge ----


def test_serve_config_kernel_plane_validation():
    from fedcrack_tpu.configs import ServeConfig

    _serve_config(quant="int8", kernel_plane="fused_int8")  # valid
    with pytest.raises(ValueError):
        _serve_config(kernel_plane="fused_bf4")
    with pytest.raises(ValueError):
        _serve_config(quant="none", kernel_plane="fused_int8")
    with pytest.raises(ValueError):
        _serve_config(quant="none", kernel_plane="fp8")


def test_serve_kernel_plane_info_gauge_single_current_series():
    from fedcrack_tpu.obs.flops import export_kernel_plane
    from fedcrack_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    export_kernel_plane("reference", requested="fp8", registry=reg)
    expo = reg.exposition()
    assert "serve_kernel_plane_info" in expo
    assert 'plane="reference"' in expo and 'requested="fp8"' in expo
    # A plane change zeroes the stale series: exactly one reads 1.
    export_kernel_plane("fused_int8", registry=reg)
    lines = [
        l
        for l in reg.exposition().splitlines()
        if l.startswith("serve_kernel_plane_info{")
    ]
    ones = [l for l in lines if l.rstrip().endswith(" 1") or l.rstrip().endswith(" 1.0")]
    assert len(lines) == 2 and len(ones) == 1
    assert 'plane="fused_int8"' in ones[0]


def test_quantize_for_plane_rejects_unknown_plane():
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import quant as quant_mod

    variables = init_variables(jax.random.key(0), ModelConfig(**TINY_KW))
    with pytest.raises(ValueError):
        quant_mod.quantize_for_plane(variables, "bf4")
    tree = quant_mod.quantize_for_plane(variables, "fused_int8").tree
    # int8 tree for both int8 planes; fp8 tree carries the fp8 leaf key.
    flavors = set()

    def walk(node):
        if quant_mod._is_qleaf(node):
            flavors.update(k for k in node if k != quant_mod.SKEY)
            return
        if isinstance(node, dict):
            for v in node.values():
                walk(v)

    walk(tree)
    assert flavors == {quant_mod.QKEY}


# ---- training-side fake-quant twin ----


def test_fake_quant_params_bounded_and_differentiable():
    """The straight-through transform: weight error <= per-channel scale,
    ndim<2 leaves (biases, BN) untouched, gradients pass through as
    identity (the stop_gradient contract)."""
    import jax
    import jax.numpy as jnp

    from fedcrack_tpu.kernels.dequant import fake_quant_params

    rng = np.random.default_rng(5)
    params = {
        "conv": {"kernel": jnp.asarray(rng.normal(0, 0.1, (3, 3, 4, 7)), jnp.float32),
                 "bias": jnp.asarray(rng.normal(0, 0.1, (7,)), jnp.float32)},
        "bn": {"scale": jnp.ones((4,), jnp.float32)},
    }
    fq = fake_quant_params(params)
    w = np.asarray(params["conv"]["kernel"])
    wq = np.asarray(fq["conv"]["kernel"])
    scale = np.max(np.abs(w.reshape(-1, 7)), axis=0) / 127.0
    assert np.all(np.abs(wq - w) <= scale + 1e-9)
    assert not np.array_equal(wq, w)  # it DID quantize
    np.testing.assert_array_equal(np.asarray(fq["conv"]["bias"]), np.asarray(params["conv"]["bias"]))
    np.testing.assert_array_equal(np.asarray(fq["bn"]["scale"]), np.asarray(params["bn"]["scale"]))

    def loss(p):
        return jnp.sum(fake_quant_params(p)["conv"]["kernel"] ** 2)

    g = jax.grad(loss)(params)["conv"]["kernel"]
    # Straight-through: d/dw sum(fq(w)^2) = 2*fq(w), finite everywhere.
    np.testing.assert_allclose(np.asarray(g), 2 * wq, rtol=1e-6, atol=1e-6)


def test_build_federated_round_lowp_validation():
    from fedcrack_tpu.parallel import build_federated_round, make_mesh
    from fedcrack_tpu.configs import ModelConfig

    mesh = make_mesh(1, 1)
    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    with pytest.raises(ValueError):
        build_federated_round(
            mesh, tiny, learning_rate=1e-3, local_epochs=1, lowp="int4"
        )


@pytest.mark.slow
def test_lowp_fake_quant_trajectory_within_tolerance():
    """3 mesh rounds per arm: lowp="null" is BIT-identical to a knob-free
    build (the escape hatch), lowp="fake_quant_int8" completes with finite
    weights and a per-round IoU within 0.15 absolute of the null oracle —
    the r12 int8-mesh-twin tolerance (BASELINE.md round 12), now covering
    the fused-dequant training step. Slow-marked (three round-program
    compilations; the r9/r12 tier-1-budget precedent) — the value-level
    twin stays tier-1 via test_fake_quant_params_bounded_and_differentiable."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        run_mesh_federation,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch, n_rounds = 2, 4, 3
    mesh = make_mesh(2, 1)
    per_client = [
        synth_crack_batch(steps * batch, img_size=16, seed=i) for i in range(2)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    active = np.ones(2, np.float32)
    ns = np.full(2, float(steps * batch), np.float32)
    state0 = create_train_state(jax.random.key(0), tiny)
    data_fn = lambda r: (images, masks, active, ns) if r == 0 else None

    runs = {}
    for lowp in (None, "null", "fake_quant_int8"):
        rf = build_federated_round(
            mesh, tiny, learning_rate=1e-3, local_epochs=1, lowp=lowp
        )
        assert rf.lowp == ("null" if lowp is None else lowp)
        vars_, recs = run_mesh_federation(
            rf, state0.variables, data_fn, n_rounds, mesh
        )
        runs[lowp] = (jax.device_get(vars_), recs)

    for a, b in zip(
        jax.tree_util.tree_leaves(runs[None][0]),
        jax.tree_util.tree_leaves(runs["null"][0]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    vars_fq, recs_fq = runs["fake_quant_int8"]
    assert all(
        np.isfinite(np.asarray(l)).all()
        for l in jax.tree_util.tree_leaves(vars_fq)
    )
    null_iou = [float(np.mean(r.metrics["iou"])) for r in runs["null"][1]]
    fq_iou = [float(np.mean(r.metrics["iou"])) for r in recs_fq]
    assert max(abs(a - b) for a, b in zip(fq_iou, null_iou)) < 0.15
